"""Sparse sort-compact aggregation plane — the shared core every
execution flavor routes through past its dense cardinality envelope.

The dense paths hold [G, F] accumulator planes indexed by the full group
key PRODUCT — which caps cardinality everywhere it is used: the fused
Pallas kernel refuses >4096 segments, the partial cache falls back past
64k groups, and the mesh/vmapped flavors require the dense plane to fit
the device. The defining time-series workload (millions of small
series, the reference's metric-engine scenario) blows every one of
those budgets while OBSERVING only a bounded number of groups per scan:
U <= N rows, regardless of how large the key product is.

This module compacts the observed groups instead of allocating the
product:

    gid   = combined int64 group id per row (masked rows -> sentinel)
    order = argsort(gid)              # stable; XLA-native, shapes static
    new   = boundaries of equal-gid runs in sorted order
    cid   = cumsum(new) - 1           # dense rank in [0, U)
    uniq  = gid at each boundary      # rank -> global id decode table

and segment-reduces over the compacted ranks with a STATIC cap (slot
budget); only the group count U is dynamic, returned as a scalar. The
tail decodes ranks back to key values exactly like the cross-region
fragment combine does — value-keyed, never product-indexed.

Two device programs consume the compaction:

* `sparse_segment_agg` — the classic XLA path: one masked `segment_agg`
  over the sorted rows (`indices_are_sorted=True`).
* `fused_sparse_segment_agg` — the tiled fused-kernel path. After
  sort-compaction the ids are non-decreasing and rise by AT MOST 1 per
  sorted row, so any R consecutive sorted rows span fewer than R
  distinct ranks. A fori_loop walks R-row windows, rebases each window
  to its first rank (`local = ids - ids[0]`, always < R), runs the
  4096-segment Pallas kernel on the window, and accumulates the window
  planes into the global [cap, ...] planes at the base offset — O(N)
  total work, one compile, arbitrary cap. The 4096-segment envelope
  becomes a TILE SIZE instead of a ceiling.

Cross-shard / cross-part partials combine in GID space
(`combine_sparse_gid_partials`): global ids are shard-invariant, so a
numpy merge over the union of observed ids replaces the collective
psum the dense mesh path uses (per-shard compact slots don't line up).

Reference analog: DataFusion's row-hash GroupedHashAggregateStream for
the high-cardinality case (BASELINE config #5: 1M tag combos); here the
hash table is a sort + run-length pass that XLA vectorizes end to end.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.ops.segment import segment_agg

#: sorts after every real combined group id (key products are guarded
#: upstream to stay below it)
GID_SENTINEL = 1 << 62

#: fused tile: R sorted rows span <= R ranks, and the window kernel
#: needs R locals + 1 dead slot inside the 4096-segment envelope
FUSED_TILE = 4088


@dataclasses.dataclass(frozen=True)
class SparseGroupSpec:
    """Static shape contract of one sparse aggregation: the compact slot
    budget (`cap`), the dense key product it replaced (`num_groups`),
    and the per-key domain sizes the tail uses to decode global ids
    back into key values (mixed-radix, row-major — the same strides the
    dense paths index with)."""

    cap: int
    num_groups: int
    sizes: tuple = ()

    @classmethod
    def plan(cls, num_groups: int, n_pad: int,
             sizes: tuple = ()) -> "SparseGroupSpec":
        """Slot budget for a scan of `n_pad` padded rows: distinct
        observed groups can never exceed the row count, so the cap is
        the row count clamped by the configured ceiling (the guard
        against a query observing more groups than the device planes
        can hold — overflow raises upstream, never truncates)."""
        from greptimedb_tpu import config

        return cls(cap=min(n_pad, config.sparse_groups_max()),
                   num_groups=num_groups, sizes=tuple(sizes))

    def decode(self, gids: np.ndarray, key_idx: int) -> np.ndarray:
        """Key-component index of each global id (host-side tail)."""
        strides = [1] * len(self.sizes)
        for i in range(len(self.sizes) - 2, -1, -1):
            strides[i] = strides[i + 1] * self.sizes[i + 1]
        return (gids // strides[key_idx]) % self.sizes[key_idx]


def sort_compact(gid: jax.Array, mask: jax.Array, cap: int):
    """Sort-compact observed group ids to dense ranks.

    Returns (order, ids, valid_s, uniq, n_groups): the stable sort
    permutation, per-SORTED-row compact ids (invalid rows -> `cap`, the
    dead segment), the sorted-row validity, the rank -> global-id
    decode table ([cap] int64, GID_SENTINEL in empty slots, ascending),
    and the dynamic observed-group count. Ranks past `cap` clip into
    the last slot so shapes stay static; callers detect overflow via
    n_groups > cap and raise — a clipped result is never served.
    """
    gid = jnp.where(mask, gid, jnp.int64(GID_SENTINEL))
    order = jnp.argsort(gid)
    sg = gid[order]
    valid_s = sg != GID_SENTINEL
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int64), sg[:-1]])
    new = valid_s & (sg != prev)
    cid = jnp.cumsum(new.astype(jnp.int32)) - 1  # compact id per sorted row
    ids = jnp.where(valid_s, jnp.clip(cid, 0, cap - 1), jnp.int32(cap))
    n_groups = new.sum()
    # observed global id per compact slot (ascending; overflow slots drop)
    uniq = jnp.full((cap,), GID_SENTINEL, dtype=jnp.int64).at[
        jnp.where(new & (cid < cap), cid, cap)
    ].set(sg, mode="drop")
    return order, ids, valid_s, uniq, n_groups


@functools.partial(jax.jit, static_argnames=("cap", "ops"))
def sparse_segment_agg(
    values: jax.Array,  # [N] or [N, F] value planes
    gid: jax.Array,  # [N] int64 combined group ids
    mask: jax.Array,  # [N] bool row validity
    cap: int,
    ops: tuple = ("sum", "count"),
    ts: Optional[jax.Array] = None,
):
    """Masked segment reduction over sort-compacted ranks: the classic
    sparse path, `segment_agg` semantics exactly (NaN = NULL, first/
    last tie-break by sorted position — identical to the whole-scan
    oracle because the sort is stable). Returns (part, uniq, n_groups)
    with part planes [cap, ...]."""
    order, ids, valid_s, uniq, n_groups = sort_compact(gid, mask, cap)
    part = segment_agg(values[order], ids, valid_s, cap, ops=ops,
                       ts=None if ts is None else ts[order],
                       indices_are_sorted=True)
    return part, uniq, n_groups


def fused_sparse_segment_agg(
    vals: jax.Array,  # [N, F] SORTED raw field values (NaN = NULL)
    ids: jax.Array,  # [N] int32 compact ids from sort_compact (dead -> cap)
    cap: int,
    want_min: bool = False,
    want_max: bool = False,
    want_sumsq: bool = False,
    tile: int = FUSED_TILE,
    block_rows: int = 512,
    interpret: bool = False,
) -> dict:
    """Tiled fused-kernel reduction over sort-compacted ranks.

    `ids` is non-decreasing with per-row increments of at most 1 (a
    cumsum of booleans in sorted order), so every `tile`-row window
    spans fewer than `tile` distinct ranks: rebased to the window's
    first rank, the window fits the Pallas kernel's 4096-segment
    envelope regardless of `cap`. The fori_loop accumulates window
    planes into [cap + tile, ...] global planes at the window's base
    offset (the overhang absorbs the last window's reach); all-dead
    windows rebase to cap-1 and land every row in the dropped dead
    slot. One trace, O(N) kernel work, arbitrary cap.

    Same contract as pallas_fused_segment_agg: values must be proven
    finite by the caller, NaN is NULL, empty groups come back as 0
    counts and +/-inf extremes (callers NaN-fill like the packers do).
    """
    from greptimedb_tpu.ops import pallas_segment as ps

    n, nf = vals.shape
    r = tile
    npad = max(-(-max(n, 1) // r) * r, r)
    vals_p = jnp.pad(vals, ((0, npad - n), (0, 0)))
    ids_p = jnp.pad(ids.astype(jnp.int32), (0, npad - n),
                    constant_values=cap)
    dt = vals.dtype
    ext = cap + r
    acc = {
        "sum": jnp.zeros((ext, nf), dt),
        "count": jnp.zeros((ext, nf), dt),
        "rows": jnp.zeros((ext,), dt),
    }
    if want_min:
        acc["min"] = jnp.full((ext, nf), jnp.inf, dt)
    if want_max:
        acc["max"] = jnp.full((ext, nf), -jnp.inf, dt)
    if want_sumsq:
        acc["sumsq"] = jnp.zeros((ext, nf), dt)

    def body(c, acc):
        start = c * r
        ids_c = jax.lax.dynamic_slice(ids_p, (start,), (r,))
        vals_c = jax.lax.dynamic_slice(vals_p, (start, 0), (r, nf))
        # first sorted row holds the window minimum; an all-dead window
        # rebases to cap-1 and every row lands in the dropped slot
        base = jnp.clip(ids_c[0], 0, cap - 1)
        local = jnp.where(ids_c <= jnp.int32(cap - 1),
                          ids_c - base, jnp.int32(r))
        out = ps.pallas_fused_segment_agg(
            vals_c, local, r + 1, want_min=want_min, want_max=want_max,
            want_sumsq=want_sumsq, block_rows=block_rows,
            interpret=interpret)

        def fold(name, combine):
            plane = out[name][:r].astype(dt)
            g = acc[name]
            off = (base,) + (jnp.int32(0),) * (g.ndim - 1)
            cur = jax.lax.dynamic_slice(
                g, off, (r,) + g.shape[1:])
            return jax.lax.dynamic_update_slice(g, combine(cur, plane),
                                                off)

        nxt = {
            "sum": fold("sum", jnp.add),
            "count": fold("count", jnp.add),
            "rows": fold("rows", jnp.add),
        }
        if want_min:
            nxt["min"] = fold("min", jnp.minimum)
        if want_max:
            nxt["max"] = fold("max", jnp.maximum)
        if want_sumsq:
            nxt["sumsq"] = fold("sumsq", jnp.add)
        return nxt

    acc = jax.lax.fori_loop(0, npad // r, body, acc)
    return {k: v[:cap] for k, v in acc.items()}


def combine_sparse_gid_partials(parts: list) -> tuple:
    """Merge per-shard (or per-part) sparse partials in GID space.

    Each partial is {"gids": int64 [u] ascending-unique observed ids,
    "planes": {op: [u] or [u, F] host arrays}}. Compact ranks differ
    per shard, but the global ids they decode to are shard-invariant —
    so the exact combine is a union + indexed fold, mirroring
    `_combine_partials` semantics op by op: additive planes add
    (counts/rows in int64), min/max fold NaN-ignoring (NaN marks an
    empty group, `_unpack_acc`'s convention), first/last pick by
    companion ts with the PARTIAL ORDER breaking exact-ts ties (first:
    earliest partial wins; last: latest) — the same left-fold the
    dense block chain applies. Returns (gids [U] ascending, planes).
    """
    parts = [p for p in parts if len(p["gids"])]
    if not parts:
        return np.zeros((0,), np.int64), {}
    uniq = np.unique(np.concatenate([p["gids"] for p in parts]))
    n = len(uniq)

    def shaped(plane):
        return (n,) + np.asarray(plane).shape[1:]

    out: dict = {}
    p0 = parts[0]["planes"]
    for op, plane in p0.items():
        sh = shaped(plane)
        if op in ("count", "rows"):
            out[op] = np.zeros(sh, np.int64)
        elif op in ("sum", "sumsq"):
            out[op] = np.zeros(sh, np.asarray(plane).dtype)
        elif op in ("min", "max", "first", "last"):
            out[op] = np.full(sh, np.nan,
                              np.asarray(plane).dtype)
        elif op == "last_ts":
            out[op] = np.full(sh, np.iinfo(np.int64).min, np.int64)
        elif op == "first_ts":
            out[op] = np.full(sh, np.iinfo(np.int64).max, np.int64)
        else:
            raise ValueError(f"cannot combine sparse partial op {op}")
    for p in parts:
        idx = np.searchsorted(uniq, p["gids"])
        pl = p["planes"]
        for op in out:
            if op in ("first", "last", "first_ts", "last_ts"):
                continue  # pairs, below
            v = np.asarray(pl[op])
            if op in ("count", "rows"):
                out[op][idx] = out[op][idx] + v.astype(np.int64)
            elif op in ("sum", "sumsq"):
                out[op][idx] = out[op][idx] + v
            elif op == "min":
                out[op][idx] = np.fmin(out[op][idx], v)
            else:  # max
                out[op][idx] = np.fmax(out[op][idx], v)
        if "last" in out:
            ts, cur = np.asarray(pl["last_ts"]), out["last_ts"][idx]
            newer = ts > cur  # strict: exact-ts tie keeps earlier partial
            sel = newer[:, None] if out["last"].ndim == 2 else newer
            out["last"][idx] = np.where(sel, np.asarray(pl["last"]),
                                        out["last"][idx])
            out["last_ts"][idx] = np.where(newer, ts, cur)
        if "first" in out:
            ts, cur = np.asarray(pl["first_ts"]), out["first_ts"][idx]
            older = ts < cur
            sel = older[:, None] if out["first"].ndim == 2 else older
            out["first"][idx] = np.where(sel, np.asarray(pl["first"]),
                                         out["first"][idx])
            out["first_ts"][idx] = np.where(older, ts, cur)
    return uniq, out


def compaction_ratio(n_groups: int, n_rows: int) -> float:
    """Observed groups per scanned row — the gauge the sparse paths
    publish (1.0 = no compaction: every row its own group)."""
    return float(n_groups) / float(max(n_rows, 1))
