"""Windowed (range-vector) kernels over a regular evaluation grid.

TPU-native replacement for the reference's `RangeArray` ragged windows +
`RangeManipulate`/`InstantManipulate` operators (promql/src/range_array.rs:68,
extension_plan/*.rs). Instead of materializing per-window sample lists,
samples are bucketed onto the step grid with one segment reduction, then:

  - window sums/counts  = cumulative-sum differences along the bucket axis
  - last/first sample   = latest/earliest-nonempty-bucket gathers (cummax /
                          reverse-cummin) + exact timestamp validation
  - window min/max      = w-step unrolled running fmin/fmax over bucket mins

Exactness: range windows require the range to be a multiple of the step
(buckets tile windows exactly); instant-selector lookback is exact for any
length because the gathered last-sample timestamp is re-validated against
the true window edge.

Shapes: samples [N] -> bucket grid [S, B, C] -> windows [S, T, C], where
S = series, T = eval steps, B = T + w buckets, C = value channels (e.g.
raw + counter-reset-adjusted values ride one kernel call).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from greptimedb_tpu.ops.segment import segment_agg

BIG = jnp.iinfo(jnp.int32).max


@functools.partial(
    jax.jit,
    static_argnames=("num_series", "num_steps", "w", "stats",
                     "sorted_input"),
)
def window_stats(
    sidx: jax.Array,  # [N] int32 series index
    ts: jax.Array,  # [N] float64 sample time (seconds)
    channels: jax.Array,  # [N, C] float value channels
    valid: jax.Array,  # [N] bool
    t0,  # scalar: first eval timestamp (seconds)
    step,  # scalar: eval step (seconds)
    num_series: int,
    num_steps: int,
    w: int,  # window length in steps
    stats: tuple[str, ...] = ("sum", "count", "last"),
    sorted_input: bool = False,
) -> dict[str, jax.Array]:
    """Compute per-(series, eval-step) window statistics. Window j covers
    (t0 + (j-w)*step, t0 + j*step] — i.e. w whole step-buckets ending at
    eval time j. Outputs [S, T, C] (ts outputs [S, T]).

    sorted_input=True asserts rows are sorted by (series, ts) — the
    storage scan's layout — and switches bucketization from scatter-adds
    (the dominant cost at dashboard scale: millions of serialized
    updates) to cumulative-sum differences and boundary gathers over
    searchsorted bucket edges."""
    S, T, B = num_series, num_steps, num_steps + w
    n, C = channels.shape

    # bucket: sample at exactly an eval time belongs to that step's bucket
    b = jnp.ceil((ts - t0) / step).astype(jnp.int32) + (w - 1)
    ok = valid & (b >= 0) & (b < B)

    seg_ops = []
    if "sum" in stats or "count" in stats:
        seg_ops += ["sum", "count"]
    if "last" in stats:
        seg_ops.append("last")
    if "first" in stats:
        seg_ops.append("first")
    if "min" in stats:
        seg_ops.append("min")
    if "max" in stats:
        seg_ops.append("max")
    seg_ops = tuple(dict.fromkeys(seg_ops))
    if sorted_input:
        per_bucket = _bucketize_sorted(sidx, ts, channels, ok, b, S, B,
                                       seg_ops)
    else:
        gid = jnp.where(ok, sidx * B + b, S * B).astype(jnp.int32)
        per_bucket = segment_agg(
            channels, gid, ok, S * B, ops=seg_ops, ts=_ts_to_int(ts),
        )

    out: dict[str, jax.Array] = {}
    j = jnp.arange(T)

    def grid(x, C_=None):
        return x.reshape(S, B) if C_ is None else x.reshape(S, B, C_)

    bcount = grid(per_bucket["count"], C) if "count" in per_bucket else None

    if "sum" in stats:
        bsum = grid(per_bucket["sum"], C)
        cs = exclusive_cumsum(bsum)
        out["sum"] = cs[:, w:w + T] - cs[:, 0:T]
    if "count" in stats:
        cc = jnp.concatenate([jnp.zeros((S, 1, C), jnp.int64),
                              jnp.cumsum(bcount.astype(jnp.int64), axis=1)], axis=1)
        out["count"] = cc[:, w:w + T] - cc[:, 0:T]

    nonempty = None
    if bcount is not None:
        nonempty = bcount[:, :, 0] > 0  # row presence: channel 0 mask
    if "last" in stats:
        lv = grid(per_bucket["last"], C)
        lt = grid(per_bucket["last_ts"])
        nb = jnp.where(nonempty, jnp.arange(B)[None, :], -1)
        latest = jax.lax.cummax(nb, axis=1)
        lb = latest[:, w - 1:w - 1 + T]  # [S, T]
        has = lb >= j[None, :]
        safe = jnp.clip(lb, 0, B - 1)
        lval = jnp.take_along_axis(lv, safe[:, :, None], axis=1)
        lts = _ts_to_float(jnp.take_along_axis(lt, safe, axis=1))
        out["last"] = jnp.where(has[:, :, None], lval, jnp.nan)
        out["last_ts"] = jnp.where(has, lts, -jnp.inf)
    if "first" in stats:
        fv = grid(per_bucket["first"], C)
        ft = grid(per_bucket["first_ts"])
        fb = jnp.where(nonempty, jnp.arange(B)[None, :], BIG)
        earliest = jnp.flip(jax.lax.cummin(jnp.flip(fb, axis=1), axis=1), axis=1)
        fbj = earliest[:, 0:T]
        has = fbj <= (j[None, :] + w - 1)
        safe = jnp.clip(fbj, 0, B - 1)
        fval = jnp.take_along_axis(fv, safe[:, :, None], axis=1)
        fts = _ts_to_float(jnp.take_along_axis(ft, safe, axis=1))
        out["first"] = jnp.where(has[:, :, None], fval, jnp.nan)
        out["first_ts"] = jnp.where(has, fts, jnp.inf)
    if "min" in stats:
        bmin = grid(per_bucket["min"], C)
        acc = bmin[:, 0:T]
        for k in range(1, w):
            acc = jnp.fmin(acc, bmin[:, k:k + T])
        out["min"] = acc
    if "max" in stats:
        bmax = grid(per_bucket["max"], C)
        acc = bmax[:, 0:T]
        for k in range(1, w):
            acc = jnp.fmax(acc, bmax[:, k:k + T])
        out["max"] = acc
    return out


def _bucketize_sorted(sidx, ts, channels, ok, b, S, B, seg_ops):
    """Per-bucket stats for (series, ts)-SORTED samples, matching
    segment_agg's output contract over gsz = S*B segments.

    Valid rows' bucket ids are globally non-decreasing (series ascending,
    ts ascending within), so bucket edges come from ONE searchsorted over
    a monotone id envelope (cummax carries the last valid id across
    interleaved invalid rows), sums/counts are cumulative-sum
    differences, and first/last rows are gathers at the edges — no
    scatters at all. min/max (rare stats: *_over_time extremes) keep the
    scatter; everything else is O(N + gsz log N) sequential traffic."""
    n, C = channels.shape
    gsz = S * B
    gid = sidx.astype(jnp.int64) * B + b.astype(jnp.int64)
    gid_mono = jax.lax.cummax(jnp.where(ok, gid, -1))
    targets = jnp.arange(gsz, dtype=jnp.int64)
    starts = jnp.searchsorted(gid_mono, targets, side="left")
    ends = jnp.searchsorted(gid_mono, targets, side="right")
    okc = jnp.concatenate([jnp.zeros(1, jnp.int64),
                           jnp.cumsum(ok.astype(jnp.int64))])
    present = (okc[ends] - okc[starts]) > 0

    per_bucket: dict[str, jax.Array] = {}
    if "sum" in seg_ops or "count" in seg_ops:
        elem = ok[:, None] & ~jnp.isnan(channels)
        zc = jnp.where(elem, channels, 0).astype(channels.dtype)
        cs = jnp.concatenate(
            [jnp.zeros((1, C), zc.dtype), jnp.cumsum(zc, axis=0)])
        per_bucket["sum"] = cs[ends] - cs[starts]
        ec = jnp.concatenate(
            [jnp.zeros((1, C), jnp.int64),
             jnp.cumsum(elem.astype(jnp.int64), axis=0)])
        per_bucket["count"] = ec[ends] - ec[starts]
    idxs = jnp.arange(n, dtype=jnp.int64)
    ts_int = _ts_to_int(ts)
    if "last" in seg_ops:
        lastpos = jax.lax.cummax(jnp.where(ok, idxs, -1))
        li = lastpos[jnp.clip(ends - 1, 0, n - 1)]
        pv = present & (li >= 0)
        safe = jnp.clip(li, 0, n - 1)
        per_bucket["last"] = jnp.where(pv[:, None], channels[safe],
                                       jnp.nan)
        per_bucket["last_ts"] = jnp.where(pv, ts_int[safe],
                                          jnp.iinfo(jnp.int64).min)
    if "first" in seg_ops:
        firstpos = jnp.flip(
            jax.lax.cummin(jnp.flip(jnp.where(ok, idxs, n))))
        fi = firstpos[jnp.clip(starts, 0, n - 1)]
        pv = present & (fi < n)
        safe = jnp.clip(fi, 0, n - 1)
        per_bucket["first"] = jnp.where(pv[:, None], channels[safe],
                                        jnp.nan)
        per_bucket["first_ts"] = jnp.where(pv, ts_int[safe],
                                           jnp.iinfo(jnp.int64).max)
    mm = tuple(o for o in ("min", "max") if o in seg_ops)
    if mm:
        gid32 = jnp.where(ok, gid, gsz).astype(jnp.int32)
        per_bucket.update(segment_agg(channels, gid32, ok, gsz, ops=mm))
    return per_bucket


def _ts_to_int(ts):
    # segment first/last need an integer time key; milliseconds keeps
    # ordering at PromQL resolution
    return (ts * 1000.0).astype(jnp.int64)


def _ts_to_float(t_int):
    return t_int.astype(jnp.float64) / 1000.0


@jax.jit
def counter_adjust(sidx_sorted: jax.Array, values_sorted: jax.Array) -> jax.Array:
    """Reset-corrected counter values. Input MUST be sorted by (series, ts).
    adjusted[i] = v[i] + cumulative resets before i; within-series
    differences of `adjusted` equal PromQL's reset-corrected deltas
    (reference promql/src/functions/extrapolate_rate.rs semantics)."""
    prev_v = jnp.concatenate([values_sorted[:1], values_sorted[:-1]])
    prev_s = jnp.concatenate([sidx_sorted[:1], sidx_sorted[:-1]])
    same = sidx_sorted == prev_s
    reset = jnp.where(same & (values_sorted < prev_v), prev_v, 0.0)
    # global cumsum is per-series-correct for *differences* because rows
    # are series-contiguous
    return values_sorted + jnp.cumsum(reset)


@functools.partial(jax.jit, static_argnames=("is_counter", "is_rate"))
def extrapolated_delta(
    first_val, first_ts, last_val, last_ts, count, window_start, window_end,
    is_counter: bool, is_rate: bool, range_s: float = 1.0,
):
    """PromQL extrapolation (reference extrapolate_rate.rs:85-92): the raw
    last-first delta is extrapolated toward the window edges, limited to
    half an average sample interval when the edge is far. All inputs
    [S, T] (vals [S, T, 1-channel already selected])."""
    sampled = last_ts - first_ts
    delta = last_val - first_val
    cnt = count.astype(first_val.dtype)
    ok = (cnt >= 2) & (sampled > 0)
    avg_interval = sampled / jnp.maximum(cnt - 1, 1)
    to_start = first_ts - window_start
    to_end = window_end - last_ts
    if is_counter:
        # counters can't be negative: limit start extrapolation to the
        # zero crossing
        with jax.numpy_dtype_promotion("standard"):
            slope = delta / jnp.maximum(sampled, 1e-10)
            zero_limit = jnp.where(slope > 0, first_val / slope, jnp.inf)
            to_start = jnp.minimum(to_start, zero_limit)
    threshold = avg_interval * 1.1
    ext_start = jnp.where(to_start < threshold, to_start, avg_interval / 2)
    ext_end = jnp.where(to_end < threshold, to_end, avg_interval / 2)
    factor = (sampled + ext_start + ext_end) / jnp.maximum(sampled, 1e-10)
    result = delta * factor
    if is_rate:
        result = result / range_s
    return jnp.where(ok, result, jnp.nan)


@functools.partial(jax.jit,
                   static_argnames=("num_series", "num_steps", "w"))
def window_edges(
    sidx: jax.Array,  # [N] int32 series index, sorted major
    ts: jax.Array,  # [N] float64 sample time (seconds), sorted within
    channels: jax.Array,  # [N, C] float value channels (NaN-free)
    t0,  # scalar: first eval timestamp (seconds)
    step,  # scalar: eval step (seconds)
    num_series: int,
    num_steps: int,
    w: int,  # window length in steps
) -> dict[str, jax.Array]:
    """first/last/count per (series, eval-window) via composite-key
    searchsorted — the boundary-gather evaluation for the rate family.

    PromQL's rate/increase/delta consume only each window's EDGE
    samples plus the in-window count (reference
    extrapolate_rate.rs:85-92; counter resets ride the pre-computed
    "adjusted" channel), so evaluating them needs no per-sample
    bucketization: with rows sorted by (series, ts), a window's
    first/last/count are two binary-search probes into one monotone
    composite key. At the tracked scale (10k series x 1 day @15s =
    57.6M samples, 240 eval points) this replaces an O(N)-per-eval
    57.6M-row pass with 4.8M probes — the same asymmetry the numpy
    straw-man anchor exploits (bench.py promql_anchor), now on device.

    Window j covers (t0 + (j-w)·step, t0 + j·step], matching
    window_stats. Requires NaN-free channels (callers gate — LWW
    tombstone NaNs would need masking the probes cannot see).
    Returns {"first": [S,T,C], "first_ts": [S,T], "last": [S,T,C],
    "last_ts": [S,T], "count": [S,T,1]} — window_stats-shaped for the
    rate consumers."""
    S, T = num_series, num_steps
    n, C = channels.shape
    ts = ts.astype(jnp.float64)
    base = jnp.min(ts)
    # series band width: larger than any in-band offset OR window edge
    K = (jnp.max(ts) - base) + (num_steps + w + 2) * jnp.abs(step) + 2.0
    key = sidx.astype(jnp.float64) * K + (ts - base)
    j = jnp.arange(T, dtype=jnp.float64)
    # clip edges into the band so an out-of-range window cannot probe a
    # NEIGHBORING series' key range
    lo_off = jnp.clip(t0 + (j - w) * step - base, -0.5, K - 1.0)
    hi_off = jnp.clip(t0 + j * step - base, -0.5, K - 1.0)
    s_base = jnp.arange(S, dtype=jnp.float64) * K
    i0 = jnp.searchsorted(  # first sample with ts > lo (exclusive edge)
        key, (s_base[:, None] + lo_off[None, :]).ravel(),
        side="right").reshape(S, T)
    i1 = jnp.searchsorted(  # one past the last sample with ts <= hi
        key, (s_base[:, None] + hi_off[None, :]).ravel(),
        side="right").reshape(S, T)
    count = i1 - i0
    has = count > 0
    fi = jnp.clip(i0, 0, max(n - 1, 0))
    li = jnp.clip(i1 - 1, 0, max(n - 1, 0))
    first = jnp.where(has[..., None], channels[fi], jnp.nan)
    last = jnp.where(has[..., None], channels[li], jnp.nan)
    first_ts = jnp.where(has, ts[fi], jnp.nan)
    last_ts = jnp.where(has, ts[li], jnp.nan)
    return {"first": first, "first_ts": first_ts, "last": last,
            "last_ts": last_ts,
            "count": count.astype(jnp.int64)[..., None]}


@functools.partial(jax.jit, static_argnames=("num_steps", "w"))
def window_edges_grid(
    grid: jax.Array,  # [P] float64 shared sample grid (seconds, sorted)
    mat: jax.Array,  # [S, P, C] values pivoted onto the grid (NaN-free)
    t0,  # scalar: first eval timestamp (seconds)
    step,  # scalar: eval step (seconds)
    num_steps: int,
    w: int,
) -> dict[str, jax.Array]:
    """window_edges when every series shares ONE complete sample grid —
    the scrape-aligned shape Prometheus data overwhelmingly has. Window
    edges become T probes into the [P] grid (not S·T probes into the
    flat samples), and first/last are column gathers from the pivoted
    matrix: rate over 10k series x 1 day @15s evaluates in
    milliseconds. Same output contract as window_edges."""
    S, P, C = mat.shape
    T = num_steps
    j = jnp.arange(T, dtype=jnp.float64)
    lo = t0 + (j - w) * step  # exclusive lower edge
    hi = t0 + j * step        # inclusive upper edge
    i0 = jnp.searchsorted(grid, lo, side="right")
    i1 = jnp.searchsorted(grid, hi, side="right")  # one past the last
    count = i1 - i0  # [T], identical for every series (complete grid)
    has = count > 0
    fi = jnp.clip(i0, 0, max(P - 1, 0))
    li = jnp.clip(i1 - 1, 0, max(P - 1, 0))
    first = jnp.where(has[None, :, None], mat[:, fi, :], jnp.nan)
    last = jnp.where(has[None, :, None], mat[:, li, :], jnp.nan)
    first_ts = jnp.broadcast_to(
        jnp.where(has, grid[fi], jnp.nan)[None, :], (S, T))
    last_ts = jnp.broadcast_to(
        jnp.where(has, grid[li], jnp.nan)[None, :], (S, T))
    count_st = jnp.broadcast_to(
        count.astype(jnp.int64)[None, :, None], (S, T, 1))
    return {"first": first, "first_ts": first_ts, "last": last,
            "last_ts": last_ts, "count": count_st}


@functools.partial(jax.jit, static_argnames=("num_steps", "w"))
def window_sums_grid(
    grid: jax.Array,  # [P] float64 shared sample grid (seconds, sorted)
    cs: jax.Array,  # [S, P+1, C] exclusive prefix sums over the pivot
    t0,
    step,
    num_steps: int,
    w: int,
) -> dict[str, jax.Array]:
    """Window sums/counts on a complete shared grid: one cumulative sum
    over the pivot (cached by the caller), then every (window, series)
    sum is a two-gather difference — the sum_over_time/avg_over_time
    analog of window_edges_grid. Window j covers
    (t0 + (j-w)·step, t0 + j·step], matching window_stats."""
    S = cs.shape[0]
    T = num_steps
    j = jnp.arange(T, dtype=jnp.float64)
    i0 = jnp.searchsorted(grid, t0 + (j - w) * step, side="right")
    i1 = jnp.searchsorted(grid, t0 + j * step, side="right")
    count = i1 - i0
    out_sum = cs[:, i1, :] - cs[:, i0, :]  # [S, T, C]
    count_st = jnp.broadcast_to(
        count.astype(jnp.int64)[None, :, None], (S, T, 1))
    return {"sum": out_sum, "count": count_st}


def exclusive_cumsum(mat: jax.Array) -> jax.Array:
    """[S, P, C] -> [S, P+1, C] exclusive prefix sums along axis 1 (the
    shared idiom of window_stats' window sums and window_sums_grid)."""
    S, _, C = mat.shape
    return jnp.concatenate(
        [jnp.zeros((S, 1, C), mat.dtype), jnp.cumsum(mat, axis=1)],
        axis=1)
