"""Parallel execution over a jax.sharding.Mesh.

The TPU-native replacement for the reference's distributed query fan-out
(SURVEY.md §2.6): regions map to shards of a device mesh; the
gather-then-aggregate of MergeScanExec (query/src/dist_plan/merge_scan.rs:122,
point-to-point Arrow Flight) becomes partial segment aggregation per shard
combined with psum/pmin/pmax over ICI. Cross-host control stays on gRPC;
data movement inside a pod rides XLA collectives.
"""

from greptimedb_tpu.parallel.mesh import (
    make_mesh,
    sharded_segment_agg,
    shard_rows,
)

__all__ = ["make_mesh", "sharded_segment_agg", "shard_rows"]
