"""Mesh construction + sharded aggregation kernels.

Sharding layout for the scan/aggregate hot path:
  - axis "shard": rows (series-partitioned regions -> data parallel). Group
    ids are global, so per-shard partial aggregates are dense [G, F] and
    combine with psum/pmin/pmax over ICI — the collective MergeScan.
  - axis "field": measurement columns (tensor-parallel analog). TSBS cpu
    tables carry 10 usage fields; sharding F keeps per-chip HBM traffic
    down on wide tables. Outputs stay field-sharded until the host gather.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from greptimedb_tpu.ops.segment import segment_agg

# ops whose partials combine with a collective. first/last pair each
# group's value with its timestamp: the shard holding the global
# oldest/newest ts wins (combine_partial_aggs), so lastpoint-class
# queries ride the mesh too.
COLLECTIVE_OPS = ("sum", "count", "min", "max", "rows", "sumsq",
                  "first", "last")


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[tuple[int, int]] = None,
    axes: tuple[str, str] = ("shard", "field"),
) -> Mesh:
    """Build a 2D (shard, field) mesh. Default: all devices on the shard
    axis, field axis of 1 (pure row sharding)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    assert shape[0] * shape[1] == n, (shape, n)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axes)


def shard_rows(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a host row-array onto the mesh sharded along the first axis
    ("shard"); callers pad to a multiple of the shard axis size first."""
    spec = P("shard") if arr.ndim == 1 else P("shard", None)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def sharded_segment_agg(
    values: jax.Array,  # [N, F]
    seg_ids: jax.Array,  # [N]
    mask: jax.Array,  # [N]
    num_segments: int,
    ops: tuple[str, ...],
    mesh: Mesh,
    ts: Optional[jax.Array] = None,  # [N] int64, required for first/last
) -> dict[str, jax.Array]:
    """Masked segment reduction over a (shard, field) mesh: per-shard dense
    partials, then psum/pmin/pmax along "shard" (first/last resolve by
    their companion timestamps). Result is replicated along "shard" and
    left sharded along "field"."""
    for op in ops:
        if op not in COLLECTIVE_OPS:
            raise ValueError(f"op {op!r} has no collective combiner")
    need_ts = bool({"first", "last"} & set(ops))
    if need_ts and ts is None:
        raise ValueError("first/last need the ts row array")
    out_ops = tuple(ops) + tuple(
        op + "_ts" for op in ("first", "last") if op in ops)

    in_specs = [P("shard", "field"), P("shard"), P("shard")]
    if need_ts:
        in_specs.append(P("shard"))

    # value planes stay field-sharded; the [G, 1] ts planes are replicated
    out_specs = tuple(P(None, None) if op.endswith("_ts")
                      else P(None, "field") for op in out_ops)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def step(v, g, m, *rest):
        from greptimedb_tpu.ops.segment import combine_partial_aggs

        part = segment_agg(v, g, m, num_segments, ops=ops,
                           ts=rest[0] if rest else None)
        part = {op: (x if x.ndim > 1 else x[:, None])
                for op, x in part.items()}
        out = combine_partial_aggs(part, "shard")
        return tuple(out[op] for op in out_ops)

    args = (values, seg_ids, mask) + ((ts,) if need_ts else ())
    res = step(*args)
    return dict(zip(out_ops, res))


def pad_to_multiple(arr: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)
