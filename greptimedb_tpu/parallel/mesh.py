"""Mesh construction + sharded aggregation kernels.

Sharding layout for the scan/aggregate hot path:
  - axis "shard": rows (series-partitioned regions -> data parallel). Group
    ids are global, so per-shard partial aggregates are dense [G, F] and
    combine with psum/pmin/pmax over ICI — the collective MergeScan.
  - axis "field": measurement columns (tensor-parallel analog). TSBS cpu
    tables carry 10 usage fields; sharding F keeps per-chip HBM traffic
    down on wide tables. Outputs stay field-sharded until the host gather.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from greptimedb_tpu.ops.segment import segment_agg

# ops whose partials combine with a collective (first/last need ts pairing,
# handled only in the single-chip streaming path for now)
COLLECTIVE_OPS = ("sum", "count", "min", "max", "rows", "sumsq")


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[tuple[int, int]] = None,
    axes: tuple[str, str] = ("shard", "field"),
) -> Mesh:
    """Build a 2D (shard, field) mesh. Default: all devices on the shard
    axis, field axis of 1 (pure row sharding)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    assert shape[0] * shape[1] == n, (shape, n)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axes)


def shard_rows(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a host row-array onto the mesh sharded along the first axis
    ("shard"); callers pad to a multiple of the shard axis size first."""
    spec = P("shard") if arr.ndim == 1 else P("shard", None)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def sharded_segment_agg(
    values: jax.Array,  # [N, F]
    seg_ids: jax.Array,  # [N]
    mask: jax.Array,  # [N]
    num_segments: int,
    ops: tuple[str, ...],
    mesh: Mesh,
) -> dict[str, jax.Array]:
    """Masked segment reduction over a (shard, field) mesh: per-shard dense
    partials, then psum/pmin/pmax along "shard". Result is replicated along
    "shard" and left sharded along "field"."""
    for op in ops:
        if op not in COLLECTIVE_OPS:
            raise ValueError(f"op {op!r} has no collective combiner")

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("shard", "field"), P("shard"), P("shard")),
        out_specs=P(None, "field"),
        check_vma=False,
    )
    def step(v, g, m):
        from greptimedb_tpu.ops.segment import combine_partial_aggs

        part = segment_agg(v, g, m, num_segments, ops=ops)
        part = {op: (x if x.ndim > 1 else x[:, None])
                for op, x in part.items()}
        out = combine_partial_aggs(part, "shard")
        return tuple(out[op] for op in ops)

    res = step(values, seg_ids, mask)
    return dict(zip(ops, res))


def pad_to_multiple(arr: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)
