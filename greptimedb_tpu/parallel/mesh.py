"""Mesh construction + sharded aggregation kernels.

Sharding layout for the scan/aggregate hot path:
  - axis "shard": rows (series-partitioned regions -> data parallel). Group
    ids are global, so per-shard partial aggregates are dense [G, F] and
    combine with psum/pmin/pmax over ICI — the collective MergeScan.
  - axis "field": measurement columns (tensor-parallel analog). TSBS cpu
    tables carry 10 usage fields; sharding F keeps per-chip HBM traffic
    down on wide tables. Outputs stay field-sharded until the host gather.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6: top-level shard_map, replication check kwarg is check_vma
    from jax import shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # older jax: experimental module, kwarg is check_rep
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {"check_rep": False}

from greptimedb_tpu.ops.segment import segment_agg

# ops whose partials combine with a collective. first/last pair each
# group's value with its timestamp: the shard holding the global
# oldest/newest ts wins (combine_partial_aggs), so lastpoint-class
# queries ride the mesh too.
COLLECTIVE_OPS = ("sum", "count", "min", "max", "rows", "sumsq",
                  "first", "last")


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Join this process to a cross-host jax.distributed job so the mesh
    spans every host's chips — the multi-host analog of the reference's
    NCCL/MPI data plane (SURVEY §2.6 item 6: collectives ride ICI inside
    a pod and DCN across pods; XLA picks the transport per mesh axis).

    Configuration (args override env):
      GREPTIMEDB_TPU_COORDINATOR   host:port of process 0
      GREPTIMEDB_TPU_NUM_PROCESSES total host processes in the job
      GREPTIMEDB_TPU_PROCESS_ID    this process's rank

    Returns True when a multi-process runtime was initialized; False for
    the single-host default. Call BEFORE the first backend touch (the
    standalone CLI does, at startup).

    Division of labor after init: the QUERY mesh stays over this host's
    local chips (config.query_mesh uses jax.local_devices() — the data
    plane feeds it process-local arrays, which cannot target another
    host's devices), while CROSS-host distribution continues to ride the
    region-level PlanFragment pushdown over Flight: each host reduces
    its own regions on its own mesh and ships [G, F] partial planes, so
    only the tiny Final combine crosses DCN — the same Partial/Final
    economics the reference gets from its datanode RPC fan-out. A future
    full-SPMD scan (jax.make_array_from_process_local_data + a global
    mesh) would slot in behind the same sharded_segment_agg contract."""
    import os

    coordinator = coordinator or os.environ.get(
        "GREPTIMEDB_TPU_COORDINATOR")
    if not coordinator:
        return False
    if num_processes is None:
        env_n = os.environ.get("GREPTIMEDB_TPU_NUM_PROCESSES")
        num_processes = int(env_n) if env_n else None  # None: auto-detect
    if process_id is None:
        env_p = os.environ.get("GREPTIMEDB_TPU_PROCESS_ID")
        process_id = int(env_p) if env_p else None
    already = getattr(jax.distributed, "is_initialized", None)
    if already is not None and already():
        return True  # idempotent: embedding + multiple server entries
    import sys

    # initialize() blocks until the job assembles (up to its 300s
    # timeout) — say what we are waiting on BEFORE the silence. stderr,
    # not logging: nothing configures a logging handler at startup.
    print(
        f"joining jax.distributed job: coordinator={coordinator} "
        f"processes={num_processes if num_processes is not None else 'auto'}"
        f" rank={process_id if process_id is not None else 'auto'}",
        file=sys.stderr, flush=True)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[tuple[int, int]] = None,
    axes: tuple[str, str] = ("shard", "field"),
) -> Mesh:
    """Build a 2D (shard, field) mesh. Default: all devices on the shard
    axis, field axis of 1 (pure row sharding)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    assert shape[0] * shape[1] == n, (shape, n)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axes)


def shard_rows(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a host row-array onto the mesh sharded along the first axis
    ("shard"); callers pad to a multiple of the shard axis size first."""
    spec = P("shard") if arr.ndim == 1 else P("shard", None)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def sharded_segment_agg(
    values: jax.Array,  # [N, F]
    seg_ids: jax.Array,  # [N]
    mask: jax.Array,  # [N]
    num_segments: int,
    ops: tuple[str, ...],
    mesh: Mesh,
    ts: Optional[jax.Array] = None,  # [N] int64, required for first/last
) -> dict[str, jax.Array]:
    """Masked segment reduction over a (shard, field) mesh: per-shard dense
    partials, then psum/pmin/pmax along "shard" (first/last resolve by
    their companion timestamps). Result is replicated along "shard" and
    left sharded along "field"."""
    for op in ops:
        if op not in COLLECTIVE_OPS:
            raise ValueError(f"op {op!r} has no collective combiner")
    need_ts = bool({"first", "last"} & set(ops))
    if need_ts and ts is None:
        raise ValueError("first/last need the ts row array")
    out_ops = tuple(ops) + tuple(
        op + "_ts" for op in ("first", "last") if op in ops)

    in_specs = [P("shard", "field"), P("shard"), P("shard")]
    if need_ts:
        in_specs.append(P("shard"))

    # value planes stay field-sharded; the [G, 1] ts planes are replicated
    out_specs = tuple(P(None, None) if op.endswith("_ts")
                      else P(None, "field") for op in out_ops)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )
    def step(v, g, m, *rest):
        from greptimedb_tpu.ops.segment import combine_partial_aggs

        part = segment_agg(v, g, m, num_segments, ops=ops,
                           ts=rest[0] if rest else None)
        part = {op: (x if x.ndim > 1 else x[:, None])
                for op, x in part.items()}
        out = combine_partial_aggs(part, "shard")
        return tuple(out[op] for op in out_ops)

    args = (values, seg_ids, mask) + ((ts,) if need_ts else ())
    res = step(*args)
    return dict(zip(out_ops, res))


def pad_to_multiple(arr: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)
