"""Part-aligned mesh shard dispatch: the hot-path bridge between the
region scan's immutable SST parts and the (shard, field) device mesh.

The legacy sharded path placed the WHOLE scan with one `jax.device_put`
over a NamedSharding — correct, but every flush (a data-version bump)
re-uploaded the entire working set because the only cacheable identity
was the snapshot. This module gives the mesh path the same file-anchored
economics the single-device hot set has (query/device_cache.py):

- `plan_shards` assigns part-aligned row segments to shards: each SST
  part splits into at most `n_shard` contiguous chunks (chunk size is a
  pure function of the immutable part, so boundaries never move), and
  chunks greedily land on the least-loaded shard in deterministic scan
  order. Appending a new file extends the plan without disturbing any
  earlier assignment — the prefix-stability that makes per-(segment,
  shard) cache keys survive flushes.
- `sharded_column` materializes one logical plane across the mesh:
  per-segment device buffers are file-anchored (key carries the part
  identity + in-part offset + owning shard) and uploaded ONCE to the
  owning shard's device; the assembled per-shard buffer (segments
  concatenated on-device + padding fill) is snapshot-anchored and
  rebuilt from the resident segments on a version bump, so a flush
  transfers ONLY its new file's rows to the shard that owns them. The
  global array forms with `jax.make_array_from_single_device_arrays` —
  no cross-device traffic at assembly.
- `sharded_mask` ships the [n_shard, L] validity/dedup mask.

Row order within a shard differs from scan order (segments interleave),
which is invisible to the collective aggregation: group ids are global
and per-shard partials combine with psum/pmin/pmax (first/last resolve
by their companion timestamps in `combine_partial_aggs`).

Shapes the plan cannot serve raise `MeshIneligible`; the executor
degrades to the single-device dense paths — typed fallback, never an
error.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.utils import device_telemetry
from greptimedb_tpu.utils.metrics import MESH_DISPATCHES, MESH_SHARD_SKEW


class MeshIneligible(Exception):
    """This scan/shape cannot ride the part-aligned mesh dispatch; the
    caller falls back to the single-device paths (typed degradation)."""


@dataclass(frozen=True)
class ShardSeg:
    """One contiguous scan slice assigned to a shard. `pkey` is the
    immutable part identity ((file_id, ts_range, pred_key)) or None for
    memtable/synthetic rows; `part_start` anchors the in-part offset so
    cache keys stay stable across scans."""

    pkey: Optional[tuple]
    part_start: int
    start: int
    end: int


@dataclass
class ShardPlan:
    n_shard: int
    segs: list  # per shard: list[ShardSeg]
    lens: list  # real rows per shard
    pad: int    # common padded per-shard length L
    skew: float  # max / mean per-shard rows

    @property
    def total_pad(self) -> int:
        return self.pad * self.n_shard


#: per-shard buffers pad to a multiple of this (TPU lane alignment; on
#: CPU it just keeps shapes stable across nearby row counts)
_PAD_QUANTUM = 128


def eligible(mesh) -> bool:
    """The part-aligned dispatch assembles one committed array per
    shard device; a mesh with a real field axis would need replicated
    placement per row shard — the legacy whole-scan device_put path
    handles that layout instead."""
    try:
        return int(mesh.shape.get("field", 1)) == 1
    except Exception:  # noqa: BLE001 — exotic mesh: legacy path
        return False


def shard_devices(mesh) -> list:
    """One device per "shard" coordinate (field axis of 1)."""
    arr = np.asarray(mesh.devices).reshape(mesh.shape["shard"], -1)
    return [arr[s][0] for s in range(arr.shape[0])]


def plan_shards(scan, n_shard: int) -> ShardPlan:
    """Assign the scan's rows to shards along part seams (see module
    docstring for the stability argument). Scans without per-part
    identity (merged/synthetic) fall back to an even contiguous split —
    still a valid plan, just snapshot-anchored only."""
    n = int(scan.num_rows)
    if n_shard <= 0:
        raise MeshIneligible("mesh has no shard axis")
    offs = getattr(scan, "sorted_part_offsets", None)
    pkeys = getattr(scan, "part_keys", ())
    parts: list[tuple] = []
    if pkeys is not None and offs is not None \
            and len(offs) == len(pkeys) + 1 and offs[-1] <= n:
        parts = [(pkeys[i], offs[i], offs[i + 1])
                 for i in range(len(pkeys)) if offs[i + 1] > offs[i]]
        if offs[-1] < n:  # memtable tail: no immutable identity
            parts.append((None, offs[-1], n))
    if not parts:
        parts = [(None, 0, n)]

    segs: list[list[ShardSeg]] = [[] for _ in range(n_shard)]
    lens = [0] * n_shard
    for pk, s0, s1 in parts:
        rows = s1 - s0
        # chunk size is a function of the PART ONLY: boundaries (and so
        # the per-segment cache keys) never move when other files come
        # and go
        chunk = -(-rows // n_shard)
        for st in range(s0, s1, max(chunk, 1)):
            en = min(st + chunk, s1)
            # deterministic greedy: least-loaded shard, lowest index wins
            s = min(range(n_shard), key=lambda i: (lens[i], i))
            segs[s].append(ShardSeg(pk, s0, st, en))
            lens[s] += en - st
    longest = max(lens) if lens else 0
    pad = max(-(-max(longest, 1) // _PAD_QUANTUM) * _PAD_QUANTUM,
              _PAD_QUANTUM)
    mean = n / n_shard if n else 1.0
    skew = (longest / mean) if n else 1.0
    return ShardPlan(n_shard=n_shard, segs=segs, lens=lens, pad=pad,
                     skew=skew)


def sharded_column(
    cache,
    mesh,
    plan: ShardPlan,
    scan,
    name_key,
    build_slice: Callable[[int, int, int], np.ndarray],
    *,
    tier: str,
    snap_version: tuple,
    extra: tuple = (),
    pad_fill=0.0,
) -> jax.Array:
    """One logical plane ([N] column or [N, W] prepared plane) across
    the mesh. `build_slice(start, end, out_rows)` materializes host rows
    [start, end) padded/filled to `out_rows` (the same builders the
    dense block path uses). Cache anatomy per shard:

    - file-anchored ("file", region, file_id, tier, window, pred, name,
      in-part offset, rows, "mshard", shard, extra): one segment's
      upload to the owning shard's device — survives version bumps.
    - snap-anchored ("snap", region, version, tier, fingerprint, name,
      "mshard", shard, pad, extra): the assembled padded shard buffer —
      concatenated on-device from resident segments (+ memtable slices
      and the padding fill, which are device-side and free), retired by
      the next data version.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = shard_devices(mesh)
    cacheable = scan.region_id >= 0 and cache is not None

    def build_shard(s: int):
        dev = devs[s]
        arrs = []
        for seg in plan.segs[s]:
            m = seg.end - seg.start

            def upload(seg=seg, m=m, dev=dev):
                return jax.device_put(
                    build_slice(seg.start, seg.end, m), dev)

            if seg.pkey is not None and cacheable:
                fid, ts_r, pred_key = seg.pkey
                key = ("file", scan.region_id, fid, tier, ts_r, pred_key,
                       name_key, seg.start - seg.part_start, m,
                       "mshard", s, extra)
                arrs.append(cache.get(key, upload))
            else:
                arr = upload()
                device_telemetry.count_h2d(arr.nbytes)
                arrs.append(arr)
        pad = plan.pad - plan.lens[s]
        with jax.default_device(dev):
            if pad or not arrs:
                if arrs:
                    tail_shape = arrs[0].shape[1:]
                    dt = arrs[0].dtype
                else:
                    sample = build_slice(0, 0, 1)
                    tail_shape = sample.shape[1:]
                    dt = sample.dtype
                # device-side fill: padding never crosses the link
                arrs.append(jnp.full((pad,) + tuple(tail_shape), pad_fill,
                                     dtype=dt))
            piece = arrs[0] if len(arrs) == 1 else jnp.concatenate(arrs)
        return piece

    if cacheable:
        pieces = [
            cache.get(("snap", scan.region_id, snap_version, tier,
                       scan.scan_fingerprint, name_key, "mshard", s,
                       plan.pad, extra),
                      lambda s=s: build_shard(s), count_h2d=False)
            for s in range(plan.n_shard)
        ]
    else:
        pieces = [build_shard(s) for s in range(plan.n_shard)]
    shape = (plan.total_pad,) + tuple(pieces[0].shape[1:])
    spec = P("shard") if len(shape) == 1 else P("shard", None)
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, spec), pieces)


def sharded_mask(mesh, plan: ShardPlan, scan, dedup_mask, *,
                 cache=None, tier: str = "", snap_version=()) -> jax.Array:
    """[n_shard * L] base validity mask: per-shard padding is False and
    dedup survivors carry through in segment order. `dedup_mask` is the
    scan-order device mask or None. Snapshot-anchored in the hot set
    (the mask is a pure function of the scan snapshot + plan), so warm
    repeats pay zero H2D."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build():
        dm = None if dedup_mask is None else np.asarray(dedup_mask)
        base = np.zeros((plan.n_shard, plan.pad), dtype=bool)
        for s in range(plan.n_shard):
            off = 0
            for seg in plan.segs[s]:
                m = seg.end - seg.start
                if dm is None:
                    base[s, off:off + m] = True
                else:
                    base[s, off:off + m] = dm[seg.start:seg.end]
                off += m
        flat = base.reshape(-1)
        return jax.device_put(flat, NamedSharding(mesh, P("shard")))

    if cache is not None and scan.region_id >= 0:
        key = ("snap", scan.region_id, snap_version, tier,
               scan.scan_fingerprint, "__mshard_mask__", plan.pad,
               dedup_mask is not None)
        return cache.get(key, build)
    out = build()
    device_telemetry.count_h2d(out.nbytes)
    return out


def note_dispatch(path: str, plan: ShardPlan) -> None:
    MESH_DISPATCHES.inc(path=path, shards=str(plan.n_shard))
    MESH_SHARD_SKEW.set(float(plan.skew))
