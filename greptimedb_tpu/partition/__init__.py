from .rule import PartitionBound, PartitionRule, RangePartitionRule

__all__ = ["PartitionBound", "PartitionRule", "RangePartitionRule"]
