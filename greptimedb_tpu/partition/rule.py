"""Table partition rules: shard rows to regions.

Mirrors reference src/partition/src/multi_dim.rs:37-74 (multi-dimensional
range partitioning on tag columns) and splitter.rs (row batches → per-region
batches). The reference walks rows one at a time through the rule; the
TPU-native version is vectorized — region assignment for a whole RecordBatch
is a single `np.searchsorted` over the partition bounds per dimension, so
write sharding (operator/src/insert.rs:114-118 analog) costs O(n log r) numpy
time with no Python-per-row work.

Bounds use the reference's semantics: region i covers
[bound[i-1], bound[i]) with the last region unbounded (MAXVALUE).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass
class PartitionBound:
    """Upper-exclusive bound of one region along the partition columns
    (lexicographic when multiple columns)."""

    values: tuple  # one value per partition column; () == MAXVALUE

    @property
    def is_maxvalue(self) -> bool:
        return len(self.values) == 0


class PartitionRule:
    columns: list[str]

    def num_regions(self) -> int:
        raise NotImplementedError

    def find_regions(self, cols: Sequence[np.ndarray]) -> np.ndarray:
        """Vectorized: one array per partition column → int32 region index
        per row."""
        raise NotImplementedError

    def split(
        self, cols: Sequence[np.ndarray], n_rows: Optional[int] = None
    ) -> dict[int, np.ndarray]:
        """Row splitter (partition/src/splitter.rs analog): region index →
        row positions, computed with one argsort over find_regions."""
        if self.num_regions() == 1:
            n = len(cols[0]) if cols else (n_rows or 0)
            return {0: np.arange(n)}
        regions = self.find_regions(cols, n_rows)
        order = np.argsort(regions, kind="stable")
        sorted_regions = regions[order]
        out: dict[int, np.ndarray] = {}
        uniq, starts = np.unique(sorted_regions, return_index=True)
        bounds = list(starts) + [len(order)]
        for i, r in enumerate(uniq):
            out[int(r)] = order[bounds[i]:bounds[i + 1]]
        return out

    def to_json(self) -> str:
        raise NotImplementedError


class RangePartitionRule(PartitionRule):
    """N ordered regions split by upper bounds on partition columns.

    Single-column: bounds are scalars, assignment is searchsorted.
    Multi-column: lexicographic comparison via rank-composition (each
    column's values are mapped through the bound values' order, then
    combined into one sortable key) — still fully vectorized.
    """

    def __init__(self, columns: list[str], bounds: list[PartitionBound]):
        # bounds: one per region; last must be MAXVALUE
        if not bounds or not bounds[-1].is_maxvalue:
            raise ValueError("last partition bound must be MAXVALUE")
        for b in bounds[:-1]:
            if len(b.values) != len(columns):
                raise ValueError("bound arity != partition column count")
        self.columns = columns
        self.bounds = bounds

    def num_regions(self) -> int:
        return len(self.bounds)

    def find_regions(
        self, cols: Sequence[np.ndarray], n_rows: Optional[int] = None
    ) -> np.ndarray:
        if len(cols) != len(self.columns):
            raise ValueError("column count mismatch")
        n = len(cols[0]) if cols else (n_rows or 0)
        if len(self.bounds) == 1:
            return np.zeros(n, dtype=np.int32)
        finite = [b.values for b in self.bounds[:-1]]
        if len(self.columns) == 1:
            edges = np.asarray([v[0] for v in finite])
            vals = np.asarray(cols[0])
            if edges.dtype.kind in ("U", "S", "O") or vals.dtype.kind in ("U", "S", "O"):
                vals = vals.astype(str)
                edges = edges.astype(str)
            return np.searchsorted(edges, vals, side="right").astype(np.int32)
        # multi-dim: compare row tuples against bound tuples lexicographically.
        # region(row) = count of bounds <= row  (bounds are sorted ascending)
        region = np.zeros(n, dtype=np.int32)
        for bound in finite:
            # le_mask: bound tuple <= row tuple (lexicographic)
            le = np.zeros(n, dtype=bool)
            eq = np.ones(n, dtype=bool)
            for c, bv in zip(cols, bound):
                cv = np.asarray(c)
                if cv.dtype.kind in ("U", "S", "O"):
                    cv = cv.astype(str)
                    bv = str(bv)
                le |= eq & (cv > bv)
                eq &= cv == bv
            le |= eq  # bound == row counts as bound <= row
            region += le.astype(np.int32)
        return region

    def to_json(self) -> str:
        return json.dumps(
            {
                "type": "range",
                "columns": self.columns,
                "bounds": [list(b.values) for b in self.bounds],
            }
        )

    @staticmethod
    def from_json(s: str) -> "RangePartitionRule":
        d = json.loads(s)
        return RangePartitionRule(
            d["columns"], [PartitionBound(tuple(v)) for v in d["bounds"]]
        )


def _hash_column(vals: np.ndarray) -> np.ndarray:
    """Stable vectorized per-value hash (uint64). Strings factorize once
    and crc32 the uniques (crc32 is stable across processes — required:
    write scatter must agree between any frontend and any replay);
    integers run a splitmix64-style scramble so adjacent series ids
    don't all land on adjacent regions."""
    import zlib

    vals = np.asarray(vals)
    if vals.dtype.kind in ("U", "S", "O"):
        s = vals.astype(str)
        uniq, inv = np.unique(s, return_inverse=True)
        hu = np.asarray([zlib.crc32(u.encode("utf-8")) for u in uniq],
                        dtype=np.uint64)
        return hu[inv]
    x = np.asarray(vals)
    if x.dtype.kind == "f":
        x = x.astype(np.float64).view(np.uint64)
    else:
        x = x.astype(np.int64).astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


class HashPartitionRule(PartitionRule):
    """N regions by a stable hash of the partition columns — the write
    scatter for workloads without a natural range key. Every region owns
    WHOLE series (all rows of one partition-column tuple hash alike), so
    LWW dedup, lastpoint pruning, and window-partition pushdown keep
    their per-region arguments; the reference's HASH PARTITION analog."""

    def __init__(self, columns: list[str], num_regions: int):
        if not columns:
            raise ValueError("hash partitioning needs >=1 column")
        if int(num_regions) < 1:
            raise ValueError("hash partitioning needs >=1 region")
        self.columns = list(columns)
        self._n = int(num_regions)

    def num_regions(self) -> int:
        return self._n

    def find_regions(
        self, cols: Sequence[np.ndarray], n_rows: Optional[int] = None
    ) -> np.ndarray:
        if len(cols) != len(self.columns):
            raise ValueError("column count mismatch")
        n = len(cols[0]) if cols else (n_rows or 0)
        if self._n == 1:
            return np.zeros(n, dtype=np.int32)
        h = np.zeros(n, dtype=np.uint64)
        with np.errstate(over="ignore"):
            for c in cols:
                h = h * np.uint64(1000003) ^ _hash_column(c)
        return (h % np.uint64(self._n)).astype(np.int32)

    def to_json(self) -> str:
        return json.dumps({"type": "hash", "columns": self.columns,
                           "regions": self._n})

    @staticmethod
    def from_json(s: str) -> "HashPartitionRule":
        d = json.loads(s)
        return HashPartitionRule(d["columns"], d["regions"])


def rule_from_json(obj) -> PartitionRule:
    """Rule loader by type tag ("range" is the pre-hash default for
    manifests written before the tag existed). Accepts a JSON string or
    the already-decoded dict the catalog stores."""
    d = json.loads(obj) if isinstance(obj, str) else obj
    if d.get("type") == "hash":
        return HashPartitionRule(d["columns"], d["regions"])
    return RangePartitionRule(
        d["columns"], [PartitionBound(tuple(v)) for v in d["bounds"]])


def single_region_rule() -> RangePartitionRule:
    return RangePartitionRule(columns=[], bounds=[PartitionBound(())])


def rule_from_partition_ast(cols: list[str], exprs: list) -> RangePartitionRule:
    """Build a RangePartitionRule from parsed PARTITION ON COLUMNS bound
    expressions (reference src/sql partition syntax → multi_dim rule).

    Recognized per-region shapes: `col < lit` (upper bound), conjunctions
    `col >= lit AND col < lit2` (upper bound lit2), and anything else —
    `col >= lit`, MAXVALUE — as the unbounded tail region. Bounds are
    sorted ascending, so region order matches bound order regardless of how
    the user listed them.
    """
    from greptimedb_tpu.sql import ast as _ast

    uppers: list = []
    tail = 0
    for e in exprs:
        b = _upper_bound_of(e, cols)
        if b is None:
            tail += 1
        else:
            uppers.append(b)
    if tail == 0:
        # no explicit catch-all: the last bound's region absorbs the tail
        if not uppers:
            raise ValueError("PARTITION clause needs at least one bound")
        uppers = sorted(uppers)[:-1]
    uppers.sort()
    bounds = [PartitionBound(tuple(u) if isinstance(u, list) else (u,)) for u in uppers]
    bounds.append(PartitionBound(()))
    return RangePartitionRule(cols, bounds)


def _upper_bound_of(e, cols: list[str]):
    from greptimedb_tpu.sql import ast as _ast

    if isinstance(e, _ast.BinaryOp):
        if e.op in ("and",):
            rb = _upper_bound_of(e.right, cols)
            return rb if rb is not None else _upper_bound_of(e.left, cols)
        if e.op in ("<", "<=") and isinstance(e.left, _ast.Column) and isinstance(e.right, _ast.Literal):
            return e.right.value
        if e.op in (">", ">=") and isinstance(e.right, _ast.Column) and isinstance(e.left, _ast.Literal):
            return e.left.value
    return None
