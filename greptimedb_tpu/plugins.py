"""Plugin system — typed extension container + hook points.

Mirrors the reference's plugins crate (a typed `Plugins` map threaded
through frontend/datanode/metasrv construction, src/common/plugins) plus
its two concrete extension seams:

- `Plugins`: a by-type container; components `insert` implementations
  and others `get` them without hard dependencies.
- function plugins: objects with `scalar_functions() -> {name: fn}`
  registered here become SQL scalar functions (the reference's
  FunctionRegistry::register path).
- request interceptors: `on_sql(sql, ctx) -> sql` rewrite/veto hooks the
  query engine runs before parsing (reference SqlQueryInterceptor,
  frontend/src/instance.rs).

`load_from_env()` imports modules named in GREPTIMEDB_TPU_PLUGINS
(comma-separated); each must expose `setup(plugins)`.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Callable, Optional, Type, TypeVar

import contextvars

T = TypeVar("T")

_default: Optional["Plugins"] = None
_default_lock = threading.Lock()

#: the Plugins container of the engine currently executing a statement —
#: expression evaluation resolves scalar functions through this so a
#: QueryEngine constructed with a custom container sees ITS functions,
#: not only the process default
_active: "contextvars.ContextVar[Optional[Plugins]]" = \
    contextvars.ContextVar("gtpu_active_plugins", default=None)


def active_plugins() -> "Plugins":
    return _active.get() or default_plugins()


def set_active(plugins: "Plugins"):
    """Returns a token for contextvars reset."""
    return _active.set(plugins)


def reset_active(token) -> None:
    _active.reset(token)


def default_plugins() -> "Plugins":
    """Process-wide default container (what standalone mode threads
    through engine + servers when no explicit Plugins is passed).
    Publication happens only after a successful env load — a broken
    plugin module raises on EVERY call instead of leaving a silently
    partial container behind."""
    global _default
    with _default_lock:
        if _default is None:
            p = Plugins()
            p.load_from_env()
            _default = p
        return _default


class Plugins:
    """Typed plugin container (reference plugins::Plugins)."""

    def __init__(self):
        self._by_type: dict[type, object] = {}
        self._scalar_functions: dict[str, Callable] = {}
        self._sql_interceptors: list[Callable] = []
        self._lock = threading.Lock()

    # ---------------------------------------------------------- container
    def insert(self, value: object) -> None:
        with self._lock:
            self._by_type[type(value)] = value

    def get(self, cls: Type[T]) -> Optional[T]:
        with self._lock:
            return self._by_type.get(cls)  # type: ignore[return-value]

    # -------------------------------------------------------------- hooks
    def register_scalar_function(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._scalar_functions[name.lower()] = fn

    def scalar_function(self, name: str) -> Optional[Callable]:
        with self._lock:
            return self._scalar_functions.get(name.lower())

    def register_sql_interceptor(self, fn: Callable) -> None:
        """fn(sql, ctx) -> sql; raise to veto the statement."""
        with self._lock:
            self._sql_interceptors.append(fn)

    def intercept_sql(self, sql: str, ctx) -> str:
        for fn in list(self._sql_interceptors):
            sql = fn(sql, ctx)
        return sql

    # ------------------------------------------------------------ loading
    def setup_module(self, module_name: str) -> None:
        mod = importlib.import_module(module_name)
        setup = getattr(mod, "setup", None)
        if setup is None:
            raise ValueError(
                f"plugin module {module_name!r} has no setup(plugins)")
        setup(self)

    def load_from_env(self, var: str = "GREPTIMEDB_TPU_PLUGINS") -> list[str]:
        loaded = []
        for name in filter(None, os.environ.get(var, "").split(",")):
            self.setup_module(name.strip())
            loaded.append(name.strip())
        return loaded
