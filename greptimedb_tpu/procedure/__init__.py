from .procedure import (
    FnStepProcedure,
    Procedure,
    ProcedureManager,
    ProcedureRecord,
    ProcedureStore,
    Status,
)

__all__ = [
    "FnStepProcedure",
    "Procedure",
    "ProcedureManager",
    "ProcedureRecord",
    "ProcedureStore",
    "Status",
]
