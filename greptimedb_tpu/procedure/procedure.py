"""Persistent, resumable multi-step procedure framework.

Mirrors reference src/common/procedure (procedure.rs:50-76 `Procedure` trait +
`Status`; local.rs:390-451 runner with retry + rollback; :480-526 crash
recovery from the persisted store). Procedures are the metadata plane's unit
of fault tolerance: every DDL, failover, and migration is a state machine
whose state is journaled to a `ProcedureStore` (kv-backed) after each step,
so a crashed coordinator can reload and resume from the last step.

TPU-native design note: unlike the reference's async tokio runner, steps here
run synchronously on the caller or a worker thread — the control plane is
latency-insensitive; determinism (for tests, SURVEY.md §4) matters more.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Optional

from ..catalog.kv import KvBackend


class ProcedureError(Exception):
    pass


@dataclass
class Status:
    """Outcome of one `Procedure.step` call.

    Mirrors common/procedure/src/procedure.rs `Status::{Executing, Done,
    Suspended}`: `done=False` means call `step` again (state was advanced and
    persisted); `done=True` means finished with `output`.
    """

    done: bool
    output: Optional[dict] = None

    @staticmethod
    def executing() -> "Status":
        return Status(done=False)

    @staticmethod
    def finished(output: Optional[dict] = None) -> "Status":
        return Status(done=True, output=output)


class Procedure:
    """One resumable state machine.

    Subclasses define `type_name`, serialize their progress in `self.state`
    (a JSON-able dict; persisted after every step), implement `step()` and
    optionally `rollback()`. `state["phase"]` is the conventional cursor.
    """

    type_name: str = "procedure"

    def __init__(self, state: Optional[dict] = None):
        self.state: dict = state if state is not None else {}

    def step(self, ctx: "ProcedureContext") -> Status:
        raise NotImplementedError

    def rollback(self, ctx: "ProcedureContext") -> None:
        """Best-effort undo when retries are exhausted (local.rs:451)."""

    def dump(self) -> str:
        return json.dumps(self.state)


@dataclass
class ProcedureContext:
    procedure_id: str
    manager: "ProcedureManager"


@dataclass
class ProcedureRecord:
    procedure_id: str
    type_name: str
    state: dict
    status: str  # running | done | failed | rolled_back
    error: Optional[str] = None
    output: Optional[dict] = None
    retries: int = 0


class ProcedureStore:
    """Journal of procedure records over a KvBackend.

    Mirrors common/procedure `ProcedureStore`: one key per procedure holding
    the latest state; finished procedures are kept (with status) for
    inspection and GC'd by `sweep`.
    """

    PREFIX = "__procedure/"

    def __init__(self, kv: KvBackend):
        self._kv = kv

    def save(self, rec: ProcedureRecord) -> None:
        self._kv.put(
            self.PREFIX + rec.procedure_id,
            json.dumps(
                {
                    "type": rec.type_name,
                    "state": rec.state,
                    "status": rec.status,
                    "error": rec.error,
                    "output": rec.output,
                    "retries": rec.retries,
                }
            ),
        )

    def load(self, procedure_id: str) -> Optional[ProcedureRecord]:
        raw = self._kv.get(self.PREFIX + procedure_id)
        if raw is None:
            return None
        d = json.loads(raw)
        return ProcedureRecord(
            procedure_id=procedure_id,
            type_name=d["type"],
            state=d["state"],
            status=d["status"],
            error=d.get("error"),
            output=d.get("output"),
            retries=d.get("retries", 0),
        )

    def list(self) -> list[ProcedureRecord]:
        out = []
        for k, _ in self._kv.range(self.PREFIX):
            rec = self.load(k[len(self.PREFIX):])
            if rec is not None:
                out.append(rec)
        return out

    def remove(self, procedure_id: str) -> None:
        self._kv.delete(self.PREFIX + procedure_id)


class ProcedureManager:
    """Runs procedures to completion with per-step persistence and retry.

    Mirrors common/procedure/src/local.rs `LocalManager`: `submit` registers
    + runs; `recover` reloads every `running` record after a crash and
    re-drives it (local.rs:480-526). Retries with capped backoff; on
    exhaustion calls `rollback` and marks `failed`.
    """

    def __init__(
        self,
        kv: KvBackend,
        max_retries: int = 3,
        retry_delay_s: float = 0.0,
    ):
        self.store = ProcedureStore(kv)
        self._kv = kv
        self._loaders: dict[str, Callable[[dict], Procedure]] = {}
        self._max_retries = max_retries
        self._retry_delay_s = retry_delay_s
        self._lock = threading.Lock()

    def register_loader(
        self, type_name: str, loader: Callable[[dict], Procedure]
    ) -> None:
        """Register a factory used by crash recovery to rebuild a procedure
        from its persisted state."""
        self._loaders[type_name] = loader

    def next_id(self) -> str:
        n = self._kv.incr("__procedure_seq")
        return f"p-{n:08d}"

    def submit(self, proc: Procedure, procedure_id: Optional[str] = None) -> ProcedureRecord:
        pid = procedure_id or self.next_id()
        rec = ProcedureRecord(
            procedure_id=pid,
            type_name=proc.type_name,
            state=proc.state,
            status="running",
        )
        self.store.save(rec)
        return self._drive(proc, rec)

    def recover(self) -> list[ProcedureRecord]:
        """Resume every procedure that was `running` when we crashed."""
        results = []
        for rec in self.store.list():
            if rec.status != "running":
                continue
            loader = self._loaders.get(rec.type_name)
            if loader is None:
                rec.status = "failed"
                rec.error = f"no loader for procedure type {rec.type_name!r}"
                self.store.save(rec)
                results.append(rec)
                continue
            proc = loader(rec.state)
            results.append(self._drive(proc, rec))
        return results

    def _drive(self, proc: Procedure, rec: ProcedureRecord) -> ProcedureRecord:
        ctx = ProcedureContext(procedure_id=rec.procedure_id, manager=self)
        while True:
            try:
                status = proc.step(ctx)
            except Exception as e:  # noqa: BLE001 — retry any step failure
                rec.retries += 1
                rec.error = f"{e}\n{traceback.format_exc(limit=3)}"
                if rec.retries > self._max_retries:
                    try:
                        proc.rollback(ctx)
                        rec.status = "rolled_back"
                    except Exception as re:  # noqa: BLE001
                        rec.status = "failed"
                        rec.error += f"; rollback failed: {re}"
                    rec.state = proc.state
                    self.store.save(rec)
                    return rec
                if self._retry_delay_s:
                    time.sleep(self._retry_delay_s * min(rec.retries, 8))
                self.store.save(rec)
                continue
            rec.state = proc.state
            if status.done:
                rec.status = "done"
                rec.output = status.output
                self.store.save(rec)
                return rec
            # persist after every advancing step — the crash-recovery point
            self.store.save(rec)


@dataclass
class FnStepProcedure(Procedure):
    """Procedure built from an ordered list of named step functions — the
    common shape of DDL/failover procedures (each phase idempotent)."""

    type_name = "fn_steps"

    def __init__(self, steps: list[tuple[str, Callable[[dict], None]]], state=None):
        super().__init__(state)
        self.steps = steps
        self.state.setdefault("phase", 0)

    def step(self, ctx: ProcedureContext) -> Status:
        i = self.state["phase"]
        if i >= len(self.steps):
            return Status.finished()
        _, fn = self.steps[i]
        fn(self.state)
        self.state["phase"] = i + 1
        if self.state["phase"] >= len(self.steps):
            return Status.finished(self.state.get("output"))
        return Status.executing()
