"""PromQL engine (mirrors reference src/promql, ~11.9k LoC).

The reference compiles PromQL to DataFusion plans with custom extension
operators (SeriesNormalize/InstantManipulate/RangeManipulate/SeriesDivide,
promql/src/planner.rs:144). The TPU-native re-design evaluates on dense
[series x eval-step] matrices instead: samples are bucketed onto the step
grid with segment kernels, range windows become cumulative-sum differences
and latest-nonempty gathers (ops/window.py), and label aggregations are
segment reductions over the series axis. `RangeArray`'s ragged windows
(range_array.rs:68) never materialize — windows are implicit in the grid.
"""

from greptimedb_tpu.promql.parser import parse_promql

__all__ = ["parse_promql", "PromqlEngine"]


def __getattr__(name):
    if name == "PromqlEngine":
        from greptimedb_tpu.promql.engine import PromqlEngine
        return PromqlEngine
    raise AttributeError(name)
