"""PromQL evaluation engine.

Mirrors the reference's PromPlanner + extension operators
(promql/src/planner.rs:144, extension_plan/*) re-designed for dense device
evaluation (see package docstring): every (sub)expression evaluates to one
of
  - SeriesMatrix: labels [S] + values [S, T] (NaN = no sample)
  - a per-step scalar array [T]
  - a python float (constant)
over the regular eval grid (start, end, step). Range-vector functions run
the window_stats kernel (ops/window.py); label aggregations are segment
reductions over the series axis; binary-op vector matching joins label
signatures on host (S is small; T×S math stays on device).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.datatypes.types import DataType
from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.ops.window import counter_adjust, extrapolated_delta, window_stats
from greptimedb_tpu.promql.parser import (
    DEFAULT_LOOKBACK_S,
    Aggregate,
    Binary,
    Call,
    Matcher,
    NumberLiteral,
    PromqlError,
    StringLiteral,
    Subquery,
    Unary,
    VectorSelector,
    parse_promql,
)
from greptimedb_tpu.query.result import QueryResult


_CALENDAR = frozenset({
    "minute", "hour", "day_of_week", "day_of_month", "day_of_year",
    "days_in_month", "month", "year",
})


def _calendar_field(fn: str, secs: np.ndarray) -> np.ndarray:
    """UTC calendar field of unix-second values, NaN-preserving
    (reference functions/: the date helpers PromQL exposes).

    Pure numpy datetime64 arithmetic: no pandas ns-resolution bounds —
    any float within int64 seconds works; everything else becomes NaN
    (Prometheus accepts arbitrary floats as input values)."""
    flat = secs.reshape(-1)
    lim = 9.0e18  # within int64 seconds
    bad = ~np.isfinite(flat) | (np.abs(flat) > lim)
    isecs = np.floor(np.where(bad, 0.0, flat)).astype(np.int64)
    if fn == "minute":
        out = ((isecs % 3600) // 60).astype(np.float64)
    elif fn == "hour":
        out = ((isecs % 86400) // 3600).astype(np.float64)
    else:
        dt = isecs.astype("datetime64[s]")
        days = dt.astype("datetime64[D]")
        months = dt.astype("datetime64[M]")
        years = dt.astype("datetime64[Y]")
        if fn == "day_of_week":
            # 1970-01-01 was a Thursday; Prometheus: Sunday = 0
            out = ((days.astype(np.int64) + 4) % 7).astype(np.float64)
        elif fn == "day_of_month":
            out = ((days - months.astype("datetime64[D]"))
                   .astype(np.int64) + 1).astype(np.float64)
        elif fn == "day_of_year":
            out = ((days - years.astype("datetime64[D]"))
                   .astype(np.int64) + 1).astype(np.float64)
        elif fn == "days_in_month":
            out = ((months + 1).astype("datetime64[D]")
                   - months.astype("datetime64[D]")).astype(np.float64)
        elif fn == "month":
            out = ((months - years.astype("datetime64[M]"))
                   .astype(np.int64) + 1).astype(np.float64)
        else:  # year
            out = (years.astype(np.int64) + 1970).astype(np.float64)
    out[bad] = np.nan
    return out.reshape(secs.shape)


def _fmt_prom_value(v: float) -> str:
    """Shortest positional-decimal float formatting (Go FormatFloat
    'f', -1): no scientific notation; Inf spelled Prometheus-style."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return np.format_float_positional(v, trim="-")


@dataclass
class SeriesMatrix:
    labels: list[dict[str, str]]  # S label sets (no __name__)
    values: jax.Array  # [S, T]
    metric: Optional[str] = None
    sample_ts: Optional[jax.Array] = None  # [S, T] for timestamp()

    @property
    def num_series(self) -> int:
        return len(self.labels)


@dataclass
class EvalParams:
    start: float
    end: float
    step: float
    times: np.ndarray  # [T] seconds

    @property
    def T(self) -> int:
        return len(self.times)


_RANGE_FUNCS = {
    "rate", "increase", "delta", "avg_over_time", "sum_over_time",
    "count_over_time", "min_over_time", "max_over_time", "last_over_time",
    "stddev_over_time", "stdvar_over_time", "present_over_time",
    "changes", "resets", "deriv", "predict_linear", "irate", "idelta",
    "absent_over_time", "holt_winters",
}

_ELEMENTWISE = {
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor,
    "exp": jnp.exp, "ln": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "sqrt": jnp.sqrt, "sgn": jnp.sign,
    "acos": jnp.arccos, "asin": jnp.arcsin, "atan": jnp.arctan,
    "cos": jnp.cos, "sin": jnp.sin, "tan": jnp.tan,
    "cosh": jnp.cosh, "sinh": jnp.sinh, "tanh": jnp.tanh,
    "deg": jnp.degrees, "rad": jnp.radians,
}


class PromqlEngine:
    def __init__(self, query_engine):
        self.qe = query_engine

    # ---- public API --------------------------------------------------------

    def eval_range(self, query: str, start: float, end: float, step: float,
                   ctx=None) -> QueryResult:
        """Range query -> long-format table (ts, value, labels...) like the
        reference's TQL output."""
        times, result = self.eval_matrix(query, start, end, step, ctx)
        return _to_long_result(times, result)

    def eval_matrix(self, query: str, start: float, end: float, step: float,
                    ctx=None):
        if step <= 0:
            raise PromqlError("step must be positive")
        from greptimedb_tpu.utils import slow_query

        # slow-query watch for the direct PromQL HTTP entry points; a
        # TQL statement arrives under execute_sql's watch, where this
        # one is a no-op (the re-entrancy guard)
        with slow_query.watch("promql", query,
                              getattr(ctx, "db", None) or "public") as w:
            node = parse_promql(query)
            n_steps = int(math.floor((end - start) / step)) + 1
            times = start + np.arange(n_steps) * step
            params = EvalParams(start, end, step, times)
            result = self._eval(node, params, ctx)
            if isinstance(result, SeriesMatrix):
                w.rows = len(result.labels)
        return times, result

    def eval_instant(self, query: str, t: float, ctx=None):
        times, result = self.eval_matrix(query, t, t, 1.0, ctx)
        return times, result

    # ---- evaluation --------------------------------------------------------

    def _eval(self, node, p: EvalParams, ctx):
        if isinstance(node, NumberLiteral):
            return node.value
        if isinstance(node, StringLiteral):
            return node.value
        if isinstance(node, Unary):
            v = self._eval(node.expr, p, ctx)
            return _map_values(v, lambda x: -x)
        if isinstance(node, VectorSelector):
            if node.range_s is not None:
                raise PromqlError("range vector outside function call")
            if node.at_s is not None:
                return self._eval_at(node, p, ctx)
            return self._eval_instant_selector(node, p, ctx)
        if isinstance(node, Call):
            return self._eval_call(node, p, ctx)
        if isinstance(node, Aggregate):
            return self._eval_aggregate(node, p, ctx)
        if isinstance(node, Binary):
            return self._eval_binary(node, p, ctx)
        raise PromqlError(f"cannot evaluate {type(node).__name__}")

    # ---- selectors ---------------------------------------------------------

    @staticmethod
    def _resolve_at(at, p: EvalParams) -> float:
        if at == "__start__":
            return p.start
        if at == "__end__":
            return p.end
        return float(at)

    def _eval_at(self, sel: VectorSelector, p: EvalParams, ctx):
        """`@ <ts>` / `@ start()` / `@ end()` (Prometheus at-modifier):
        evaluate the selector at ONE fixed instant, then broadcast that
        value across every output step."""
        t_fix = self._resolve_at(sel.at_s, p)
        pinned = VectorSelector(sel.metric, sel.matchers, sel.range_s,
                                sel.offset_s, None)
        p1 = EvalParams(start=t_fix, end=t_fix, step=p.step,
                        times=np.asarray([t_fix]))
        v = self._eval_instant_selector(pinned, p1, ctx)
        return SeriesMatrix(
            v.labels, jnp.broadcast_to(v.values, (v.values.shape[0], p.T)),
            v.metric,
            sample_ts=(jnp.broadcast_to(v.sample_ts,
                                        (v.values.shape[0], p.T))
                       if v.sample_ts is not None else None))

    def _eval_instant_selector(self, sel: VectorSelector, p: EvalParams, ctx,
                               lookback: float = DEFAULT_LOOKBACK_S):
        loaded = self._load(sel, p, ctx, window=lookback)
        if loaded is None:
            return SeriesMatrix([], jnp.zeros((0, p.T)))
        sidx, ts, chans, labels, metric = loaded
        w = max(1, int(math.ceil(lookback / p.step)))
        st = window_stats(sidx, ts, chans, ~jnp.isnan(chans[:, 0]),
                          p.start, p.step, len(labels), p.T, w,
                          stats=("count", "last"),
                          sorted_input=_sorted_ws())
        vals = st["last"][:, :, 0]
        lts = st["last_ts"]
        # exact lookback: bucket window may overcover; validate sample ts
        ok = lts > (jnp.asarray(p.times)[None, :] - lookback)
        vals = jnp.where(ok, vals, jnp.nan)
        return SeriesMatrix(labels, vals, metric,
                            sample_ts=jnp.where(ok, lts, jnp.nan))

    def _range_stats(self, sel, p: EvalParams, ctx,
                     stats: tuple[str, ...], extra_channels=()):
        """Evaluate a range selector OR subquery into window stats.
        Returns (stats dict, labels, metric, w, range_s) or None when
        empty."""
        range_s = getattr(sel, "range_s", None)
        if range_s is None:
            raise PromqlError("expected a range vector (metric[duration])")
        ratio = range_s / p.step
        w = int(round(ratio))
        if abs(ratio - w) > 1e-9 or w < 1:
            raise PromqlError(
                f"range {range_s}s must be a positive multiple of step {p.step}s "
                "(blocked-window evaluation)")
        loaded = self._load_any(sel, p, ctx, window=range_s,
                                extra_channels=extra_channels)
        if loaded is None:
            return None
        sidx, ts, chans, labels, metric = loaded
        st = None
        if "sum" in stats and set(stats) <= {"sum", "count"} \
                and not isinstance(sel, Subquery) \
                and _edges_enabled():
            # sum/avg_over_time fast path: one cached cumulative sum
            # over the pivot turns every window sum into a two-gather
            # difference (window_sums_grid). Count-only stats skip this
            # — the edges path below derives counts from probes alone,
            # without materializing a pivot-sized cumsum.
            pivot = self._grid_pivot(sidx, ts, chans, len(labels))
            if pivot is not None:
                from greptimedb_tpu.ops.window import window_sums_grid

                grid, mat = pivot
                st = window_sums_grid(grid, self._grid_cumsum(mat),
                                      p.start, p.step, p.T, w)
        if st is None and set(stats) <= {"count", "first", "last"} \
                and not isinstance(sel, Subquery) \
                and _edges_enabled():
            # rate-family fast path: scrape-aligned series share ONE
            # complete sample grid, so window edges are T probes into
            # the grid + column gathers from a pivoted [S, P, C] matrix
            # (ops/window.py window_edges_grid — the asymmetry the
            # numpy straw-man anchor exploits, now on device). The
            # pivot (plus its NaN-free check: LWW tombstones ride as
            # NaN the probes cannot mask) is cached with the loaded
            # series, so repeated evals pay only the probes.
            pivot = self._grid_pivot(sidx, ts, chans, len(labels))
            if pivot is not None:
                from greptimedb_tpu.ops.window import window_edges_grid

                grid, mat = pivot
                st = window_edges_grid(grid, mat, p.start, p.step,
                                       p.T, w)
        if st is None:
            st = window_stats(sidx, ts, chans, ~jnp.isnan(chans[:, 0]),
                              p.start, p.step, len(labels), p.T, w,
                              stats=stats, sorted_input=_sorted_ws())
        return st, labels, metric, w, range_s

    def _grid_pivot(self, sidx, ts, chans, n_series):
        """(grid [P], mat [S, P, C]) when every series has exactly the
        same complete, NaN-free sample grid; None otherwise. Identity-
        cached against the loaded arrays (which the load cache pins),
        so detection + pivot run once per scan snapshot."""
        ex = getattr(self.qe, "executor", None)
        cache = getattr(ex, "_promql_pivot_cache", None) if ex else None
        if cache is None and ex is not None:
            cache = ex._promql_pivot_cache = []
        if cache is not None:
            for c_sidx, c_chans, result in cache:
                if c_sidx is sidx and c_chans is chans:
                    return result
        result = None
        n = int(chans.shape[0])
        S = n_series
        if S > 0 and n % S == 0:
            P = n // S
            ts_np = np.asarray(ts)
            grid = ts_np[:P]
            if (ts_np.reshape(S, P) == grid[None, :]).all() \
                    and not bool(jnp.isnan(chans).any()):
                result = (jnp.asarray(grid), chans.reshape(S, P,
                                                           chans.shape[1]))
        if cache is not None:
            cache.append((sidx, chans, result))
            del cache[:-2]  # two live scans at most (load cache holds 4)
        return result

    #: pivots larger than this don't cache their prefix sums (the
    #: cumsum doubles the pivot's memory; recompute instead)
    _CUMSUM_CACHE_BYTES = 512 << 20

    def _grid_cumsum(self, mat):
        """Exclusive prefix sums [S, P+1, C] over a pivoted matrix,
        identity-cached beside the pivot (window_sums_grid consumes
        them). Oversized pivots compute fresh each eval rather than
        doubling resident memory."""
        from greptimedb_tpu.ops.window import exclusive_cumsum

        ex = getattr(self.qe, "executor", None)
        cache = getattr(ex, "_promql_cumsum_cache", None) if ex else None
        if cache is None and ex is not None:
            cache = ex._promql_cumsum_cache = []
        if cache is not None:
            for c_mat, cs in cache:
                if c_mat is mat:
                    return cs
        cs = exclusive_cumsum(mat)
        if cache is not None and cs.nbytes <= self._CUMSUM_CACHE_BYTES:
            cache.append((mat, cs))
            del cache[:-2]
        return cs

    def _load_any(self, sel, p: EvalParams, ctx, window: float,
                  extra_channels=()):
        if isinstance(sel, Subquery):
            return self._load_subquery(sel, p, ctx, extra_channels)
        return self._load(sel, p, ctx, window, extra_channels)

    def _load_subquery(self, sq: Subquery, p: EvalParams, ctx,
                       extra_channels=()):
        """Evaluate the inner expr on the subquery's own grid, flatten the
        matrix to (series, ts, value) samples, and hand back the same
        loaded tuple a storage scan produces — downstream window kernels
        can't tell the difference (reference planner subquery support)."""
        sub_step = sq.step_s if sq.step_s else p.step
        lo = p.start - sq.range_s - sq.offset_s
        hi = p.end - sq.offset_s
        # Prometheus aligns subquery steps to absolute multiples of step
        first = math.ceil(lo / sub_step) * sub_step
        n = int(math.floor((hi - first) / sub_step)) + 1
        if n <= 0:
            return None
        times = first + np.arange(n) * sub_step
        inner = EvalParams(first, times[-1], sub_step, times)
        v = self._eval(sq.expr, inner, ctx)
        if not isinstance(v, SeriesMatrix):
            raise PromqlError("subquery needs an instant-vector expression")
        if v.num_series == 0:
            return None
        vals = np.asarray(v.values)
        S, T2 = vals.shape
        sidx = np.repeat(np.arange(S, dtype=np.int32), T2)
        ts = np.tile(times + sq.offset_s, S)  # back on the outer timeline
        flat = vals.reshape(-1)
        keep = ~np.isnan(flat)  # absent inner samples aren't samples
        if not keep.any():
            return None
        d_sidx = jnp.asarray(sidx[keep])
        d_ts = jnp.asarray(ts[keep])
        d_vals = jnp.asarray(flat[keep])
        channels = self._make_channels(d_sidx, d_ts, d_vals,
                                       extra_channels, p)
        return d_sidx, d_ts, channels, v.labels, v.metric

    def _make_channels(self, d_sidx, d_ts, d_vals, extra_channels, p):
        """Derived per-sample channels riding the window kernel alongside
        the raw value: counter-reset-adjusted values, change/reset
        indicators, regression moments, previous-sample value/ts."""
        chans = [d_vals]
        if "adjusted" in extra_channels:
            chans.append(counter_adjust(d_sidx, d_vals))
        if extra_channels and {"changes", "resets", "prev"} & set(extra_channels):
            prev_v = jnp.concatenate([d_vals[:1], d_vals[:-1]])
            same = jnp.concatenate([jnp.zeros(1, bool),
                                    (d_sidx[1:] == d_sidx[:-1])])
            if "changes" in extra_channels:
                chans.append(jnp.where(same & (d_vals != prev_v), 1.0, 0.0))
            if "resets" in extra_channels:
                chans.append(jnp.where(same & (d_vals < prev_v), 1.0, 0.0))
            if "prev" in extra_channels:
                prev_t = jnp.concatenate([d_ts[:1], d_ts[:-1]])
                chans.append(jnp.where(same, prev_v, jnp.nan))
                chans.append(jnp.where(same, prev_t, jnp.nan))
        if "deriv" in extra_channels:
            tr = d_ts - p.start  # well-conditioned regression coordinates
            chans += [d_vals * tr, tr, tr * tr]
        return jnp.stack(chans, axis=1)

    def _load(self, sel: VectorSelector, p: EvalParams, ctx, window: float,
              extra_channels=()):
        """Scan + matcher-filter + series factorization. Returns device
        arrays sorted by (series, ts): sidx [N], ts seconds [N],
        channels [N, C], labels, metric. Channel 0 is the raw value;
        extra_channels in {"adjusted", "changes", "resets", "deriv"} append
        derived channels."""
        matchers = list(sel.matchers)
        metric = sel.metric
        field_name = None
        rest: list[Matcher] = []
        for m in matchers:
            if m.label == "__name__":
                if m.op != "=":
                    raise PromqlError("__name__ supports '=' only")
                metric = m.value
            elif m.label == "__field__":
                if m.op != "=":
                    raise PromqlError("__field__ supports '=' only")
                field_name = m.value
            else:
                rest.append(m)
        if metric is None:
            raise PromqlError("selector needs a metric name")

        qe = self.qe
        from greptimedb_tpu.catalog.catalog import CatalogError
        from greptimedb_tpu.query.engine import QueryContext
        ctx = ctx or QueryContext()
        try:
            info = qe._table(metric, ctx)
        except CatalogError:
            return None
        schema = info.schema
        fields = schema.field_columns
        if field_name is None:
            if len(fields) == 1:
                field_name = fields[0].name
            elif any(f.name == "greptime_value" for f in fields):
                field_name = "greptime_value"
            else:
                raise PromqlError(
                    f"metric {metric!r} has {len(fields)} fields; select one "
                    "with {__field__=\"...\"}"
                    )
        elif field_name not in {f.name for f in fields}:
            raise PromqlError(f"no field {field_name!r} in {metric!r}")

        ts_col = schema.time_index
        unit = ts_col.dtype.time_unit.nanos_per_unit
        offset = sel.offset_s
        lo = int((p.start - window - offset) * 1e9) // unit
        hi = int((p.end - offset) * 1e9) // unit + 1
        # push =/=~ matchers into the inverted index (reference applies
        # index predicates at sst/parquet/reader.rs:335-425); != and !~
        # can't prune (a segment bitmap proves presence, not absence).
        # The exact matcher masks below still run on everything scanned.
        from greptimedb_tpu.storage.index import InSet, Regex
        idx_preds: dict[str, list] = {}
        tag_set = {c.name for c in schema.tag_columns}
        for m in rest:
            if m.label not in tag_set:
                continue
            if m.op == "=":
                idx_preds.setdefault(m.label, []).append(InSet.of([m.value]))
            elif m.op == "=~":
                idx_preds.setdefault(m.label, []).append(Regex(m.value))
        from greptimedb_tpu.utils import tracing

        with tracing.span("promql_scan", metric=metric,
                          field=field_name):
            scan = qe.region_engine.scan(
                info.region_ids[0], (lo, hi), [field_name],
                tag_predicates={k: tuple(v)
                                for k, v in idx_preds.items()} or None)
        if scan is None or scan.num_rows == 0:
            return None

        # loaded-series cache: everything below (matcher masks, series
        # factorization + label decode, the 9.6M-row device lexsort,
        # channel building) is query-invariant for a given scan snapshot
        # + selector — the PromQL analog of the prepared planes. Keyed on
        # the scan identity, so data_version changes invalidate; "deriv"
        # channels embed p.start and key on it.
        ex = getattr(self.qe, "executor", None)
        lcache = None
        ckey = None
        if ex is not None and scan.region_id >= 0:
            lcache = getattr(ex, "_promql_load_cache", None)
            if lcache is None:
                from collections import OrderedDict

                lcache = ex._promql_load_cache = OrderedDict()
            ckey = (scan.region_id, scan.data_version,
                    scan.scan_fingerprint, field_name, offset,
                    tuple(sorted((m.label, m.op, m.value) for m in rest)),
                    tuple(extra_channels), not info.append_mode,
                    p.start if "deriv" in extra_channels else None)
            hit = lcache.get(ckey)
            if hit is not None:
                lcache.move_to_end(ckey)
                d_sidx, d_ts, channels, labels = hit
                return d_sidx, d_ts, channels, labels, metric

        tag_names = [c.name for c in schema.tag_columns]
        mask = np.ones(scan.num_rows, dtype=bool)
        for m in rest:
            mask &= _matcher_mask(m, scan, tag_names)
            if not mask.any():
                return None
        # dedup for non-append tables rides the same sort below
        rows = np.flatnonzero(mask)
        codes = [scan.columns[t][rows] for t in tag_names]
        ts_raw = scan.columns[ts_col.name][rows]
        vals = np.asarray(scan.columns[field_name][rows], dtype=np.float64)

        if tag_names:
            sizes = [len(scan.tag_dicts[t]) + 1 for t in tag_names]
            combined = codes[0].astype(np.int64) + 1
            for c, s in zip(codes[1:], sizes[1:]):
                combined = combined * s + (c.astype(np.int64) + 1)
            uniq, sidx = np.unique(combined, return_inverse=True)
            # decode labels per unique series
            labels = []
            strides = [1] * len(sizes)
            for i in range(len(sizes) - 2, -1, -1):
                strides[i] = strides[i + 1] * sizes[i + 1]
            for u in uniq:
                lab = {}
                for t_name, stride, size in zip(tag_names, strides, sizes):
                    code = int(u // stride % size) - 1
                    if code >= 0:
                        lab[t_name] = str(scan.tag_dicts[t_name][code])
                labels.append(lab)
        else:
            sidx = np.zeros(len(rows), dtype=np.int64)
            labels = [{}]

        ts_sec = ts_raw.astype(np.float64) * (unit / 1e9) + offset
        # sort by (series, ts): required by counter_adjust / indicator
        # channels, and makes segment ids sorted for the kernel. The
        # storage scan already yields (tags..., ts)-sorted rows for a
        # single flushed SST and series codes factorize in tag order —
        # prove sortedness on host and skip the device lexsort chain
        # (round-5: forcing that chain was 5.5 s of a 22 s first eval
        # at 28.8M rows)
        d_sidx = jnp.asarray(sidx.astype(np.int32))
        d_ts = jnp.asarray(ts_sec)
        d_vals = jnp.asarray(vals)
        if info.append_mode:
            ds = np.diff(sidx)
            host_sorted = bool(np.all(
                (ds > 0) | ((ds == 0) & (np.diff(ts_sec) >= 0))))
            if not host_sorted:
                order = jnp.lexsort((d_ts, d_sidx))
                d_sidx, d_ts, d_vals = (d_sidx[order], d_ts[order],
                                        d_vals[order])
        else:
            # non-append tables: last-write-wins by SEQ, not by scan
            # position — compaction re-inserts merged files after newer
            # flushes, so concat order is NOT write order. Sort with
            # seq as the tiebreaker, keep each duplicate run's last
            # row, and suppress it entirely when that winner is a
            # DELETE tombstone (the same contract ops/dedup.py's
            # sort_dedup enforces for SQL scans).
            from greptimedb_tpu.storage.region import OP_PUT

            d_seq = jnp.asarray(scan.seq[rows].astype(np.int64))
            d_op = jnp.asarray(scan.op_type[rows].astype(np.int8))
            order = jnp.lexsort((d_seq, d_ts, d_sidx))
            d_sidx, d_ts, d_vals, d_op = (d_sidx[order], d_ts[order],
                                          d_vals[order], d_op[order])
            nxt_s = jnp.concatenate([d_sidx[1:],
                                     jnp.full((1,), -1, d_sidx.dtype)])
            nxt_t = jnp.concatenate([d_ts[1:], jnp.full((1,), -jnp.inf)])
            dup_next = (d_sidx == nxt_s) & (d_ts == nxt_t)
            keep = ~dup_next & (d_op == OP_PUT)
            d_vals = jnp.where(keep, d_vals, jnp.nan)

        channels = self._make_channels(d_sidx, d_ts, d_vals,
                                       extra_channels, p)
        if lcache is not None:
            lcache[ckey] = (d_sidx, d_ts, channels, labels)
            while len(lcache) > 4:
                lcache.popitem(last=False)
        return d_sidx, d_ts, channels, labels, metric

    # ---- calls -------------------------------------------------------------

    def _eval_call(self, call: Call, p: EvalParams, ctx):
        fn = call.func
        if fn in _RANGE_FUNCS:
            # `rate(m[5m] @ T)`: pin the whole range evaluation at T and
            # broadcast — never silently evaluate on the normal grid
            sel = next((a for a in call.args
                        if isinstance(a, VectorSelector)), None)
            if sel is not None and sel.at_s is not None:
                t_fix = self._resolve_at(sel.at_s, p)
                pinned = VectorSelector(sel.metric, sel.matchers,
                                        sel.range_s, sel.offset_s, None)
                call2 = Call(call.func, tuple(
                    pinned if a is sel else a for a in call.args))
                p1 = EvalParams(start=t_fix, end=t_fix, step=p.step,
                                times=np.asarray([t_fix]))
                v = self._eval_range_func(call2, p1, ctx)
                if isinstance(v, SeriesMatrix):
                    return SeriesMatrix(
                        v.labels,
                        jnp.broadcast_to(v.values,
                                         (v.values.shape[0], p.T)),
                        v.metric)
                return v
            return self._eval_range_func(call, p, ctx)
        if fn == "time":
            return jnp.asarray(p.times)
        if fn in _CALENDAR:
            # Prometheus calendar functions: input VALUES are unix
            # seconds (default vector(time())); output the UTC field
            if call.args:
                v = self._eval(call.args[0], p, ctx)
            else:
                v = SeriesMatrix([{}], jnp.asarray(p.times)[None, :])
            if not isinstance(v, SeriesMatrix):
                v = SeriesMatrix([{}], _broadcast_scalar(v, p)[None, :])
            vals = np.asarray(v.values, dtype=np.float64)
            out = _calendar_field(fn, vals)
            # functions drop __name__ (same as the _map_values path)
            return SeriesMatrix(v.labels, jnp.asarray(out))
        if fn == "scalar":
            v = self._eval(call.args[0], p, ctx)
            if isinstance(v, SeriesMatrix):
                return v.values[0] if v.num_series == 1 else jnp.full(p.T, jnp.nan)
            return v
        if fn == "vector":
            v = self._eval(call.args[0], p, ctx)
            arr = _broadcast_scalar(v, p)
            return SeriesMatrix([{}], arr[None, :])
        if fn == "timestamp":
            v = self._eval(call.args[0], p, ctx)
            if not isinstance(v, SeriesMatrix) or v.sample_ts is None:
                raise PromqlError("timestamp() needs an instant selector")
            return SeriesMatrix(v.labels, v.sample_ts, None)
        if fn in ("clamp", "clamp_min", "clamp_max"):
            v = self._eval(call.args[0], p, ctx)
            if not isinstance(v, SeriesMatrix):
                raise PromqlError(f"{fn} needs a vector")
            args = [_scalar_of(self._eval(a, p, ctx)) for a in call.args[1:]]
            if fn == "clamp":
                out = jnp.clip(v.values, args[0], args[1])
            elif fn == "clamp_min":
                out = jnp.maximum(v.values, args[0])
            else:
                out = jnp.minimum(v.values, args[0])
            return SeriesMatrix(v.labels, out)
        if fn == "round":
            v = self._eval(call.args[0], p, ctx)
            to = _scalar_of(self._eval(call.args[1], p, ctx)) if len(call.args) > 1 else 1.0
            return SeriesMatrix(v.labels, jnp.round(v.values / to) * to)
        if fn in _ELEMENTWISE:
            v = self._eval(call.args[0], p, ctx)
            return _map_values(v, _ELEMENTWISE[fn])
        if fn in ("sort", "sort_desc"):
            v = self._eval(call.args[0], p, ctx)
            if not isinstance(v, SeriesMatrix) or v.num_series <= 1:
                return v
            # order series by their value at the (last) evaluated instant,
            # NaN last — matches Prometheus sort() on instant vectors
            key = np.asarray(v.values[:, -1]).astype(np.float64)
            rank = np.where(np.isnan(key), np.inf,
                            key if fn == "sort" else -key)
            order = np.argsort(rank, kind="stable")
            return SeriesMatrix([v.labels[i] for i in order],
                                v.values[np.asarray(order)], v.metric)
        if fn == "absent":
            v = self._eval(call.args[0], p, ctx)
            if not isinstance(v, SeriesMatrix):
                raise PromqlError("absent needs an instant vector")
            lab = _absent_labels(call.args[0])
            if v.num_series == 0:
                return SeriesMatrix([lab], jnp.ones((1, p.T)))
            all_absent = jnp.isnan(v.values).all(axis=0)
            return SeriesMatrix(
                [lab], jnp.where(all_absent, 1.0, jnp.nan)[None, :])
        if fn == "histogram_quantile":
            return self._histogram_quantile(call, p, ctx)
        if fn == "label_replace":
            return self._label_replace(call, p, ctx)
        if fn == "label_join":
            return self._label_join(call, p, ctx)
        raise PromqlError(f"unsupported function {fn!r}")

    def _eval_range_func(self, call: Call, p: EvalParams, ctx):
        fn = call.func
        sel = call.args[0]
        if not isinstance(sel, (VectorSelector, Subquery)):
            raise PromqlError(f"{fn} needs a range selector argument")

        if fn in ("rate", "increase", "delta"):
            counter = fn in ("rate", "increase")
            extra = ("adjusted",) if counter else ()
            r = self._range_stats(sel, p, ctx,
                                  ("count", "first", "last"), extra)
            if r is None:
                return SeriesMatrix([], jnp.zeros((0, p.T)))
            st, labels, metric, w, range_s = r
            ch = 1 if counter else 0
            times = jnp.asarray(p.times)
            vals = extrapolated_delta(
                st["first"][:, :, ch], st["first_ts"],
                st["last"][:, :, ch], st["last_ts"],
                st["count"][:, :, 0],
                times[None, :] - range_s, times[None, :],
                is_counter=counter, is_rate=(fn == "rate"), range_s=range_s,
            )
            return SeriesMatrix(labels, vals)

        if fn in ("irate", "idelta"):
            # last two samples in the window (reference functions/
            # instant_delta.rs): the window kernel's "last" gather carries
            # the previous-sample value/ts as extra channels
            r = self._range_stats(sel, p, ctx, ("count", "last"), ("prev",))
            if r is None:
                return SeriesMatrix([], jnp.zeros((0, p.T)))
            st, labels, metric, w, range_s = r
            last_v = st["last"][:, :, 0]
            prev_v = st["last"][:, :, 1]
            prev_t = st["last"][:, :, 2]
            last_t = st["last_ts"]
            wstart = jnp.asarray(p.times)[None, :] - range_s
            ok = (~jnp.isnan(prev_v)) & (prev_t > wstart) & (last_t > prev_t)
            if fn == "idelta":
                out = last_v - prev_v
            else:
                # counter semantics: reset -> delta is the raw new value
                delta = jnp.where(last_v < prev_v, last_v, last_v - prev_v)
                out = delta / (last_t - prev_t)
            return SeriesMatrix(labels, jnp.where(ok, out, jnp.nan))

        if fn == "absent_over_time":
            r = self._range_stats(sel, p, ctx, ("count",))
            lab = _absent_labels(sel)
            if r is None:
                return SeriesMatrix([lab], jnp.ones((1, p.T)))
            st, labels, metric, w, range_s = r
            any_present = (st["count"][:, :, 0] > 0).any(axis=0)
            return SeriesMatrix(
                [lab], jnp.where(any_present, jnp.nan, 1.0)[None, :])

        if fn == "holt_winters":
            return self._holt_winters(call, sel, p, ctx)

        if fn in ("changes", "resets"):
            r = self._range_stats(sel, p, ctx, ("sum", "count"), (fn,))
            if r is None:
                return SeriesMatrix([], jnp.zeros((0, p.T)))
            st, labels, metric, w, range_s = r
            present = st["count"][:, :, 0] > 0
            return SeriesMatrix(labels, jnp.where(present, st["sum"][:, :, 1], jnp.nan))

        if fn in ("deriv", "predict_linear"):
            r = self._range_stats(sel, p, ctx, ("sum", "count"), ("deriv",))
            if r is None:
                return SeriesMatrix([], jnp.zeros((0, p.T)))
            st, labels, metric, w, range_s = r
            n = st["count"][:, :, 0].astype(jnp.float64)
            sv, svt, t1, t2 = (st["sum"][:, :, i] for i in range(4))
            denom = n * t2 - t1 * t1
            slope = jnp.where((n >= 2) & (denom != 0), (n * svt - sv * t1) / denom, jnp.nan)
            if fn == "deriv":
                return SeriesMatrix(labels, slope)
            horizon = _scalar_of(self._eval(call.args[1], p, ctx))
            intercept = (sv - slope * t1) / jnp.maximum(n, 1)
            now_r = jnp.asarray(p.times)[None, :] - p.start
            return SeriesMatrix(labels, intercept + slope * (now_r + horizon))

        # *_over_time family
        stat_map = {
            "avg_over_time": ("sum", "count"), "sum_over_time": ("sum", "count"),
            "count_over_time": ("count",), "present_over_time": ("count",),
            "min_over_time": ("min", "count"), "max_over_time": ("max", "count"),
            "last_over_time": ("count", "last"),
            "stddev_over_time": ("sum", "count"), "stdvar_over_time": ("sum", "count"),
        }
        extra = ()
        if fn in ("stddev_over_time", "stdvar_over_time"):
            extra = ("sq",)
        stats = stat_map[fn]
        if fn in ("stddev_over_time", "stdvar_over_time"):
            r = self._range_stats_sq(sel, p, ctx)
        else:
            r = self._range_stats(sel, p, ctx, stats, extra)
        if r is None:
            return SeriesMatrix([], jnp.zeros((0, p.T)))
        st, labels, metric, w, range_s = r
        cnt = st["count"][:, :, 0]
        present = cnt > 0
        if fn == "sum_over_time":
            out = jnp.where(present, st["sum"][:, :, 0], jnp.nan)
        elif fn == "avg_over_time":
            out = jnp.where(present, st["sum"][:, :, 0] / jnp.maximum(cnt, 1), jnp.nan)
        elif fn in ("count_over_time",):
            out = jnp.where(present, cnt.astype(jnp.float64), jnp.nan)
        elif fn == "present_over_time":
            out = jnp.where(present, 1.0, jnp.nan)
        elif fn == "min_over_time":
            out = st["min"][:, :, 0]
        elif fn == "max_over_time":
            out = st["max"][:, :, 0]
        elif fn == "last_over_time":
            out = st["last"][:, :, 0]
        elif fn in ("stddev_over_time", "stdvar_over_time"):
            s, sq = st["sum"][:, :, 0], st["sum"][:, :, 1]
            n = jnp.maximum(cnt.astype(jnp.float64), 1)
            var = jnp.maximum(sq / n - (s / n) ** 2, 0.0)  # population, like PromQL
            out = jnp.where(present, jnp.sqrt(var) if fn == "stddev_over_time" else var, jnp.nan)
        return SeriesMatrix(labels, out)

    def _histogram_quantile(self, call: Call, p: EvalParams, ctx):
        """φ-quantile over `le`-bucketed classic histograms (reference
        extension_plan/histogram_fold.rs:61: group by labels-minus-le,
        cumulative buckets, linear interpolation within the bucket)."""
        phi = _scalar_of(self._eval(call.args[0], p, ctx))
        v = self._eval(call.args[1], p, ctx)
        if not isinstance(v, SeriesMatrix):
            raise PromqlError("histogram_quantile needs an instant vector")
        groups: dict[tuple, list[tuple[float, int]]] = {}
        glabels: dict[tuple, dict] = {}
        for i, lab in enumerate(v.labels):
            le_s = lab.get("le")
            if le_s is None:
                continue
            try:
                le = float(le_s.replace("+Inf", "inf")) \
                    if isinstance(le_s, str) else float(le_s)
            except ValueError:
                continue
            rest = {k: x for k, x in lab.items() if k != "le"}
            sig = tuple(sorted(rest.items()))
            groups.setdefault(sig, []).append((le, i))
            glabels[sig] = rest
        if not groups:
            return SeriesMatrix([], jnp.zeros((0, p.T)))
        out_labels, outs = [], []
        vals = v.values
        for sig, buckets in sorted(groups.items()):
            buckets.sort()
            les = np.asarray([b[0] for b in buckets])
            idx = np.asarray([b[1] for b in buckets])
            if not np.isinf(les[-1]):
                # no +Inf bucket: quantile undefined (Prometheus -> NaN)
                out_labels.append(glabels[sig])
                outs.append(jnp.full(p.T, jnp.nan))
                continue
            counts = vals[idx]  # [B, T] cumulative by construction
            # enforce monotonicity like Prometheus (scrape races)
            counts = jax.lax.cummax(jnp.nan_to_num(counts), axis=0)
            total = counts[-1]
            rank = phi * total
            # first bucket whose cumulative count reaches the rank
            reached = counts >= rank[None, :]
            b = jnp.argmax(reached, axis=0)
            B = len(les)
            d_les = jnp.asarray(les)
            upper = d_les[b]
            lower = jnp.where(b > 0, d_les[jnp.maximum(b - 1, 0)], 0.0)
            cum_prev = jnp.where(b > 0,
                                 jnp.take_along_axis(
                                     counts, jnp.maximum(b - 1, 0)[None, :],
                                     axis=0)[0], 0.0)
            cum_b = jnp.take_along_axis(counts, b[None, :], axis=0)[0]
            in_bucket = jnp.maximum(cum_b - cum_prev, 1e-300)
            frac = (rank - cum_prev) / in_bucket
            interp = lower + (upper - lower) * jnp.clip(frac, 0.0, 1.0)
            # highest bucket (= +Inf): return the highest finite bound
            highest_finite = d_les[B - 2] if B >= 2 else jnp.nan
            res = jnp.where(b >= B - 1, highest_finite, interp)
            # first bucket with non-positive upper bound: no interpolation
            res = jnp.where((b == 0) & (upper <= 0), upper, res)
            res = jnp.where(total > 0, res, jnp.nan)
            if phi < 0:
                res = jnp.full(p.T, -jnp.inf)
            elif phi > 1:
                res = jnp.full(p.T, jnp.inf)
            elif math.isnan(phi):
                res = jnp.full(p.T, jnp.nan)
            out_labels.append(glabels[sig])
            outs.append(res)
        return SeriesMatrix(out_labels, jnp.stack(outs, axis=0))

    def _holt_winters(self, call: Call, sel, p: EvalParams, ctx):
        """Double exponential smoothing (reference functions/
        holt_winters.rs). Sequential per-window recurrence — evaluated on
        host over the loaded samples (windows are small; the scan itself
        still rides the device path)."""
        sf = _scalar_of(self._eval(call.args[1], p, ctx))
        tf = _scalar_of(self._eval(call.args[2], p, ctx))
        if not 0 < sf < 1 or not 0 < tf < 1:
            raise PromqlError("holt_winters factors must be in (0, 1)")
        range_s = sel.range_s
        if range_s is None:
            raise PromqlError(
                "holt_winters needs a range vector (metric[duration])")
        loaded = self._load_any(sel, p, ctx, window=range_s)
        if loaded is None:
            return SeriesMatrix([], jnp.zeros((0, p.T)))
        sidx, ts, chans, labels, metric = loaded
        sidx = np.asarray(sidx)
        ts = np.asarray(ts)
        vals = np.asarray(chans[:, 0])
        ok = ~np.isnan(vals)
        sidx, ts, vals = sidx[ok], ts[ok], vals[ok]
        S, T = len(labels), p.T
        out = np.full((S, T), np.nan)
        starts = np.searchsorted(sidx, np.arange(S))
        ends = np.searchsorted(sidx, np.arange(S), side="right")
        for s in range(S):
            s_ts = ts[starts[s]:ends[s]]
            s_v = vals[starts[s]:ends[s]]
            for j, t in enumerate(p.times):
                lo = np.searchsorted(s_ts, t - range_s, side="right")
                hi = np.searchsorted(s_ts, t, side="right")
                x = s_v[lo:hi]
                if len(x) < 2:
                    continue
                s0, b = x[0], x[1] - x[0]
                for i in range(1, len(x)):
                    s1 = sf * x[i] + (1 - sf) * (s0 + b)
                    b = tf * (s1 - s0) + (1 - tf) * b
                    s0 = s1
                out[s, j] = s0
        return SeriesMatrix(labels, jnp.asarray(out))

    def _range_stats_sq(self, sel, p, ctx):
        """Range stats with a squared-value channel (stddev/stdvar)."""
        range_s = sel.range_s
        w = int(round(range_s / p.step))
        loaded = self._load_any(sel, p, ctx, window=range_s)
        if loaded is None:
            return None
        sidx, ts, chans, labels, metric = loaded
        chans = jnp.concatenate([chans, chans[:, :1] ** 2], axis=1)
        st = window_stats(sidx, ts, chans, ~jnp.isnan(chans[:, 0]),
                          p.start, p.step, len(labels), p.T, w,
                          stats=("sum", "count"),
                          sorted_input=_sorted_ws())
        return st, labels, metric, w, range_s

    # ---- aggregation -------------------------------------------------------

    def _eval_aggregate(self, agg: Aggregate, p: EvalParams, ctx):
        v = self._eval(agg.expr, p, ctx)
        if not isinstance(v, SeriesMatrix):
            raise PromqlError(f"{agg.op} needs an instant vector")
        if v.num_series == 0:
            return SeriesMatrix([], jnp.zeros((0, p.T)))

        # group signatures
        sigs = []
        out_labels = []
        for lab in v.labels:
            if agg.by:
                kept = {k: lab.get(k, "") for k in agg.by if k in lab}
            elif agg.without:
                kept = {k: x for k, x in lab.items() if k not in agg.without}
            elif agg.grouping:
                kept = {}
            else:
                kept = {}
            sigs.append(tuple(sorted(kept.items())))
            out_labels.append(kept)
        uniq = sorted(set(sigs))
        gidx = np.asarray([uniq.index(s) for s in sigs], dtype=np.int32)
        G = len(uniq)
        glabels = [dict(u) for u in uniq]

        vals = v.values  # [S, T]
        if agg.op in ("sum", "avg", "min", "max", "count", "group",
                      "stddev", "stdvar"):
            ops = {
                "sum": ("sum",), "avg": ("sum", "count"),
                "min": ("min",), "max": ("max",),
                "count": ("count",), "group": ("count",),
                "stddev": ("sum", "sumsq", "count"),
                "stdvar": ("sum", "sumsq", "count"),
            }[agg.op]
            need = set(ops) | {"count"}
            st = segment_agg(vals, jnp.asarray(gidx),
                             jnp.ones(v.num_series, bool), G,
                             ops=tuple(sorted(need)))
            cnt = st["count"]
            present = cnt > 0
            if agg.op == "sum":
                out = jnp.where(present, st["sum"], jnp.nan)
            elif agg.op == "avg":
                out = jnp.where(present, st["sum"] / jnp.maximum(cnt, 1), jnp.nan)
            elif agg.op in ("min", "max"):
                out = st[agg.op]
            elif agg.op in ("count",):
                out = jnp.where(present, cnt.astype(jnp.float64), jnp.nan)
            elif agg.op == "group":
                out = jnp.where(present, 1.0, jnp.nan)
            else:  # stddev / stdvar (population)
                n = jnp.maximum(cnt.astype(jnp.float64), 1)
                var = jnp.maximum(st["sumsq"] / n - (st["sum"] / n) ** 2, 0.0)
                out = jnp.where(present, var if agg.op == "stdvar" else jnp.sqrt(var), jnp.nan)
            return SeriesMatrix(glabels, out)

        if agg.op in ("topk", "bottomk"):
            k = int(_scalar_of(self._eval(agg.param, p, ctx)))
            vv = vals if agg.op == "topk" else -vals
            filled = jnp.where(jnp.isnan(vv), -jnp.inf, vv)
            keep = jnp.zeros(vals.shape, bool)
            for g in range(G):
                rows = np.flatnonzero(gidx == g)
                sub = filled[rows]
                kk = min(k, len(rows))
                thresh = -jnp.sort(-sub, axis=0)[kk - 1]
                keep = keep.at[rows].set(sub >= thresh[None, :])
            out = jnp.where(keep & ~jnp.isnan(vals), vals, jnp.nan)
            return SeriesMatrix(v.labels, out, v.metric)

        if agg.op == "quantile":
            q = _scalar_of(self._eval(agg.param, p, ctx))
            outs = []
            for g in range(G):
                rows = np.flatnonzero(gidx == g)
                outs.append(jnp.nanquantile(vals[rows], q, axis=0))
            return SeriesMatrix(glabels, jnp.stack(outs, axis=0))

        if agg.op == "count_values":
            if not isinstance(agg.param, StringLiteral):
                raise PromqlError(
                    "count_values needs a string label parameter")
            label_name = agg.param.value
            vn = np.asarray(vals, dtype=np.float64)  # [S, T]
            S, T = vn.shape
            valid = ~np.isnan(vn)
            # sparse factorization: memory stays O(samples + series*T),
            # never a dense [G, D, T] cube (near-unique float values make
            # D ~ S*T)
            distinct, inv = np.unique(vn[valid], return_inverse=True)
            D = len(distinct)
            if D == 0:
                return SeriesMatrix([], jnp.zeros((0, p.T)))
            srow, scol = np.nonzero(valid)
            key = (gidx[srow].astype(np.int64) * D + inv) * T + scol
            uk, uc = np.unique(key, return_counts=True)
            gd = uk // T
            col = (uk % T).astype(np.int64)
            pairs, pair_inv = np.unique(gd, return_inverse=True)
            rows_m = np.full((len(pairs), T), np.nan)
            rows_m[pair_inv, col] = uc.astype(np.float64)
            out_labels2 = []
            for pair in pairs:
                lab = dict(glabels[int(pair // D)])
                lab[label_name] = _fmt_prom_value(float(distinct[pair % D]))
                out_labels2.append(lab)
            return SeriesMatrix(out_labels2, jnp.asarray(rows_m))

        raise PromqlError(f"unsupported aggregation {agg.op!r}")

    # ---- binary ops --------------------------------------------------------

    def _eval_binary(self, node: Binary, p: EvalParams, ctx):
        lhs = self._eval(node.lhs, p, ctx)
        rhs = self._eval(node.rhs, p, ctx)
        lv = isinstance(lhs, SeriesMatrix)
        rv = isinstance(rhs, SeriesMatrix)

        if node.op in ("and", "or", "unless"):
            if not (lv and rv):
                raise PromqlError(f"{node.op} needs vector operands")
            return _set_op(node, lhs, rhs, p)

        if not lv and not rv:
            a, b = _broadcast_scalar(lhs, p), _broadcast_scalar(rhs, p)
            out = _apply_op(node.op, a, b)
            if node.op in _CMP and not node.bool_mod:
                out = jnp.where(out != 0, a, jnp.nan)
            return out
        if lv and not rv:
            b = _broadcast_scalar(rhs, p)
            out = _apply_op(node.op, lhs.values, b[None, :])
            if node.op in _CMP:
                out = (out.astype(jnp.float64) if node.bool_mod
                       else jnp.where(out, lhs.values, jnp.nan))
            return SeriesMatrix(_strip(lhs.labels) if node.op not in _CMP or node.bool_mod else lhs.labels, out)
        if rv and not lv:
            a = _broadcast_scalar(lhs, p)
            out = _apply_op(node.op, a[None, :], rhs.values)
            if node.op in _CMP:
                out = (out.astype(jnp.float64) if node.bool_mod
                       else jnp.where(out, rhs.values, jnp.nan))
            return SeriesMatrix(_strip(rhs.labels) if node.op not in _CMP or node.bool_mod else rhs.labels, out)

        # vector-vector: join on signature
        lsig = [_signature(l, node) for l in lhs.labels]
        rsig = {_signature(l, node): i for i, l in enumerate(rhs.labels)}
        li, ri, labels = [], [], []
        for i, s in enumerate(lsig):
            j = rsig.get(s)
            if j is not None:
                li.append(i)
                ri.append(j)
                labels.append(_strip([lhs.labels[i]])[0] if not node.group_left
                              else lhs.labels[i])
        if not li:
            return SeriesMatrix([], jnp.zeros((0, p.T)))
        a = lhs.values[np.asarray(li)]
        b = rhs.values[np.asarray(ri)]
        out = _apply_op(node.op, a, b)
        if node.op in _CMP:
            out = out.astype(jnp.float64) if node.bool_mod else jnp.where(out, a, jnp.nan)
        return SeriesMatrix(labels, out)

    # ---- label functions ---------------------------------------------------

    def _label_replace(self, call: Call, p, ctx):
        v = self._eval(call.args[0], p, ctx)
        dst, repl, src, regex = (_string_of(a) for a in call.args[1:5])
        rx = re.compile(regex)
        labels = []
        for lab in v.labels:
            m = rx.fullmatch(lab.get(src, ""))
            lab = dict(lab)
            if m is not None:
                val = m.expand(repl.replace("$", "\\")) if "$" in repl else repl
                if val:
                    lab[dst] = val
                else:
                    lab.pop(dst, None)
            labels.append(lab)
        return SeriesMatrix(labels, v.values, v.metric, v.sample_ts)

    def _label_join(self, call: Call, p, ctx):
        v = self._eval(call.args[0], p, ctx)
        dst = _string_of(call.args[1])
        sep = _string_of(call.args[2])
        srcs = [_string_of(a) for a in call.args[3:]]
        labels = []
        for lab in v.labels:
            lab = dict(lab)
            lab[dst] = sep.join(lab.get(s, "") for s in srcs)
            labels.append(lab)
        return SeriesMatrix(labels, v.values, v.metric, v.sample_ts)


# ---- helpers ---------------------------------------------------------------

_CMP = {"==", "!=", "<", "<=", ">", ">="}


def _apply_op(op, a, b):
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.fmod(a, b)
    if op == "^":
        return jnp.power(a, b)
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise PromqlError(f"unknown operator {op}")


def _set_op(node: Binary, lhs: SeriesMatrix, rhs: SeriesMatrix, p: EvalParams):
    lsig = [_signature(l, node) for l in lhs.labels]
    rsigs = {_signature(l, node) for l in rhs.labels}
    if node.op == "and":
        keep = [i for i, s in enumerate(lsig) if s in rsigs]
        idx = np.asarray(keep, dtype=np.int64)
        # also require rhs sample present at t
        rmap = {_signature(l, node): i for i, l in enumerate(rhs.labels)}
        rsel = np.asarray([rmap[lsig[i]] for i in keep], dtype=np.int64)
        vals = jnp.where(~jnp.isnan(rhs.values[rsel]), lhs.values[idx], jnp.nan) \
            if keep else jnp.zeros((0, p.T))
        return SeriesMatrix([lhs.labels[i] for i in keep], vals, lhs.metric)
    if node.op == "unless":
        rmap = {_signature(l, node): i for i, l in enumerate(rhs.labels)}
        vals_list, labels = [], []
        for i, s in enumerate(lsig):
            j = rmap.get(s)
            if j is None:
                vals_list.append(lhs.values[i])
            else:
                vals_list.append(jnp.where(jnp.isnan(rhs.values[j]),
                                           lhs.values[i], jnp.nan))
            labels.append(lhs.labels[i])
        vals = jnp.stack(vals_list) if vals_list else jnp.zeros((0, p.T))
        return SeriesMatrix(labels, vals, lhs.metric)
    # or: lhs plus rhs series whose signature isn't in lhs
    lsigs = set(lsig)
    extra = [i for i, l in enumerate(rhs.labels)
             if _signature(l, node) not in lsigs]
    labels = list(lhs.labels) + [rhs.labels[i] for i in extra]
    vals = jnp.concatenate([lhs.values, rhs.values[np.asarray(extra, dtype=np.int64)]]
                           ) if extra else lhs.values
    return SeriesMatrix(labels, vals, lhs.metric)


def _signature(lab: dict, node: Binary) -> tuple:
    if node.on:
        return tuple((k, lab.get(k, "")) for k in node.on)
    items = {k: v for k, v in lab.items()}
    if node.ignoring:
        for k in node.ignoring:
            items.pop(k, None)
    return tuple(sorted(items.items()))


def _strip(labels: list[dict]) -> list[dict]:
    return [dict(l) for l in labels]


def _map_values(v, f):
    if isinstance(v, SeriesMatrix):
        return SeriesMatrix(v.labels, f(v.values))
    if isinstance(v, (int, float)):
        return f(jnp.asarray(v)).item() if False else float(f(jnp.asarray(float(v))))
    return f(v)


def _broadcast_scalar(v, p: EvalParams):
    if isinstance(v, SeriesMatrix):
        raise PromqlError("expected a scalar")
    if isinstance(v, (int, float)):
        return jnp.full(p.T, float(v))
    return jnp.asarray(v)


def _scalar_of(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    arr = np.asarray(v)
    return float(arr.reshape(-1)[0])


def _string_of(node) -> str:
    if isinstance(node, StringLiteral):
        return node.value
    raise PromqlError("expected a string literal")


def _absent_labels(node) -> dict:
    """Prometheus derives absent()'s output labels from the selector's
    equality matchers."""
    sel = node
    if isinstance(sel, Subquery):
        sel = sel.expr
    if isinstance(sel, VectorSelector):
        return {m.label: m.value for m in sel.matchers
                if m.op == "=" and m.label not in ("__name__", "__field__")}
    return {}


def _sorted_ws() -> bool:
    """Bucketization flavor for window_stats: XLA lowers scatter-adds
    fine on CPU (measured 2.8x faster than the searchsorted/cumsum path
    at 9.6M samples), but on TPU scatters serialize row-by-row — there
    the sorted-input boundary path wins. Inputs are (series, ts)-sorted
    either way (_load lexsorts)."""
    import jax

    return jax.default_backend() in ("tpu", "axon")


def _edges_enabled() -> bool:
    """Rate-family boundary evaluation (window_edges). On by default;
    =off pins the dense window_stats path (differential debugging)."""
    import os

    return os.environ.get("GREPTIMEDB_TPU_PROMQL_EDGES",
                          "on").lower() not in ("off", "0", "false")


def _matcher_mask(m: Matcher, scan, tag_names) -> np.ndarray:
    """Row mask for one label matcher, via the tag dictionary."""
    if m.label not in tag_names:
        # missing label behaves as empty string
        empty_match = (m.op == "=" and m.value == "") or \
            (m.op == "!=" and m.value != "") or \
            (m.op == "=~" and re.fullmatch(m.value, "") is not None) or \
            (m.op == "!~" and re.fullmatch(m.value, "") is None)
        return np.ones(scan.num_rows, bool) if empty_match else np.zeros(scan.num_rows, bool)
    codes = scan.columns[m.label]
    values = scan.tag_dicts[m.label]
    lut = np.zeros(len(values) + 1, dtype=bool)  # slot -1 -> last (empty)
    if m.op == "=":
        lut[:-1] = values == m.value if len(values) else False
        lut[-1] = m.value == ""
    elif m.op == "!=":
        lut[:-1] = values != m.value
        lut[-1] = m.value != ""
    else:
        rx = re.compile(m.value)
        hits = np.asarray([rx.fullmatch(str(x)) is not None for x in values], dtype=bool) \
            if len(values) else np.zeros(0, bool)
        empty_hit = rx.fullmatch("") is not None
        if m.op == "=~":
            lut[:-1] = hits
            lut[-1] = empty_hit
        else:
            lut[:-1] = ~hits
            lut[-1] = not empty_hit
    return lut[codes]


def _to_long_result(times: np.ndarray, result) -> QueryResult:
    """Matrix -> long-format table (tags..., ts, value), NaN cells dropped
    (matches the reference's TQL tabular output)."""
    if not isinstance(result, SeriesMatrix):
        arr = np.asarray(_broadcast_with(times, result))
        ts_ms = (times * 1000).astype(np.int64)
        return QueryResult(["ts", "value"],
                           [DataType.TIMESTAMP_MILLISECOND, DataType.FLOAT64],
                           [ts_ms, arr])
    vals = np.asarray(result.values)
    S, T = vals.shape if vals.size else (0, len(times))
    label_keys = sorted({k for lab in result.labels for k in lab})
    ts_ms = (times * 1000).astype(np.int64)
    rows_ts, rows_val = [], []
    rows_labels = {k: [] for k in label_keys}
    for s in range(S):
        present = ~np.isnan(vals[s])
        n = int(present.sum())
        if n == 0:
            continue
        rows_ts.append(ts_ms[present])
        rows_val.append(vals[s][present])
        for k in label_keys:
            rows_labels[k].append(np.full(n, result.labels[s].get(k), dtype=object))
    if rows_ts:
        ts_col = np.concatenate(rows_ts)
        val_col = np.concatenate(rows_val)
        lab_cols = {k: np.concatenate(v) for k, v in rows_labels.items()}
    else:
        ts_col = np.empty(0, np.int64)
        val_col = np.empty(0)
        lab_cols = {k: np.empty(0, object) for k in label_keys}
    names = label_keys + ["ts", "value"]
    dtypes = [DataType.STRING] * len(label_keys) + \
        [DataType.TIMESTAMP_MILLISECOND, DataType.FLOAT64]
    cols = [lab_cols[k] for k in label_keys] + [ts_col, val_col]
    return QueryResult(names, dtypes, cols)


def _broadcast_with(times, v):
    if isinstance(v, (int, float)):
        return np.full(len(times), float(v))
    return np.asarray(v)
