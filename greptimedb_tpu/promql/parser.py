"""PromQL parser (the reference links the promql-parser crate; here a
hand-written tokenizer + pratt parser covering the language surface the
reference's planner handles: selectors with matchers, range vectors,
offset, binary ops with bool/on/ignoring/group_left modifiers,
aggregations with by/without, functions, subqueries `expr[range:step]`).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

DEFAULT_LOOKBACK_S = 300.0  # 5m, reference InstantManipulate lookback


class PromqlError(Exception):
    pass


# ---- AST -------------------------------------------------------------------


@dataclass(frozen=True)
class Matcher:
    label: str
    op: str  # = != =~ !~
    value: str


@dataclass(frozen=True)
class VectorSelector:
    metric: Optional[str]
    matchers: tuple[Matcher, ...] = ()
    range_s: Optional[float] = None  # set -> range vector
    offset_s: float = 0.0
    at_s: Optional[float] = None


@dataclass(frozen=True)
class NumberLiteral:
    value: float


@dataclass(frozen=True)
class StringLiteral:
    value: str


@dataclass(frozen=True)
class Call:
    func: str
    args: tuple = ()


@dataclass(frozen=True)
class Aggregate:
    op: str  # sum avg min max count topk bottomk quantile stddev stdvar count_values group
    expr: object
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()
    grouping: bool = False  # True if by/without present
    param: object = None  # k for topk, q for quantile


@dataclass(frozen=True)
class Binary:
    op: str
    lhs: object
    rhs: object
    bool_mod: bool = False
    on: Optional[tuple[str, ...]] = None
    ignoring: Optional[tuple[str, ...]] = None
    group_left: bool = False
    group_right: bool = False


@dataclass(frozen=True)
class Unary:
    op: str
    expr: object


@dataclass(frozen=True)
class Subquery:
    """`expr[range:step]` — inner expr evaluated on its own grid, then
    consumed like a range vector (reference planner subquery support)."""

    expr: object
    range_s: float
    step_s: Optional[float] = None  # None -> outer eval step
    offset_s: float = 0.0


AGG_OPS = {"sum", "avg", "min", "max", "count", "topk", "bottomk", "quantile",
           "stddev", "stdvar", "group", "count_values"}

# ---- lexer -----------------------------------------------------------------

_TOK = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*)
  | (?P<duration>\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y)(?:\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y))*)
  | (?P<number>0x[0-9a-fA-F]+|(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|[iI][nN][fF](?![a-zA-Z0-9_:.])|[nN][aA][nN](?![a-zA-Z0-9_:.]))
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<ident>[a-zA-Z_:][a-zA-Z0-9_:.]*)
  | (?P<op>=~|!~|!=|==|<=|>=|[-+*/%^(){}\[\],=<>@])
    """,
    re.VERBOSE,
)

_DUR_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
              "d": 86400.0, "w": 604800.0, "y": 31536000.0}
_DUR_PART = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)")


def parse_duration_s(text: str) -> float:
    total = 0.0
    pos = 0
    for m in _DUR_PART.finditer(text):
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(text) or total == 0 and text not in ("0s", "0ms"):
        if pos != len(text):
            raise PromqlError(f"bad duration {text!r}")
    return total


@dataclass
class Tok:
    kind: str
    value: str


def _tokenize(q: str) -> list[Tok]:
    out = []
    pos = 0
    while pos < len(q):
        m = _TOK.match(q, pos)
        if not m:
            raise PromqlError(f"unexpected character {q[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "string":
            text = _unescape(text[1:-1])
        out.append(Tok(kind, text))
    out.append(Tok("eof", ""))
    return out


def _unescape(s: str) -> str:
    return s.encode().decode("unicode_escape")


# ---- parser ----------------------------------------------------------------

_PRECEDENCE = {
    "or": 1, "unless": 2, "and": 2,
    "==": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3,
    "+": 4, "-": 4, "*": 5, "/": 5, "%": 5, "^": 6,
}


class _Parser:
    def __init__(self, q: str):
        self.toks = _tokenize(q)
        self.i = 0

    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def eat(self, kind: str, value: Optional[str] = None) -> bool:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            self.next()
            return True
        return False

    def expect(self, kind: str, value: Optional[str] = None) -> Tok:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            raise PromqlError(f"expected {value or kind}, got {t.kind}:{t.value}")
        return t

    def parse(self):
        e = self.parse_expr(0)
        if self.peek().kind != "eof":
            t = self.peek()
            raise PromqlError(f"unexpected trailing {t.kind}:{t.value}")
        return e

    def parse_expr(self, min_prec: int):
        lhs = self.parse_unary()
        while True:
            t = self.peek()
            op = t.value if t.kind in ("op", "ident") else None
            if op not in _PRECEDENCE or _PRECEDENCE[op] < min_prec:
                return lhs
            self.next()
            bool_mod = False
            on = ignoring = None
            gl = gr = False
            if self.peek().kind == "ident" and self.peek().value == "bool":
                self.next()
                bool_mod = True
            if self.peek().kind == "ident" and self.peek().value in ("on", "ignoring"):
                kw = self.next().value
                labels = self._label_list()
                if kw == "on":
                    on = labels
                else:
                    ignoring = labels
                if self.peek().kind == "ident" and self.peek().value in ("group_left", "group_right"):
                    kw2 = self.next().value
                    if self.eat("op", "("):
                        while not self.eat("op", ")"):
                            self.next()
                    gl, gr = kw2 == "group_left", kw2 == "group_right"
            prec = _PRECEDENCE[op]
            # ^ is right-associative
            rhs = self.parse_expr(prec if op == "^" else prec + 1)
            lhs = Binary(op, lhs, rhs, bool_mod, on, ignoring, gl, gr)

    def parse_unary(self):
        if self.eat("op", "-"):
            return Unary("-", self.parse_unary())
        if self.eat("op", "+"):
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value == "[":
                self.next()
                dur = self.expect("duration").value
                step = self._subquery_step()
                self.expect("op", "]")
                if step is not None:
                    e = Subquery(e, parse_duration_s(dur), step[0])
                else:
                    if not isinstance(e, VectorSelector) or e.range_s is not None:
                        raise PromqlError("range modifier on non-selector")
                    e = VectorSelector(e.metric, e.matchers, parse_duration_s(dur),
                                       e.offset_s, e.at_s)
            elif t.kind == "ident" and t.value == "offset":
                self.next()
                neg = self.eat("op", "-")
                dur = parse_duration_s(self.expect("duration").value)
                if isinstance(e, Subquery):
                    e = Subquery(e.expr, e.range_s, e.step_s,
                                 (-dur if neg else dur))
                elif isinstance(e, VectorSelector):
                    e = VectorSelector(e.metric, e.matchers, e.range_s,
                                       (-dur if neg else dur), e.at_s)
                else:
                    raise PromqlError("offset on non-selector")
            elif t.kind == "op" and t.value == "@":
                self.next()
                if not isinstance(e, VectorSelector):
                    raise PromqlError(
                        "@ modifier is only supported on selectors "
                        "(not subqueries)")
                nt = self.peek()
                if nt.kind == "ident" and nt.value in ("start", "end"):
                    # @ start() / @ end() resolve to the query range's
                    # boundaries at eval time (Prometheus preprocessors)
                    self.next()
                    self.expect("op", "(")
                    self.expect("op", ")")
                    at = f"__{nt.value}__"
                else:
                    neg = self.eat("op", "-")
                    tok = self.expect("number").value.lower()
                    if tok.startswith("0x") or tok in ("inf", "nan"):
                        raise PromqlError(
                            f"@ needs a decimal timestamp, got {tok!r}")
                    at = float(tok) * (-1.0 if neg else 1.0)
                e = VectorSelector(e.metric, e.matchers, e.range_s, e.offset_s, at)
            else:
                return e

    def parse_primary(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = t.value.lower()
            if v.startswith("0x"):
                return NumberLiteral(float(int(v, 16)))
            if v == "inf":
                return NumberLiteral(float("inf"))
            if v == "nan":
                return NumberLiteral(float("nan"))
            return NumberLiteral(float(t.value))
        if t.kind == "duration":
            self.next()
            return NumberLiteral(parse_duration_s(t.value))
        if t.kind == "string":
            self.next()
            return StringLiteral(t.value)
        if t.kind == "op" and t.value == "(":
            self.next()
            e = self.parse_expr(0)
            self.expect("op", ")")
            return e
        if t.kind == "op" and t.value == "{":
            return self._selector(None)
        if t.kind == "ident":
            name = self.next().value
            if name in AGG_OPS:
                return self._aggregate(name)
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                args = []
                while not self.eat("op", ")"):
                    args.append(self.parse_expr(0))
                    self.eat("op", ",")
                return Call(name, tuple(args))
            return self._selector(name)
        raise PromqlError(f"unexpected token {t.kind}:{t.value}")

    def _subquery_step(self):
        """Inside `[dur ...`: detect the subquery `:step` part. The
        tokenizer folds a leading ':' into an ident (metric names may
        contain ':'), so ':1m' or ':' arrive as idents. Returns None when
        this is a plain range vector, else a 1-tuple holding the step
        (None = default resolution)."""
        t = self.peek()
        if t.kind != "ident" or not t.value.startswith(":"):
            return None
        self.next()
        rest = t.value[1:]
        if rest:
            return (parse_duration_s(rest),)
        if self.peek().kind == "duration":
            return (parse_duration_s(self.next().value),)
        return (None,)

    def _selector(self, metric: Optional[str]) -> VectorSelector:
        matchers: list[Matcher] = []
        if self.peek().kind == "op" and self.peek().value == "{":
            self.next()
            while not self.eat("op", "}"):
                label = self.expect("ident").value
                op_t = self.next()
                if op_t.value not in ("=", "!=", "=~", "!~"):
                    raise PromqlError(f"bad matcher op {op_t.value}")
                val = self.expect("string").value
                matchers.append(Matcher(label, op_t.value, val))
                self.eat("op", ",")
        if metric is None and not matchers:
            raise PromqlError("empty selector")
        return VectorSelector(metric, tuple(matchers))

    def _label_list(self) -> tuple[str, ...]:
        self.expect("op", "(")
        labels = []
        while not self.eat("op", ")"):
            labels.append(self.expect("ident").value)
            self.eat("op", ",")
        return tuple(labels)

    def _aggregate(self, op: str) -> Aggregate:
        by: tuple[str, ...] = ()
        without: tuple[str, ...] = ()
        grouping = False
        if self.peek().kind == "ident" and self.peek().value in ("by", "without"):
            kw = self.next().value
            labels = self._label_list()
            grouping = True
            if kw == "by":
                by = labels
            else:
                without = labels
        self.expect("op", "(")
        args = [self.parse_expr(0)]
        while self.eat("op", ","):
            args.append(self.parse_expr(0))
        self.expect("op", ")")
        if self.peek().kind == "ident" and self.peek().value in ("by", "without"):
            kw = self.next().value
            labels = self._label_list()
            grouping = True
            if kw == "by":
                by = labels
            else:
                without = labels
        param = None
        expr = args[-1]
        if len(args) == 2:
            param = args[0]
        elif len(args) > 2:
            raise PromqlError(f"{op} takes at most 2 args")
        return Aggregate(op, expr, by, without, grouping, param)


def parse_promql(q: str):
    return _Parser(q).parse()
