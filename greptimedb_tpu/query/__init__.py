"""Query engine (mirrors reference src/query + src/operator).

SQL/PromQL parse into one logical plan algebra (reference
QueryStatement::{Sql, Promql}, query/src/parser.rs:46-48); physical
execution composes jit-compiled device kernels over padded column blocks:
filter masks -> group ids -> segment reductions, with host numpy only at
the edges (result assembly, ORDER BY over group counts).
"""

from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.query.result import QueryResult

__all__ = ["QueryEngine", "QueryResult"]
