"""Query engine (mirrors reference src/query + src/operator).

SQL/PromQL parse into one logical plan algebra (reference
QueryStatement::{Sql, Promql}, query/src/parser.rs:46-48); physical
execution composes jit-compiled device kernels over padded column blocks:
filter masks -> group ids -> segment reductions, with host numpy only at
the edges (result assembly, ORDER BY over group counts).

`QueryEngine` is exported lazily (PEP 562): importing a light sibling
like `query.result` (the Flight server needs only the QueryResult
container) must NOT execute `query.engine` — that module pulls jax and
the whole kernel stack, which a storage-only datanode process never
needs (gtpu-lint `jax-import` guards this).
"""

from greptimedb_tpu.query.result import QueryResult

__all__ = ["QueryEngine", "QueryResult"]


def __getattr__(name: str):
    if name == "QueryEngine":
        from greptimedb_tpu.query.engine import QueryEngine

        return QueryEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
