"""Device columnar hot set: the HBM-resident analog of the reference's
page cache (mito2/src/cache.rs:53-61 + write/file caches).

The reference amortizes repeated scans through an in-memory parquet page
cache; on TPU the equivalent currency is *device-resident column blocks* —
host->HBM transfer is the scan bottleneck (SURVEY.md §7 hard part #4).

Two classes of entry share one bytes-budgeted LRU:

- **file-anchored** (keys ``("file", region_id, file_id, ...)``): column
  blocks of an immutable SST part. These stay pinned across queries AND
  data versions — a flush only uploads its new file; the old files' HBM
  blocks keep serving. They die with their file, driven by the exact
  same seams that kill the host part cache (compaction swap, retention
  expiry, DROP/TRUNCATE): storage/region.py calls `invalidate_files`
  whenever it drops decoded parts.
- **snapshot-anchored** (keys ``("snap", region_id, data_version, ...)``):
  anything whose rows move with the memtable (memtable tail blocks,
  whole-scan sparse/sharded arrays, synthetic reduced scans). A newer
  data version evicts the region's older snapshot generation on insert,
  so live ingest cannot strand dead uploads in HBM.

Upload/compute overlap: `prefetch(key, build)` schedules the NEXT
block's host-side build (pad + cast + H2D dispatch) on a single
background worker while the caller consumes the current one — double
buffering, so cold dense aggregation approaches max(host build, device
work) instead of their sum. A later `get` joins the in-flight build;
the cumulative hit ratio lands on the
greptimedb_tpu_scan_pipeline_overlap gauge.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable

import jax

from greptimedb_tpu import config
from greptimedb_tpu.utils import device_telemetry
from greptimedb_tpu.utils import ledger
from greptimedb_tpu.utils.metrics import (
    DEVICE_CACHE_EVENTS,
    DEVICE_HOT_SET_BYTES,
    DEVICE_HOT_SET_EVENTS,
    SCAN_PIPELINE_OVERLAP,
)

#: live DeviceCache instances — the storage layer's invalidation seams
#: reach every executor's hot set through the module-level functions
#: below (region.py looks this module up in sys.modules so a pure
#: storage process never imports jax for it)
_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def invalidate_files(region_id: int, file_ids) -> None:
    """Drop file-anchored hot-set entries for removed SSTs — called from
    the same region seams that drop host part-cache entries."""
    for cache in list(_CACHES):
        cache.invalidate_files(region_id, file_ids)


def invalidate_region(region_id: int) -> None:
    for cache in list(_CACHES):
        cache.invalidate_region(region_id)


def upload_prefetch_enabled() -> bool:
    """Double-buffered block upload knob ([scan] upload_prefetch /
    GREPTIMEDB_TPU_UPLOAD_PREFETCH); on by default."""
    return os.environ.get("GREPTIMEDB_TPU_UPLOAD_PREFETCH", "1") \
        not in ("0", "false", "off")


class DeviceCache:
    """Thread-safe: concurrent server threads (and the executor's
    background device warm-up) build/evict under one lock; `build`
    itself runs outside it, so duplicate concurrent builds are possible
    but accounting never double-counts (last writer wins)."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes if budget_bytes is not None else config.device_cache_bytes()
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # newest snapshot generation (data_version) seen per region:
        # snap-anchored entries of an older generation die on the first
        # newer insert instead of lingering until LRU pressure
        self._snap_gen: dict[int, int] = {}
        # tombstones for recently-invalidated files (region_id, file_id):
        # a build in flight when invalidate_files ran would otherwise
        # re-insert blocks for the dead file AFTER the drop — keys no
        # future scan can ever request, squatting on HBM budget until
        # unrelated LRU churn. Bounded ring; file ids are never reused.
        self._dead_files: OrderedDict[tuple, None] = OrderedDict()
        # snap keys need the same in-flight-build guard but data_versions
        # ARE reused (TRUNCATE resets them): a per-region epoch, bumped by
        # invalidate_region, is captured when a build starts and checked
        # at _store — a stale-epoch snap block never becomes resident,
        # so a pre-truncate upload can't serve once the recreated
        # region's data_version climbs back to the colliding value
        self._region_epoch: dict[int, int] = {}
        # double-buffer prefetch: in-flight background builds by key;
        # ONE worker on purpose — the pipeline is host-build of block
        # i+1 against consumption of block i, not a second fan-out
        self._inflight: dict[tuple, object] = {}
        self._prefetch_pool = None
        self.prefetch_issued = 0
        self.prefetch_joined = 0
        # scrape-time residency gauge sums _bytes over live caches
        device_telemetry.register_cache(self)
        _CACHES.add(self)

    @staticmethod
    def _is_file_key(key: tuple) -> bool:
        return len(key) >= 3 and key[0] == "file"

    @staticmethod
    def _is_snap_key(key: tuple) -> bool:
        return len(key) >= 3 and key[0] == "snap"

    def get(self, key: tuple, build: Callable[[], jax.Array],
            count_h2d: bool = True) -> jax.Array:
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                DEVICE_CACHE_EVENTS.inc(event="hit")
                DEVICE_HOT_SET_EVENTS.inc(event="hit")
                ledger.cache_event("device_hot_set", "hit")
                return hit
            fut = self._inflight.get(key)
        if fut is not None:
            from greptimedb_tpu.utils import deadline as dl

            try:
                arr = dl.wait_future(fut, "device prefetch join")
            except (dl.DeadlineExceeded, dl.Cancelled):
                raise  # typed unwind, not a failed prefetch
            except Exception:  # noqa: BLE001 — prefetch is best-effort
                arr = None
            if arr is not None:
                # a joined prefetch is NOT a miss: the upload happened,
                # just off-thread — counting it as one would make
                # steady-state double buffering read as a broken cache
                DEVICE_CACHE_EVENTS.inc(event="prefetch_join")
                with self._lock:
                    self.prefetch_joined += 1
                    issued = self.prefetch_issued
                    joined = self.prefetch_joined
                SCAN_PIPELINE_OVERLAP.set(joined / max(issued, 1))
                return arr
        with self._lock:
            self.misses += 1
            epoch = self._key_epoch_locked(key)
        DEVICE_CACHE_EVENTS.inc(event="miss")
        DEVICE_HOT_SET_EVENTS.inc(event="miss")
        ledger.cache_event("device_hot_set", "miss")
        arr = build()
        # a cache-miss build materializes the block on device: that IS
        # the H2D upload this cache exists to amortize. count_h2d=False
        # is for DERIVED entries (e.g. a mesh shard buffer concatenated
        # on-device from already-resident segment uploads) whose build
        # moves no bytes over the link itself.
        if count_h2d:
            device_telemetry.count_h2d(arr.nbytes)
        self._store(key, arr, epoch=epoch)
        return arr

    def prefetch(self, key: tuple, build: Callable[[], jax.Array]) -> None:
        """Schedule `build` on the background worker so a later `get`
        finds the block resident (or joins the in-flight build). No-op
        when the key is already cached or being built."""
        with self._lock:
            if key in self._lru or key in self._inflight:
                return
            if self._prefetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="gtpu-hbm-prefetch")
            self.prefetch_issued += 1
            epoch = self._key_epoch_locked(key)
            self._inflight[key] = self._prefetch_pool.submit(
                self._build_prefetched, key, build, epoch)

    def _build_prefetched(self, key: tuple, build, epoch):
        try:
            arr = build()
            device_telemetry.count_h2d(arr.nbytes)
            self._store(key, arr, epoch=epoch)
            return arr
        finally:
            with self._lock:
                self._inflight.pop(key, None)

    def _key_epoch_locked(self, key: tuple):
        """Region epoch a snap-key build starts under (None for other
        keys); caller holds the lock."""
        if self._is_snap_key(key):
            return self._region_epoch.get(key[1], 0)
        return None

    def _store(self, key: tuple, arr, epoch=None) -> None:
        nbytes = arr.nbytes
        if nbytes > self.budget:
            return
        evictions = 0
        pin = False
        with self._lock:
            if (self._is_file_key(key)
                    and (key[1], key[2]) in self._dead_files):
                # the file died while this block was building: serve the
                # caller's array (its scan pinned the file) but never
                # let the dead key into residency
                return
            if self._is_snap_key(key):
                region, version = key[1], key[2]
                if (epoch is not None
                        and self._region_epoch.get(region, 0) != epoch):
                    # the region was invalidated (TRUNCATE/DROP) while
                    # this block was building: serve the caller's array
                    # but never let the pre-invalidation snapshot into
                    # residency — its data_version may recur post-reset
                    return
                gen = self._snap_gen.get(region)
                if gen is not None and version < gen:
                    # an in-flight build for an already-retired
                    # generation landing late: no future scan can
                    # request this key — refuse, don't squat HBM
                    return
                if gen is None or version > gen:
                    # a newer snapshot generation retires the older one:
                    # those uploads can never be referenced again
                    if gen is not None:
                        evictions += self._drop_locked(
                            lambda k: self._is_snap_key(k)
                            and k[1] == region and k[2] < version)
                    self._snap_gen[region] = version
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            elif self._is_file_key(key):
                pin = True
            self._lru[key] = arr
            self._bytes += nbytes
            while self._bytes > self.budget and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._bytes -= evicted.nbytes
                evictions += 1
            DEVICE_HOT_SET_BYTES.set(float(self._bytes))
        if pin:
            DEVICE_HOT_SET_EVENTS.inc(event="pin")
        if evictions:
            DEVICE_CACHE_EVENTS.inc(float(evictions), event="evict")
            DEVICE_HOT_SET_EVENTS.inc(float(evictions), event="evict")

    def _drop_locked(self, pred) -> int:
        """Remove entries matching `pred(key)`; caller holds the lock.
        Returns the count removed."""
        doomed = [k for k in self._lru if pred(k)]
        for k in doomed:
            arr = self._lru.pop(k)
            self._bytes -= arr.nbytes
        return len(doomed)

    #: dead-file tombstone ring bound — far above any live working set
    _DEAD_FILES_CAP = 4096

    def invalidate_files(self, region_id: int, file_ids) -> None:
        """Drop file-anchored entries for dead SSTs (compaction swap,
        retention expiry, DROP/TRUNCATE — the part-cache seams)."""
        gone = set(file_ids)
        with self._lock:
            for fid in gone:
                self._dead_files[(region_id, fid)] = None
                self._dead_files.move_to_end((region_id, fid))
            while len(self._dead_files) > self._DEAD_FILES_CAP:
                self._dead_files.popitem(last=False)
            n = self._drop_locked(
                lambda k: self._is_file_key(k) and k[1] == region_id
                and k[2] in gone)
            DEVICE_HOT_SET_BYTES.set(float(self._bytes))
        if n:
            DEVICE_HOT_SET_EVENTS.inc(float(n), event="evict")

    def invalidate_region(self, region_id: int) -> None:
        with self._lock:
            n = self._drop_locked(
                lambda k: len(k) >= 2 and k[0] in ("file", "snap")
                and k[1] == region_id)
            self._snap_gen.pop(region_id, None)
            self._region_epoch[region_id] = \
                self._region_epoch.get(region_id, 0) + 1
            DEVICE_HOT_SET_BYTES.set(float(self._bytes))
        if n:
            DEVICE_HOT_SET_EVENTS.inc(float(n), event="evict")

    def file_keys(self, region_id: int = None) -> list:
        """Resident file-anchored keys (diagnostics + tests)."""
        with self._lock:
            return [k for k in self._lru if self._is_file_key(k)
                    and (region_id is None or k[1] == region_id)]

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
            self._snap_gen.clear()
            DEVICE_HOT_SET_BYTES.set(0.0)
