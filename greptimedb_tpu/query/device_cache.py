"""Device block cache: the HBM-resident analog of the reference's page
cache (mito2/src/cache.rs:53-61 + write/file caches).

The reference amortizes repeated scans through an in-memory parquet page
cache; on TPU the equivalent currency is *device-resident column blocks* —
host->HBM transfer is the scan bottleneck (SURVEY.md §7 hard part #4), so
hot blocks stay pinned in HBM keyed by (region, data version, column,
block window, dtype). Any write/flush/compact bumps the region's data
version, so stale blocks simply stop being referenced and age out via LRU.

Upload/compute overlap: `prefetch(key, build)` schedules the NEXT
block's host-side build (pad + cast + H2D dispatch) on a single
background worker while the caller consumes the current one — double
buffering, so cold dense aggregation approaches max(host build, device
work) instead of their sum. A later `get` joins the in-flight build;
the cumulative hit ratio lands on the
greptimedb_tpu_scan_pipeline_overlap gauge.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable

import jax

from greptimedb_tpu import config
from greptimedb_tpu.utils import device_telemetry
from greptimedb_tpu.utils.metrics import (
    DEVICE_CACHE_EVENTS,
    SCAN_PIPELINE_OVERLAP,
)


def upload_prefetch_enabled() -> bool:
    """Double-buffered block upload knob ([scan] upload_prefetch /
    GREPTIMEDB_TPU_UPLOAD_PREFETCH); on by default."""
    return os.environ.get("GREPTIMEDB_TPU_UPLOAD_PREFETCH", "1") \
        not in ("0", "false", "off")


class DeviceCache:
    """Thread-safe: concurrent server threads (and the executor's
    background device warm-up) build/evict under one lock; `build`
    itself runs outside it, so duplicate concurrent builds are possible
    but accounting never double-counts (last writer wins)."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes if budget_bytes is not None else config.device_cache_bytes()
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # double-buffer prefetch: in-flight background builds by key;
        # ONE worker on purpose — the pipeline is host-build of block
        # i+1 against consumption of block i, not a second fan-out
        self._inflight: dict[tuple, object] = {}
        self._prefetch_pool = None
        self.prefetch_issued = 0
        self.prefetch_joined = 0
        # scrape-time residency gauge sums _bytes over live caches
        device_telemetry.register_cache(self)

    def get(self, key: tuple, build: Callable[[], jax.Array]) -> jax.Array:
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                DEVICE_CACHE_EVENTS.inc(event="hit")
                return hit
            fut = self._inflight.get(key)
        if fut is not None:
            try:
                arr = fut.result()
            except Exception:  # noqa: BLE001 — prefetch is best-effort
                arr = None
            if arr is not None:
                # a joined prefetch is NOT a miss: the upload happened,
                # just off-thread — counting it as one would make
                # steady-state double buffering read as a broken cache
                DEVICE_CACHE_EVENTS.inc(event="prefetch_join")
                with self._lock:
                    self.prefetch_joined += 1
                    issued = self.prefetch_issued
                    joined = self.prefetch_joined
                SCAN_PIPELINE_OVERLAP.set(joined / max(issued, 1))
                return arr
        with self._lock:
            self.misses += 1
        DEVICE_CACHE_EVENTS.inc(event="miss")
        arr = build()
        # a cache-miss build materializes the block on device: that IS
        # the H2D upload this cache exists to amortize
        device_telemetry.count_h2d(arr.nbytes)
        self._store(key, arr)
        return arr

    def prefetch(self, key: tuple, build: Callable[[], jax.Array]) -> None:
        """Schedule `build` on the background worker so a later `get`
        finds the block resident (or joins the in-flight build). No-op
        when the key is already cached or being built."""
        with self._lock:
            if key in self._lru or key in self._inflight:
                return
            if self._prefetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="gtpu-hbm-prefetch")
            self.prefetch_issued += 1
            self._inflight[key] = self._prefetch_pool.submit(
                self._build_prefetched, key, build)

    def _build_prefetched(self, key: tuple, build):
        try:
            arr = build()
            device_telemetry.count_h2d(arr.nbytes)
            self._store(key, arr)
            return arr
        finally:
            with self._lock:
                self._inflight.pop(key, None)

    def _store(self, key: tuple, arr) -> None:
        nbytes = arr.nbytes
        if nbytes > self.budget:
            return
        evictions = 0
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._lru[key] = arr
            self._bytes += nbytes
            while self._bytes > self.budget and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._bytes -= evicted.nbytes
                evictions += 1
        if evictions:
            DEVICE_CACHE_EVENTS.inc(float(evictions), event="evict")

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
