"""Device block cache: the HBM-resident analog of the reference's page
cache (mito2/src/cache.rs:53-61 + write/file caches).

The reference amortizes repeated scans through an in-memory parquet page
cache; on TPU the equivalent currency is *device-resident column blocks* —
host->HBM transfer is the scan bottleneck (SURVEY.md §7 hard part #4), so
hot blocks stay pinned in HBM keyed by (region, data version, column,
block window, dtype). Any write/flush/compact bumps the region's data
version, so stale blocks simply stop being referenced and age out via LRU.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

import jax

from greptimedb_tpu import config


class DeviceCache:
    """Thread-safe: concurrent server threads (and the executor's
    background device warm-up) build/evict under one lock; `build`
    itself runs outside it, so duplicate concurrent builds are possible
    but accounting never double-counts (last writer wins)."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes if budget_bytes is not None else config.device_cache_bytes()
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple, build: Callable[[], jax.Array]) -> jax.Array:
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                return hit
            self.misses += 1
        arr = build()
        nbytes = arr.nbytes
        if nbytes <= self.budget:
            with self._lock:
                old = self._lru.pop(key, None)
                if old is not None:
                    self._bytes -= old.nbytes
                self._lru[key] = arr
                self._bytes += nbytes
                while self._bytes > self.budget and self._lru:
                    _, evicted = self._lru.popitem(last=False)
                    self._bytes -= evicted.nbytes
        return arr

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
