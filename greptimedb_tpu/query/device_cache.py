"""Device block cache: the HBM-resident analog of the reference's page
cache (mito2/src/cache.rs:53-61 + write/file caches).

The reference amortizes repeated scans through an in-memory parquet page
cache; on TPU the equivalent currency is *device-resident column blocks* —
host->HBM transfer is the scan bottleneck (SURVEY.md §7 hard part #4), so
hot blocks stay pinned in HBM keyed by (region, data version, column,
block window, dtype). Any write/flush/compact bumps the region's data
version, so stale blocks simply stop being referenced and age out via LRU.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

import jax

from greptimedb_tpu import config
from greptimedb_tpu.utils import device_telemetry
from greptimedb_tpu.utils.metrics import DEVICE_CACHE_EVENTS


class DeviceCache:
    """Thread-safe: concurrent server threads (and the executor's
    background device warm-up) build/evict under one lock; `build`
    itself runs outside it, so duplicate concurrent builds are possible
    but accounting never double-counts (last writer wins)."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes if budget_bytes is not None else config.device_cache_bytes()
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # scrape-time residency gauge sums _bytes over live caches
        device_telemetry.register_cache(self)

    def get(self, key: tuple, build: Callable[[], jax.Array]) -> jax.Array:
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                DEVICE_CACHE_EVENTS.inc(event="hit")
                return hit
            self.misses += 1
        DEVICE_CACHE_EVENTS.inc(event="miss")
        arr = build()
        nbytes = arr.nbytes
        # a cache-miss build materializes the block on device: that IS
        # the H2D upload this cache exists to amortize
        device_telemetry.count_h2d(nbytes)
        if nbytes <= self.budget:
            evictions = 0
            with self._lock:
                old = self._lru.pop(key, None)
                if old is not None:
                    self._bytes -= old.nbytes
                self._lru[key] = arr
                self._bytes += nbytes
                while self._bytes > self.budget and self._lru:
                    _, evicted = self._lru.popitem(last=False)
                    self._bytes -= evicted.nbytes
                    evictions += 1
            if evictions:
                DEVICE_CACHE_EVENTS.inc(float(evictions), event="evict")
        return arr

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
