"""Distributed partial aggregation — the dist_plan / MergeScan analog.

The reference splits commutative aggregates into a Partial step executed
on each datanode's regions and a Final combine at the frontend
(query/src/dist_plan/analyzer.rs:35, merge_scan.rs:122). Here:

- `partial_region_agg` runs ON the node owning a region: scan, filter,
  evaluate group keys + aggregate args, and reduce to primitive planes
  (sum/count/min/max/first/last/sumsq/rows) with ONE fused device
  segment reduction. Group keys travel as decoded VALUES, so partials
  from different regions (with different tag dictionaries) combine by
  value at the frontend.
- `combine_partials` merges per-region results: additive planes add,
  min/max fold, first/last resolve by their companion timestamps.

The fragment itself crosses the wire as JSON (plan_ser.AggFragment —
the substrait analog) via the Flight `region_agg` ticket.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.query.expr import BindContext, bind_expr, eval_host
from greptimedb_tpu.query.plan_ser import AggFragment


def partial_region_agg(executor, region_id: int, frag: AggFragment,
                       schema=None) -> Optional[dict]:
    """Compute one region's partial aggregate. Returns
    {"keys": [np.ndarray per key], "planes": {op: [G, F] np.ndarray}}
    with G = observed groups in this region, or None for an empty scan."""
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.query.expr import collect_columns

    from types import SimpleNamespace

    from greptimedb_tpu.storage.index import extract_tag_predicates

    ts_range = tuple(frag.ts_range) if frag.ts_range else None
    # probe the schema first so projection + index pruning match what the
    # frontend's gather path gets (physical.py execute: scan_node.columns
    # + extract_tag_predicates)
    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    needed: set[str] = {ts_name}
    collect_columns(frag.where, needed)
    for _, k in frag.keys:
        collect_columns(k, needed)
    for a in frag.args:
        collect_columns(a, needed)
    proj = [c for c in schema.names if c in needed]
    tag_preds = extract_tag_predicates(frag.where, schema) or None
    scan = executor.engine.scan(region_id, ts_range, proj, tag_preds)
    if scan is None or scan.num_rows == 0:
        return None

    ctx = BindContext(schema, scan.tag_dicts)
    bound_where = bind_expr(frag.where, ctx) if frag.where is not None \
        else None
    # _filtered_row_indices only consults .schema and (via dedup)
    # .append_mode — a region-local shim stands in for the TableInfo the
    # frontend holds
    shim = SimpleNamespace(schema=schema, append_mode=frag.append_mode)
    idx = executor._filtered_row_indices(scan, shim, ctx, bound_where,
                                         where_unbound=frag.where)
    if len(idx) == 0:
        return None

    host: dict[str, np.ndarray] = {}
    for name, arr in scan.columns.items():
        taken = arr[idx]
        if name in scan.tag_dicts:
            taken = DictVector(taken, scan.tag_dicts[name]).decode()
        host[name] = taken
    if ts_range is not None:
        # scan ts_range is coarse (row-group pruning); apply the exact
        # closed bounds here — the frontend derived them from WHERE
        lo, hi = ts_range
        tsv = host[ts_name].astype(np.int64)
        m = np.ones(len(tsv), dtype=bool)
        if lo is not None:
            m &= tsv >= lo
        if hi is not None:
            m &= tsv <= hi
        if not m.all():
            host = {k: v[m] for k, v in host.items()}
    n = len(host[ts_name])

    # group keys: evaluate, factorize by VALUE (null-safe: NULL is its
    # own group, matching the single-node path's semantics)
    key_uniqs: list[np.ndarray] = []
    gcode = np.zeros(n, dtype=np.int64)
    for _, kexpr in frag.keys:
        vals = np.asarray(eval_host(kexpr, host, schema))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (n,))
        uniq, codes = _factorize_with_null(vals)
        key_uniqs.append(uniq)
        gcode = gcode * len(uniq) + codes
    if frag.keys:
        gids_uniq, gcode = np.unique(gcode, return_inverse=True)
        num_groups = len(gids_uniq)
    else:
        gids_uniq = np.zeros(1, dtype=np.int64)
        num_groups = 1

    if frag.args:
        planes = []
        for a in frag.args:
            p = np.asarray(eval_host(a, host, schema))
            if p.dtype == object or p.dtype.kind in ("U", "S"):
                # string argument: only count() rides pushdown (frontend
                # gating), which needs just validity — 1.0 per non-null
                p = np.where(
                    np.asarray([v is None for v in p.ravel()]).reshape(p.shape)
                    if p.dtype == object else np.zeros(p.shape, bool),
                    np.nan, 1.0)
            planes.append(np.asarray(p, dtype=np.float64))
        vals = np.stack([np.broadcast_to(p, (n,)) for p in planes], axis=1)
    else:
        vals = np.zeros((n, 1), dtype=np.float64)

    ops = set(frag.ops)
    need_ts = bool({"first", "last"} & ops)
    out = segment_agg(
        jnp.asarray(vals), jnp.asarray(gcode.astype(np.int32)),
        jnp.ones(n, dtype=bool), num_groups, ops=tuple(sorted(ops)),
        ts=jnp.asarray(host[ts_name].astype(np.int64)) if need_ts else None,
    )
    planes_np = {k: np.asarray(v) for k, v in out.items()}

    # decode each group's key values from the compacted ids
    key_cols: list[np.ndarray] = []
    rem = gids_uniq
    for uniq in reversed(key_uniqs):
        key_cols.append(uniq[rem % len(uniq)])
        rem = rem // len(uniq)
    key_cols.reverse()
    return {"keys": key_cols, "planes": planes_np}


def _factorize_with_null(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """np.unique with NULL support: None (object arrays) and NaN (float
    arrays) can't be sorted/equality-matched by np.unique, so nulls get
    their own trailing code with a None marker in the value table."""
    if vals.dtype == object:
        null_mask = np.asarray([v is None for v in vals])
    elif vals.dtype.kind == "f":
        null_mask = np.isnan(vals)
    else:
        null_mask = None
    if null_mask is None or not null_mask.any():
        if vals.dtype == object:
            # None-free object arrays still need a sortable dtype
            uniq, codes = np.unique(vals.astype(str), return_inverse=True)
            return uniq.astype(object), codes
        return np.unique(vals, return_inverse=True)
    codes = np.empty(len(vals), dtype=np.int64)
    nn = vals[~null_mask]
    if vals.dtype == object:
        uniq_nn, codes_nn = np.unique(nn.astype(str), return_inverse=True)
        uniq_nn = uniq_nn.astype(object)
    else:
        uniq_nn, codes_nn = np.unique(nn, return_inverse=True)
    codes[~null_mask] = codes_nn
    codes[null_mask] = len(uniq_nn)
    uniq = np.empty(len(uniq_nn) + 1, dtype=object)
    uniq[:len(uniq_nn)] = uniq_nn
    uniq[len(uniq_nn)] = None
    return uniq, codes


class _NullKey:
    """Singleton stand-in for NULL in combine index tuples: None and NaN
    both normalize to it, restoring equality that NaN breaks."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance


_NULL = _NullKey()


def _norm_key(v):
    if v is None:
        return _NULL
    if isinstance(v, (float, np.floating)) and v != v:
        return _NULL
    return v


_ADDITIVE = frozenset({"sum", "count", "rows", "sumsq"})


def combine_partials(partials: list, n_keys: int, ops: tuple) -> Optional[dict]:
    """Final combine of per-region partials (merge_scan.rs:122 role).
    Returns {"keys": [np.ndarray], "planes": {op: [G, F]}} over the union
    of group keys, or None if every partial was empty."""
    partials = [p for p in partials if p is not None]
    if not partials:
        return None
    index: dict[tuple, int] = {}
    rows_keys: list[tuple] = []  # original values (None/NaN preserved)
    for p in partials:
        kc = p["keys"]
        g = len(kc[0]) if kc else 1
        for i in range(g):
            kt = tuple(_norm_key(c[i]) for c in kc)
            if kt not in index:
                index[kt] = len(rows_keys)
                rows_keys.append(tuple(c[i] for c in kc))
    G = len(rows_keys)
    sample = partials[0]["planes"]
    acc: dict[str, np.ndarray] = {}
    for op, plane in sample.items():
        f = plane.shape[1] if plane.ndim == 2 else 1
        if op in ("min",):
            acc[op] = np.full((G, f), np.nan)
        elif op in ("max",):
            acc[op] = np.full((G, f), np.nan)
        elif op in ("first", "last"):
            acc[op] = np.full((G, f), np.nan)
        elif op in ("first_ts",):
            acc[op] = np.full((G, f), np.iinfo(np.int64).max, dtype=np.int64)
        elif op in ("last_ts",):
            acc[op] = np.full((G, f), np.iinfo(np.int64).min, dtype=np.int64)
        else:
            acc[op] = np.zeros((G, f))
    for p in partials:
        kc = p["keys"]
        g = len(kc[0]) if kc else 1
        pos = np.fromiter(
            (index[tuple(_norm_key(c[i]) for c in kc)] for i in range(g)),
            dtype=np.int64, count=g)
        planes = {op: (pl if pl.ndim == 2 else pl[:, None])
                  for op, pl in p["planes"].items()}
        for op, pl in planes.items():
            if op in _ADDITIVE:
                np.add.at(acc[op], pos, pl)
            elif op == "min":
                cur = acc[op][pos]
                acc[op][pos] = np.where(
                    np.isnan(cur) | (pl < cur), pl, cur)
            elif op == "max":
                cur = acc[op][pos]
                acc[op][pos] = np.where(
                    np.isnan(cur) | (pl > cur), pl, cur)
            elif op == "first":
                ts = planes["first_ts"].astype(np.int64)
                cur_ts = acc["first_ts"][pos]
                take = ts < cur_ts
                acc[op][pos] = np.where(take, pl, acc[op][pos])
                acc["first_ts"][pos] = np.where(take, ts, cur_ts)
            elif op == "last":
                ts = planes["last_ts"].astype(np.int64)
                cur_ts = acc["last_ts"][pos]
                take = ts > cur_ts
                acc[op][pos] = np.where(take, pl, acc[op][pos])
                acc["last_ts"][pos] = np.where(take, ts, cur_ts)
            # first_ts / last_ts handled with their value planes
    key_cols = [np.asarray([kt[i] for kt in rows_keys])
                for i in range(n_keys)]
    for op in ("count", "rows"):
        if op in acc:
            acc[op] = acc[op].astype(np.int64)
    return {"keys": key_cols, "planes": acc}
