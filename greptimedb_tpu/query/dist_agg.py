"""Distributed partial aggregation — the dist_plan / MergeScan analog.

The reference splits commutative aggregates into a Partial step executed
on each datanode's regions and a Final combine at the frontend
(query/src/dist_plan/analyzer.rs:35, merge_scan.rs:122). Here:

- `partial_region_agg` runs ON the node owning a region: scan, filter,
  evaluate group keys + aggregate args, and reduce to primitive planes
  (sum/count/min/max/first/last/sumsq/rows) with ONE fused device
  segment reduction. Group keys travel as decoded VALUES, so partials
  from different regions (with different tag dictionaries) combine by
  value at the frontend.
- `combine_partials` merges per-region results: additive planes add,
  min/max fold, first/last resolve by their companion timestamps.

The fragment itself crosses the wire as JSON (plan_ser.PlanFragment —
the substrait analog) via the Flight `region_frag` ticket;
`execute_region_fragment` is the region-side interpreter dispatching to
the partial-agg / top-k / filtered-rows pipelines by terminal stage.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Optional

import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.query.expr import BindContext, bind_expr, eval_host
from greptimedb_tpu.query.plan_ser import PlanFragment


#: marker for a cached empty region contribution (None itself means
#: "cache miss" to the cache API)
_FRAG_NONE = {"__frag_none__": True}


def execute_region_fragment(executor, region_id: int, frag: PlanFragment,
                            schema=None) -> Optional[dict]:
    """Interpret a PlanFragment over one region's rows. Returns
    {"keys": ..., "planes": ...} for a partial_agg terminal, or
    {"cols": {...}} of candidate/filtered rows otherwise; None when the
    region contributes nothing.

    Partial-agg terminals memoize their plane in the partial-aggregate
    cache keyed by the region's (incarnation, data_version) + the
    fragment JSON: a repeated dashboard fragment over an unchanged
    region answers from the cached plane without touching SSTs (ISSUE
    13 cluster tier). Any write bumps data_version; TRUNCATE resets the
    incarnation; compaction/expiry both bump the version AND drop the
    region's entries through the invalidation seam."""
    from greptimedb_tpu.query import partial_cache as pc

    if frag.stage("partial_agg") is not None \
            and frag.stage("vmapped_agg") is None and pc.enabled():
        reg = version = None
        try:
            reg = executor.engine.region(region_id)
            version = getattr(reg, "data_version", None)
        except Exception:  # noqa: BLE001 — remote probe: no local identity
            pass
        if version is not None:
            cache = pc.global_cache()
            key = ("frag", region_id, getattr(reg, "incarnation", 0),
                   version, frag.to_json())
            hit = cache.get(key)
            if hit is not None:
                return None if hit is _FRAG_NONE \
                    or hit.get("__frag_none__") else hit
            epoch = cache.epoch(region_id)
            out = _execute_region_fragment_uncached(
                executor, region_id, frag, schema)
            cache.put(key, _FRAG_NONE if out is None else out,
                      epoch=epoch)
            return out
    return _execute_region_fragment_uncached(executor, region_id, frag,
                                             schema)


def _execute_region_fragment_uncached(executor, region_id: int,
                                      frag: PlanFragment,
                                      schema=None) -> Optional[dict]:
    filt = frag.stage("filter")
    where = filt["expr"] if filt else None
    agg = frag.stage("partial_agg")
    common = dict(where=where, ts_range=frag.ts_range,
                  append_mode=frag.append_mode, tz=frag.tz)
    vm = frag.stage("vmapped_agg")
    if vm is not None:
        from greptimedb_tpu.query.vmapped import run_vmapped_region_partial

        return run_vmapped_region_partial(executor, region_id, vm,
                                          schema=schema, **common)
    if agg is not None:
        shim = SimpleNamespace(keys=agg["keys"], args=agg["args"],
                               ops=agg["ops"], **common)
        lastp = frag.stage("lastpoint")
        prescan = None
        if lastp is not None and where is None and frag.ts_range is None:
            prescan = _lastpoint_prescan(executor, region_id,
                                         lastp["tag"], shim, schema)
        return partial_region_agg(executor, region_id, shim, schema,
                                  prescan=prescan)
    sort = frag.stage("sort")
    limit = frag.stage("limit")
    prune = frag.stage("prune")
    window = frag.stage("window")
    columns = list(prune["columns"]) if prune else None
    if window is not None:
        return partial_region_window(executor, region_id, columns,
                                     window["calls"], schema=schema,
                                     **common)
    if sort is not None and limit is not None:
        shim = SimpleNamespace(sort_keys=sort["keys"], k=limit["k"],
                               columns=columns, **common)
        return partial_region_topk(executor, region_id, shim, schema)
    return partial_region_rows(executor, region_id, columns,
                               limit["k"] if limit else None,
                               schema=schema, **common)


def _lastpoint_prescan(executor, region_id: int, tag: str, shim,
                       schema=None):
    """Newest-first pruned scan for a lastpoint-class partial_agg
    fragment: the region visits SSTs in descending ts_max order and
    stops once every series provably holds its winner in the visited
    set (Region.scan_last) — the partial planes then reduce a few
    thousand candidate rows instead of the whole region. Returns None
    (full-scan partial) when the engine can't serve it exactly
    (tombstones, no scan_last, projection mismatch) — the fragment
    still returns partial planes either way, never raw rows."""
    from greptimedb_tpu.query.expr import collect_columns

    eng = executor.engine
    if not hasattr(eng, "scan_last"):
        return None
    probe = eng.region(region_id)
    schema = schema or probe.schema
    needed: set[str] = {schema.time_index.name}
    for _, kexpr in shim.keys:
        collect_columns(kexpr, needed)
    for a in shim.args:
        collect_columns(a, needed)
    proj = [c for c in schema.names if c in needed]
    try:
        return eng.scan_last(region_id, tag, proj)
    except Exception:  # noqa: BLE001 — pruning is an optimization only
        return None


def partial_region_rows(executor, region_id: int, columns, k,
                        *, where, ts_range, append_mode, tz,
                        schema=None) -> Optional[dict]:
    """Filter/prune(/limit) pushdown for plain scans: only the rows that
    survive WHERE — projected to the referenced columns — cross the
    wire, instead of the raw region scan (filter and projection are
    Commutative in the reference's classification,
    commutativity.rs:27-52; the frontend re-evaluates nothing but the
    final projection expressions)."""
    from greptimedb_tpu.query.expr import collect_columns

    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    ts_range = tuple(ts_range) if ts_range else None
    needed: set[str] = {ts_name}
    collect_columns(where, needed)
    if columns is None:
        needed.update(schema.names)
    else:
        needed.update(columns)
    host = _region_host_columns(executor, region_id, where, ts_range,
                                needed, append_mode, schema, tz=tz)
    if host is None:
        return None
    if columns is not None:
        # the filter already ran here — filter-only columns would be
        # dead weight on the wire; ship exactly the pruned projection
        host = {name: arr for name, arr in host.items()
                if name in columns}
    if k is not None and host:
        n = len(next(iter(host.values())))
        if n > k:
            host = {name: arr[:k] for name, arr in host.items()}
    return {"cols": host}


def partial_region_window(executor, region_id: int, columns, calls,
                          *, where, ts_range, append_mode, tz,
                          schema=None) -> Optional[dict]:
    """Window-partition pushdown: when every OVER clause's PARTITION BY
    covers the table's partition-rule columns, each region holds its
    window partitions WHOLE, so the entire window computation commutes
    with MergeScan (the reference's ConditionalCommutative class,
    commutativity.rs) — the wire carries filtered rows plus the computed
    window columns, never raw scans gathered for a frontend-only pass."""
    from greptimedb_tpu.query.expr import collect_columns
    from greptimedb_tpu.query.window import _eval_window

    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    ts_range = tuple(ts_range) if ts_range else None
    needed: set[str] = {ts_name}
    collect_columns(where, needed)
    for _, call in calls:
        collect_columns(call, needed)
    if columns is None:
        needed.update(schema.names)
    else:
        needed.update(columns)
    host = _region_host_columns(executor, region_id, where, ts_range,
                                needed, append_mode, schema, tz=tz)
    if host is None:
        return None
    n = len(host[ts_name])

    def resolve(e):
        return e

    def dtype_of(e):
        from greptimedb_tpu.sql import ast as _ast

        if isinstance(e, _ast.Column) and e.name in schema.names:
            return schema.column(e.name).dtype
        return None

    for name, call in calls:
        host[name] = _eval_window(call, host, n, resolve, dtype_of)
    if columns is not None:
        keep = set(columns) | {name for name, _ in calls}
        host = {k: v for k, v in host.items() if k in keep}
    return {"cols": host}


def _region_host_columns(executor, region_id: int, where, ts_range,
                         needed: set, append_mode: bool,
                         schema=None, tz=None, seq_min=None,
                         stats_out=None, prescan=None) -> Optional[dict]:
    """Shared Partial-step prologue: scan (projected + index-pruned),
    LWW-dedup/filter, decode tags, apply the exact ts bounds. Returns the
    filtered host column dict, or None for an empty result. `tz` is the
    FRONTEND's session timezone: naive ts literals in the shipped WHERE
    must coerce identically on the region. `seq_min` restricts to rows
    written after that sequence (the incremental-flow fold boundary);
    `stats_out` (a dict) receives {"rows", "max_seq"} of the RAW scan —
    pre-filter, so the caller's boundary advances past rows WHERE
    rejects and never rescans them."""
    from greptimedb_tpu.query.expr import reset_session_tz, set_session_tz

    tz_token = set_session_tz(tz)
    try:
        return _region_host_columns_inner(
            executor, region_id, where, ts_range, needed, append_mode,
            schema, seq_min=seq_min, stats_out=stats_out, prescan=prescan)
    finally:
        reset_session_tz(tz_token)


def _region_host_columns_inner(executor, region_id, where, ts_range, needed,
                               append_mode, schema, seq_min=None,
                               stats_out=None, prescan=None):
    from types import SimpleNamespace

    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.storage.index import extract_tag_predicates

    # probe the schema first so projection + index pruning match what the
    # frontend's gather path gets (physical.py execute: scan_node.columns
    # + extract_tag_predicates)
    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    proj = [c for c in schema.names if c in needed]
    tag_preds = extract_tag_predicates(where, schema) or None
    if prescan is not None:
        # lastpoint-pruned candidate rows stand in for the region scan
        # (same dedup/filter tail below — scan_last's contract is that
        # the subset contains every LWW winner)
        scan = prescan
    elif seq_min is not None:
        scan = executor.engine.scan(region_id, ts_range, proj, tag_preds,
                                    seq_min=seq_min)
    else:
        scan = executor.engine.scan(region_id, ts_range, proj, tag_preds)
    if stats_out is not None:
        stats_out["rows"] = 0 if scan is None else int(scan.num_rows)
        if scan is None or scan.num_rows == 0:
            stats_out["max_seq"] = None
            stats_out["max_ts"] = None
        else:
            stats_out["max_seq"] = int(np.max(scan.seq))
            stats_out["max_ts"] = int(np.max(
                scan.columns[schema.time_index.name]))
    if scan is None or scan.num_rows == 0:
        return None

    ctx = BindContext(schema, scan.tag_dicts)
    bound_where = bind_expr(where, ctx) if where is not None else None
    # _filtered_row_indices only consults .schema and (via dedup)
    # .append_mode — a region-local shim stands in for the TableInfo the
    # frontend holds
    shim = SimpleNamespace(schema=schema, append_mode=append_mode)
    idx = executor._filtered_row_indices(scan, shim, ctx, bound_where,
                                         where_unbound=where)
    if len(idx) == 0:
        return None

    host: dict[str, np.ndarray] = {}
    for name, arr in scan.columns.items():
        taken = arr[idx]
        if name in scan.tag_dicts:
            taken = DictVector(taken, scan.tag_dicts[name]).decode()
        host[name] = taken
    if ts_range is not None:
        # scan ts_range is coarse (row-group pruning); apply the exact
        # [lo, hi) bounds here (extract_ts_bounds emits half-open upper
        # bounds) — the frontend derived them from WHERE
        lo, hi = ts_range
        tsv = host[ts_name].astype(np.int64)
        m = np.ones(len(tsv), dtype=bool)
        if lo is not None:
            m &= tsv >= lo
        if hi is not None:
            m &= tsv < hi
        if not m.all():
            host = {k: v[m] for k, v in host.items()}
    if len(host[ts_name]) == 0:
        return None
    return host


def partial_region_agg(executor, region_id: int, frag,
                       schema=None, seq_min=None,
                       stats_out=None, prescan=None) -> Optional[dict]:
    """Compute one region's partial aggregate. Returns
    {"keys": [np.ndarray per key], "planes": {op: [G, F] np.ndarray}}
    with G = observed groups in this region, or None for an empty scan.

    `seq_min` folds only rows written after that sequence (incremental
    flow ticks); `stats_out` (a dict) then receives {"rows": raw scan
    row count, "max_seq": highest sequence scanned} for the caller's
    boundary bookkeeping."""
    from greptimedb_tpu.query.expr import collect_columns

    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    ts_range = tuple(frag.ts_range) if frag.ts_range else None
    needed: set[str] = {ts_name}
    collect_columns(frag.where, needed)
    for _, k in frag.keys:
        collect_columns(k, needed)
    for a in frag.args:
        collect_columns(a, needed)
    host = _region_host_columns(executor, region_id, frag.where, ts_range,
                                needed, frag.append_mode, schema,
                                tz=frag.tz, seq_min=seq_min,
                                stats_out=stats_out, prescan=prescan)
    if host is None:
        return None
    n = len(host[ts_name])

    # group keys: evaluate, factorize by VALUE (null-safe: NULL is its
    # own group, matching the single-node path's semantics)
    key_uniqs: list[np.ndarray] = []
    gcode = np.zeros(n, dtype=np.int64)
    for _, kexpr in frag.keys:
        vals = np.asarray(eval_host(kexpr, host, schema))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (n,))
        uniq, codes = _factorize_with_null(vals)
        key_uniqs.append(uniq)
        gcode = gcode * len(uniq) + codes
    if frag.keys:
        gids_uniq, gcode = np.unique(gcode, return_inverse=True)
        num_groups = len(gids_uniq)
    else:
        gids_uniq = np.zeros(1, dtype=np.int64)
        num_groups = 1

    if frag.args:
        planes = []
        for a in frag.args:
            p = np.asarray(eval_host(a, host, schema))
            if p.dtype == object or p.dtype.kind in ("U", "S"):
                # string argument: only count() rides pushdown (frontend
                # gating), which needs just validity — 1.0 per non-null
                p = np.where(
                    np.asarray([v is None for v in p.ravel()]).reshape(p.shape)
                    if p.dtype == object else np.zeros(p.shape, bool),
                    np.nan, 1.0)
            planes.append(np.asarray(p, dtype=np.float64))
        vals = np.stack([np.broadcast_to(p, (n,)) for p in planes], axis=1)
    else:
        vals = np.zeros((n, 1), dtype=np.float64)

    ops = set(frag.ops)
    need_ts = bool({"first", "last"} & ops)
    out = segment_agg(
        jnp.asarray(vals), jnp.asarray(gcode.astype(np.int32)),
        jnp.ones(n, dtype=bool), num_groups, ops=tuple(sorted(ops)),
        ts=jnp.asarray(host[ts_name].astype(np.int64)) if need_ts else None,
    )
    planes_np = {k: np.asarray(v) for k, v in out.items()}

    # decode each group's key values from the compacted ids
    key_cols: list[np.ndarray] = []
    rem = gids_uniq
    for uniq in reversed(key_uniqs):
        key_cols.append(uniq[rem % len(uniq)])
        rem = rem // len(uniq)
    key_cols.reverse()
    return {"keys": key_cols, "planes": planes_np}


def _factorize_with_null(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """np.unique with NULL support: None (object arrays) and NaN (float
    arrays) can't be sorted/equality-matched by np.unique, so nulls get
    their own trailing code with a None marker in the value table."""
    if vals.dtype == object:
        null_mask = np.asarray([v is None for v in vals])
    elif vals.dtype.kind == "f":
        null_mask = np.isnan(vals)
    else:
        null_mask = None
    if null_mask is None or not null_mask.any():
        if vals.dtype == object:
            # None-free object arrays still need a sortable dtype
            uniq, codes = np.unique(vals.astype(str), return_inverse=True)
            return uniq.astype(object), codes
        return np.unique(vals, return_inverse=True)
    codes = np.empty(len(vals), dtype=np.int64)
    nn = vals[~null_mask]
    if vals.dtype == object:
        uniq_nn, codes_nn = np.unique(nn.astype(str), return_inverse=True)
        uniq_nn = uniq_nn.astype(object)
    else:
        uniq_nn, codes_nn = np.unique(nn, return_inverse=True)
    codes[~null_mask] = codes_nn
    codes[null_mask] = len(uniq_nn)
    uniq = np.empty(len(uniq_nn) + 1, dtype=object)
    uniq[:len(uniq_nn)] = uniq_nn
    uniq[len(uniq_nn)] = None
    return uniq, codes


_ADDITIVE = frozenset({"sum", "count", "rows", "sumsq"})


def _concat_union(cols: list[np.ndarray]) -> np.ndarray:
    """Concatenate arrays preserving a common non-object dtype when
    possible (date_bin keys stay int64), widening to object otherwise."""
    cols = [np.asarray(c) for c in cols]
    dtypes = {c.dtype for c in cols}
    if len(dtypes) == 1 and cols[0].dtype != object:
        return np.concatenate(cols)
    return np.concatenate([c.astype(object) for c in cols])


def combine_partials(partials: list, n_keys: int, ops: tuple) -> Optional[dict]:
    """Final combine of per-region partials (merge_scan.rs:122 role).
    Returns {"keys": [np.ndarray], "planes": {op: [G, F]}} over the union
    of group keys, or None if every partial was empty.

    Fully vectorized: all partials' groups stack into one [R, F] matrix,
    group identity resolves with one np.unique pass per key column, and
    every plane combines with a single scatter (np.add.at / np.fmin.at /
    lexsort for first/last) — no per-group Python. At bench scale
    (48k groups x N regions) the former dict-per-group loop dominated
    the distributed win (round-2 VERDICT weak #5)."""
    partials = [p for p in partials if p is not None]
    if not partials:
        return None
    counts = [len(p["keys"][0]) if p["keys"] else 1 for p in partials]
    R = int(np.sum(counts))
    if n_keys:
        # factorize each key column over the stacked values; composite
        # codes identify groups across regions by VALUE (dictionaries
        # differ per region)
        stacks = [_concat_union([p["keys"][j] for p in partials])
                  for j in range(n_keys)]
        gc = np.zeros(R, dtype=np.int64)
        for s in stacks:
            uniq, codes = _factorize_with_null(s)
            if len(uniq) and gc.max(initial=0) > (2**62) // max(len(uniq), 1):
                # keep the composite inside int64: compact before mixing in
                _, gc = np.unique(gc, return_inverse=True)
            gc = gc * len(uniq) + codes
        _, first_idx, pos = np.unique(gc, return_index=True,
                                      return_inverse=True)
        # stable first-seen group order (matches the former dict behavior)
        order = np.argsort(first_idx, kind="stable")
        rank = np.empty(len(order), dtype=np.int64)
        rank[order] = np.arange(len(order))
        pos = rank[pos]
        first_idx = first_idx[order]
        G = len(first_idx)
        key_cols = [s[first_idx] for s in stacks]
    else:
        pos = np.zeros(R, dtype=np.int64)
        G = 1
        key_cols = []

    sample = partials[0]["planes"]
    stacked: dict[str, np.ndarray] = {}
    for op in sample:
        stacked[op] = np.concatenate(
            [p["planes"][op] if p["planes"][op].ndim == 2
             else p["planes"][op][:, None] for p in partials], axis=0
        ).astype(np.float64 if op not in ("first_ts", "last_ts")
                 else np.int64)

    acc: dict[str, np.ndarray] = {}
    for op, pl in stacked.items():
        f = pl.shape[1]
        if op in _ADDITIVE:
            a = np.zeros((G, f))
            np.add.at(a, pos, pl)
            acc[op] = a
        elif op == "min":
            a = np.full((G, f), np.nan)
            np.fmin.at(a, pos, pl)  # fmin(NaN, x) = x: NaN init is empty
            acc[op] = a
        elif op == "max":
            a = np.full((G, f), np.nan)
            np.fmax.at(a, pos, pl)
            acc[op] = a
    for op, ts_op, pick_last in (("first", "first_ts", False),
                                 ("last", "last_ts", True)):
        if op not in stacked:
            continue
        pl = stacked[op]
        ts = stacked[ts_op][:, 0]  # ONE ts per group (segment_agg emits
        # a single per-group ts shared by every value field)
        f = pl.shape[1]
        vout = np.full((G, f), np.nan)
        tsout = np.full(
            (G, 1),
            np.iinfo(np.int64).min if pick_last else np.iinfo(np.int64).max,
            dtype=np.int64)
        # sort by (group, ts): the first/last row of each group run is
        # the oldest/newest partial — empty-region sentinels sort to the
        # never-picked end automatically; the winner row is shared by all
        # value fields
        o = np.lexsort((ts, pos))
        boundary = np.empty(R, dtype=bool)
        if R:
            boundary[0] = True
            boundary[1:] = pos[o][1:] != pos[o][:-1]
        if pick_last:
            picks = np.append(np.flatnonzero(boundary)[1:] - 1, R - 1) \
                if R else np.empty(0, dtype=np.int64)
        else:
            picks = np.flatnonzero(boundary)
        rows = o[picks]
        vout[pos[rows], :] = pl[rows, :]
        tsout[pos[rows], 0] = ts[rows]
        acc[op] = vout
        acc[ts_op] = tsout
    for op in ("count", "rows"):
        if op in acc:
            acc[op] = acc[op].astype(np.int64)
    return {"keys": key_cols, "planes": acc}


# ---- sort/limit (top-k) pushdown -------------------------------------------


def sort_order_for(sort_keys: list, host: dict, schema, n: int) -> np.ndarray:
    """Row order for [(expr, asc)] sort keys over host columns. Uses
    order-preserving factorized codes so asc/desc works for every dtype
    (negating object/string arrays isn't possible directly)."""
    code_arrays = []
    for kexpr, asc in sort_keys:
        vals = np.asarray(eval_host(kexpr, host, schema))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (n,))
        uniq, codes = _factorize_with_null(vals)
        code_arrays.append(codes if asc else -codes)
    # lexsort: primary key LAST
    return np.lexsort(tuple(reversed(code_arrays)))


def partial_region_topk(executor, region_id: int, frag,
                        schema=None) -> Optional[dict]:
    """One region's top-k candidates for a sort+limit scan: filter, sort
    locally, truncate to k rows. Only k rows — not the raw scan — return
    to the frontend (sort+limit stages; the reference classifies Limit as
    PartialCommutative over MergeScan, commutativity.rs:27-52)."""
    from greptimedb_tpu.query.expr import collect_columns

    probe = executor.engine.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    ts_range = tuple(frag.ts_range) if frag.ts_range else None
    needed: set[str] = {ts_name}
    collect_columns(frag.where, needed)
    for kexpr, _ in frag.sort_keys:
        collect_columns(kexpr, needed)
    if frag.columns is None:
        needed.update(schema.names)
    else:
        needed.update(frag.columns)
    host = _region_host_columns(executor, region_id, frag.where, ts_range,
                                needed, frag.append_mode, schema,
                                tz=frag.tz)
    if host is None:
        return None
    n = len(host[ts_name])
    order = sort_order_for(frag.sort_keys, host, schema, n)[:frag.k]
    return {"cols": {name: arr[order] for name, arr in host.items()}}


def merge_topk(partials: list) -> Optional[dict]:
    """Concatenate per-region top-k candidates (the final sort+limit runs
    in the frontend's shared post-processing)."""
    partials = [p for p in partials if p is not None]
    if not partials:
        return None
    names = list(partials[0]["cols"])
    return {"cols": {name: _concat_union([p["cols"][name]
                                          for p in partials])
                     for name in names}}
