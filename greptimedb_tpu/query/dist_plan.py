"""Commutativity classification of the plan prefix for distributed
execution — the dist_plan analyzer analog.

The reference walks the optimized plan bottom-up, classifies every node
(Commutative / PartialCommutative / ConditionalCommutative /
NonCommutative) and pushes the whole commutative prefix below MergeScan
(query/src/dist_plan/analyzer.rs:35, commutativity.rs:27-52). The same
taxonomy here, over this engine's plan parts:

| node            | class                 | region side        | frontend |
|-----------------|-----------------------|--------------------|----------|
| Filter (WHERE)  | Commutative           | filter stage       | nothing  |
| Projection      | Commutative (columns) | prune stage        | exprs    |
| Sort + Limit    | PartialCommutative    | sort+limit to k    | re-sort  |
| bare Limit      | PartialCommutative    | limit to k         | re-limit |
| Aggregate       | Partial/Final split   | partial_agg planes | combine  |
| Sort w/o Limit  | NonCommutative        | (filter/prune only)| sort     |
| host aggs       | input Commutative     | filter+prune rows  | full agg |

`classify_prefix` returns (PlanFragment, mode) — mode tells the
frontend which Final step to run over what comes back: "agg" combines
partial planes, "topk" re-sorts candidate rows, "rows" treats the union
of filtered rows as the scan relation, "rows_agg" re-enters the device
aggregation over the filtered-row union (non-decomposable aggregates
whose input still commutes). None means nothing pushes and the caller
gathers scans (MergeScan fallback)."""

from __future__ import annotations

from typing import Optional

from greptimedb_tpu.query.expr import collect_columns, current_session_tz
from greptimedb_tpu.query.plan_ser import PlanFragment
from greptimedb_tpu.sql import ast


def classify_prefix(table, where, agg, project, sort, limit, offset,
                    ts_range, scan_node,
                    needs_host_agg, infer_dtype,
                    primitives) -> Optional[tuple[PlanFragment, str]]:
    """Build the largest region-side-executable PlanFragment for this
    plan, or None when only a raw gather works. `needs_host_agg` /
    `infer_dtype` / `primitives` come from the physical layer (shared
    with single-node planning so eligibility matches exactly)."""
    tz = current_session_tz()
    base = dict(ts_range=ts_range, append_mode=table.append_mode, tz=tz)
    stages: list = []
    if where is not None:
        stages.append({"op": "filter", "expr": where})

    if agg is not None:
        decomposable = not any(needs_host_agg(s, table.schema)
                               for s in agg.aggs)
        if decomposable:
            for spec in agg.aggs:
                if spec.arg is None:
                    continue
                dt = infer_dtype(spec.arg, table.schema)
                if dt is not None and not (dt.is_numeric or dt.is_timestamp):
                    # string argument: only count() decomposes into the
                    # validity plane; everything else needs raw values
                    if spec.func not in ("count", "rows"):
                        decomposable = False
                        break
        if decomposable:
            arg_exprs: list[ast.Expr] = []
            for spec in agg.aggs:
                if spec.arg is not None and spec.arg not in arg_exprs:
                    arg_exprs.append(spec.arg)
            ops: set = {"rows"}
            for spec in agg.aggs:
                ops.update(primitives[spec.func])
            stages.append({"op": "partial_agg", "keys": list(agg.keys),
                           "args": arg_exprs, "ops": sorted(ops)})
            return PlanFragment(stages=stages, **base), "agg"
        # Non-decomposable aggregates (order statistics / string args):
        # the aggregate itself is NonCommutative, but its INPUT still
        # commutes — push filter + projection-to-needed-columns and
        # re-enter the normal device aggregation over the row union at
        # the frontend (round-4 verdict #7; the reference ships the
        # same shape as MergeScan below a frontend-only aggregate,
        # commutativity.rs:27-52). Without a WHERE the gather path's
        # scan caches win, except when the projection drops columns —
        # then the wire saving still pays.
        needed: set = {table.schema.time_index.name}
        for _, kexpr in agg.keys:
            collect_columns(kexpr, needed)
        for spec in agg.aggs:
            if spec.arg is not None:
                collect_columns(spec.arg, needed)
        cols = sorted(c for c in needed if c in table.schema.names)
        if where is None and len(cols) >= len(table.schema.names):
            return None
        stages.append({"op": "prune", "columns": cols})
        return PlanFragment(stages=stages, **base), "rows_agg"

    # non-aggregate scans: prune to the referenced columns
    columns = scan_node.columns
    if columns is not None:
        stages.append({"op": "prune", "columns": list(columns)})

    if sort is not None and limit is not None:
        sort_keys = []
        needed: set = set()
        for ob in sort.keys:
            if ob.nulls_first is not None:
                return None  # NULLS FIRST/LAST isn't replicated region-side
            sort_keys.append((ob.expr, ob.asc))
            collect_columns(ob.expr, needed)
        if not all(c in table.schema.names for c in needed):
            return None  # sort key references a projection alias
        stages.append({"op": "sort", "keys": sort_keys})
        stages.append({"op": "limit", "k": int(limit) + int(offset or 0)})
        return PlanFragment(stages=stages, **base), "topk"

    if limit is not None and sort is None:
        # bare LIMIT: any k rows per region satisfy it
        stages.append({"op": "limit", "k": int(limit) + int(offset or 0)})
        return PlanFragment(stages=stages, **base), "rows"

    if where is not None:
        # filter+prune-only fragment: ship the filtered rows, not the
        # scan. Without a WHERE there is nothing to reduce region-side —
        # the gather path (with its scan caches) is strictly better.
        return PlanFragment(stages=stages, **base), "rows"
    return None
