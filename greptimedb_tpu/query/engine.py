"""QueryEngine + statement executor (mirrors reference
`StatementExecutor` dispatch, operator/src/statement.rs:110-267, and
`DatafusionQueryEngine::execute`, query/src/datafusion.rs:271).

One engine, two language frontends (SQL here, PromQL via promql/) lowering
into the same logical plan algebra, executed by the device-kernel physical
layer.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.catalog import Catalog, CatalogError, TableInfo
from greptimedb_tpu.datatypes.recordbatch import RecordBatch
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
from greptimedb_tpu.datatypes.types import DataType, SemanticType, parse_sql_type
from greptimedb_tpu.datatypes.vector import DictVector
from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query.expr import PlanError, eval_host, has_aggregate
from greptimedb_tpu.query.physical import PhysicalExecutor
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast, parse_sql
from greptimedb_tpu.storage.engine import RegionEngine


# session-owned context; re-exported here for the many call sites that
# import it from the engine module
from greptimedb_tpu.session import QueryContext  # noqa: E402


class QueryEngine:
    def __init__(self, catalog: Catalog, region_engine: RegionEngine,
                 metric_engine=None, plugins=None,
                 default_timezone: str = "UTC", concurrency=None):
        from greptimedb_tpu.auth import PermissionChecker
        from greptimedb_tpu.concurrency import ConcurrencyPlane
        from greptimedb_tpu.plugins import default_plugins

        self.catalog = catalog
        self.region_engine = region_engine
        self.default_timezone = default_timezone
        self.permission_checker = PermissionChecker()
        self.plugins = plugins if plugins is not None else default_plugins()
        self.executor = PhysicalExecutor(region_engine)
        # frontend concurrency plane (concurrency/ package): admission
        # control + plan cache + cross-query batching; every statement
        # routes through it (pass concurrency= to inject a tuned one)
        self.concurrency = concurrency if concurrency is not None \
            else ConcurrencyPlane()
        # per-thread statement-scope flags (plan-cache skip noted once
        # per top-level statement)
        self._skip_tls = threading.local()
        from collections import OrderedDict

        self._stmt_cache: "OrderedDict[str, list]" = OrderedDict()
        self._stmt_cache_lock = threading.Lock()
        self._open_regions: set[int] = set()
        if metric_engine is None and hasattr(region_engine, "register_opener"):
            from greptimedb_tpu.storage.metric_engine import MetricEngine

            metric_engine = MetricEngine(region_engine, catalog.kv)
        self.metric_engine = metric_engine
        # eager: registers the file-region opener so external tables
        # reopen after restart (same reason the metric engine is eager)
        if hasattr(region_engine, "register_opener"):
            from greptimedb_tpu.storage.file_engine import FileEngine

            self._file_engine = FileEngine(region_engine, catalog.kv)

    # ---- entry points ------------------------------------------------------

    def execute_sql(self, sql: str, ctx: Optional[QueryContext] = None) -> list[QueryResult]:
        ctx = ctx or QueryContext()
        if ctx.timezone is None:
            # every protocol builds its own ctx; the engine-level default
            # (default_timezone option) applies unless the client set one
            ctx.timezone = self.default_timezone
        from greptimedb_tpu.utils import deadline as dl

        if dl.current() is not None:
            # nested statement (view expansion, TQL-in-SQL, a batch
            # member re-entering) rides the outer statement's token —
            # a fresh one would let inner work outlive the outer kill
            if ctx.cancel_token is None:
                ctx.cancel_token = dl.current()
            return self._dispatch_lane(sql, ctx)
        # top level: the statement runs under one CancelToken for its
        # whole life — deadline from the client (timeout_ms stamped by
        # the server), the session vars, or [query] default_timeout_ms;
        # registered so KILL QUERY / DELETE /v1/queries can find it
        token = ctx.cancel_token  # servers pre-create for disconnect
        created = token is None
        if created:
            token = dl.CancelToken()
            ctx.cancel_token = token
        token.set_timeout(self._resolve_timeout_ms(ctx))
        qid = dl.RUNNING.register(
            token, sql, db=ctx.db,
            channel=getattr(ctx.channel, "value", str(ctx.channel)),
            tenant=ctx.tenant or "", trace_id=ctx.trace_id or "")
        try:
            with dl.activate(token):
                return self._dispatch_lane(sql, ctx)
        finally:
            dl.RUNNING.unregister(qid)
            if created:
                ctx.cancel_token = None

    def _dispatch_lane(self, sql: str, ctx: QueryContext) -> list[QueryResult]:
        # parse-free fast lane: a known statement template executes its
        # cached bound plan with zero parse/AST/planning; everything
        # else (and every first sighting) takes _execute_sql_slow below
        fl = self.concurrency.fast_lane
        if fl.enabled:
            return fl.execute(self, sql, ctx)
        return self._execute_sql_slow(sql, ctx)

    def _resolve_timeout_ms(self, ctx: QueryContext):
        """Deadline precedence: explicit client timeout (header) >
        session vars (MySQL max_execution_time / PG statement_timeout,
        landed in ctx.extensions via SET) > [query] default_timeout_ms;
        0/absent everywhere = unbounded."""
        from greptimedb_tpu.utils import deadline as dl

        if ctx.timeout_ms is not None and ctx.timeout_ms > 0:
            return float(ctx.timeout_ms)
        for var in ("max_execution_time", "statement_timeout"):
            t = dl.parse_timeout_ms(ctx.extensions.get(var))
            if t is not None and t > 0:
                return t
        t = dl.default_timeout_ms()
        return t if t > 0 else None

    def _execute_sql_slow(self, sql: str, ctx: QueryContext,
                          _intercepted: bool = False) -> list[QueryResult]:
        """The full statement path: intercept, parse, dispatch. The
        fast lane routes through here on any miss or fallback — this IS
        the authoritative semantics the lane must match byte-for-byte.
        `_intercepted=True` means the fast lane already ran the plugin
        interceptor chain on this exact text (it must run ONCE per
        statement — auditing/rate-limit interceptors count calls)."""
        import time as _time

        if ctx.timezone is None:
            ctx.timezone = self.default_timezone
        # plugin interceptors may rewrite or veto the statement before
        # parsing (reference SqlQueryInterceptor, frontend/src/instance.rs)
        if not _intercepted:
            sql = self.plugins.intercept_sql(sql, ctx)
        from greptimedb_tpu.plugins import reset_active, set_active

        # expression evaluation resolves plugin scalar functions against
        # THIS engine's container for the duration of the statement
        token = set_active(self.plugins)
        from greptimedb_tpu.utils import slow_query
        from greptimedb_tpu.utils.metrics import STAGE_SECONDS

        try:
            # slow-query watch: crosses the threshold -> structured
            # record (trace id, text, duration, rows, path, stage
            # breakdown) in the ring behind
            # information_schema.slow_queries and /v1/slow_queries
            with slow_query.watch("sql", sql, ctx.db) as w:
                # last_path is thread-local and only the aggregate paths
                # assign it — clear it so a non-aggregate slow statement
                # doesn't inherit the previous query's path
                self.executor.last_path = None
                t_parse = _time.perf_counter()
                stmts = self._parse_cached(sql)
                STAGE_SECONDS.observe(_time.perf_counter() - t_parse,
                                      stage="parse")
                # bounded admission + per-tenant fair scheduling: wait
                # time counts into the slow-query watch (queueing IS
                # part of the latency the operator debugs); nested
                # statements ride their top-level slot
                with self.concurrency.admission.slot(
                        self.concurrency.tenant_of(ctx)):
                    results = [self.execute_statement(s, ctx)
                               for s in stmts]
                last = results[-1] if results else None
                if last is not None:
                    w.rows = last.num_rows if last.is_query \
                        else last.affected_rows
                w.execution_path = self.executor.last_path
                return results
        finally:
            reset_active(token)

    def _parse_cached(self, sql: str) -> list:
        """Parse with a small LRU over the raw SQL text. Dashboards and
        load generators repeat identical statements, and parse was ~30%
        of a warm single-groupby round trip. Safe to share: the AST is
        only mutated during parsing; every post-parse transform copies
        via dataclasses.replace (reference caches at the same layer with
        its prepared-statement plans)."""
        if len(sql) > 2048:
            # bulk INSERT texts never repeat — caching their (large)
            # ASTs would pin hundreds of MB for a zero hit rate; the
            # cache exists for short repeated dashboard SELECTs
            return parse_sql(sql)
        cache = self._stmt_cache
        with self._stmt_cache_lock:
            stmts = cache.get(sql)
            if stmts is not None:
                cache.move_to_end(sql)
                return stmts
        stmts = parse_sql(sql)  # parse outside the lock: it dominates
        with self._stmt_cache_lock:
            cache[sql] = stmts
            while len(cache) > 512:
                cache.popitem(last=False)
        return stmts

    def execute_one(self, sql: str, ctx: Optional[QueryContext] = None) -> QueryResult:
        results = self.execute_sql(sql, ctx)
        if not results:
            raise PlanError("empty statement")
        return results[-1]

    def execute_statement(self, stmt: ast.Statement, ctx: QueryContext) -> QueryResult:
        # statement authorization (reference checks permissions in the
        # frontend before dispatch, src/frontend/src/instance.rs:305-338)
        self.permission_checker.check(ctx.user, stmt, ctx.db)
        # new top-level statement: its first plan-cache skip (if any)
        # is the one that gets counted/recorded
        self._skip_tls.noted = False
        from greptimedb_tpu.utils import ledger, slow_query, tracing
        from greptimedb_tpu.utils.metrics import STMT_DURATION
        ctx.trace_id = tracing.set_trace(ctx.trace_id)
        from greptimedb_tpu.query.expr import reset_session_tz, set_session_tz

        # naive timestamp literals — WHERE, BETWEEN, CAST, INSERT —
        # coerce in the session timezone everywhere in this statement
        tz_token = set_session_tz(ctx.timezone or self.default_timezone)
        try:
            with STMT_DURATION.time(stmt=type(stmt).__name__), \
                    tracing.span(f"stmt:{type(stmt).__name__}") as sp:
                # the statement's resource-ledger slice is stamped onto
                # its root span (diffed: a multi-statement request
                # shares one request-scoped ledger)
                with ledger.attach() as led:
                    led0 = led.snapshot() if led is not None else {}
                    try:
                        from greptimedb_tpu.fault.retry import (
                            Cancelled,
                            DeadlineExceeded,
                        )
                        from greptimedb_tpu.utils import deadline as dl

                        try:
                            dl.check(f"{type(stmt).__name__} start")
                            return self._execute_statement(stmt, ctx)
                        except (DeadlineExceeded, Cancelled) as e:
                            # stamp the terminal deadline event on the
                            # statement span, the resource ledger, and
                            # (if the statement turns out slow — it
                            # usually is, that's why it expired) the
                            # slow-query record
                            tok = dl.current()
                            kind = (tok.kind if tok and tok.kind else
                                    ("expired"
                                     if isinstance(e, DeadlineExceeded)
                                     else "cancelled"))
                            sp["deadline_event"] = kind
                            ledger.add(f"deadline_{kind}", 1)
                            slow_query.annotate(deadline_event=kind)
                            raise
                    finally:
                        if led is not None:
                            d = ledger.diff(led0, led.snapshot())
                            if d:
                                sp["ledger"] = ledger.format_dict(d)
                                from greptimedb_tpu.utils import roofline
                                from greptimedb_tpu.utils.metrics import \
                                    QUERY_ACHIEVED_GBPS
                                rf = roofline.stamp(sp, d)
                                if rf is not None:
                                    QUERY_ACHIEVED_GBPS.observe(
                                        rf["achieved_gbps"],
                                        stmt=type(stmt).__name__)
        finally:
            reset_session_tz(tz_token)

    def _execute_statement(self, stmt: ast.Statement, ctx: QueryContext) -> QueryResult:
        if isinstance(stmt, ast.Select):
            return self._select(stmt, ctx)
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt, ctx)
        if isinstance(stmt, ast.CreateDatabase):
            if stmt.name.lower() == "information_schema":
                raise CatalogError("'information_schema' is reserved")
            self.catalog.create_database(stmt.name, stmt.if_not_exists)
            return QueryResult.of_affected(1)
        if isinstance(stmt, ast.SetVar):
            return self._set_var(stmt, ctx)
        if isinstance(stmt, ast.KillQuery):
            from greptimedb_tpu.utils import deadline as dl

            if not dl.RUNNING.kill(stmt.query_id,
                                   reason="KILL QUERY"):
                raise PlanError(
                    f"unknown query id: {stmt.query_id} (see "
                    "information_schema.running_queries)")
            return QueryResult.of_affected(1)
        if isinstance(stmt, ast.Union):
            return self._union(stmt, ctx)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt, ctx)
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt, ctx)
        if isinstance(stmt, ast.CreateView):
            if "." in stmt.name:
                prefix = stmt.name.rsplit(".", 1)[0]
                if not self.catalog.database_exists(prefix):
                    # DDL must not silently fold a typo'd db prefix into
                    # the view name (reads tolerate dotted names; DDL
                    # creating new objects must be strict)
                    raise PlanError(f"database {prefix!r} not found")
            db, name = self._db_and_name(stmt.name, ctx)
            # the definition must at least parse and name a single query
            defs = parse_sql(stmt.query_sql)
            if len(defs) != 1 or not isinstance(defs[0],
                                                (ast.Select, ast.Union,
                                                 ast.Tql)):
                raise PlanError("CREATE VIEW requires a single query")
            try:
                self.catalog.create_view(db, name, stmt.query_sql,
                                         or_replace=stmt.or_replace,
                                         if_not_exists=stmt.if_not_exists)
            except CatalogError as e:
                raise PlanError(str(e)) from None
            return QueryResult.of_affected(0)
        if isinstance(stmt, ast.DropView):
            db, name = self._db_and_name(stmt.name, ctx)
            try:
                self.catalog.drop_view(db, name, if_exists=stmt.if_exists)
            except CatalogError as e:
                raise PlanError(str(e)) from None
            return QueryResult.of_affected(0)
        if isinstance(stmt, ast.ShowViews):
            views = sorted(self.catalog.list_views(ctx.db))
            return QueryResult(["Views"], [DataType.STRING],
                               [np.asarray(views, dtype=object)])
        if isinstance(stmt, ast.DropTable):
            return self._drop_table(stmt, ctx)
        if isinstance(stmt, ast.TruncateTable):
            return self._truncate(stmt, ctx)
        if isinstance(stmt, ast.ShowTables):
            from greptimedb_tpu.catalog import information_schema as infoschema
            db = stmt.database or ctx.db
            if db.lower() == infoschema.INFORMATION_SCHEMA:
                names = infoschema.table_names()
            else:
                names = self.catalog.list_tables(db)
            if stmt.like:
                from greptimedb_tpu.query.expr import _like_to_regex
                rx = _like_to_regex(stmt.like)
                names = [n for n in names if rx.fullmatch(n)]
            return QueryResult(["Tables"], [DataType.STRING],
                               [np.asarray(names, dtype=object)])
        if isinstance(stmt, ast.ShowDatabases):
            dbs = list(self.catalog.list_databases()) + ["information_schema"]
            return QueryResult(["Databases"], [DataType.STRING],
                               [np.asarray(sorted(dbs), dtype=object)])
        if isinstance(stmt, ast.DescribeTable):
            return self._describe(stmt, ctx)
        if isinstance(stmt, ast.ShowCreateTable):
            return self._show_create(stmt, ctx)
        if isinstance(stmt, ast.Explain):
            return self._explain(stmt, ctx)
        if isinstance(stmt, ast.Use):
            if stmt.database.lower() != "information_schema" and \
                    not self.catalog.database_exists(stmt.database):
                raise CatalogError(f"database {stmt.database!r} not found")
            ctx.db = stmt.database
            return QueryResult.of_affected(0)
        if isinstance(stmt, ast.AlterTable):
            return self._alter(stmt, ctx)
        if isinstance(stmt, ast.AdminFunc):
            return self._admin(stmt, ctx)
        if isinstance(stmt, ast.Tql):
            return self._tql(stmt, ctx)
        if isinstance(stmt, ast.CopyTable):
            return self._copy_table(stmt, ctx)
        if isinstance(stmt, ast.CopyDatabase):
            return self._copy_database(stmt, ctx)
        if isinstance(stmt, ast.CreateFlow):
            self.flow_engine.create_flow(stmt, ctx)
            return QueryResult.of_affected(0)
        if isinstance(stmt, ast.DropFlow):
            self.flow_engine.drop_flow(stmt.name, ctx.db, stmt.if_exists)
            return QueryResult.of_affected(0)
        if isinstance(stmt, ast.ShowFlows):
            flows = self.flow_engine.list_flows(ctx.db)
            return QueryResult(
                ["Flows", "Sink", "Source", "Query"],
                [DataType.STRING] * 4,
                [np.asarray([f.name for f in flows], dtype=object),
                 np.asarray([f.sink_table for f in flows], dtype=object),
                 np.asarray([f.source_table for f in flows], dtype=object),
                 np.asarray([f.sql for f in flows], dtype=object)],
            )
        raise PlanError(f"unsupported statement {type(stmt).__name__}")

    @property
    def flow_engine(self):
        if not hasattr(self, "_flow_engine"):
            from greptimedb_tpu.flow import FlowEngine

            self._flow_engine = FlowEngine(self)
        return self._flow_engine

    # ---- CTEs / subqueries -------------------------------------------------

    def _with_ctes(self, ctes, ctx: QueryContext) -> QueryContext:
        """Execute each CTE once and register it as a virtual relation in
        a copied context; CTEs shadow real tables and are visible to
        later CTEs, derived tables, and join sides."""
        ctx2 = ctx.with_db(ctx.db)
        ctx2.extensions = dict(ctx.extensions)
        vmap = dict(ctx2.extensions.get("__virtual_tables__") or {})
        ctx2.extensions["__virtual_tables__"] = vmap
        for name, stmt, col_names in ctes:
            r = self._execute_statement(stmt, ctx2)
            if not r.is_query:
                raise PlanError(f"CTE {name!r} must be a query")
            names = list(col_names) if col_names else list(r.names)
            if col_names and len(col_names) != len(r.names):
                raise PlanError(
                    f"CTE {name!r} declares {len(col_names)} columns but "
                    f"its query returns {len(r.names)}")
            if len(set(names)) != len(names):
                raise PlanError(
                    f"CTE {name!r} produces duplicate column names; "
                    "alias them in the CTE query")
            vmap[name.lower()] = (names, list(r.dtypes),
                                  [np.asarray(c) for c in r.columns])
        return ctx2

    def _virtual_table(self, table: Optional[str], ctx: QueryContext):
        if table is None:
            return None
        vmap = ctx.extensions.get("__virtual_tables__")
        return vmap.get(table.lower()) if vmap else None

    def _fold_tree(self, e, ctx: QueryContext, predicate: bool = False):
        """Replace uncorrelated ast.Subquery nodes with literals by
        executing them now. Correlated subqueries fail naturally inside
        with 'unknown column'. `predicate` marks WHERE/HAVING/ON position,
        where UNKNOWN (NULL) may legally collapse to FALSE."""
        if isinstance(e, ast.Subquery):
            stmt = e.stmt
            if e.exists and isinstance(stmt, (ast.Select, ast.Union)) \
                    and stmt.limit is None:
                # only row existence matters — don't materialize the rest
                stmt = dataclasses.replace(stmt, limit=1)
            r = self._execute_statement(stmt, ctx)
            if not r.is_query:
                raise PlanError("subquery must be a query")
            if e.exists:
                return ast.Literal(bool(r.num_rows))
            if len(r.names) != 1:
                raise PlanError(
                    "scalar subquery must return exactly one column")
            if r.num_rows == 0:
                return ast.Literal(None)
            if r.num_rows > 1:
                raise PlanError("scalar subquery returned more than one row")
            v = r.columns[0][0]
            v = v.item() if isinstance(v, np.generic) else v
            return ast.Literal(None if _is_nan_scalar(v) else v)
        if isinstance(e, ast.InList) and len(e.items) == 1 \
                and isinstance(e.items[0], ast.Subquery):
            r = self._execute_statement(e.items[0].stmt, ctx)
            if len(r.names) != 1:
                raise PlanError("IN subquery must return exactly one column")
            vals = [v.item() if isinstance(v, np.generic) else v
                    for v in r.columns[0].tolist()]
            nonnull = [v for v in vals
                       if v is not None and not _is_nan_scalar(v)]
            # the LHS is a comparison OPERAND: UNKNOWN≡FALSE never
            # applies inside it, whatever position the IN itself holds
            expr = self._fold_tree(e.expr, ctx, False)
            if e.negated and len(nonnull) != len(vals):
                # NOT IN over a list containing NULL is never TRUE:
                # matched → FALSE, unmatched → UNKNOWN. In predicate
                # position both exclude the row, so FALSE is exact; in
                # projection position preserve the FALSE/NULL split
                if predicate:
                    return ast.Literal(False)
                if not nonnull:  # every element NULL: always UNKNOWN
                    return ast.Literal(None)
                return ast.Case(
                    None,
                    ((ast.InList(expr, tuple(ast.Literal(v)
                                             for v in nonnull)),
                      ast.Literal(False)),),
                    ast.Literal(None))
            if not nonnull:
                # x IN (empty) is FALSE; NOT IN (empty) is TRUE
                return ast.Literal(bool(e.negated))
            return ast.InList(expr, tuple(ast.Literal(v) for v in nonnull),
                              e.negated)
        # UNKNOWN ≡ FALSE survives only through AND/OR conjunctions; any
        # other enclosing operator (NOT, IS NULL, CASE, comparisons) can
        # distinguish them, so the flag resets before descending
        child_pred = (predicate and isinstance(e, ast.BinaryOp)
                      and e.op in ("and", "or"))
        if isinstance(e, (list, tuple)):
            return type(e)(self._fold_tree(x, ctx, predicate) for x in e)
        # descend any expression-carrying dataclass (incl. non-Expr
        # carriers like WindowSpec) but never into embedded statements —
        # those execute atomically via the Subquery branch above
        if dataclasses.is_dataclass(e) and not isinstance(e, type) \
                and not isinstance(e, ast.Statement):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)) or (
                        dataclasses.is_dataclass(v)
                        and not isinstance(v, (type, ast.Statement))):
                    nv = self._fold_tree(v, ctx, child_pred)
                    if nv != v:
                        changes[f.name] = nv
            return dataclasses.replace(e, **changes) if changes else e
        return e

    def _fold_select_subqueries(self, sel: ast.Select,
                                ctx: QueryContext) -> ast.Select:
        if not _has_subquery(sel):
            return sel
        changes: dict = {
            "items": [dataclasses.replace(it,
                                          expr=self._fold_tree(it.expr, ctx))
                      for it in sel.items]}
        if sel.where is not None:
            changes["where"] = self._fold_tree(sel.where, ctx,
                                               predicate=True)
        if sel.having is not None:
            changes["having"] = self._fold_tree(sel.having, ctx,
                                                predicate=True)
        if sel.group_by:
            changes["group_by"] = [self._fold_tree(g, ctx)
                                   for g in sel.group_by]
        if sel.order_by:
            changes["order_by"] = [
                dataclasses.replace(ob, expr=self._fold_tree(ob.expr, ctx))
                for ob in sel.order_by]
        if sel.joins:
            changes["joins"] = [
                dataclasses.replace(
                    j, on=self._fold_tree(j.on, ctx, predicate=True)
                    if j.on is not None else None)
                for j in sel.joins]
        return dataclasses.replace(sel, **changes)

    # ---- table resolution --------------------------------------------------

    def _db_and_name(self, name: str, ctx: QueryContext) -> tuple[str, str]:
        db = ctx.db
        if "." in name:
            candidate_db, rest = name.rsplit(".", 1)
            if self.catalog.database_exists(candidate_db):
                return candidate_db, rest
        return db, name

    def _view_sql(self, name: str, ctx: QueryContext):
        db, short = self._db_and_name(name, ctx)
        return self.catalog.view(db, short)

    def _select_view(self, sel: ast.Select, vsql: str,
                     ctx: QueryContext) -> QueryResult:
        """SELECT over a view. Simple views (single-table
        projection/filter) INLINE into the outer query — the reference's
        approach — so the merged query keeps the device scan path,
        distributed pushdown, and RANGE ... ALIGN. Complex views
        (aggregates, joins, limits) materialize through the normal
        engine and the outer select evaluates over their columns."""
        from greptimedb_tpu.query import range_select as rs
        from greptimedb_tpu.query.join import execute_select_over

        inner_stmts = parse_sql(vsql)
        if len(inner_stmts) != 1:
            raise PlanError("view definition must be a single query")
        inlined = self._try_inline_view(sel, inner_stmts[0], ctx)
        if inlined is not None:
            return self._select(inlined, ctx)
        if rs.is_range_select(sel):
            # RANGE/ALIGN needs the base table's time-index machinery —
            # refusing beats silently dropping the alignment semantics
            raise PlanError(
                "RANGE ... ALIGN is only supported over simple "
                "(projection/filter) views; query the underlying table "
                "or fold the RANGE into the view")
        view_db, short = self._db_and_name(sel.table, ctx)
        # the defining query resolves unqualified names in the VIEW's
        # database, and nested views are depth-limited (a ↔ b cycles
        # must be a PlanError, not a RecursionError)
        inner_ctx = ctx.with_db(view_db)
        inner_ctx.extensions = dict(ctx.extensions)
        depth = int(inner_ctx.extensions.get("__view_depth__", 0)) + 1
        if depth > 16:
            raise PlanError(
                f"view nesting deeper than 16 at {view_db}.{short} "
                "(possible view cycle)")
        inner_ctx.extensions["__view_depth__"] = depth
        base = self._execute_statement(inner_stmts[0], inner_ctx)
        if not base.is_query:
            raise PlanError("view definition is not a query")
        if len(set(base.names)) != len(base.names):
            dupes = sorted({n for n in base.names
                            if base.names.count(n) > 1})
            raise PlanError(
                f"view {view_db}.{short} produces duplicate column "
                f"name(s) {dupes}; alias them in the view definition")
        cols = dict(zip(base.names, base.columns))
        dtypes = dict(zip(base.names, base.dtypes))
        return execute_select_over(self, sel, cols, dtypes,
                                   alias=sel.table_alias or short)

    def _try_inline_view(self, sel: ast.Select, inner,
                         ctx: QueryContext) -> Optional[ast.Select]:
        """Merge the outer select into a SIMPLE view definition
        (single table, projection + filter only): outer column refs
        substitute to the view's defining expressions, WHEREs conjoin,
        and the merged query plans against the base table. Returns None
        when the view is too complex to inline."""
        if not isinstance(inner, ast.Select):
            return None
        if (inner.joins or inner.group_by or inner.having or inner.distinct
                or inner.order_by or inner.limit is not None or inner.offset
                or inner.ctes or inner.from_subquery is not None
                or inner.table is None or inner.align is not None):
            return None
        from greptimedb_tpu.query.expr import has_aggregate
        from greptimedb_tpu.query.window import select_has_window

        if select_has_window(inner):
            return None
        if any(has_aggregate(it.expr) for it in inner.items):
            return None  # aggregate-only view (no GROUP BY): materialize
        if any(_expr_has_subquery(it.expr) for it in inner.items) or (
                inner.where is not None
                and _expr_has_subquery(inner.where)):
            return None
        # resolve the base table's schema in the VIEW's database
        view_db, _ = self._db_and_name(sel.table, ctx)
        inner_ctx = ctx.with_db(view_db)
        try:
            info = self._table(inner.table, inner_ctx)
        except (CatalogError, PlanError):
            return None
        # exposed name -> defining expression, in the VIEW's item order
        # (Star expands in place so positional clients see the view's
        # declared column order)
        mapping: dict[str, ast.Expr] = {}
        for it in inner.items:
            if isinstance(it.expr, ast.Star):
                for c in info.schema.names:
                    if c in mapping:
                        return None  # duplicate: materialize path errors
                    mapping[c] = ast.Column(c)
                continue
            name = it.alias or (it.expr.name
                                if isinstance(it.expr, ast.Column)
                                else None)
            if name is None:
                return None  # unnamed computed column: can't reference it
            if name in mapping:
                # duplicate output name: let the materialize path raise
                # its duplicate-column error
                return None
            mapping[name] = it.expr
        alias = sel.table_alias or sel.table

        class _Unmappable(Exception):
            pass

        def leaf(e):
            if isinstance(e, ast.Column):
                if e.table not in (None, alias, sel.table):
                    raise _Unmappable()
                if e.name not in mapping:
                    raise _Unmappable()
                return mapping[e.name]
            return NotImplemented

        def subst(e):
            return _rewrite_tree(e, leaf)

        def item_sub(it):
            if isinstance(it.expr, ast.Star):
                return it
            new_expr = subst(it.expr)
            alias = it.alias
            # keep the VIEW-level spelling when substitution changed the
            # expression: sum(dbl) must not surface as "sum(v * 2)"
            if alias is None and new_expr != it.expr:
                from greptimedb_tpu.query.planner import _default_name

                alias = _default_name(it.expr)
            return dataclasses.replace(it, expr=new_expr, alias=alias)

        try:
            items = []
            for it in sel.items:
                if isinstance(it.expr, ast.Star):
                    # SELECT * over the view projects the VIEW's outputs
                    for name, expr in mapping.items():
                        items.append(ast.SelectItem(expr, alias=name))
                else:
                    items.append(item_sub(it))
            where = subst(sel.where) if sel.where is not None else None
            if inner.where is not None:
                where = inner.where if where is None else \
                    ast.BinaryOp("and", where, inner.where)
            merged = dataclasses.replace(
                sel, items=items, table=inner.table, table_alias=None,
                where=where,
                group_by=[subst(g) for g in sel.group_by],
                having=subst(sel.having) if sel.having is not None else None,
                order_by=[dataclasses.replace(ob, expr=subst(ob.expr))
                          for ob in sel.order_by],
                align_by=[subst(a) for a in sel.align_by],
                align_to=subst(sel.align_to)
                if sel.align_to is not None else None)
        except _Unmappable:
            return None
        # run in the view's database so the base table resolves there
        if view_db != ctx.db:
            merged = dataclasses.replace(merged, table=f"{view_db}.{inner.table}") \
                if "." not in inner.table else merged
        return merged

    def _table(self, name: str, ctx: QueryContext) -> TableInfo:
        # db.table only when the prefix names a real database — otherwise
        # it's a table name containing dots ("sys.cpu")
        db, name = self._db_and_name(name, ctx)
        info = self.catalog.table(db, name)
        self._ensure_open(info)
        return info

    def _ensure_open(self, info: TableInfo) -> None:
        for rid in info.region_ids:
            if rid not in self._open_regions:
                try:
                    self.region_engine.region(rid)
                except KeyError:
                    self.region_engine.open_region(rid)
                self._open_regions.add(rid)

    # ---- SELECT ------------------------------------------------------------

    def _note_plan_cache_skip(self, reason: str) -> None:
        """A statement shape the plan cache cannot hold: count it with a
        reason label and stamp the slow-query record, so an uncacheable
        dashboard query is visible instead of just slow. Once per
        top-level statement — a CTE body re-entering _select must not
        double-count or overwrite the outer statement's reason."""
        if not self.concurrency.plan_cache.enabled:
            return
        if getattr(self._skip_tls, "noted", False):
            return
        self._skip_tls.noted = True
        from greptimedb_tpu.utils import slow_query
        from greptimedb_tpu.utils.metrics import PLAN_CACHE_EVENTS

        PLAN_CACHE_EVENTS.inc(event="skip", reason=reason)
        slow_query.annotate(plan_cache_skip=reason)

    def _select(self, sel: ast.Select, ctx: QueryContext) -> QueryResult:
        from greptimedb_tpu.catalog import information_schema as infoschema
        from greptimedb_tpu.query.join import execute_select_over

        if sel.ctes:
            self._note_plan_cache_skip("cte")
        elif sel.joins:
            self._note_plan_cache_skip("join")
        elif sel.from_subquery is not None:
            self._note_plan_cache_skip("subquery")
        if sel.ctes:
            # WITH ...: run each CTE once, visible to later CTEs and the
            # body (reference: DataFusion CTE planning)
            ctx = self._with_ctes(sel.ctes, ctx)
            sel = dataclasses.replace(sel, ctes=[])
        # uncorrelated scalar/IN/EXISTS subqueries fold to literals
        # before planning (reference: DataFusion subquery decorrelation)
        sel = self._fold_select_subqueries(sel, ctx)
        if sel.from_subquery is not None and not sel.joins:
            # FROM (SELECT ...) alias — materialize the derived table,
            # evaluate the outer pipeline over its columns (view path)
            base = self._execute_statement(sel.from_subquery, ctx)
            if not base.is_query:
                raise PlanError("derived table must be a query")
            return execute_select_over(
                self, sel, dict(zip(base.names, base.columns)),
                dict(zip(base.names, base.dtypes)), alias=sel.table_alias)
        vt = self._virtual_table(sel.table, ctx)
        if vt is not None and not sel.joins:
            names, vdtypes, vcols = vt
            return execute_select_over(
                self, sel, dict(zip(names, vcols)),
                dict(zip(names, vdtypes)),
                alias=sel.table_alias or sel.table)
        if sel.joins:
            # joins first: an information_schema BASE table with joins
            # must not fall into the (join-less) virtual executor — the
            # join executor materializes each side via _select, which
            # handles infoschema sides itself
            from greptimedb_tpu.query.join import execute_join_select

            return execute_join_select(self, sel, ctx)
        if sel.table is not None and \
                infoschema.is_information_schema_query(sel.table, ctx.db):
            return infoschema.execute_virtual_select(self, sel, ctx)
        if sel.table is not None:
            vsql = self._view_sql(sel.table, ctx)
            if vsql is not None:
                return self._select_view(sel, vsql, ctx)
        if sel.table is None:
            # SELECT <literals> — session funcs substitute here too
            sel = _subst_session_funcs(sel, ctx)
            names, cols, dtypes = [], [], []
            for i, it in enumerate(sel.items):
                v = eval_host(it.expr, {}, None, None)
                arr = np.asarray([v]) if np.ndim(v) == 0 else np.asarray(v)
                names.append(it.alias or f"column{i}")
                dtypes.append(None)
                cols.append(arr)
            return QueryResult(names, dtypes, cols)
        info = self._table(sel.table, ctx)
        sel = _subst_session_funcs(sel, ctx)
        # concurrency plane: a top-level SELECT on a busy server may
        # coalesce/stack with shape-compatible concurrent queries; the
        # plane always lands back in _select_table below
        return self.concurrency.execute_select(self, sel, info, ctx)

    def _select_table(self, sel: ast.Select, info: TableInfo,
                      ctx: QueryContext) -> QueryResult:
        """The single-table SELECT pipeline below the concurrency plane
        (window pushdown, RANGE..ALIGN, rollup substitution, the plan
        cache, device execution). Batch leaders re-enter here with the
        combined statement."""
        from greptimedb_tpu.query.join import execute_select_over
        from greptimedb_tpu.query import range_select as rs
        from greptimedb_tpu.query.window import select_has_window

        if select_has_window(sel):
            self._note_plan_cache_skip("window")
            if sel.group_by:
                # SQL evaluation order: aggregate first (full device agg
                # path — all aggregate functions), then windows over the
                # G-row grouped relation
                from greptimedb_tpu.query.join import split_groupby_window

                inner, outer = split_groupby_window(sel)
                base = self._select(inner, ctx)
                return execute_select_over(
                    self, outer, dict(zip(base.names, base.columns)),
                    dict(zip(base.names, base.dtypes)))
            # window-partition pushdown: PARTITION BY covering the
            # table's partition-rule columns means each region holds its
            # window partitions whole — compute the windows region-side
            # and ship filtered rows + window columns, not raw scans
            res = self._try_window_pushdown(sel, info, ctx)
            if res is not None:
                return res
            # window functions: device scan+filter materializes the base
            # relation, windows evaluate on host over the filtered rows.
            # Project only referenced columns (a Star or an unresolvable
            # qualifier falls back to everything).
            base_items = [ast.SelectItem(ast.Star())]
            if not any(isinstance(it.expr, ast.Star) for it in sel.items):
                from greptimedb_tpu.query.join import _columns_in

                refs: set = set()
                for it in sel.items:
                    _columns_in(it.expr, refs)
                for ob in sel.order_by:
                    _columns_in(ob.expr, refs)
                _columns_in(sel.where, refs)
                for g in sel.group_by:
                    _columns_in(g, refs)
                _columns_in(sel.having, refs)
                alias = sel.table_alias or sel.table
                names = {c for t, c in refs if t in (None, alias, sel.table)}
                qual_ok = all(t in (None, alias, sel.table)
                              for t, _ in refs)
                if qual_ok and names <= set(info.schema.names):
                    base_items = [ast.SelectItem(ast.Column(c))
                                  for c in sorted(names)]
            base_sel = ast.Select(items=base_items, table=sel.table,
                                  where=sel.where)
            base = self._select(base_sel, ctx)
            outer = dataclasses.replace(sel, where=None, table=None)
            return execute_select_over(
                self, outer, dict(zip(base.names, base.columns)),
                dict(zip(base.names, base.dtypes)),
                alias=sel.table_alias or sel.table)
        if rs.is_range_select(sel):
            self._note_plan_cache_skip("range_select")
            rplan = rs.plan_range_select(sel, info)
            return rs.execute_range_select(self.executor, rplan)
        # shape-keyed plan cache: repeated dashboard statements re-bind
        # a cached validated plan instead of re-planning; the entry also
        # memoizes a negative rollup-substitution probe (version-stamped
        # — any rollup state change re-probes)
        import time as _time

        from greptimedb_tpu.utils.metrics import STAGE_SECONDS

        t_plan = _time.perf_counter()
        plan, entry, binding = self.concurrency.plan_cache.lookup(sel, info)
        # non-aggregate statements never probe, so their memo is
        # trivially safe; a probed shape may memoize the negative
        # outcome only when it was STRUCTURAL (shape_note) — coverage /
        # alignment failures depend on this query's literal values and
        # must not disable substitution for sibling parameter bindings
        sub_note = {"memoizable": True}
        sub_stamp = None
        if sel.group_by or any(has_aggregate(it.expr) for it in sel.items):
            # rollup substitution: eligible coarse-bucket aggregates are
            # served from downsampled plane SSTs (maintenance/rollup.py);
            # None = ineligible/uncovered, fall through to the raw scan
            if entry is None or not entry.skip_substitution():
                from greptimedb_tpu.concurrency.plan_cache import (
                    substitution_stamp,
                )
                from greptimedb_tpu.maintenance.rollup import try_substitute

                # pre-probe stamp: a roll finishing mid-probe must not
                # lend its fresher version to this negative outcome
                sub_stamp = substitution_stamp()
                # the probe itself is planning work, but a POSITIVE
                # substitution runs the whole substituted query inside
                # try_substitute — attribute that to execute, not plan
                t_sub = _time.perf_counter()
                res = try_substitute(self, sel, info, ctx,
                                     shape_note=sub_note)
                if res is not None:
                    STAGE_SECONDS.observe(t_sub - t_plan, stage="plan")
                    STAGE_SECONDS.observe(_time.perf_counter() - t_sub,
                                          stage="execute")
                    return res
                if entry is not None and sub_note.get("memoizable"):
                    entry.mark_sub_ineligible(sub_stamp)
        if plan is None:
            plan = plan_select(sel, info)
            entry = self.concurrency.plan_cache.store(binding, sel, info,
                                                      plan)
            if entry is not None and sub_note.get("memoizable"):
                entry.mark_sub_ineligible(sub_stamp)
        STAGE_SECONDS.observe(_time.perf_counter() - t_plan, stage="plan")
        # stamp a fast-lane build ticket (if this thread armed one):
        # the statement is about to execute exactly this plan-cache
        # plan, which is what a text-template entry memoizes
        self.concurrency.fast_lane.note_plan_execution(sel, info, entry)
        t_exec = _time.perf_counter()
        try:
            return self.executor.execute(plan)
        finally:
            STAGE_SECONDS.observe(_time.perf_counter() - t_exec,
                                  stage="execute")

    def _try_window_pushdown(self, sel: ast.Select, info, ctx):
        """Ship [filter, prune, window] PlanFragments when every window
        call's PARTITION BY covers the partition-rule columns (rows of
        one window partition never span regions — the reference's
        ConditionalCommutative classification, commutativity.rs). The
        union of per-region rows + computed window columns feeds the
        normal outer select. Returns None when the shape doesn't
        commute — caller falls back to the gather path."""
        eng = self.region_engine
        if (len(info.region_ids) <= 1 or not info.partition_rules
                or not hasattr(eng, "execute_fragment")
                or sel.having is not None):
            return None
        from greptimedb_tpu.partition.rule import PartitionRule, rule_from_json
        from greptimedb_tpu.query.expr import extract_ts_bounds
        from greptimedb_tpu.query.join import _columns_in, execute_select_over
        from greptimedb_tpu.query.plan_ser import PlanFragment
        from greptimedb_tpu.query.window import (
            SUPPORTED,
            collect_window_calls,
            substitute_window_calls,
        )

        rule = info.partition_rules
        if not isinstance(rule, PartitionRule):
            rule = rule_from_json(rule)
        rule_cols = set(rule.columns)
        calls = collect_window_calls(sel)
        if not calls:
            return None
        schema = info.schema
        names_set = set(schema.names)
        for fc in calls:
            if fc.name not in SUPPORTED:
                return None
            part_cols = {p.name for p in fc.over.partition_by
                         if isinstance(p, ast.Column)}
            if not rule_cols <= part_cols:
                return None
        refs: set = set()
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                return None  # projection set must be statically known
            _columns_in(it.expr, refs)
        for ob in sel.order_by:
            _columns_in(ob.expr, refs)
        _columns_in(sel.where, refs)
        alias = sel.table_alias or sel.table
        if not all(t in (None, alias, sel.table) for t, _ in refs):
            return None
        cols = {c for _, c in refs}
        if not cols <= names_set:
            return None
        from greptimedb_tpu.query.expr import current_session_tz

        ts_col = schema.time_index
        ts_range = extract_ts_bounds(sel.where, ts_col.name, ts_col.dtype)
        mapping = [(fc, ast.Column(f"__win_{i}"))
                   for i, fc in enumerate(calls)]
        stages: list = []
        if sel.where is not None:
            stages.append({"op": "filter", "expr": sel.where})
        stages.append({"op": "prune", "columns": sorted(cols)})
        stages.append({"op": "window",
                       "calls": [(col.name, fc) for fc, col in mapping]})
        frag = PlanFragment(stages=stages, ts_range=ts_range,
                            append_mode=info.append_mode,
                            tz=current_session_tz())
        from concurrent.futures import ThreadPoolExecutor

        from greptimedb_tpu.query.dist_agg import merge_topk
        from greptimedb_tpu.utils import tracing

        from greptimedb_tpu.utils.metrics import FRAGMENT_PUSHDOWNS

        FRAGMENT_PUSHDOWNS.inc(mode="window")
        with tracing.span("window_pushdown", regions=len(info.region_ids)):
            from greptimedb_tpu.utils import deadline as dl

            one = dl.propagate(tracing.propagate(
                lambda rid: eng.execute_fragment(rid, frag)))

            with ThreadPoolExecutor(
                    max_workers=min(8, len(info.region_ids))) as pool:
                partials = list(pool.map(one, info.region_ids))
        merged = merge_topk(partials)  # column-wise union of region rows
        outer = substitute_window_calls(
            dataclasses.replace(sel, where=None, table=None,
                                table_alias=None),
            mapping)
        self.executor.last_path = "window_pushdown"
        base_cols = merged["cols"] if merged else \
            {name: np.empty(0, dtype=object)
             for name in sorted(cols) + [c.name for _, c in mapping]}
        return execute_select_over(
            self, outer, base_cols,
            {c.name: c.dtype for c in schema.columns
             if c.name in base_cols},
            # qualified references (alias.col / table.col) passed the
            # gate; the relation must expose them like the gather path
            alias=alias)

    # ---- DDL ---------------------------------------------------------------

    def _create_table_partitioned(
        self, stmt: ast.CreateTable, ctx: QueryContext, rule
    ) -> QueryResult:
        """CREATE TABLE split into one region per partition (reference
        PARTITION ON COLUMNS clause, partition/src/multi_dim.rs)."""
        return self._create_table(stmt, ctx, rule=rule)

    def _invalidate_plans(self, db: str, name: str) -> None:
        """DDL changed `db.name`: evict its cached plan shapes (the
        content-comparison safety net would also catch it, but explicit
        eviction keeps the cache from serving a doomed rebind and makes
        the invalidation observable in gtpu_plan_cache_events_total)."""
        self.concurrency.invalidate_table(db, name)

    def _create_table(
        self, stmt: ast.CreateTable, ctx: QueryContext, rule=None
    ) -> QueryResult:
        if rule is None and stmt.partitions:
            from greptimedb_tpu.partition.rule import rule_from_partition_ast

            rule = rule_from_partition_ast(stmt.partitions[0], stmt.partitions[1])
        db = ctx.db
        name = stmt.name
        if "." in name:
            db, name = name.rsplit(".", 1)
        # a DROP+CREATE cycle must not serve the old table's shapes
        self._invalidate_plans(db, name)
        time_index = stmt.time_index
        pks = list(stmt.primary_keys)
        for c in stmt.columns:
            if c.is_time_index:
                time_index = c.name
            if c.is_primary_key and c.name not in pks:
                pks.append(c.name)
        if time_index is None and stmt.columns:
            raise PlanError("CREATE TABLE requires a TIME INDEX column")
        cols = []
        for c in stmt.columns:
            dtype = parse_sql_type(c.type_name)
            if c.name == time_index:
                sem = SemanticType.TIMESTAMP
            elif c.name in pks:
                sem = SemanticType.TAG
            else:
                sem = SemanticType.FIELD
            default = None
            if c.default is not None and isinstance(c.default, ast.Literal):
                default = c.default.value
            cols.append(ColumnSchema(c.name, dtype, sem, c.nullable, default))
        schema = Schema(cols) if stmt.columns else None
        if stmt.external or stmt.engine == "file":
            return self._create_file_table(db, name, schema, stmt, ctx)
        if schema is None:
            raise PlanError("CREATE TABLE requires a column list")
        if stmt.engine == "metric":
            return self._create_metric_table(db, name, schema, stmt, ctx)
        if rule is None and not stmt.partitions:
            rule = self._default_hash_rule(schema)
        ddl = getattr(self.region_engine, "ddl_manager", None)
        if ddl is not None:
            # cluster mode: DDL is a journaled procedure across datanodes
            # (DdlManager, common/meta/src/ddl_manager.rs)
            from greptimedb_tpu.meta.ddl import DdlError

            try:
                info = ddl.create_table(
                    db, name, schema, options=dict(stmt.options),
                    if_not_exists=stmt.if_not_exists,
                    num_regions=rule.num_regions() if rule is not None else 1,
                    partition_rules=(json.loads(rule.to_json())
                                     if rule is not None else None),
                    column_order=[c.name for c in stmt.columns],
                )
            except DdlError as e:
                raise PlanError(str(e)) from None
            self._open_regions.update(info.region_ids)
            return QueryResult.of_affected(0)
        info = self.catalog.create_table(
            db, name, schema, options=dict(stmt.options),
            if_not_exists=stmt.if_not_exists,
            num_regions=rule.num_regions() if rule is not None else 1,
            partition_rules=json.loads(rule.to_json()) if rule is not None else None,
            column_order=[c.name for c in stmt.columns],
        )
        for rid in info.region_ids:
            self.region_engine.create_region(rid, schema)
            self._open_regions.add(rid)
        return QueryResult.of_affected(0)

    def _default_hash_rule(self, schema):
        """[partition] default_hash_regions: cluster DDL without an
        explicit PARTITION clause spreads the new table over N hash
        partitions on the leading tag (or [partition] hash_columns) so
        ingest scatters and scans fan out without per-table ceremony.
        Single-node engines (no placement selector) keep one region."""
        from greptimedb_tpu import config

        n = config.default_hash_partitions()
        if n <= 1 or not hasattr(self.region_engine, "select_node"):
            return None
        tag_names = [c.name for c in schema.tag_columns]
        cols = config.hash_partition_columns()
        cols = [c for c in cols if c in tag_names] if cols \
            else tag_names[:1]
        if not cols:
            return None
        from greptimedb_tpu.partition.rule import HashPartitionRule

        return HashPartitionRule(cols, n)

    def _create_file_table(self, db, name, schema, stmt, ctx) -> QueryResult:
        """CREATE EXTERNAL TABLE: an external file as a read-only table
        (reference file-engine, src/file-engine/src/engine.rs)."""
        location = stmt.options.get("location")
        if not location:
            raise PlanError(
                "CREATE EXTERNAL TABLE requires WITH (location = '...')")
        if self.catalog.table_exists(db, name):
            if stmt.if_not_exists:
                return QueryResult.of_affected(0)
            raise CatalogError(f"table {db}.{name} already exists")
        rid, schema = self.file_engine.create_file_table(
            db, name, schema, location, stmt.options.get("format"))
        info = self.catalog.create_table(
            db, name, schema,
            options={**dict(stmt.options), "engine": "file"},
            if_not_exists=True,
            column_order=[c.name for c in stmt.columns] or None,
            region_ids=[rid])
        self._open_regions.add(rid)
        return QueryResult.of_affected(0)

    @property
    def file_engine(self):
        if not hasattr(self, "_file_engine"):
            from greptimedb_tpu.storage.file_engine import FileEngine

            self._file_engine = FileEngine(self.region_engine, self.catalog.kv)
        return self._file_engine

    def _refresh_column_order(self, info: TableInfo,
                              added: Optional[str] = None,
                              dropped: Optional[str] = None) -> None:
        if info.column_order:
            if added:
                info.column_order = list(info.column_order) + [added]
            if dropped:
                info.column_order = [n for n in info.column_order
                                     if n != dropped]

    def _copy_table(self, stmt: ast.CopyTable, ctx: QueryContext) -> QueryResult:
        """COPY <table> TO/FROM '<path>' (reference
        operator/src/statement/copy_table_{to,from}.rs)."""
        from greptimedb_tpu import datasource

        if stmt.direction == "to":
            sel = ast.Select(items=[ast.SelectItem(ast.Star())],
                             table=stmt.table)
            result = self._select(sel, ctx)
            n = datasource.write_file(
                datasource.result_to_table(result), stmt.path,
                stmt.options.get("format"))
            return QueryResult.of_affected(n)
        t = datasource.read_file(stmt.path, stmt.options.get("format"))
        n = datasource.insert_arrow_table(self, stmt.table, t, ctx)
        return QueryResult.of_affected(n)

    def _copy_database(self, stmt: ast.CopyDatabase, ctx: QueryContext) -> QueryResult:
        """COPY DATABASE TO/FROM '<dir>': one parquet file per table
        (reference operator/src/statement/copy_database.rs)."""
        import os

        from greptimedb_tpu import datasource

        db = stmt.database
        fmt = stmt.options.get("format", "parquet")
        dctx = ctx.with_db(db)
        total = 0
        if stmt.direction == "to":
            os.makedirs(stmt.path, exist_ok=True)
            for name in self.catalog.list_tables(db):
                sub = ast.CopyTable(
                    name, "to", os.path.join(stmt.path, f"{name}.{fmt}"),
                    dict(stmt.options))
                total += self._copy_table(sub, dctx).affected_rows
            return QueryResult.of_affected(total)
        for fname in sorted(os.listdir(stmt.path)):
            base, ext = os.path.splitext(fname)
            ext = ext.lstrip(".").lower()
            if ext in ("ndjson", "jsonl"):
                ext = "json"
            if ext not in datasource.FORMATS:
                continue
            if not self.catalog.table_exists(db, base):
                continue
            sub = ast.CopyTable(base, "from",
                                os.path.join(stmt.path, fname),
                                dict(stmt.options))
            total += self._copy_table(sub, dctx).affected_rows
        return QueryResult.of_affected(total)

    def _create_metric_table(self, db, name, schema: Schema, stmt, ctx) -> QueryResult:
        """CREATE TABLE ... ENGINE=metric: a logical table multiplexed onto
        the shared physical region (reference metric-engine, SURVEY §2.3)."""
        if self.metric_engine is None:
            raise PlanError("metric engine not configured")
        fields = schema.field_columns
        if len(fields) != 1:
            raise PlanError("metric engine tables need exactly one field column")
        if self.catalog.table_exists(db, name):
            if stmt.if_not_exists:
                return QueryResult.of_affected(0)
            raise CatalogError(f"table {db}.{name} already exists")
        meta = self.metric_engine.create_logical_table(
            db, name, [c.name for c in schema.tag_columns],
            ts_name=schema.time_index.name, value_name=fields[0].name,
        )
        self.catalog.create_table(
            db, name, schema, options={**dict(stmt.options), "engine": "metric"},
            if_not_exists=True,
            column_order=[c.name for c in stmt.columns] or None,
            region_ids=[meta.logical_region],
        )
        self._open_regions.add(meta.logical_region)
        return QueryResult.of_affected(0)

    def _drop_table(self, stmt: ast.DropTable, ctx: QueryContext) -> QueryResult:
        db = ctx.db
        name = stmt.name
        if "." in name:
            db, name = name.rsplit(".", 1)
        self._invalidate_plans(db, name)
        ddl = getattr(self.region_engine, "ddl_manager", None)
        if ddl is not None:
            dropped_rids: list = []
            try:
                info = self.catalog.table(db, name)
                engine_kind = info.options.get("engine")
                dropped_rids = list(info.region_ids)
            except CatalogError:
                engine_kind = None
            if engine_kind not in ("metric", "file"):
                from greptimedb_tpu.meta.ddl import DdlError

                try:
                    ddl.drop_table(db, name, if_exists=stmt.if_exists)
                except DdlError as e:
                    raise PlanError(str(e)) from None
                for rid in dropped_rids:
                    self._open_regions.discard(rid)
                return QueryResult.of_affected(0)
        info = self.catalog.drop_table(db, name, stmt.if_exists)
        if info is None:
            return QueryResult.of_affected(0)
        if info.options.get("engine") == "metric" and self.metric_engine:
            self.metric_engine.drop_logical_table(db, name)
            for rid in info.region_ids:
                self._open_regions.discard(rid)
            return QueryResult.of_affected(0)
        if info.options.get("engine") == "file":
            for rid in info.region_ids:
                self.file_engine.drop_file_table(rid)
                self._open_regions.discard(rid)
            return QueryResult.of_affected(0)
        from greptimedb_tpu.maintenance.rollup import drop_companions
        from greptimedb_tpu.storage.engine import RegionRequest, RequestType
        for rid in info.region_ids:
            try:
                self.region_engine.region(rid)
            except KeyError:
                self.region_engine.open_region(rid)
            self.region_engine.handle_request(RegionRequest(RequestType.DROP, rid))
            # rollup planes must die with the raw data, or substituted
            # aggregates would resurrect the dropped table's rows
            drop_companions(self.region_engine, rid)
            self._open_regions.discard(rid)
        return QueryResult.of_affected(0)

    def _truncate(self, stmt: ast.TruncateTable, ctx: QueryContext) -> QueryResult:
        info = self._table(stmt.name, ctx)
        self._invalidate_plans(info.db, info.name)
        engine_kind = info.options.get("engine")
        if engine_kind == "file":
            raise PlanError("file engine tables are read-only; "
                            "TRUNCATE is not supported")
        if engine_kind == "metric":
            raise PlanError("TRUNCATE is not supported on metric engine "
                            "logical tables")
        from greptimedb_tpu.maintenance.rollup import drop_companions
        from greptimedb_tpu.storage.engine import RegionRequest, RequestType
        for rid in info.region_ids:
            self.region_engine.handle_request(RegionRequest(RequestType.DROP, rid))
            # coverage claims over truncated data must go with it
            drop_companions(self.region_engine, rid)
            self.region_engine.create_region(rid, info.schema)
        return QueryResult.of_affected(0)

    def _alter(self, stmt: ast.AlterTable, ctx: QueryContext) -> QueryResult:
        info = self._table(stmt.name, ctx)
        self._invalidate_plans(info.db, info.name)
        if stmt.action == "add_column":
            col = stmt.column
            dtype = parse_sql_type(col.type_name)
            if col.is_time_index or col.is_primary_key:
                raise PlanError("can only ADD nullable field columns")
            new_schema = Schema(
                list(info.schema.columns)
                + [ColumnSchema(col.name, dtype, SemanticType.FIELD, True,
                                col.default.value if isinstance(col.default, ast.Literal) else None)]
            )
            self._refresh_column_order(info, added=col.name)
            return self._apply_alter(info, new_schema)
        if stmt.action == "drop_column":
            cols = [c for c in info.schema.columns if c.name != stmt.column_name]
            dropped = info.schema.column(stmt.column_name)
            if dropped.semantic is not SemanticType.FIELD:
                raise PlanError("can only DROP field columns")
            new_schema = Schema(cols)
            self._refresh_column_order(info, dropped=stmt.column_name)
            return self._apply_alter(info, new_schema)
        raise PlanError(f"unsupported ALTER action {stmt.action}")

    def _apply_alter(self, info: TableInfo, new_schema: Schema) -> QueryResult:
        """Propagate an ALTER: journaled procedure in cluster mode
        (AlterTableProcedure), direct region+catalog update standalone."""
        ddl = getattr(self.region_engine, "ddl_manager", None)
        if ddl is not None:
            from greptimedb_tpu.meta.ddl import DdlError

            try:
                ddl.alter_table(info.db, info.name, new_schema,
                                info.region_ids,
                                column_order=info.column_order,
                                old_schema=info.schema)
            except DdlError as e:
                raise PlanError(str(e)) from None
            return QueryResult.of_affected(0)
        for rid in info.region_ids:
            self.region_engine.alter_region_schema(rid, new_schema)
        info.schema = new_schema
        self.catalog.update_table(info)
        return QueryResult.of_affected(0)

    # ---- DML ---------------------------------------------------------------

    def _set_var(self, stmt: ast.SetVar, ctx: QueryContext) -> QueryResult:
        """Session variables (reference SetVariables,
        operator/src/statement.rs): time_zone takes effect; client-compat
        chatter (NAMES, sql_mode, autocommit, ...) is accepted and
        recorded but changes nothing."""
        name = stmt.name.rsplit(".", 1)[-1]  # strip session./global.
        if name in ("time_zone", "timezone"):
            # SET TIME ZONE DEFAULT (value None) restores the engine
            # default rather than the string 'None'. Validate NOW: a
            # typo'd zone must fail at SET, not on a later INSERT
            if stmt.value is None:
                ctx.timezone = self.default_timezone
            else:
                from greptimedb_tpu.utils.time import tzinfo_for

                try:
                    tzinfo_for(str(stmt.value))
                except ValueError as e:
                    raise PlanError(str(e)) from None
                ctx.timezone = str(stmt.value)
        else:
            ctx.extensions[name] = stmt.value
        return QueryResult.of_affected(0)

    def _union(self, stmt: ast.Union, ctx: QueryContext) -> QueryResult:
        """UNION [ALL]: concatenate branch results (reference: DataFusion
        set operations); plain UNION dedups whole rows."""
        if stmt.ctes:
            ctx = self._with_ctes(stmt.ctes, ctx)
        results = [self._select(b, ctx) for b in stmt.branches]
        first = results[0]
        width = len(first.names)
        for r in results[1:]:
            if len(r.names) != width:
                raise PlanError(
                    f"UNION branches have {width} vs {len(r.names)} columns")
        cols = []
        for i in range(width):
            parts = [np.asarray(r.columns[i]) for r in results]
            if any(p.dtype == object for p in parts):
                parts = [p.astype(object) for p in parts]
            cols.append(np.concatenate(parts))

        def row_key(i):
            # NULL floats are NaN and NaN != NaN — normalize so UNION
            # treats NULLs as not distinct (SQL semantics)
            return tuple(
                None if (isinstance(v, float) and v != v) else v
                for v in (c[i] for c in cols))

        if not stmt.all and cols and len(cols[0]):
            seen: set = set()
            keep = []
            for i in range(len(cols[0])):
                row = row_key(i)
                if row not in seen:
                    seen.add(row)
                    keep.append(i)
            cols = [c[keep] for c in cols]
        out = QueryResult(list(first.names), list(first.dtypes), cols)
        # trailing ORDER BY / LIMIT / OFFSET over the whole union
        n = out.num_rows
        idx = np.arange(n)
        for ob in reversed(stmt.order_by):
            name = ob.expr.name if isinstance(ob.expr, ast.Column) else None
            if name is None or name not in out.names:
                raise PlanError(
                    "UNION ORDER BY must name an output column")
            col = np.asarray(out.column(name))[idx]
            try:
                srt = np.argsort(col, kind="stable")
            except TypeError:
                srt = np.asarray(sorted(
                    range(len(col)),
                    key=lambda i: (col[i] is None, col[i])), dtype=np.int64)
            if not ob.asc:
                srt = srt[::-1]
            idx = idx[srt]
        off = stmt.offset or 0
        stop = off + stmt.limit if stmt.limit is not None else None
        idx = idx[off:stop]
        if len(idx) != n or stmt.order_by:
            out = QueryResult(out.names, out.dtypes,
                              [np.asarray(c)[idx] for c in out.columns])
        return out

    def _insert(self, stmt: ast.Insert, ctx: QueryContext) -> QueryResult:
        info = self._table(stmt.table, ctx)
        schema = info.schema
        if stmt.select is not None:
            # INSERT ... SELECT: run the query, bind its columns
            # positionally to the target list (reference
            # operator/src/statement.rs DML path)
            from greptimedb_tpu import datasource

            sub = self._select(stmt.select, ctx)
            target_cols = stmt.columns or info.column_order or schema.names
            unknown_t = set(target_cols) - set(schema.names)
            if unknown_t:
                raise PlanError(
                    f"unknown insert columns {sorted(unknown_t)}")
            if len(sub.names) != len(target_cols):
                raise PlanError(
                    f"INSERT ... SELECT: {len(sub.names)} source columns "
                    f"for {len(target_cols)} target columns")
            t = datasource.result_to_table(sub)
            t = t.rename_columns(list(target_cols))
            n = datasource.insert_arrow_table(self, stmt.table, t, ctx)
            return QueryResult.of_affected(n)
        # positional VALUES bind in the user-declared column order
        col_names = stmt.columns or info.column_order or schema.names
        unknown = set(col_names) - set(schema.names)
        if unknown:
            raise PlanError(f"unknown insert columns {sorted(unknown)}")
        ncols = len(col_names)
        cv = stmt.columnar_values
        if cv is not None:
            # parser literal fast lane: ready-made raw value columns —
            # zero per-cell work here. The arity against THIS table's
            # column list must still hold (the parser doesn't know the
            # schema).
            if len(cv) != ncols:
                raise PlanError("INSERT row arity mismatch")
            nrows = len(cv[0]) if cv else 0
            by_col: dict[str, list] = dict(zip(col_names, cv))
        else:
            nrows = len(stmt.rows)
            # literal tuples (the overwhelming VALUES shape) transpose
            # column-wise without per-value dispatch
            if all(len(row) == ncols and all(type(e) is ast.Literal
                                             for e in row)
                   for row in stmt.rows):
                by_col = {}
                for name, col in zip(col_names, zip(*stmt.rows)):
                    by_col[name] = [None if (v := e.value) != v else v
                                    for e in col]
            else:
                by_col = {n: [] for n in col_names}
                for row in stmt.rows:
                    if len(row) != ncols:
                        raise PlanError("INSERT row arity mismatch")
                    for n, e in zip(col_names, row):
                        v = eval_host(e, {}, schema, None) \
                            if not isinstance(e, ast.Literal) else e.value
                        v = None if _is_nan_scalar(v) else v
                        by_col[n].append(v)
        # decode through the ingest columnar slab seam — the same
        # vectorized per-dtype conversions every protocol front door
        # uses (ingest.py), one pass per column
        from greptimedb_tpu import ingest as _ingest

        try:
            batch = _ingest.sql_values_batch(schema, by_col, nrows,
                                             ctx.timezone)
        except ValueError as e:
            if "time index" in str(e):
                raise PlanError(str(e)) from None
            raise
        n = self._sharded_write(info, batch, delete=False)
        from greptimedb_tpu.utils.metrics import INGEST_ROWS

        INGEST_ROWS.inc(n, protocol="sql")
        return QueryResult.of_affected(n)

    def _sharded_write(self, info: TableInfo, batch: RecordBatch, delete: bool) -> int:
        """Row→region sharding via the table's partition rule (reference
        operator/src/insert.rs:114-118 + partition/src/splitter.rs)."""
        write = self.region_engine.delete if delete else self.region_engine.put
        if len(info.region_ids) == 1 or not info.partition_rules:
            return write(info.region_ids[0], batch)
        rule = _cached_rule(info)
        cols = []
        for cname in rule.columns:
            col = batch.columns[cname]
            cols.append(col.decode() if hasattr(col, "decode") else np.asarray(col))
        n = 0
        for region_idx, rows in rule.split(cols, n_rows=batch.num_rows).items():
            rid = info.region_ids[region_idx]
            part = batch.take(rows)
            # compact each slice's tag dictionaries to the values its
            # rows USE: take() keeps the whole statement's dictionary,
            # so without this every region's tag registry would learn
            # every other region's series — poisoning registry-based
            # pruning (lastpoint termination) forever
            part = RecordBatch(part.schema, {
                name: (col.compact() if isinstance(col, DictVector)
                       else col)
                for name, col in part.columns.items()})
            n += write(rid, part)
        return n

    def _delete(self, stmt: ast.Delete, ctx: QueryContext) -> QueryResult:
        info = self._table(stmt.table, ctx)
        schema = info.schema
        key_cols = [c.name for c in schema.tag_columns] + [schema.time_index.name]
        sel = ast.Select(
            items=[ast.SelectItem(ast.Column(n)) for n in key_cols],
            table=stmt.table, where=stmt.where,
        )
        rows = self._select(sel, ctx)
        n = rows.num_rows
        if n == 0:
            return QueryResult.of_affected(0)
        cols: dict = {}
        d = dict(zip(rows.names, rows.columns))
        for c in schema.columns:
            if c.name in d:
                if c.semantic is SemanticType.TAG:
                    cols[c.name] = DictVector.encode(list(d[c.name]))
                else:
                    cols[c.name] = np.asarray(d[c.name], dtype=np.int64)
            elif c.dtype.is_float:
                cols[c.name] = np.full(n, np.nan, dtype=c.dtype.to_numpy())
            elif c.dtype.is_string:
                cols[c.name] = DictVector.encode([None] * n)
            else:
                cols[c.name] = np.zeros(n, dtype=c.dtype.to_numpy())
        batch = RecordBatch(schema, cols)
        affected = self._sharded_write(info, batch, delete=True)
        return QueryResult.of_affected(affected)

    # ---- introspection -----------------------------------------------------

    def _describe(self, stmt: ast.DescribeTable, ctx: QueryContext) -> QueryResult:
        info = self._table(stmt.name, ctx)
        names, types, keys, nulls, defaults, semantics = [], [], [], [], [], []
        cols = ([info.schema.column(n) for n in info.column_order]
                if info.column_order else info.schema.columns)
        for c in cols:
            names.append(c.name)
            types.append(c.dtype.value)
            keys.append("PRI" if c.semantic in (SemanticType.TAG, SemanticType.TIMESTAMP) else "")
            nulls.append("YES" if c.nullable else "NO")
            defaults.append("" if c.default is None else str(c.default))
            semantics.append(
                {"tag": "TAG", "timestamp": "TIMESTAMP", "field": "FIELD"}[c.semantic.value]
            )
        return QueryResult(
            ["Column", "Type", "Key", "Null", "Default", "Semantic Type"],
            [DataType.STRING] * 6,
            [np.asarray(x, dtype=object) for x in
             (names, types, keys, nulls, defaults, semantics)],
        )

    def _show_create(self, stmt: ast.ShowCreateTable, ctx: QueryContext) -> QueryResult:
        if stmt.is_view or self._view_sql(stmt.name, ctx) is not None:
            db, name = self._db_and_name(stmt.name, ctx)
            vsql = self.catalog.view(db, name)
            if vsql is None:
                raise CatalogError(f"view {db}.{name} not found")
            return QueryResult(
                ["View", "Create View"],
                [DataType.STRING, DataType.STRING],
                [np.asarray([name], dtype=object),
                 np.asarray([f'CREATE VIEW "{name}" AS {vsql}'],
                            dtype=object)])
        info = self._table(stmt.name, ctx)
        lines = [f"CREATE TABLE IF NOT EXISTS \"{info.name}\" ("]
        defs = []
        for c in info.schema.columns:
            null = "" if c.nullable else " NOT NULL"
            defs.append(f'  "{c.name}" {_render_type(c.dtype)}{null}')
        defs.append(f'  TIME INDEX ("{info.schema.time_index.name}")')
        tags = [c.name for c in info.schema.tag_columns]
        if tags:
            defs.append("  PRIMARY KEY (" + ", ".join(f'"{t}"' for t in tags) + ")")
        lines.append(",\n".join(defs))
        lines.append(")")
        lines.append("ENGINE=mito")
        if info.options:
            opts = ", ".join(f"'{k}' = '{v}'" for k, v in info.options.items())
            lines.append(f"WITH ({opts})")
        ddl = "\n".join(lines)
        return QueryResult(
            ["Table", "Create Table"], [DataType.STRING, DataType.STRING],
            [np.asarray([info.name], dtype=object), np.asarray([ddl], dtype=object)],
        )

    def _explain(self, stmt: ast.Explain, ctx: QueryContext) -> QueryResult:
        if isinstance(stmt.inner, ast.Select) and stmt.inner.joins:
            sides = [stmt.inner.table] + [j.table for j in stmt.inner.joins]
            text = "Join: " + " ⋈ ".join(
                f"{t} (view)" if self._view_sql(t, ctx) is not None else t
                for t in sides) + "\n  (host hash join over device scans)"
        elif isinstance(stmt.inner, ast.Select) and stmt.inner.table is not None:
            vsql = self._view_sql(stmt.inner.table, ctx)
            if vsql is not None:
                text = (f"View: {stmt.inner.table} AS {vsql}\n"
                        "  (outer select evaluates over the view result)")
            else:
                info = self._table(stmt.inner.table, ctx)
                plan = plan_select(stmt.inner, info)
                text = lp.explain_plan(plan)
        else:
            text = f"{type(stmt.inner).__name__}"
        lines = text.split("\n")
        if stmt.analyze:
            # EXPLAIN ANALYZE: run the statement and report per-stage
            # wall time from the trace spans, including remote region
            # spans joined by trace id (reference query/src/analyze.rs +
            # merge_scan.rs:245-259 metrics piggyback)
            # the inner statement really runs: it needs its OWN
            # authorization (EXPLAIN itself only required read — without
            # this a read-only user could EXPLAIN ANALYZE a DELETE)
            self.permission_checker.check(ctx.user, stmt.inner, ctx.db)
            lines += self._analyze_run(
                lambda: self._execute_statement(stmt.inner, ctx),
                show_path=True)
        return QueryResult(["plan"], [DataType.STRING],
                           [np.asarray(lines, dtype=object)])

    def _analyze_run(self, run, show_path: bool = False) -> list[str]:
        """Execute `run` under a FRESH trace id and report its span tree
        (shared by EXPLAIN ANALYZE and TQL ANALYZE). A fresh id matters:
        connection-scoped contexts pin one trace id, and reusing it would
        dump every prior statement's spans into this report. The
        connection's trace AND parent-span context are restored
        afterwards (adopt_remote with a cleared parent makes the inner
        run its own tree root instead of a child of the request span)."""
        import time as _time

        from greptimedb_tpu.utils import ledger, tracing

        tid = tracing.new_trace_id()
        with tracing.adopt_remote(tid, None):
            # a fresh ledger too: the report must attribute THIS
            # statement's resources, not the whole request's
            with ledger.attach_fresh() as led:
                t0 = _time.perf_counter()
                # ANALYZE must run ITS OWN execution: riding a batch
                # leader's run would report someone else's (empty) trace
                with self.concurrency.suppress_batching():
                    result = run()
                total_ms = (_time.perf_counter() - t0) * 1000.0
            spans = tracing.spans_for(tid)
        lines = ["", f"ANALYZE trace={tid} total={total_ms:.2f} ms "
                     f"rows={result.num_rows}"]
        if show_path:
            path = getattr(self.executor, "last_path", None)
            if path:
                lines.append(f"  execution path: {path}")
        # the merged per-process span TREE: children nest under their
        # parents (remote datanode spans re-parent under the frontend
        # span that issued the RPC via the piggybacked linkage), each
        # parent reporting self-time, each remote process marked with a
        # [node] line (merge_scan.rs:245-259 piggyback analog)
        lines.extend(tracing.render_tree(spans))
        if led is not None:
            summary = led.summary()
            if summary:
                lines.append(f"  resource ledger: {summary}")
                from greptimedb_tpu.utils import roofline
                rf = roofline.account(ledger.derive(led.snapshot()))
                if rf is not None:
                    lines.append(f"  roofline: {roofline.format_line(rf)}")
        return lines

    # ---- admin -------------------------------------------------------------

    #: ADMIN fn name -> maintenance job kind (the async job-id flow)
    _ADMIN_JOBS = {"flush_table": "flush", "compact_table": "compact",
                   "rollup_table": "rollup", "expire_table": "expire"}

    def _admin(self, stmt: ast.AdminFunc, ctx: QueryContext) -> QueryResult:
        fn = stmt.func
        args = [a.value if isinstance(a, ast.Literal) else None for a in fn.args]
        maint = getattr(self.region_engine, "maintenance", None)
        if fn.name in self._ADMIN_JOBS:
            info = self._table(str(args[0]), ctx)
            kind = self._ADMIN_JOBS[fn.name]
            if maint is None:
                # no plane (maintenance_workers=0, or a frontend router):
                # flush/compact keep their pre-plane synchronous shape
                if kind == "flush":
                    for rid in info.region_ids:
                        self.region_engine.flush(rid)
                elif kind == "compact":
                    for rid in info.region_ids:
                        self.region_engine.compact(rid)
                else:
                    raise PlanError(
                        f"{fn.name} needs the maintenance plane "
                        "(engine.maintenance_workers > 0)")
                return QueryResult.of_affected(0)
            params: dict = {}
            if kind == "compact":
                # manual compaction is a full merge (reference manual
                # strict-window strategy); background TWCS stays windowed
                params["strategy"] = "full"
            if kind == "rollup":
                from greptimedb_tpu.maintenance import parse_duration_ms

                res_ms = parse_duration_ms(args[1]) if len(args) > 1 \
                    else (maint.rollup_rules[0].resolution_ms
                          if maint.rollup_rules else 60_000)
                maint.rule_for(res_ms)  # register ad-hoc resolutions
                params["resolution"] = res_ms
            elif kind == "expire" and len(args) > 1:
                from greptimedb_tpu.maintenance import parse_duration_ms

                params["ttl_ms"] = parse_duration_ms(args[1])
            job_ids = [maint.submit(kind, rid, params).job_id
                       for rid in info.region_ids]
            return QueryResult(["job_id"], [DataType.INT64],
                               [np.asarray(job_ids, dtype=np.int64)])
        if fn.name == "maintenance_status":
            if maint is None:
                raise PlanError("maintenance plane is disabled")
            job = maint.job(int(args[0]))
            if job is None:
                raise PlanError(f"unknown maintenance job {args[0]}")
            d = job.to_dict()
            names = ["job_id", "kind", "region_id", "state", "error",
                     "duration_ms", "detail"]
            dtypes = [DataType.INT64, DataType.STRING, DataType.INT64,
                      DataType.STRING, DataType.STRING, DataType.FLOAT64,
                      DataType.STRING]
            cols = [np.asarray([d["job_id"]], dtype=np.int64),
                    np.asarray([d["kind"]], dtype=object),
                    np.asarray([d["region_id"]], dtype=np.int64),
                    np.asarray([d["state"]], dtype=object),
                    np.asarray([d["error"]], dtype=object),
                    np.asarray([d["duration_ms"] if d["duration_ms"]
                                is not None else np.nan]),
                    np.asarray([json.dumps(d["detail"],
                                           sort_keys=True)],
                               dtype=object)]
            return QueryResult(names, dtypes, cols)
        if fn.name in ("flush_region", "compact_region"):
            rid = int(args[0])
            if maint is not None:
                kind = "flush" if fn.name == "flush_region" else "compact"
                job = maint.submit(kind, rid)
                return QueryResult(["job_id"], [DataType.INT64],
                                   [np.asarray([job.job_id],
                                               dtype=np.int64)])
            if fn.name == "flush_region":
                self.region_engine.flush(rid)
            else:
                self.region_engine.compact(rid)
            return QueryResult.of_affected(0)
        if fn.name == "flush_flow":
            # tick the named flow now (reference flow flush admin fn,
            # common/function/src/flush_flow.rs)
            try:
                n = self.flow_engine.flush(str(args[0]), ctx.db)
            except KeyError as e:
                raise PlanError(str(e)) from None
            return QueryResult.of_affected(n)
        raise PlanError(f"unknown admin function {fn.name!r}")

    # ---- TQL (PromQL embedded in SQL) --------------------------------------

    def _tql(self, stmt: ast.Tql, ctx: QueryContext) -> QueryResult:
        from greptimedb_tpu.promql.engine import PromqlEngine
        from greptimedb_tpu.query.physical import (_TierCtx,
                                                   accelerator_link)
        from greptimedb_tpu import config as _cfg
        import jax as _jax

        # PromQL evaluation materializes intermediate series matrices on
        # host between stages — over a remote accelerator link that
        # readback dominates every evaluation, so the whole TQL pipeline
        # takes the host tier unless the chip is co-located (same policy
        # as PhysicalExecutor.tier_for, including mode force/off)
        tier = "device"
        if _jax.default_backend() != "cpu":
            mode = _cfg.host_tier_mode()
            if mode == "force":
                tier = "host"
            elif mode != "off" and not accelerator_link()["colocated"]:
                tier = "host"
        with _TierCtx(tier):
            return self._tql_inner(stmt, ctx)

    def _tql_inner(self, stmt: ast.Tql, ctx: QueryContext) -> QueryResult:
        from greptimedb_tpu.promql.engine import PromqlEngine

        engine = PromqlEngine(self)
        if stmt.explain or stmt.analyze:
            # TQL EXPLAIN: the parsed PromQL tree (reference
            # operator/src/statement/tql.rs); TQL ANALYZE additionally
            # runs the query and appends per-stage span timings
            from greptimedb_tpu.promql.parser import parse_promql

            lines = [f"PromQL: {stmt.query}",
                     _explain_promql(parse_promql(stmt.query))]
            if stmt.analyze:
                lines += self._analyze_run(
                    lambda: engine.eval_range(stmt.query, stmt.start,
                                              stmt.end, stmt.step, ctx))
            return QueryResult(["plan"], [DataType.STRING],
                               [np.asarray(lines, dtype=object)])
        return engine.eval_range(stmt.query, stmt.start, stmt.end, stmt.step, ctx)


def _explain_promql(node, indent: int = 0) -> str:
    """Render the PromQL AST as an operator tree (the reference shows the
    DataFusion plan of the compiled query; here the evaluation tree IS
    the plan)."""
    from greptimedb_tpu.promql import parser as pp

    pad = "  " * indent
    if isinstance(node, pp.VectorSelector):
        parts = [node.metric or ""]
        if node.matchers:
            parts.append("{" + ",".join(
                f"{m.label}{m.op}{m.value!r}" for m in node.matchers) + "}")
        if node.range_s:
            parts.append(f"[{node.range_s:g}s]")
        if node.offset_s:
            parts.append(f" offset {node.offset_s:g}s")
        if node.at_s is not None:
            parts.append(f" @ {node.at_s}")
        return f"{pad}Selector: {''.join(parts)}"
    if isinstance(node, pp.NumberLiteral):
        return f"{pad}Number: {node.value:g}"
    if isinstance(node, pp.StringLiteral):
        return f"{pad}String: {node.value!r}"
    if isinstance(node, pp.Call):
        inner = "\n".join(_explain_promql(a, indent + 1)
                          for a in node.args)
        return f"{pad}Call: {node.func}" + ("\n" + inner if inner else "")
    if isinstance(node, pp.Aggregate):
        mods = ""
        if node.by:
            mods = f" by ({', '.join(node.by)})"
        elif node.without:
            mods = f" without ({', '.join(node.without)})"
        head = f"{pad}Aggregate: {node.op}{mods}"
        if node.param is not None:
            head += "\n" + _explain_promql(node.param, indent + 1)
        return head + "\n" + _explain_promql(node.expr, indent + 1)
    if isinstance(node, pp.Binary):
        return (f"{pad}Binary: {node.op}\n"
                + _explain_promql(node.lhs, indent + 1) + "\n"
                + _explain_promql(node.rhs, indent + 1))
    if isinstance(node, pp.Subquery):
        return (f"{pad}Subquery: [{node.range_s:g}s:"
                f"{node.step_s or ''}]"
                + "\n" + _explain_promql(node.expr, indent + 1))
    if isinstance(node, pp.Unary):
        return f"{pad}Unary: {node.op}\n" + _explain_promql(node.expr,
                                                            indent + 1)
    return f"{pad}{type(node).__name__}"


def _subst_expr(e, ctx):
    """Replace session-dependent zero-arg functions (database(),
    timezone()) with literals before planning."""
    import dataclasses

    if isinstance(e, ast.FuncCall):
        if e.name in ("database", "current_schema", "schema"):
            return ast.Literal(ctx.db)
        if e.name == "timezone":
            return ast.Literal(ctx.timezone)
    if not dataclasses.is_dataclass(e):
        return e
    changes = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Expr):
            nv = _subst_expr(v, ctx)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, (tuple, list)) and any(
                isinstance(x, ast.Expr) for x in v):
            nv = type(v)(_subst_expr(x, ctx) if isinstance(x, ast.Expr) else x
                         for x in v)
            changes[f.name] = nv
    return dataclasses.replace(e, **changes) if changes else e


def _subst_session_funcs(sel: ast.Select, ctx: QueryContext) -> ast.Select:
    import dataclasses

    items = [dataclasses.replace(it, expr=_subst_expr(it.expr, ctx))
             for it in sel.items]
    return dataclasses.replace(sel, items=items)


def _cached_rule(info: TableInfo):
    """Parse the table's partition rule once and memoize it on the
    TableInfo (hot write path: no JSON round-trip per INSERT)."""
    from greptimedb_tpu.partition.rule import PartitionRule, rule_from_json

    rule = getattr(info, "_rule_cache", None)
    if rule is None:
        rule = (
            info.partition_rules
            if isinstance(info.partition_rules, PartitionRule)
            else rule_from_json(info.partition_rules)
        )
        info._rule_cache = rule
    return rule


def _render_type(dt: DataType) -> str:
    if dt.is_timestamp:
        return {"s": "TIMESTAMP(0)", "ms": "TIMESTAMP(3)",
                "us": "TIMESTAMP(6)", "ns": "TIMESTAMP(9)"}[dt.time_unit.value]
    return dt.value.upper()


def _is_nan_scalar(v) -> bool:
    return isinstance(v, float) and v != v


def _rewrite_tree(e, leaf):
    """Generic expression rewrite: `leaf(node)` returns a replacement or
    NotImplemented to descend. Descends containers and any
    expression-carrying dataclass (incl. non-Expr carriers like
    WindowSpec) but never into embedded statements."""
    out = leaf(e)
    if out is not NotImplemented:
        return out
    if isinstance(e, (list, tuple)):
        return type(e)(_rewrite_tree(x, leaf) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _rewrite_tree(v, leaf)
                if nv != v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e


def _expr_has_subquery(e) -> bool:
    if isinstance(e, ast.Subquery):
        return True
    if isinstance(e, (list, tuple)):
        return any(_expr_has_subquery(x) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and isinstance(e, ast.Expr):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) \
                    and _expr_has_subquery(v):
                return True
    return False


def _has_subquery(sel: ast.Select) -> bool:
    if any(_expr_has_subquery(it.expr) for it in sel.items):
        return True
    for e in (sel.where, sel.having):
        if e is not None and _expr_has_subquery(e):
            return True
    if any(_expr_has_subquery(g) for g in sel.group_by):
        return True
    if any(_expr_has_subquery(ob.expr) for ob in sel.order_by):
        return True
    return any(j.on is not None and _expr_has_subquery(j.on)
               for j in sel.joins)
