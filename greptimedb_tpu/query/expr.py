"""Expression compilation: bind -> (device | host) evaluation.

Binding rewrites an AST expression against a scan context so the device
never sees strings (SURVEY.md §7 hard part #2):
  - tag-column string comparisons become int32 code comparisons
  - LIKE on a tag becomes an InList of matching codes (pattern evaluated
    against the small dictionary on host)
  - timestamp literals are coerced to the column's storage unit
Bound expressions are frozen/hashable, so they ride into jit as *static*
arguments and the evaluator below is plain traced JAX.

The host evaluator mirrors device semantics over numpy and additionally
handles aggregate-result substitution (post-aggregation HAVING/ORDER BY/
projection) via an identity-keyed env.
"""

from __future__ import annotations

import contextvars
import re
from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.types import DataType
from greptimedb_tpu.sql import ast
from greptimedb_tpu.utils.time import (
    coerce_ts_literal as _coerce_ts_literal_raw,
)

# session timezone for naive timestamp-literal coercion. A contextvar —
# not a parameter — because coercion happens at every depth of binding,
# host eval, and ts-bound extraction; the engine installs it per
# statement and region-side fragment execution re-installs the
# frontend's value (it travels inside the fragment).
_SESSION_TZ: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_session_tz", default=None)


def set_session_tz(tz):
    return _SESSION_TZ.set(tz)


def reset_session_tz(token) -> None:
    _SESSION_TZ.reset(token)


def current_session_tz():
    return _SESSION_TZ.get()


def coerce_ts_literal(value, dtype, tz=None):
    return _coerce_ts_literal_raw(value, dtype, tz or _SESSION_TZ.get())

MISSING_CODE = -2  # literal not present in the tag dictionary: matches nothing


class PlanError(Exception):
    pass


@dataclass
class BindContext:
    schema: Schema
    tag_dicts: dict[str, np.ndarray]  # tag name -> value table

    def __post_init__(self):
        self.tag_names = {c.name for c in self.schema.tag_columns}
        self._lookup = {
            name: {v: i for i, v in enumerate(vals)}
            for name, vals in self.tag_dicts.items()
        }

    def code_of(self, tag: str, value) -> int:
        if value is None:
            return -1
        return self._lookup.get(tag, {}).get(value, MISSING_CODE)

    def codes_matching(self, tag: str, pred: Callable[[str], bool]) -> list[int]:
        return [i for i, v in enumerate(self.tag_dicts.get(tag, ())) if pred(v)]

    def column_dtype(self, name: str) -> DataType:
        return self.schema.column(name).dtype


# ---- binding ---------------------------------------------------------------


def bind_expr(e: ast.Expr, ctx: BindContext) -> ast.Expr:
    """Rewrite tag/timestamp literals; recurse structurally."""
    if isinstance(e, ast.BinaryOp):
        l, r = e.left, e.right
        if e.op in ("=", "!=", "<", "<=", ">", ">="):
            tag = _tag_side(l, r, ctx)
            if tag is not None:
                col, lit, flipped = tag
                if e.op in ("=", "!="):
                    return ast.BinaryOp(e.op, col, ast.Literal(ctx.code_of(col.name, lit.value)))
                # ordering comparison: evaluate against the (small) dictionary
                # on host -> membership test over matching codes, so the
                # device still only sees int32 codes
                op = _flip(e.op) if flipped else e.op
                litv = str(lit.value)  # tags are strings; compare as strings
                cmp = {
                    "<": lambda v: v < litv,
                    "<=": lambda v: v <= litv,
                    ">": lambda v: v > litv,
                    ">=": lambda v: v >= litv,
                }[op]
                codes = ctx.codes_matching(col.name, lambda v: cmp(str(v)))
                return ast.InList(col, tuple(ast.Literal(c) for c in codes))
            ts = _ts_side(l, r, ctx)
            if ts is not None:
                col, lit, flipped = ts
                coerced = ast.Literal(coerce_ts_literal(lit.value, ctx.column_dtype(col.name)))
                op = _flip(e.op) if flipped else e.op
                return ast.BinaryOp(op, col, coerced)
        if e.op == "like":
            if isinstance(l, ast.Column) and l.name in ctx.tag_names and isinstance(r, ast.Literal):
                rx = _like_to_regex(str(r.value))
                codes = ctx.codes_matching(l.name, lambda v: rx.fullmatch(v) is not None)
                return ast.InList(l, tuple(ast.Literal(c) for c in codes))
            # non-tag LIKE (string FIELD columns): pass through — the host
            # filter path evaluates it; the device path raises at eval
            return ast.BinaryOp(e.op, bind_expr(l, ctx), bind_expr(r, ctx))
        return ast.BinaryOp(e.op, bind_expr(l, ctx), bind_expr(r, ctx))
    if isinstance(e, ast.UnaryOp):
        return ast.UnaryOp(e.op, bind_expr(e.operand, ctx))
    if isinstance(e, ast.Between):
        col = e.expr
        if isinstance(col, ast.Column) and col.name in ctx.schema.names and \
           ctx.column_dtype(col.name).is_timestamp:
            lo = ast.Literal(coerce_ts_literal(_lit(e.low), ctx.column_dtype(col.name)))
            hi = ast.Literal(coerce_ts_literal(_lit(e.high), ctx.column_dtype(col.name)))
            return ast.Between(col, lo, hi, e.negated)
        if isinstance(col, ast.Column) and col.name in ctx.tag_names and \
                isinstance(e.low, ast.Literal) and isinstance(e.high, ast.Literal):
            # string BETWEEN on a tag: evaluate against the dictionary on
            # host (same trick as ordered comparisons above) so the device
            # only ever sees int32 codes
            lo, hi = str(e.low.value), str(e.high.value)
            codes = ctx.codes_matching(col.name, lambda v: lo <= str(v) <= hi)
            inl = ast.InList(col, tuple(ast.Literal(c) for c in codes))
            return ast.UnaryOp("not", inl) if e.negated else inl
        return ast.Between(bind_expr(e.expr, ctx), bind_expr(e.low, ctx),
                           bind_expr(e.high, ctx), e.negated)
    if isinstance(e, ast.InList):
        if isinstance(e.expr, ast.Column) and e.expr.name in ctx.tag_names:
            codes = tuple(
                ast.Literal(ctx.code_of(e.expr.name, _lit(i))) for i in e.items
            )
            return ast.InList(e.expr, codes, e.negated)
        return ast.InList(bind_expr(e.expr, ctx),
                          tuple(bind_expr(i, ctx) for i in e.items), e.negated)
    if isinstance(e, ast.IsNull):
        return ast.IsNull(bind_expr(e.expr, ctx), e.negated)
    if isinstance(e, ast.FuncCall):
        return ast.FuncCall(e.name, tuple(bind_expr(a, ctx) for a in e.args), e.distinct)
    if isinstance(e, ast.Cast):
        return ast.Cast(bind_expr(e.expr, ctx), e.type_name)
    if isinstance(e, ast.Case):
        return ast.Case(
            bind_expr(e.operand, ctx) if e.operand else None,
            tuple((bind_expr(c, ctx), bind_expr(v, ctx)) for c, v in e.whens),
            bind_expr(e.else_, ctx) if e.else_ else None,
        )
    return e


def _lit(e: ast.Expr):
    if not isinstance(e, ast.Literal):
        raise PlanError(f"expected literal, got {e}")
    return e.value


class HostBindContext(BindContext):
    """Binding for host-side evaluation over DECODED columns: timestamp
    literals still coerce to the column unit, but tag comparisons stay as
    string comparisons (no dictionary-code rewriting — host rows carry
    real strings, not codes)."""

    def __post_init__(self):
        super().__post_init__()
        self.tag_names = set()


def bind_host_expr(e, schema):
    return bind_expr(e, HostBindContext(schema, {}))


def _tag_side(l, r, ctx):
    if isinstance(l, ast.Column) and l.name in ctx.tag_names and isinstance(r, ast.Literal):
        return l, r, False
    if isinstance(r, ast.Column) and r.name in ctx.tag_names and isinstance(l, ast.Literal):
        return r, l, True
    return None


def _ts_side(l, r, ctx):
    if (isinstance(l, ast.Column) and l.name in ctx.schema.names
            and ctx.column_dtype(l.name).is_timestamp and isinstance(r, ast.Literal)):
        return l, r, False
    if (isinstance(r, ast.Column) and r.name in ctx.schema.names
            and ctx.column_dtype(r.name).is_timestamp and isinstance(l, ast.Literal)):
        return r, l, True
    return None


def _flip(op: str) -> str:
    return {"<": ">", ">": "<", "<=": ">=", ">=": "<="}.get(op, op)


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.IGNORECASE | re.DOTALL)


# ---- device evaluation (traced JAX; expr must be bound) --------------------

_DEVICE_FUNCS = {
    "abs": jnp.abs, "sqrt": jnp.sqrt, "exp": jnp.exp,
    "ln": jnp.log, "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "floor": jnp.floor, "ceil": jnp.ceil, "signum": jnp.sign,
    "trunc": jnp.trunc,
}


def eval_device(e: ast.Expr, cols: dict, ctx_tags: frozenset, schema: Schema):
    """Evaluate a bound expression over device column arrays. `e` is static
    under jit; this runs at trace time."""

    def ev(x):
        return eval_device(x, cols, ctx_tags, schema)

    if isinstance(e, ast.Column):
        if e.name not in cols:
            raise PlanError(f"column {e.name!r} not available on device")
        return cols[e.name]
    if isinstance(e, ast.Literal):
        if e.value is None:
            return jnp.nan
        if isinstance(e.value, bool):
            return jnp.asarray(e.value)
        return jnp.asarray(e.value)
    if isinstance(e, ast.Interval):
        return jnp.asarray(e.nanos)
    if isinstance(e, ast.BinaryOp):
        if e.op == "and":
            return _as_bool(ev(e.left)) & _as_bool(ev(e.right))
        if e.op == "or":
            return _as_bool(ev(e.left)) | _as_bool(ev(e.right))
        a, b = ev(e.left), ev(e.right)
        if e.op == "+":
            return a + b
        if e.op == "-":
            return a - b
        if e.op == "*":
            return a * b
        if e.op == "/":
            if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
                return a // b
            return a / b
        if e.op == "%":
            return a % b
        if e.op == "=":
            return a == b
        if e.op == "!=":
            return a != b
        if e.op == "<":
            return a < b
        if e.op == "<=":
            return a <= b
        if e.op == ">":
            return a > b
        if e.op == ">=":
            return a >= b
        raise PlanError(f"unsupported device op {e.op!r}")
    if isinstance(e, ast.UnaryOp):
        v = ev(e.operand)
        return ~_as_bool(v) if e.op == "not" else -v
    if isinstance(e, ast.Between):
        x = ev(e.expr)
        res = (x >= ev(e.low)) & (x <= ev(e.high))
        return ~res if e.negated else res
    if isinstance(e, ast.InList):
        x = ev(e.expr)
        if not e.items:
            res = jnp.zeros(x.shape, dtype=bool)
        else:
            res = x == ev(e.items[0])
            for item in e.items[1:]:
                res = res | (x == ev(item))
        return ~res if e.negated else res
    if isinstance(e, ast.IsNull):
        x = e.expr
        if isinstance(x, ast.Column) and x.name in ctx_tags:
            res = cols[x.name] < 0
        else:
            v = ev(x)
            res = jnp.isnan(v) if jnp.issubdtype(v.dtype, jnp.floating) else jnp.zeros(v.shape, bool)
        return ~res if e.negated else res
    if isinstance(e, ast.FuncCall):
        if e.order_within is not None:
            raise PlanError(
                f"ORDER BY inside {e.name}() is only supported for "
                "first_value/last_value")
        return _eval_device_func(e, ev, cols, schema)
    if isinstance(e, ast.Cast):
        v = ev(e.expr)
        t = e.type_name.lower()
        if t in ("double", "float64"):
            return v.astype(jnp.float64)
        if t in ("float", "float32", "real"):
            return v.astype(jnp.float32)
        if t in ("bigint", "int64"):
            return v.astype(jnp.int64)
        if t in ("int", "integer", "int32"):
            return v.astype(jnp.int32)
        raise PlanError(f"unsupported device cast to {e.type_name!r}")
    if isinstance(e, ast.Case):
        if e.operand is not None:
            op = ev(e.operand)
            conds = [op == ev(c) for c, _ in e.whens]
        else:
            conds = [_as_bool(ev(c)) for c, _ in e.whens]
        vals = [ev(v) for _, v in e.whens]
        out = ev(e.else_) if e.else_ is not None else jnp.nan
        for c, v in zip(reversed(conds), reversed(vals)):
            out = jnp.where(c, v, out)
        return out
    raise PlanError(f"cannot evaluate {e!r} on device")


def _eval_device_func(e: ast.FuncCall, ev, cols, schema: Schema):
    name = e.name
    if name in ("date_bin", "time_bucket"):
        # date_bin(interval, ts[, origin]) -> bucket START timestamp
        interval, ts_expr = e.args[0], e.args[1]
        step = _interval_in_col_unit(interval, ts_expr, schema)
        ts = ev(ts_expr)
        origin = 0
        if len(e.args) > 2:
            origin = int(_lit(e.args[2]))
        return (ts - origin) // step * step + origin
    if name == "date_trunc":
        unit_lit, ts_expr = e.args[0], e.args[1]
        unit = str(_lit(unit_lit)).lower()
        nanos = _TRUNC_UNITS.get(unit)
        if nanos is None:
            raise PlanError(f"date_trunc unit {_lit(unit_lit)!r} unsupported")
        step = _scale_to_col_unit(nanos, ts_expr, schema)
        ts = ev(ts_expr)
        # weeks start on Monday (PostgreSQL semantics); the epoch is a
        # Thursday, so shift by 3 days before flooring
        shift = _scale_to_col_unit(3 * 86400 * 10**9, ts_expr, schema) \
            if unit == "week" else 0
        return (ts + shift) // step * step - shift
    if name in ("pow", "power"):
        return jnp.power(ev(e.args[0]), ev(e.args[1]))
    if name == "round":
        v = ev(e.args[0])
        if len(e.args) > 1:
            d = int(_lit(e.args[1]))
            f = 10.0 ** d
            return jnp.round(v * f) / f
        return jnp.round(v)
    if name == "clamp":
        return jnp.clip(ev(e.args[0]), ev(e.args[1]), ev(e.args[2]))
    if name in ("mod", "atan2") and len(e.args) == 2:
        f = jnp.mod if name == "mod" else jnp.arctan2
        return f(ev(e.args[0]), ev(e.args[1]))
    if name in ("greatest", "least") and len(e.args) >= 2:
        f = jnp.maximum if name == "greatest" else jnp.minimum
        out = ev(e.args[0])
        for a in e.args[1:]:
            out = f(out, ev(a))
        return out
    if name == "coalesce" and e.args:
        out = ev(e.args[0])
        for a in e.args[1:]:
            nxt = ev(a)
            out = jnp.where(jnp.isnan(out), nxt, out)
        return out
    if name in _DEVICE_FUNCS and len(e.args) == 1:
        return _DEVICE_FUNCS[name](ev(e.args[0]))
    if name == "to_unixtime":
        ts_expr = e.args[0]
        unit = _col_unit_nanos(ts_expr, schema)
        return ev(ts_expr) * unit // 10**9
    raise PlanError(f"unsupported device function {name!r}")


_TRUNC_UNITS = {
    "second": 10**9, "minute": 60 * 10**9, "hour": 3600 * 10**9,
    "day": 86400 * 10**9, "week": 7 * 86400 * 10**9,
}


def _col_unit_nanos(ts_expr: ast.Expr, schema: Schema) -> int:
    if isinstance(ts_expr, ast.Column) and ts_expr.name in schema.names:
        dt = schema.column(ts_expr.name).dtype
        if dt.is_timestamp:
            return dt.time_unit.nanos_per_unit
    return 1  # already nanoseconds or plain int


def _interval_in_col_unit(interval, ts_expr: ast.Expr, schema: Schema) -> int:
    return _scale_to_col_unit(_interval_nanos(interval), ts_expr, schema)


def _interval_nanos(e) -> int:
    """Interval AST node or a duration string literal ('1m', '1 minute')
    → nanoseconds. date_bin/time_bucket accept both spellings."""
    if isinstance(e, ast.Interval):
        return e.nanos
    if isinstance(e, ast.Literal) and isinstance(e.value, str):
        from greptimedb_tpu.promql.parser import parse_duration_s
        s = e.value.strip().lower()
        verbose = {"second": "s", "seconds": "s", "minute": "m",
                   "minutes": "m", "hour": "h", "hours": "h", "day": "d",
                   "days": "d", "week": "w", "weeks": "w",
                   "millisecond": "ms", "milliseconds": "ms"}
        parts = s.split()
        if len(parts) == 2 and parts[1] in verbose:
            s = parts[0] + verbose[parts[1]]
        try:
            nanos = int(parse_duration_s(s) * 1e9)
        except Exception as exc:  # noqa: BLE001 — planner boundary
            raise PlanError(f"bad interval {e.value!r}") from exc
        if nanos <= 0:
            raise PlanError(f"interval must be positive, got {e.value!r}")
        return nanos
    if isinstance(e, ast.Literal) and isinstance(e.value, (int, float)):
        if int(e.value) <= 0:
            raise PlanError("interval must be positive")
        return int(e.value)
    raise PlanError("expected interval")


def _scale_to_col_unit(nanos: int, ts_expr: ast.Expr, schema: Schema) -> int:
    unit = _col_unit_nanos(ts_expr, schema)
    step = max(nanos // unit, 1)
    return step


def _as_bool(v):
    if v.dtype == jnp.bool_:
        return v
    return v != 0


# ---- host evaluation (numpy; strings allowed; env substitution) ------------


def eval_host(
    e: ast.Expr,
    cols: dict[str, np.ndarray],
    schema: Optional[Schema] = None,
    env: Optional[dict] = None,
    n: Optional[int] = None,
):
    """Numpy twin of eval_device. `env` maps expression *nodes* (hashable)
    to precomputed arrays — how aggregate results and group keys flow into
    post-aggregation expressions."""

    def ev(x):
        return eval_host(x, cols, schema, env, n)

    if env is not None and e in env:
        return env[e]
    if isinstance(e, ast.Column):
        if e.name in cols:
            return cols[e.name]
        raise PlanError(f"unknown column {e.name!r}")
    if isinstance(e, ast.Literal):
        return np.nan if e.value is None else e.value
    if isinstance(e, ast.Interval):
        return e.nanos
    if isinstance(e, ast.BinaryOp):
        if e.op == "and":
            return _np_bool(ev(e.left)) & _np_bool(ev(e.right))
        if e.op == "or":
            return _np_bool(ev(e.left)) | _np_bool(ev(e.right))
        a, b = ev(e.left), ev(e.right)
        if e.op == "like":
            rx = _like_to_regex(str(b))
            return np.asarray([v is not None and rx.fullmatch(str(v)) is not None
                               for v in np.atleast_1d(a)])
        ops = {
            "+": lambda: a + b, "-": lambda: a - b, "*": lambda: a * b,
            "%": lambda: a % b,
            "=": lambda: _str_eq(a, b), "!=": lambda: ~_str_eq(a, b),
            "<": lambda: a < b, "<=": lambda: a <= b,
            ">": lambda: a > b, ">=": lambda: a >= b,
        }
        if e.op == "/":
            if np.issubdtype(np.result_type(np.asarray(a), np.asarray(b)), np.integer):
                return np.asarray(a) // np.asarray(b)
            return np.asarray(a) / np.asarray(b)
        if e.op in ops:
            return ops[e.op]()
        raise PlanError(f"unsupported host op {e.op!r}")
    if isinstance(e, ast.UnaryOp):
        v = ev(e.operand)
        return ~_np_bool(v) if e.op == "not" else -v
    if isinstance(e, ast.Between):
        x = ev(e.expr)
        res = (x >= ev(e.low)) & (x <= ev(e.high))
        return ~res if e.negated else res
    if isinstance(e, ast.InList):
        x = np.asarray(ev(e.expr))
        items = [_scalar(ev(i)) for i in e.items]
        if x.dtype == object:
            res = np.isin(x.astype(str), [str(i) for i in items])
        else:
            res = np.isin(x, items)
        return ~res if e.negated else res
    if isinstance(e, ast.IsNull):
        v = np.asarray(ev(e.expr))
        if v.dtype == object:
            res = np.asarray([x is None for x in v])
        elif np.issubdtype(v.dtype, np.floating):
            res = np.isnan(v)
        else:
            res = np.zeros(v.shape, bool)
        return ~res if e.negated else res
    if isinstance(e, ast.FuncCall):
        if e.order_within is not None:
            raise PlanError(
                f"ORDER BY inside {e.name}() is only supported for "
                "first_value/last_value")
        return _eval_host_func(e, ev, schema)
    if isinstance(e, ast.Cast):
        v = ev(e.expr)
        t = e.type_name.lower()
        if t in ("double", "float64", "float", "real", "float32"):
            return np.asarray(v, dtype=np.float64)
        if t in ("bigint", "int64", "int", "integer", "int32"):
            return np.asarray(v).astype(np.int64)
        if t in ("string", "varchar", "text"):
            return np.asarray([None if x is None else str(x) for x in np.atleast_1d(v)],
                              dtype=object)
        if t.startswith("timestamp"):
            from greptimedb_tpu.datatypes.types import parse_sql_type
            dtype = parse_sql_type(t)
            arr = np.atleast_1d(v)
            return np.asarray([coerce_ts_literal(x, dtype) for x in arr], dtype=np.int64)
        if t in ("boolean", "bool"):
            arr = np.atleast_1d(v)
            if arr.dtype.kind in ("U", "O", "S"):
                def _b(x):
                    if x is None:
                        return None
                    s = str(x).strip().lower()
                    if s in ("true", "t", "1", "yes"):
                        return True
                    if s in ("false", "f", "0", "no"):
                        return False
                    raise PlanError(f"invalid boolean literal {x!r}")
                out = np.asarray([_b(x) for x in arr], dtype=object)
                return out if np.ndim(v) else out[0]
            return arr.astype(bool) if np.ndim(v) else bool(arr[0])
        raise PlanError(f"unsupported cast to {e.type_name!r}")
    if isinstance(e, ast.Case):
        whens = e.whens
        if e.operand is not None:
            op = np.asarray(ev(e.operand))
            conds = [_str_eq(op, ev(c)) for c, _ in whens]
        else:
            conds = [_np_bool(np.asarray(ev(c))) for c, _ in whens]
        vals = [ev(v) for _, v in whens]
        out = ev(e.else_) if e.else_ is not None else np.nan
        res = np.select(conds, [np.broadcast_to(v, conds[0].shape) for v in vals],
                        default=out)
        return res
    raise PlanError(f"cannot evaluate {e!r} on host")


def _eval_host_func(e: ast.FuncCall, ev, schema):
    name = e.name
    np_funcs = {
        "abs": np.abs, "sqrt": np.sqrt, "exp": np.exp, "ln": np.log,
        "log": np.log, "log2": np.log2, "log10": np.log10,
        "floor": np.floor, "ceil": np.ceil, "signum": np.sign,
        "sin": np.sin, "cos": np.cos, "tan": np.tan, "trunc": np.trunc,
        "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
        "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
        "degrees": np.degrees, "radians": np.radians,
    }
    if name in np_funcs and len(e.args) == 1:
        return np_funcs[name](np.asarray(ev(e.args[0]), dtype=np.float64))
    if name in ("pow", "power"):
        return np.power(ev(e.args[0]), ev(e.args[1]))
    if name in ("mod", "atan2") and len(e.args) == 2:
        f = np.mod if name == "mod" else np.arctan2
        return f(np.asarray(ev(e.args[0]), dtype=np.float64),
                 np.asarray(ev(e.args[1]), dtype=np.float64))
    if name in ("greatest", "least") and len(e.args) >= 2:
        f = np.maximum if name == "greatest" else np.minimum
        out = np.asarray(ev(e.args[0]))
        for a in e.args[1:]:
            out = f(out, np.asarray(ev(a)))
        return out
    if name == "coalesce" and e.args:
        vals = [np.atleast_1d(np.asarray(ev(a))) for a in e.args]
        if any(v.dtype == object for v in vals):
            # string/tag columns: float/NaN semantics would raise; merge
            # elementwise on `is None` instead
            n = max(v.shape[0] for v in vals)
            out = np.broadcast_to(vals[0], (n,)).astype(object).copy()
            for v in vals[1:]:
                nxt = np.broadcast_to(v, (n,))
                # missing = None or NaN (float NULLs keep NaN semantics
                # even when boxed in an object array)
                missing = np.asarray(
                    [x is None or x != x for x in out], dtype=bool)
                out[missing] = nxt[missing]
            return out
        out = vals[0].astype(np.float64)
        for a in vals[1:]:
            nxt = np.broadcast_to(a.astype(np.float64), out.shape)
            out = np.where(np.isnan(out), nxt, out)
        return out
    if name == "clamp" and len(e.args) == 3:
        return np.clip(np.asarray(ev(e.args[0]), dtype=np.float64),
                       ev(e.args[1]), ev(e.args[2]))
    if name == "to_unixtime" and len(e.args) == 1:
        unit = _col_unit_nanos(e.args[0], schema) if schema else 10**6
        return np.asarray(ev(e.args[0])) * unit // 10**9
    if name == "date_format" and len(e.args) == 2:
        import datetime as _dt
        unit = _col_unit_nanos(e.args[0], schema) if schema else 10**6
        fmt = str(_lit(e.args[1]))
        vals = np.atleast_1d(np.asarray(ev(e.args[0]), dtype=np.int64))
        out = np.asarray([
            _dt.datetime.fromtimestamp(v * unit / 1e9, _dt.timezone.utc)
            .strftime(fmt) for v in vals.tolist()], dtype=object)
        return out
    if name == "version":
        return "8.0.0-greptimedb-tpu"
    if name == "build":
        from greptimedb_tpu import __version__
        return f"greptimedb_tpu {__version__} (jax/XLA TPU backend)"
    if name in ("database", "current_schema", "schema"):
        return "public"  # overridden with session db in engine._select
    if name == "timezone":
        return "UTC"
    if name == "round":
        v = np.asarray(ev(e.args[0]), dtype=np.float64)
        d = int(_lit(e.args[1])) if len(e.args) > 1 else 0
        return np.round(v, d)
    if name in ("date_bin", "time_bucket"):
        interval, ts_expr = e.args[0], e.args[1]
        step = _interval_in_col_unit(interval, ts_expr, schema) if schema else _lit_interval(interval)
        ts = np.asarray(ev(ts_expr))
        return ts // step * step
    if name == "date_trunc":
        unit_lit, ts_expr = e.args[0], e.args[1]
        unit = str(_lit(unit_lit)).lower()
        nanos = _TRUNC_UNITS.get(unit)
        if nanos is None:
            raise PlanError(f"date_trunc unit {_lit(unit_lit)!r} unsupported")
        step = _scale_to_col_unit(nanos, ts_expr, schema) if schema else nanos
        ts = np.asarray(ev(ts_expr))
        shift = 0
        if unit == "week":
            # weeks start on Monday; epoch is a Thursday (device branch)
            shift_ns = 3 * 86400 * 10**9
            shift = (_scale_to_col_unit(shift_ns, ts_expr, schema)
                     if schema else shift_ns)
        return (ts + shift) // step * step - shift
    if name == "now":
        import time as _time
        return int(_time.time() * 1000)
    if name == "date_part":
        # date_part('year', ts) / EXTRACT(year FROM ts) — calendar field
        # extraction (reference: DataFusion date_part)
        import datetime as _dt
        unit = str(_lit(e.args[0])).lower()
        ts_expr = e.args[1]
        col_unit = _col_unit_nanos(ts_expr, schema) if schema else 10**6
        vals = np.atleast_1d(np.asarray(ev(ts_expr), dtype=np.int64))
        secs = vals * col_unit / 1e9
        getters = {
            "year": lambda d: d.year, "month": lambda d: d.month,
            "day": lambda d: d.day, "hour": lambda d: d.hour,
            "minute": lambda d: d.minute, "second": lambda d: d.second,
            "dow": lambda d: (d.weekday() + 1) % 7,  # Sunday = 0
            "doy": lambda d: d.timetuple().tm_yday,
            "week": lambda d: d.isocalendar()[1],
            "quarter": lambda d: (d.month - 1) // 3 + 1,
            "epoch": None,
        }
        if unit not in getters:
            raise PlanError(f"date_part unit {unit!r} unsupported")
        if unit == "epoch":
            return secs
        get = getters[unit]
        return np.asarray([
            get(_dt.datetime.fromtimestamp(s, _dt.timezone.utc))
            for s in secs.tolist()], dtype=np.int64)
    if name in _STRING_FUNCS:
        return _STRING_FUNCS[name](e, ev)
    # extension seam: plugin-registered scalar functions (resolved against
    # the executing engine's container, falling back to the process default)
    from greptimedb_tpu.plugins import active_plugins
    plugin_fn = active_plugins().scalar_function(name)
    if plugin_fn is not None:
        return plugin_fn(*(ev(a) for a in e.args))
    raise PlanError(f"unsupported host function {name!r}")


def _obj_col(v) -> np.ndarray:
    return np.atleast_1d(np.asarray(v, dtype=object))


def _str_map(fn):
    """Element-wise NULL-preserving string transform."""
    def apply(e, ev):
        vals = _obj_col(ev(e.args[0]))
        return np.asarray(
            [None if v is None else fn(str(v)) for v in vals], dtype=object)
    return apply


def _fn_concat(e, ev):
    # DataFusion concat skips NULL arguments (the reference's behavior)
    cols = [_obj_col(ev(a)) for a in e.args]
    n = max(len(c) for c in cols)
    cols = [np.broadcast_to(c, (n,)) if len(c) != n else c for c in cols]
    return np.asarray(
        ["".join(str(c[i]) for c in cols if c[i] is not None)
         for i in range(n)], dtype=object)


def _fn_length(e, ev):
    vals = _obj_col(ev(e.args[0]))
    return np.asarray(
        [None if v is None else len(str(v)) for v in vals], dtype=object)


def _fn_substr(e, ev):
    vals = _obj_col(ev(e.args[0]))
    start = int(_lit(e.args[1]))
    ln = int(_lit(e.args[2])) if len(e.args) > 2 else None
    # SQL substr is 1-based and the length window anchors at the TRUE
    # start even when it is <= 0 (substr('alphabet', 0, 3) = 'al')
    i0 = max(start - 1, 0)
    i1 = None if ln is None else max(start - 1 + ln, 0)
    return np.asarray(
        [None if v is None else str(v)[i0:i1] for v in vals], dtype=object)


def _fn_replace(e, ev):
    vals = _obj_col(ev(e.args[0]))
    old, new = str(_lit(e.args[1])), str(_lit(e.args[2]))
    return np.asarray(
        [None if v is None else str(v).replace(old, new) for v in vals],
        dtype=object)


def _fn_affix(method):
    def apply(e, ev):
        vals = _obj_col(ev(e.args[0]))
        probe = str(_lit(e.args[1]))
        # NULL input stays NULL (three-valued logic), not FALSE
        return np.asarray(
            [None if v is None else getattr(str(v), method)(probe)
             for v in vals], dtype=object)
    return apply


#: string scalar functions (reference: DataFusion string fns used by the
#: sqlness suites — lower/upper/trim/length/concat/substr/replace/...)
_STRING_FUNCS = {
    "lower": _str_map(str.lower),
    "upper": _str_map(str.upper),
    "trim": _str_map(str.strip),
    "ltrim": _str_map(str.lstrip),
    "rtrim": _str_map(str.rstrip),
    "reverse": _str_map(lambda s: s[::-1]),
    "length": _fn_length,
    "char_length": _fn_length,
    "character_length": _fn_length,
    "concat": _fn_concat,
    "substr": _fn_substr,
    "substring": _fn_substr,
    "replace": _fn_replace,
    "starts_with": _fn_affix("startswith"),
    "ends_with": _fn_affix("endswith"),
}


def _lit_interval(e):
    return _interval_nanos(e)


def _np_bool(v):
    v = np.asarray(v)
    return v if v.dtype == bool else v != 0


def _str_eq(a, b):
    a_obj = isinstance(a, np.ndarray) and a.dtype == object
    b_obj = isinstance(b, np.ndarray) and b.dtype == object
    if a_obj or b_obj or isinstance(a, str) or isinstance(b, str):
        av = a.astype(str) if isinstance(a, np.ndarray) else str(a)
        bv = b.astype(str) if isinstance(b, np.ndarray) else str(b)
        return np.asarray(av == bv)
    return np.asarray(a == b)


def _scalar(v):
    arr = np.asarray(v)
    return arr.item() if arr.ndim == 0 else v


# ---- time-range extraction (scan pruning) ----------------------------------


def extract_ts_bounds(
    where: Optional[ast.Expr], ts_name: str, dtype: DataType
) -> Optional[tuple[Optional[int], Optional[int]]]:
    """Half-open [lo, hi) bounds on the time index from the conjunctive
    prefix of WHERE (the reference's scan_region time-predicate pruning,
    read/scan_region.rs:148)."""
    if where is None:
        return None
    lo: Optional[int] = None
    hi: Optional[int] = None

    def visit(e):
        nonlocal lo, hi
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            visit(e.left)
            visit(e.right)
            return
        if isinstance(e, ast.BinaryOp) and e.op in ("=", "<", "<=", ">", ">="):
            side = None
            if isinstance(e.left, ast.Column) and e.left.name == ts_name and isinstance(e.right, ast.Literal):
                side = (e.op, e.right.value)
            elif isinstance(e.right, ast.Column) and e.right.name == ts_name and isinstance(e.left, ast.Literal):
                side = (_flip(e.op), e.left.value)
            if side is None:
                return
            op, raw = side
            try:
                v = coerce_ts_literal(raw, dtype)
            except (ValueError, TypeError):
                return
            if op == ">=":
                lo = v if lo is None else max(lo, v)
            elif op == ">":
                lo = v + 1 if lo is None else max(lo, v + 1)
            elif op == "<":
                hi = v if hi is None else min(hi, v)
            elif op == "<=":
                hi = v + 1 if hi is None else min(hi, v + 1)
            elif op == "=":
                lo = v if lo is None else max(lo, v)
                hi = v + 1 if hi is None else min(hi, v + 1)
        if isinstance(e, ast.Between) and not e.negated:
            if isinstance(e.expr, ast.Column) and e.expr.name == ts_name:
                try:
                    l = coerce_ts_literal(_lit(e.low), dtype)
                    h = coerce_ts_literal(_lit(e.high), dtype)
                except (ValueError, TypeError, PlanError):
                    return
                lo = l if lo is None else max(lo, l)
                hi = h + 1 if hi is None else min(hi, h + 1)

    visit(where)
    if lo is None and hi is None:
        return None
    return lo, hi


def split_conjuncts(where) -> list:
    """The AND-conjunction atoms of a WHERE clause (None -> []) — the
    one splitter shared by join pushdown, rollup eligibility, and the
    cross-query batcher, so their notion of 'a conjunct' can't drift."""
    if where is None:
        return []
    if isinstance(where, ast.BinaryOp) and where.op == "and":
        return split_conjuncts(where.left) + split_conjuncts(where.right)
    return [where]


def collect_columns(e: Optional[ast.Expr], out: set[str]) -> set[str]:
    """All column names referenced by an expression."""
    if e is None:
        return out
    if isinstance(e, ast.Column):
        out.add(e.name)
    elif isinstance(e, ast.BinaryOp):
        collect_columns(e.left, out)
        collect_columns(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        collect_columns(e.operand, out)
    elif isinstance(e, ast.Between):
        for x in (e.expr, e.low, e.high):
            collect_columns(x, out)
    elif isinstance(e, ast.InList):
        collect_columns(e.expr, out)
        for i in e.items:
            collect_columns(i, out)
    elif isinstance(e, ast.IsNull):
        collect_columns(e.expr, out)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            collect_columns(a, out)
    elif isinstance(e, ast.Cast):
        collect_columns(e.expr, out)
    elif isinstance(e, ast.Case):
        if e.operand:
            collect_columns(e.operand, out)
        for c, v in e.whens:
            collect_columns(c, out)
            collect_columns(v, out)
        if e.else_:
            collect_columns(e.else_, out)
    return out


def has_aggregate(e: Optional[ast.Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, ast.FuncCall):
        if e.name in AGG_FUNCS:
            return True
        return any(has_aggregate(a) for a in e.args)
    if isinstance(e, ast.BinaryOp):
        return has_aggregate(e.left) or has_aggregate(e.right)
    if isinstance(e, ast.UnaryOp):
        return has_aggregate(e.operand)
    if isinstance(e, ast.Between):
        return any(has_aggregate(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, ast.InList):
        return has_aggregate(e.expr) or any(has_aggregate(i) for i in e.items)
    if isinstance(e, ast.IsNull):
        return has_aggregate(e.expr)
    if isinstance(e, ast.Cast):
        return has_aggregate(e.expr)
    if isinstance(e, ast.Case):
        parts = [e.operand, e.else_] + [x for w in e.whens for x in w]
        return any(has_aggregate(p) for p in parts if p is not None)
    return False


def collect_aggregates(e: Optional[ast.Expr], out: list) -> list:
    """All aggregate FuncCall nodes in an expression (deduplicated)."""
    if e is None:
        return out
    if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS:
        if e not in out:
            out.append(e)
        return out
    if isinstance(e, ast.FuncCall):
        for a in e.args:
            collect_aggregates(a, out)
    elif isinstance(e, ast.BinaryOp):
        collect_aggregates(e.left, out)
        collect_aggregates(e.right, out)
    elif isinstance(e, ast.UnaryOp):
        collect_aggregates(e.operand, out)
    elif isinstance(e, ast.Between):
        for x in (e.expr, e.low, e.high):
            collect_aggregates(x, out)
    elif isinstance(e, ast.Case):
        for w in e.whens:
            collect_aggregates(w[0], out)
            collect_aggregates(w[1], out)
        if e.operand:
            collect_aggregates(e.operand, out)
        if e.else_:
            collect_aggregates(e.else_, out)
    elif isinstance(e, ast.Cast):
        collect_aggregates(e.expr, out)
    elif isinstance(e, ast.InList):
        collect_aggregates(e.expr, out)
    elif isinstance(e, ast.IsNull):
        collect_aggregates(e.expr, out)
    return out


AGG_FUNCS = {
    "count", "sum", "avg", "mean", "min", "max", "first", "last",
    "last_value", "first_value", "stddev", "variance",
    "argmax", "argmin", "median", "percentile", "approx_percentile_cont",
    "polyval",
}
