"""Order-statistic aggregates computed host-side (mirrors reference
common/function UDAFs: argmax, argmin, percentile, median, polyval —
src/common/function/src/scalars/aggregate/).

These need the full value multiset per group (not a streaming segment
reduction), so they run as a vectorized numpy pass over the scan's host
columns — sort rows by (group, value) once, then per-group answers come
from segment boundaries. The device segment kernels stay untouched for
the hot streaming aggregates; host aggs compose with them in one query.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: aggregate funcs routed through this module
HOST_AGGS = frozenset({"argmax", "argmin", "median", "percentile", "polyval"})


def compute_host_agg(func: str, gid: np.ndarray, values: np.ndarray,
                     mask: np.ndarray, num_groups: int,
                     extra: tuple = ()) -> np.ndarray:
    """Return a per-group array (length num_groups) for `func`.

    gid: int group id per row; values: float per row; mask: row validity.
    Rows with NaN values are excluded (SQL NULL semantics).
    """
    values = np.asarray(values, dtype=np.float64)
    valid = mask & ~np.isnan(values)
    gid_v = gid[valid]
    val_v = values[valid]
    idx_v = np.flatnonzero(valid)

    out = np.full(num_groups, np.nan)
    if gid_v.size == 0:
        return out

    if func in ("argmax", "argmin"):
        # sort by (gid, value); last row of each group's run is the max.
        # lexsort is stable, so ties resolve to the later row for argmax
        # (matching "last occurrence of the extreme") and the earlier row
        # for argmin via the reversed value order.
        order = np.lexsort((idx_v, val_v, gid_v))
        g_sorted = gid_v[order]
        # last position of each gid run
        last = np.flatnonzero(np.r_[g_sorted[1:] != g_sorted[:-1], True])
        first = np.r_[0, last[:-1] + 1]
        pick = last if func == "argmax" else first
        out[g_sorted[pick]] = idx_v[order][pick]
        return out

    if func in ("median", "percentile"):
        q = float(extra[0]) if func == "percentile" else 50.0
        if not 0.0 <= q <= 100.0:
            from greptimedb_tpu.query.expr import PlanError
            raise PlanError(f"percentile {q} out of [0, 100]")
        order = np.lexsort((val_v, gid_v))
        g_sorted = gid_v[order]
        v_sorted = val_v[order]
        last = np.flatnonzero(np.r_[g_sorted[1:] != g_sorted[:-1], True])
        first = np.r_[0, last[:-1] + 1]
        counts = last - first + 1
        # linear interpolation at q/100 * (n-1), vectorized over groups
        pos = first + (q / 100.0) * (counts - 1)
        lo = np.floor(pos).astype(np.int64)
        hi = np.ceil(pos).astype(np.int64)
        frac = pos - lo
        vals = v_sorted[lo] * (1 - frac) + v_sorted[hi] * frac
        out[g_sorted[first]] = vals
        return out

    if func == "polyval":
        # rows of each group are polynomial coefficients (highest degree
        # first, in row order); evaluate at x = extra[0]
        x = float(extra[0])
        order = np.lexsort((idx_v, gid_v))
        g_sorted = gid_v[order]
        v_sorted = val_v[order]
        last = np.flatnonzero(np.r_[g_sorted[1:] != g_sorted[:-1], True])
        first = np.r_[0, last[:-1] + 1]
        counts = last - first + 1
        pos_in_group = np.arange(g_sorted.size) - np.repeat(first, counts)
        degree = np.repeat(counts, counts) - 1 - pos_in_group
        terms = v_sorted * np.power(x, degree.astype(np.float64))
        sums = np.add.reduceat(terms, first)
        out[g_sorted[first]] = sums
        return out

    from greptimedb_tpu.query.expr import PlanError
    raise PlanError(f"unknown host aggregate {func!r}")


def row_group_ids(keys, strides, scan, extra_cols) -> np.ndarray:
    """Per-row group id on host, replicating the device key formula
    (physical._agg_block): tag → code+1, bucket → col//step − base,
    pre → factorized codes."""
    some = next(iter(scan.columns.values()))
    gid = np.zeros(len(some), dtype=np.int64)
    for k, stride in zip(keys, strides):
        col = extra_cols.get(k.column)
        if col is None:
            col = scan.columns[k.column]
        col = np.asarray(col)
        if k.kind == "tag":
            arr = (col + 1).astype(np.int64)
        elif k.kind == "bucket":
            arr = (col // k.step - k.base).astype(np.int64)
        else:
            arr = col.astype(np.int64)
        gid += np.clip(arr, 0, k.size - 1) * stride
    return gid


def host_row_mask(scan, bound_where, schema, mask_len: int,
                  dedup_mask: Optional[np.ndarray]) -> np.ndarray:
    """Row validity on host: the BOUND WHERE predicate evaluated over the
    raw scan columns (tag codes, coerced ts ints — device semantics),
    plus the last-write-wins dedup mask."""
    mask = np.ones(mask_len, dtype=bool)
    if dedup_mask is not None:
        mask &= np.asarray(dedup_mask)[:mask_len]
    if bound_where is not None:
        from greptimedb_tpu.query.expr import eval_host

        w = eval_host(bound_where, scan.columns, schema, None, mask_len)
        mask &= np.broadcast_to(np.asarray(w, dtype=bool), (mask_len,))
    return mask


def decoded_columns(scan) -> dict:
    """scan columns with tag codes decoded to strings (host eval space)."""
    out = {}
    for name, col in scan.columns.items():
        if name in scan.tag_dicts:
            d = scan.tag_dicts[name]
            codes = np.asarray(col)
            vals = np.empty(len(codes), dtype=object)
            ok = codes >= 0
            vals[ok] = d[codes[ok]]
            vals[~ok] = None
            out[name] = vals
        else:
            out[name] = np.asarray(col)
    return out


def compute_host_agg_str(func: str, gid: np.ndarray, values: np.ndarray,
                         ts: Optional[np.ndarray], mask: np.ndarray,
                         num_groups: int) -> np.ndarray:
    """String-typed first/last/min/max: the device segment kernel only
    reduces numbers (tag codes are dictionary positions, not orderable
    values), so these pick per group from the decoded host values.
    Returns an object array with None for empty groups."""
    valid = mask & np.asarray(
        [v is not None and not (isinstance(v, float) and v != v)
         for v in values])
    if func == "count":
        # count of non-NULL string values per group (the device planes
        # only count numerics)
        return np.bincount(gid[valid], minlength=num_groups)[
            :num_groups].astype(np.int64)
    if func == "count_distinct":
        out_i = np.zeros(num_groups, dtype=np.int64)
        if valid.any():
            gid_v = gid[valid]
            key = np.asarray([str(v) for v in values[valid]])
            order = np.lexsort((key, gid_v))
            g_s, k_s = gid_v[order], key[order]
            new = np.r_[True, (g_s[1:] != g_s[:-1]) | (k_s[1:] != k_s[:-1])]
            np.add.at(out_i, g_s[new], 1)
        return out_i
    out = np.full(num_groups, None, dtype=object)
    if not valid.any():
        return out
    gid_v = gid[valid]
    val_v = values[valid]
    if func in ("first", "last"):
        ts_v = np.asarray(ts)[valid]
        order = np.lexsort((ts_v, gid_v))
    else:  # min / max — lexicographic over the string values
        order = np.lexsort((val_v.astype(str), gid_v))
    g_sorted = gid_v[order]
    last = np.flatnonzero(np.r_[g_sorted[1:] != g_sorted[:-1], True])
    first = np.r_[0, last[:-1] + 1]
    pick = first if func in ("first", "min") else last
    out[g_sorted[pick]] = val_v[order][pick]
    return out
