"""Host hash-join executor for multi-table SELECTs.

Mirrors the reference's join capability (full SQL via DataFusion's hash
join). Joins in a TSDB serve metadata/dimension enrichment — modest
cardinalities off the scan/aggregate hot path — so the TPU-first design
keeps them on host: materialize each side (each side's scan still uses
the device path + caches), equi-hash-join, then evaluate the remaining
select pipeline over the joined columns with the shared host evaluator.

Supported: INNER / LEFT [OUTER] joins, conjunctions of equality
predicates in ON, qualified (alias.col) and unambiguous bare column
references, WHERE, projection incl. expressions, GROUP BY aggregates
(count/sum/avg/min/max), HAVING, ORDER BY, LIMIT/OFFSET, DISTINCT.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from greptimedb_tpu.query.expr import PlanError, eval_host
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast

_AGGS = {"count", "sum", "avg", "min", "max"}


def execute_join_select(qe, sel: ast.Select, ctx) -> QueryResult:
    # each side: (table_name_or_None, alias, derived_subquery_or_None)
    if sel.from_subquery is not None:
        if sel.table_alias is None:
            raise PlanError("derived table in a join requires an alias")
        sides = [(None, sel.table_alias, sel.from_subquery)]
    else:
        sides = [(sel.table, sel.table_alias or sel.table, None)]
    for j in sel.joins:
        sides.append((j.table, j.alias or j.table, j.subquery))
    names = [alias for _, alias, _ in sides]
    if len(set(names)) != len(names):
        raise PlanError(f"duplicate table alias in join: {names}")

    # materialize each side through the normal single-table path (device
    # scan + caches), pushing down single-side WHERE conjuncts and the
    # referenced-column projection so only the needed slice crosses into
    # the host join (the reference pushes the same through DataFusion's
    # join planning)
    conjuncts = _split_conjuncts(sel.where)
    side_cols = _referenced_by_side(sel, sides)
    # the null-supplying side(s) of an outer join must NOT have WHERE
    # conjuncts pushed into their scan: `WHERE right.x IS NULL`
    # (anti-join) would drop the very rows whose absence produces the
    # NULLs. LEFT → right side; RIGHT/FULL → conservatively all sides
    # (the accumulated left is a composite).
    unpushable = {j.alias or j.table for j in sel.joins if j.kind == "left"}
    if any(j.kind in ("right", "full") for j in sel.joins):
        unpushable = set(names)
    mats = []
    for table, alias, subq in sides:
        if subq is not None:
            r = qe._execute_statement(subq, ctx)
            if not r.is_query:
                raise PlanError("derived table must be a query")
            mats.append({"alias": alias,
                         "cols": dict(zip(r.names,
                                          (np.asarray(c)
                                           for c in r.columns))),
                         "dtypes": dict(zip(r.names, r.dtypes))})
            continue
        pushed = [] if alias in unpushable else \
            [_strip_qualifier(c, alias) for c in conjuncts
             if _only_references(c, alias, sides)]
        where = None
        for p in pushed:
            where = p if where is None else ast.BinaryOp("and", where, p)
        wanted = side_cols.get(alias)
        if not wanted:  # no map (Star/bare refs) or nothing referenced
            items = [ast.SelectItem(ast.Star())]
        else:
            items = [ast.SelectItem(ast.Column(c)) for c in sorted(wanted)]
        sub = ast.Select(items=items, table=table, where=where)
        try:
            r = qe._select(sub, ctx)
        except PlanError:
            # conservative fallback: a pushdown the single-table path
            # can't evaluate (pruning is an optimization, never required)
            sub = ast.Select(items=[ast.SelectItem(ast.Star())],
                             table=table)
            r = qe._select(sub, ctx)
        mats.append({"alias": alias,
                     "cols": dict(zip(r.names,
                                      (np.asarray(c) for c in r.columns))),
                     "dtypes": dict(zip(r.names, r.dtypes))})

    # left-deep fold: joined = base; for each join: hash-join with next
    joined_cols, joined_dtypes = _qualify(mats[0])
    for j, mat in zip(sel.joins, mats[1:]):
        right_cols, right_dtypes = _qualify(mat)
        pairs = [] if j.kind == "cross" else \
            _equi_pairs(j.on, joined_cols, right_cols)
        joined_cols, joined_dtypes = _hash_join(
            joined_cols, joined_dtypes, right_cols, right_dtypes,
            pairs, j.kind)

    # expose unambiguous bare names too
    bare: dict[str, Optional[str]] = {}
    for q in joined_cols:
        b = q.split(".", 1)[1]
        bare[b] = None if b in bare else q
    env_cols = dict(joined_cols)
    for b, q in bare.items():
        if q is not None:
            env_cols[b] = joined_cols[q]
            joined_dtypes[b] = joined_dtypes[q]

    state = {"cols": env_cols,
             "n": len(next(iter(env_cols.values()))) if env_cols else 0}

    def resolve(e):
        return _resolve_columns(e, state["cols"])

    def ev(e):
        return eval_host(resolve(e), state["cols"], None, None, state["n"])

    if sel.where is not None:
        mask = np.broadcast_to(np.asarray(ev(sel.where), dtype=bool),
                               (state["n"],))
        idx = np.nonzero(mask)[0]
        state["cols"] = {k: v[idx] for k, v in state["cols"].items()}
        state["n"] = len(idx)
    env_cols = state["cols"]
    n = state["n"]

    from greptimedb_tpu.query.window import rewrite_select, select_has_window
    if select_has_window(sel):
        if _has_grouping_aggs(sel):
            # SQL evaluation order: group first, windows over the groups
            inner, outer = split_groupby_window(sel)
            r = _aggregate(inner, env_cols, joined_dtypes, n, resolve)
            return execute_select_over(
                qe, outer, dict(zip(r.names, r.columns)),
                dict(zip(r.names, r.dtypes)))
        sel = rewrite_select(sel, env_cols, n, resolve, joined_dtypes)

    has_agg = sel.group_by or any(
        _contains_agg(it.expr) for it in sel.items)
    if has_agg:
        return _aggregate(sel, env_cols, joined_dtypes, n, resolve)

    # plain projection
    out_names, out_cols, out_dtypes = [], [], []
    for i, it in enumerate(sel.items):
        if isinstance(it.expr, ast.Star):
            for q in joined_cols:
                out_names.append(q)
                out_cols.append(env_cols[q])
                out_dtypes.append(joined_dtypes.get(q))
            continue
        v = ev(it.expr)
        arr = np.asarray([v] * n) if np.ndim(v) == 0 else np.asarray(v)
        out_names.append(it.alias or _expr_name(it.expr))
        out_cols.append(arr)
        out_dtypes.append(None)
    r = QueryResult(out_names, out_dtypes, out_cols)
    # ORDER BY may reference unprojected columns: evaluate keys over the
    # full joined namespace, not the projected output
    return _post(sel, r, resolve, env=env_cols)


def execute_select_over(qe, sel: ast.Select, base_cols: dict,
                        base_dtypes: dict, alias=None) -> QueryResult:
    """Evaluate a full SELECT pipeline over in-memory columns — the
    execution path for views (the view query materializes through the
    normal engine; the outer select then runs here) and any other
    virtual relation."""
    env = {k: np.asarray(v) for k, v in base_cols.items()}
    dtypes = dict(base_dtypes)
    if alias:
        for k in list(env):
            env[f"{alias}.{k}"] = env[k]
            dtypes[f"{alias}.{k}"] = dtypes.get(k)
    n = len(next(iter(env.values()))) if env else 0

    state = {"cols": env, "n": n}

    def resolve(e):
        return _resolve_columns(e, state["cols"])

    def ev(e):
        return eval_host(resolve(e), state["cols"], None, None, state["n"])

    if sel.where is not None:
        mask = np.broadcast_to(np.asarray(ev(sel.where), dtype=bool),
                               (state["n"],))
        idx = np.nonzero(mask)[0]
        state["cols"] = {k: v[idx] for k, v in state["cols"].items()}
        state["n"] = len(idx)
    env = state["cols"]
    n = state["n"]

    from greptimedb_tpu.query.window import rewrite_select, select_has_window
    if select_has_window(sel):
        if _has_grouping_aggs(sel):
            inner, outer = split_groupby_window(sel)
            r = _aggregate(inner, env, dtypes, n, resolve)
            return execute_select_over(
                qe, outer, dict(zip(r.names, r.columns)),
                dict(zip(r.names, r.dtypes)))
        sel = rewrite_select(sel, env, n, resolve, dtypes)

    if sel.group_by or any(_contains_agg(it.expr) for it in sel.items):
        return _aggregate(sel, env, dtypes, n, resolve)

    out_names, out_cols, out_dtypes = [], [], []
    for i, it in enumerate(sel.items):
        if isinstance(it.expr, ast.Star):
            for k in base_cols:
                out_names.append(k)
                out_cols.append(env[k])
                out_dtypes.append(dtypes.get(k))
            continue
        v = ev(it.expr)
        arr = np.asarray([v] * n) if np.ndim(v) == 0 else np.asarray(v)
        out_names.append(it.alias or _expr_name(it.expr))
        out_cols.append(arr)
        out_dtypes.append(None)
    r = QueryResult(out_names, out_dtypes, out_cols)
    return _post(sel, r, resolve, env=env)


# ---- pushdown helpers ------------------------------------------------------


def _split_conjuncts(where):
    from greptimedb_tpu.query.expr import split_conjuncts

    return split_conjuncts(where)


def _columns_in(e, out: set):
    if isinstance(e, ast.Column):
        out.add((e.table, e.name))
    elif isinstance(e, (list, tuple)):
        # descends into nested containers too — Case.whens is a tuple of
        # (when_expr, then_expr) tuples
        for x in e:
            _columns_in(x, out)
    elif dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            # non-Expr expression carriers descend too: FuncCall.over is
            # a WindowSpec whose PARTITION BY/ORDER BY reference columns
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v) and not isinstance(v, type)):
                _columns_in(v, out)


def _only_references(conjunct, alias: str, sides) -> bool:
    """True iff every column in the conjunct is qualified with `alias` —
    safe to evaluate inside that side's scan (bare names are left to the
    post-join filter; qualification is the pushdown opt-in)."""
    cols: set = set()
    _columns_in(conjunct, cols)
    return bool(cols) and all(t == alias for t, _ in cols)


def _strip_qualifier(e, alias: str):
    return _rewrite_columns(
        e, lambda c: ast.Column(c.name) if c.table == alias else c)


def _referenced_by_side(sel, sides) -> dict:
    """alias -> column-name set to project per side, or {} (meaning: no
    per-side map — project everything) when a Star or any bare (or
    unattributable) reference appears."""
    cols: set = set()
    star = False
    for it in sel.items:
        if isinstance(it.expr, ast.Star):
            star = True
        else:
            _columns_in(it.expr, cols)
    _columns_in(sel.where, cols)
    for j in sel.joins:
        _columns_in(j.on, cols)
    for g in sel.group_by:
        _columns_in(g, cols)
    _columns_in(sel.having, cols)
    for ob in sel.order_by:
        _columns_in(ob.expr, cols)
    if star or any(t is None for t, _ in cols):
        return {}
    aliases = {alias for _, alias, _ in sides}
    if any(t not in aliases for t, _ in cols):
        return {}
    out: dict = {}
    for t, c in cols:
        out.setdefault(t, set()).add(c)
    # a side nothing references still needs its join keys (covered above
    # via ON) — and at least one column to materialize row count
    for _, alias, _ in sides:
        out.setdefault(alias, set())
    return out


# ---- helpers ---------------------------------------------------------------


def _qualify(mat):
    cols = {f"{mat['alias']}.{k}": v for k, v in mat["cols"].items()}
    dtypes = {f"{mat['alias']}.{k}": v for k, v in mat["dtypes"].items()}
    return cols, dtypes


def _rewrite_columns(e, repl):
    """Apply `repl` to every Column node, descending dataclass fields AND
    nested containers (Case.whens is a tuple of (when, then) tuples;
    FuncCall.over is a WindowSpec carrying PARTITION BY/ORDER BY exprs)."""
    if isinstance(e, ast.Column):
        return repl(e)
    if isinstance(e, (list, tuple)):
        return type(e)(_rewrite_columns(x, repl) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v) and not isinstance(v, type)):
                nv = _rewrite_columns(v, repl)
                if nv != v:
                    changes[f.name] = nv
        if changes:
            return dataclasses.replace(e, **changes)
    return e


def _resolve_columns(e, cols: dict):
    """Rewrite Column nodes to the joined namespace: alias-qualified
    references become 'alias.col'; bare names must be unambiguous."""

    def repl(c: ast.Column):
        if c.table:
            q = f"{c.table}.{c.name}"
            if q not in cols:
                raise PlanError(f"unknown column {q!r} in join")
            return ast.Column(q)
        if c.name in cols:
            return c
        matches = [q for q in cols
                   if "." in q and q.split(".", 1)[1] == c.name]
        if len(matches) == 1:
            return ast.Column(matches[0])
        if len(matches) > 1:
            raise PlanError(f"ambiguous column {c.name!r}: {matches}")
        raise PlanError(f"unknown column {c.name!r} in join")

    return _rewrite_columns(e, repl)


def _equi_pairs(on, left_cols: dict, right_cols: dict):
    """(left_key, right_key) pairs from a conjunction of equalities."""
    pairs = []

    def side_of(c: ast.Column):
        if c.table:
            q = f"{c.table}.{c.name}"
            if q in left_cols:
                return "l", q
            if q in right_cols:
                return "r", q
            raise PlanError(f"unknown column {q!r} in ON")
        lm = [q for q in left_cols if q.split(".", 1)[1] == c.name]
        rm = [q for q in right_cols if q.split(".", 1)[1] == c.name]
        if len(lm) + len(rm) != 1:
            raise PlanError(
                f"ambiguous or unknown ON column {c.name!r}")
        return ("l", lm[0]) if lm else ("r", rm[0])

    def walk(e):
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if (isinstance(e, ast.BinaryOp) and e.op == "="
                and isinstance(e.left, ast.Column)
                and isinstance(e.right, ast.Column)):
            s1, q1 = side_of(e.left)
            s2, q2 = side_of(e.right)
            if {s1, s2} != {"l", "r"}:
                raise PlanError("ON clause must compare the two sides")
            pairs.append((q1, q2) if s1 == "l" else (q2, q1))
            return
        raise PlanError(
            "only conjunctions of column equalities are supported in ON")

    walk(on)
    if not pairs:
        raise PlanError("ON clause has no equality condition")
    return pairs


def _key_tuple(cols: dict, keys: list, i: int):
    return tuple(None if _is_nan(cols[k][i]) else cols[k][i] for k in keys)


def _is_nan(v) -> bool:
    return isinstance(v, float) and v != v


def _hash_join(lcols, ldtypes, rcols, rdtypes, pairs, kind: str):
    """Hash join of two qualified column dicts. kinds: inner, left,
    right, full (null-extended on the respective side), cross
    (cartesian, no pairs)."""
    rn = len(next(iter(rcols.values()))) if rcols else 0
    ln = len(next(iter(lcols.values()))) if lcols else 0
    if kind == "cross":
        li = np.repeat(np.arange(ln, dtype=np.int64), rn)
        ri = np.tile(np.arange(rn, dtype=np.int64), ln)
    else:
        lk = [p[0] for p in pairs]
        rk = [p[1] for p in pairs]
        table: dict = {}
        for i in range(rn):
            key = _key_tuple(rcols, rk, i)
            if any(k is None for k in key):
                continue  # NULL never matches in SQL equality
            table.setdefault(key, []).append(i)
        li_l, ri_l = [], []
        matched_r = np.zeros(rn, dtype=bool)
        for i in range(ln):
            key = _key_tuple(lcols, lk, i)
            hits = table.get(key) if not any(k is None for k in key) else None
            if hits:
                for j in hits:
                    li_l.append(i)
                    ri_l.append(j)
                    matched_r[j] = True
            elif kind in ("left", "full"):
                li_l.append(i)
                ri_l.append(-1)  # NULL right row
        if kind in ("right", "full"):
            for j in np.flatnonzero(~matched_r):
                li_l.append(-1)  # NULL left row
                ri_l.append(int(j))
        li = np.asarray(li_l, dtype=np.int64)
        ri = np.asarray(ri_l, dtype=np.int64)

    def take(cols: dict, idx: np.ndarray) -> dict:
        miss = idx < 0
        out = {}
        for k, v in cols.items():
            v = np.asarray(v)
            taken = v[np.clip(idx, 0, None)] if len(v) else \
                np.empty(len(idx), dtype=v.dtype)
            if miss.any():
                taken = taken.astype(object)
                taken[miss] = None
            out[k] = taken
        return out

    out = take(lcols, li)
    out.update(take(rcols, ri))
    dtypes = {**ldtypes, **rdtypes}
    return out, dtypes


def _has_grouping_aggs(sel: ast.Select) -> bool:
    """True when the SELECT needs an aggregation pass before windows:
    GROUP BY, or any non-window aggregate call — INCLUDING one appearing
    only inside an OVER clause (e.g. rank() OVER (ORDER BY avg(v)):
    valid SQL, one implicit group)."""
    if sel.group_by:
        return True
    from greptimedb_tpu.query.planner import _FUNC_CANON

    found = [False]

    def walk(e):
        if found[0]:
            return
        if isinstance(e, ast.FuncCall):
            if e.over is None and e.name.lower() in _FUNC_CANON:
                found[0] = True
                return
            for a in e.args:
                walk(a)
            if e.over is not None:
                walk(e.over.partition_by)
                for o, _ in e.over.order_by:
                    walk(o)
            return
        if isinstance(e, (list, tuple)):
            for x in e:
                walk(x)
        elif dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    walk(v)

    for it in sel.items:
        walk(it.expr)
    for ob in sel.order_by:
        walk(ob.expr)
    return found[0]


def split_groupby_window(sel: ast.Select):
    """SELECT mixing GROUP BY (or plain aggregates) with window
    functions: SQL evaluates windows AFTER grouping, over the grouped
    relation (reference: DataFusion plans WindowAggExec above
    AggregateExec). Returns (inner, outer): `inner` is the window-free
    aggregate — group keys under their display names, each distinct
    aggregate call as __ga_i — and `outer` re-expresses the original
    items over inner's output with the window calls intact. The caller
    runs inner through the normal (device) aggregate path, then the
    window machinery over its G-row result."""
    from greptimedb_tpu.query.planner import _FUNC_CANON

    aggs: list[ast.FuncCall] = []

    def collect(e):
        if isinstance(e, ast.FuncCall):
            if e.over is None and e.name.lower() in _FUNC_CANON:
                if e not in aggs:
                    aggs.append(e)
                return
            for a in e.args:
                collect(a)
            if e.over is not None:
                collect(e.over.partition_by)
                for o, _ in e.over.order_by:
                    collect(o)
            return
        if isinstance(e, (list, tuple)):
            for x in e:
                collect(x)
        elif dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    collect(v)

    for it in sel.items:
        collect(it.expr)
    for ob in sel.order_by:
        collect(ob.expr)

    repl: list[tuple] = []
    inner_items: list[ast.SelectItem] = []
    alias_to_expr = {it.alias: it.expr for it in sel.items if it.alias}
    for i, k in enumerate(sel.group_by):
        if isinstance(k, ast.Column) and k.name in alias_to_expr:
            # GROUP BY <item alias>: group by the aliased expression and
            # surface it under the user's alias
            expr = alias_to_expr[k.name]
            inner_items.append(ast.SelectItem(expr, alias=k.name))
            repl.append((expr, ast.Column(k.name)))
            continue
        if isinstance(k, ast.Column):
            inner_items.append(ast.SelectItem(k))
            repl.append((k, ast.Column(k.name)))
        else:
            nm = next((it.alias for it in sel.items
                       if it.alias and it.expr == k), None) or f"__gk_{i}"
            inner_items.append(ast.SelectItem(k, alias=nm))
            repl.append((k, ast.Column(nm)))
    for i, a in enumerate(aggs):
        nm = f"__ga_{i}"
        inner_items.append(ast.SelectItem(a, alias=nm))
        repl.append((a, ast.Column(nm)))

    def replace(e):
        for orig, col in repl:
            if e == orig:
                return col
        if isinstance(e, (list, tuple)):
            return type(e)(replace(x) for x in e)
        if dataclasses.is_dataclass(e) and not isinstance(e, type) \
                and isinstance(e, (ast.Expr, ast.WindowSpec)):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, ast.WindowSpec, list, tuple)):
                    nv = replace(v)
                    if nv != v:
                        changes[f.name] = nv
            if changes:
                return dataclasses.replace(e, **changes)
        return e

    out_items = []
    for it in sel.items:
        ne = replace(it.expr)
        alias = it.alias
        if alias is None and ne != it.expr:
            # keep the user-visible column header (e.g. "avg(v)") when
            # the expression collapsed to an internal alias
            alias = _expr_name(it.expr)
        out_items.append(dataclasses.replace(it, expr=ne, alias=alias))
    out_order = [dataclasses.replace(ob, expr=replace(ob.expr))
                 for ob in sel.order_by]
    inner = dataclasses.replace(
        sel, items=inner_items, order_by=[], limit=None, offset=None,
        distinct=False)
    outer = dataclasses.replace(
        sel, items=out_items, table=None, table_alias=None, joins=[],
        where=None, group_by=[], having=None, order_by=out_order,
        ctes=[], from_subquery=None)
    return inner, outer


def _contains_agg(e) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.over is not None:
            return False  # sum(x) OVER (...) is a window, not an aggregate
        if e.name.lower() in _AGGS:
            return True
        return any(_contains_agg(a) for a in e.args)
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, ast.Expr) and _contains_agg(v):
                return True
            if isinstance(v, (list, tuple)) and any(
                    isinstance(x, ast.Expr) and _contains_agg(x)
                    for x in v):
                return True
    return False


def _agg_value(name: str, vals: np.ndarray):
    clean = np.asarray([v for v in vals
                        if v is not None and not _is_nan(v)])
    if name == "count":
        return len(clean)
    if len(clean) == 0:
        return None
    if name == "sum":
        return float(np.sum(clean.astype(np.float64)))
    if name == "min":
        return clean.min()
    if name == "max":
        return clean.max()
    return float(np.mean(clean.astype(np.float64)))


def _aggregate(sel, cols, dtypes, n, resolve) -> QueryResult:
    group_exprs = [resolve(g) for g in sel.group_by]
    key_arrays = []
    for g in group_exprs:
        v = eval_host(g, cols, None, None, n)
        key_arrays.append(np.asarray([v] * n) if np.ndim(v) == 0
                          else np.asarray(v))
    groups: dict = {}
    if key_arrays:
        for i in range(n):
            # NaN is NULL here and NaN != NaN — normalize so all NULL
            # rows land in ONE group (SQL GROUP BY semantics)
            key = tuple(None if _is_nan(a[i]) else a[i]
                        for a in key_arrays)
            groups.setdefault(key, []).append(i)
    else:
        groups[()] = list(range(n))

    def agg_for(expr, idx):
        """Evaluate one select item for one group."""
        def rec(e):
            if isinstance(e, ast.FuncCall) and e.name.lower() in _AGGS:
                fname = e.name.lower()
                if fname == "count" and (not e.args or isinstance(
                        e.args[0], ast.Star)):
                    return len(idx)
                arg = resolve(e.args[0])
                vals = eval_host(arg, {k: v[idx] for k, v in cols.items()},
                                 None, None, len(idx))
                vals = np.asarray([vals] * len(idx)) if np.ndim(vals) == 0 \
                    else np.asarray(vals)
                return _agg_value(fname, vals)
            if isinstance(e, ast.Column):
                rv = eval_host(resolve(e), cols, None, None, n)
                return np.asarray(rv)[idx[0]] if len(idx) else None
            if isinstance(e, ast.Literal):
                return e.value
            if isinstance(e, ast.BinaryOp):
                import operator as op

                if e.op == "and":
                    return bool(rec(e.left)) and bool(rec(e.right))
                if e.op == "or":
                    return bool(rec(e.left)) or bool(rec(e.right))
                f = {"+": op.add, "-": op.sub, "*": op.mul,
                     "/": op.truediv, "%": op.mod,
                     "=": op.eq, "!=": op.ne, "<": op.lt, "<=": op.le,
                     ">": op.gt, ">=": op.ge}.get(e.op)
                if f is None:
                    raise PlanError(
                        f"unsupported op {e.op!r} over join aggregates")
                return f(rec(e.left), rec(e.right))
            raise PlanError(
                f"unsupported expression over join aggregates: {e}")
        return rec(expr)

    if group_exprs:
        # None keys (LEFT JOIN null-extended rows) aren't comparable to
        # strings — sort NULL groups last, per component
        keys = sorted(groups, key=lambda k: tuple(
            (v is None, v) for v in k))
    else:
        keys = list(groups)
    out_names, rows_by_col = [], []
    for it in sel.items:
        if isinstance(it.expr, ast.Star):
            raise PlanError("SELECT * with GROUP BY over a join")
        out_names.append(it.alias or _expr_name(it.expr))
    table_rows = []
    for key in keys:
        idx = groups[key]
        if sel.having is not None:
            hv = agg_for(resolve(sel.having), idx)
            if not bool(hv):
                continue
        table_rows.append([agg_for(it.expr, idx) for it in sel.items])
    cols_out = [np.asarray([r[i] for r in table_rows], dtype=object)
                for i in range(len(out_names))] if table_rows else \
        [np.empty(0, dtype=object) for _ in out_names]
    # tighten numeric dtypes: all-int columns (counts) stay integer like
    # the single-table path; mixed numerics become float64
    tightened = []
    for c in cols_out:
        try:
            if len(c) and all(isinstance(v, (int, np.integer))
                              and not isinstance(v, bool) for v in c):
                tightened.append(c.astype(np.int64))
            elif len(c) and all(isinstance(v, (int, float, np.floating,
                                               np.integer))
                                and v is not None for v in c):
                tightened.append(c.astype(np.float64))
            else:
                tightened.append(c)
        except (TypeError, ValueError):
            tightened.append(c)
    r = QueryResult(out_names, [None] * len(out_names), tightened)
    return _post(sel, r, resolve)


def _post(sel, r: QueryResult, resolve,
          env: Optional[dict] = None) -> QueryResult:
    """ORDER BY / DISTINCT / LIMIT / OFFSET. Order keys resolve against
    the output columns by name first, then (if `env` is given, i.e. rows
    are still 1:1 with the joined relation) against the full joined
    namespace — SQL allows ordering by unprojected columns."""
    n = r.num_rows
    idx = np.arange(n)
    if sel.order_by:
        for ob in reversed(sel.order_by):
            name = _expr_name(ob.expr)
            qualified = isinstance(ob.expr, ast.Column) and ob.expr.table
            if qualified and f"{ob.expr.table}.{ob.expr.name}" in r.names:
                # Star projections emit qualified output names
                col = np.asarray(
                    r.column(f"{ob.expr.table}.{ob.expr.name}"))[idx]
            elif qualified and env is not None:
                # a qualified key must NOT bind to a bare output alias
                # that happens to share the column's name
                full = np.asarray(
                    eval_host(resolve(ob.expr), env, None, None, n))
                col = np.broadcast_to(full, (n,))[idx] \
                    if np.ndim(full) == 0 else full[idx]
            elif name in r.names:
                col = np.asarray(r.column(name))[idx]
            elif env is not None:
                full = np.asarray(
                    eval_host(resolve(ob.expr), env, None, None, n))
                col = np.broadcast_to(full, (n,))[idx] \
                    if np.ndim(full) == 0 else full[idx]
            else:
                raise PlanError(
                    f"ORDER BY {name!r} is not an output column")
            try:
                srt = np.argsort(col, kind="stable")
            except TypeError:  # mixed object dtype (None vs str)
                srt = np.asarray(sorted(
                    range(len(col)),
                    key=lambda i: (col[i] is None, col[i])), dtype=np.int64)
            if not ob.asc:
                srt = srt[::-1]
            idx = idx[srt]
    if sel.distinct and len(idx):
        seen, keep = set(), []
        for i in idx:
            row = tuple(c[i] for c in r.columns)
            if row not in seen:
                seen.add(row)
                keep.append(i)
        idx = np.asarray(keep, dtype=np.int64)
    off = sel.offset or 0
    stop = off + sel.limit if sel.limit is not None else None
    idx = idx[off:stop]
    return QueryResult(r.names, r.dtypes,
                       [np.asarray(c)[idx] for c in r.columns])


def _expr_name(e) -> str:
    if isinstance(e, ast.Column):
        return e.name
    if isinstance(e, ast.FuncCall):
        return f"{e.name}({', '.join(_expr_name(a) for a in e.args)})"
    if isinstance(e, ast.Star):
        return "*"
    if isinstance(e, ast.Literal):
        return str(e.value)
    return str(e)
