"""Logical plan algebra (mirrors reference DataFusion LogicalPlan usage in
src/query; deliberately minimal — single-table chains for round 1).

Both the SQL planner and the PromQL compiler lower into this algebra
(reference parser.rs:46-48 — one engine, two frontends).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from greptimedb_tpu.catalog.catalog import TableInfo
from greptimedb_tpu.sql import ast


@dataclass
class LogicalPlan:
    pass


@dataclass
class Scan(LogicalPlan):
    table: TableInfo
    columns: Optional[list[str]] = None  # projection pushdown
    ts_range: Optional[tuple[Optional[int], Optional[int]]] = None  # pushdown


@dataclass
class Filter(LogicalPlan):
    input: LogicalPlan
    predicate: ast.Expr


@dataclass
class AggSpec:
    name: str  # output name
    func: str  # sum|count|avg|min|max|first|last|stddev|variance|rows|host aggs
    arg: Optional[ast.Expr]  # None for count(*)
    call: ast.FuncCall  # original node (env key for post-agg exprs)
    extra_args: tuple = ()  # literal params (percentile p, polyval x)


@dataclass
class Aggregate(LogicalPlan):
    input: LogicalPlan
    keys: list[tuple[str, ast.Expr]]  # (output name, key expr)
    aggs: list[AggSpec]


@dataclass
class Having(LogicalPlan):
    input: LogicalPlan
    predicate: ast.Expr


@dataclass
class Project(LogicalPlan):
    input: LogicalPlan
    items: list[tuple[str, ast.Expr]]


@dataclass
class Sort(LogicalPlan):
    input: LogicalPlan
    keys: list[ast.OrderByItem]


@dataclass
class Limit(LogicalPlan):
    input: LogicalPlan
    limit: Optional[int]
    offset: int = 0


def explain_plan(plan: LogicalPlan, indent: int = 0) -> str:
    pad = "  " * indent
    if isinstance(plan, Scan):
        return (f"{pad}Scan: {plan.table.db}.{plan.table.name} "
                f"columns={plan.columns} ts_range={plan.ts_range}")
    if isinstance(plan, Filter):
        return f"{pad}Filter: {plan.predicate}\n" + explain_plan(plan.input, indent + 1)
    if isinstance(plan, Aggregate):
        keys = ", ".join(n for n, _ in plan.keys)
        aggs = ", ".join(f"{a.func}({a.name})" for a in plan.aggs)
        return f"{pad}Aggregate: keys=[{keys}] aggs=[{aggs}]\n" + explain_plan(plan.input, indent + 1)
    if isinstance(plan, Having):
        return f"{pad}Having: {plan.predicate}\n" + explain_plan(plan.input, indent + 1)
    if isinstance(plan, Project):
        return f"{pad}Project: {[n for n, _ in plan.items]}\n" + explain_plan(plan.input, indent + 1)
    if isinstance(plan, Sort):
        return f"{pad}Sort: {len(plan.keys)} keys\n" + explain_plan(plan.input, indent + 1)
    if isinstance(plan, Limit):
        return f"{pad}Limit: {plan.limit} offset {plan.offset}\n" + explain_plan(plan.input, indent + 1)
    return f"{pad}{type(plan).__name__}"
