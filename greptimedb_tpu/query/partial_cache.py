"""Partial-aggregate cache: per-part [G, F] planes, delta-only folding.

The LSM design makes an immutable SST part's contribution to a given
aggregate shape a FIXED plane: the part's rows never change until
compaction/expiry/DROP rewrites the file, so re-reducing the part on
every query is pure waste (PAPER.md §1 — mito2's immutable parquet
parts + append-only memtable). PR 5/7/12 already key the host part
cache, the HBM hot set, and the mesh shard buffers by file identity;
this module adds the top layer: memoize the *aggregated partials*
themselves, so query execution becomes

    gather cached part partials
      -> compute partials only for uncached parts + the memtable delta
      -> combine by group-key VALUE (query/dist_agg.combine_partials)
      -> the shared Final step (_finalize_combined_agg)

Entries are value-space partials — ``{"keys": [per-key decoded value
arrays], "planes": {op: [G_part, F]}}`` — exactly the shape one region
ships for a distributed PlanFragment. Caching VALUES (not dictionary
codes) makes entries immune to group-key dictionary drift: tag
dictionaries grow append-only between flushes, and the combine step
re-factorizes by value, so a partial cached under an older (smaller)
dictionary merges correctly with partials computed under a newer one.

Key discipline mirrors the device hot set (query/device_cache.py):

- **part entries** ``("part", region_id, file_id, part_ts_range,
  pred_key, shape_fp)`` anchor to the immutable file (+ the window/
  predicate that selected its rows) and a canonical plan-shape
  fingerprint. They survive data-version bumps — a flush leaves every
  cached part partial valid and adds only the new file's rows to the
  delta — and die through the exact region seams that kill host parts
  and HBM blocks: compaction swap, retention expiry, DROP/TRUNCATE
  (storage/region.py notifies this module alongside device_cache).
- **fragment entries** ``("frag", region_id, incarnation,
  data_version, frag_fp)`` memoize a whole region's partial plane for a
  repeated distributed PlanFragment (cluster mode): the datanode
  answers from the cached plane without touching SSTs; any write bumps
  data_version and the next fragment recomputes.

DELETE rides the same tombstone-reachability argument as scan_last: a
tombstone anywhere in the scan voids the per-part decomposition (the
delete may mask rows in a DIFFERENT part), so the executor falls back
to the classic whole-scan fold — typed degradation, never an error.

This module deliberately imports numpy only (no jax): the datanode's
fragment seam uses it inside storage-only processes.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Optional

import numpy as np

from greptimedb_tpu.utils.metrics import (
    PARTIAL_AGG_CACHE_BYTES,
    PARTIAL_AGG_CACHE_EVENTS,
)


class PartialCacheIneligible(Exception):
    """This scan/shape cannot ride the incremental per-part fold; the
    executor falls back to the classic whole-scan paths (typed
    degradation, mirroring VmapIneligible / MeshIneligible)."""


def enabled() -> bool:
    """[query] partial_cache / GREPTIMEDB_TPU_PARTIAL_CACHE; on by
    default."""
    return os.environ.get("GREPTIMEDB_TPU_PARTIAL_CACHE", "1").lower() \
        not in ("0", "false", "off")


def budget_bytes() -> int:
    """[query] partial_cache_bytes / GREPTIMEDB_TPU_PARTIAL_CACHE_BYTES
    (<= 0 = auto, matching the option doc); partials are [G, F] planes
    (KBs each), so a modest default covers thousands of (part, shape)
    combinations."""
    env = os.environ.get("GREPTIMEDB_TPU_PARTIAL_CACHE_BYTES")
    try:
        v = int(env) if env else 0
    except ValueError:
        v = 0
    return v if v > 0 else (256 << 20)


def groups_max() -> int:
    """Largest dense group count the incremental path materializes per
    part ([G, F] readback per part; beyond this the classic single-
    readback fold wins)."""
    return int(os.environ.get("GREPTIMEDB_TPU_PARTIAL_CACHE_GROUPS_MAX",
                              str(1 << 16)))


#: live caches — storage-layer invalidation seams reach every instance
#: through the module functions below (region.py looks this module up in
#: sys.modules, so a storage-only process never pays the import)
_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def invalidate_files(region_id: int, file_ids) -> None:
    """Region seam fan-out: compaction swap / retention expiry /
    DROP-TRUNCATE killed these SSTs — their partial planes must die with
    them (same contract as device_cache.invalidate_files)."""
    for cache in list(_CACHES):
        cache.invalidate_files(region_id, file_ids)


def invalidate_region(region_id: int) -> None:
    for cache in list(_CACHES):
        cache.invalidate_region(region_id)


#: accounted floor per entry: dict/tuple overhead + the key itself (a
#: fragment key embeds the fragment JSON) — without it, empty-marker
#: entries cost 0 accounted bytes and the byte budget would never bound
#: their COUNT (version-churning fragment keys grow one entry per write)
_ENTRY_OVERHEAD = 512


def partial_nbytes(partial: dict) -> int:
    """Approximate host bytes of one cached partial (planes + decoded
    key columns; object arrays estimate ~48 B/element for the boxed
    strings the pointer-width nbytes hides)."""
    total = _ENTRY_OVERHEAD
    for arr in partial.get("planes", {}).values():
        total += int(np.asarray(arr).nbytes)
    for arr in partial.get("keys", ()):
        a = np.asarray(arr)
        total += int(a.nbytes) + (48 * len(a) if a.dtype == object else 0)
    return total


class PartialAggCache:
    """Bytes-budgeted LRU of host-side partial-aggregate planes.
    Thread-safe; `put` runs under the same dead-file tombstone guard as
    the device hot set — a partial computed for a file that died while
    the fold was in flight never becomes resident."""

    _DEAD_FILES_CAP = 4096

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget if budget is not None else budget_bytes()
        self._lru: "OrderedDict[tuple, tuple]" = OrderedDict()  # key -> (partial, nbytes)
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self._dead_files: "OrderedDict[tuple, None]" = OrderedDict()
        # per-region epoch for fragment entries: data_versions are
        # reused after TRUNCATE recreates the region, so
        # invalidate_region bumps the epoch and in-flight puts started
        # under the old one are refused at store time
        self._region_epoch: dict[int, int] = {}
        _CACHES.add(self)

    @staticmethod
    def _region_of(key: tuple) -> Optional[int]:
        return key[1] if len(key) >= 2 and key[0] in ("part", "frag") \
            else None

    def epoch(self, region_id: int) -> int:
        with self._lock:
            return self._region_epoch.get(region_id, 0)

    def get(self, key: tuple) -> Optional[dict]:
        from greptimedb_tpu.utils import ledger

        with self._lock:
            hit = self._lru.get(key)
            if hit is None:
                self.misses += 1
            else:
                self._lru.move_to_end(key)
                self.hits += 1
        if hit is None:
            PARTIAL_AGG_CACHE_EVENTS.inc(event="miss")
            ledger.cache_event("partial_agg", "miss")
            return None
        PARTIAL_AGG_CACHE_EVENTS.inc(event="hit")
        ledger.cache_event("partial_agg", "hit")
        return hit[0]

    def put(self, key: tuple, partial: dict,
            epoch: Optional[int] = None) -> None:
        nbytes = partial_nbytes(partial)
        if nbytes > self.budget:
            return  # an entry that can never fit must not wipe the cache
        evictions = 0
        with self._lock:
            region = self._region_of(key)
            if key[0] == "part" and (region, key[2]) in self._dead_files:
                # the file died while this partial was computing: the
                # caller's scan pinned it (its result is fine), but the
                # dead key must never become resident
                return
            if epoch is not None and region is not None \
                    and self._region_epoch.get(region, 0) != epoch:
                # region invalidated (TRUNCATE/DROP) mid-compute: a
                # recreated region may reuse the colliding data_version
                return
            if key[0] == "frag":
                # generation retirement: fragment keys embed (incarnation,
                # data_version), and lookups always use the CURRENT pair —
                # entries under any older pair are unreachable forever.
                # Writes bump the version without any invalidation seam,
                # so without this sweep a hot small region would strand
                # one dead entry per (write, fragment) combination.
                gen = (key[2], key[3])
                evictions += self._drop_locked(
                    lambda k: k[0] == "frag" and k[1] == region
                    and (k[2], k[3]) != gen)
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._lru[key] = (partial, nbytes)
            self._bytes += nbytes
            while self._bytes > self.budget and self._lru:
                _, (_, nb) = self._lru.popitem(last=False)
                self._bytes -= nb
                evictions += 1
            PARTIAL_AGG_CACHE_BYTES.set(float(self._bytes))
        if evictions:
            PARTIAL_AGG_CACHE_EVENTS.inc(float(evictions), event="evict")

    def _drop_locked(self, pred) -> int:
        doomed = [k for k in self._lru if pred(k)]
        for k in doomed:
            _, nb = self._lru.pop(k)
            self._bytes -= nb
        return len(doomed)

    def invalidate_files(self, region_id: int, file_ids) -> None:
        """Drop part entries for dead SSTs, and every fragment plane of
        the region (its data changed; the version key already prevents
        stale serves — this is bookkeeping so ghosts don't hold the
        budget)."""
        gone = set(file_ids)
        with self._lock:
            for fid in gone:
                self._dead_files[(region_id, fid)] = None
                self._dead_files.move_to_end((region_id, fid))
            while len(self._dead_files) > self._DEAD_FILES_CAP:
                self._dead_files.popitem(last=False)
            n = self._drop_locked(
                lambda k: (k[0] == "part" and k[1] == region_id
                           and k[2] in gone)
                or (k[0] == "frag" and k[1] == region_id))
            PARTIAL_AGG_CACHE_BYTES.set(float(self._bytes))
        if n:
            PARTIAL_AGG_CACHE_EVENTS.inc(float(n), event="invalidate")

    def invalidate_region(self, region_id: int) -> None:
        with self._lock:
            n = self._drop_locked(
                lambda k: self._region_of(k) == region_id)
            self._region_epoch[region_id] = \
                self._region_epoch.get(region_id, 0) + 1
            PARTIAL_AGG_CACHE_BYTES.set(float(self._bytes))
        if n:
            PARTIAL_AGG_CACHE_EVENTS.inc(float(n), event="invalidate")

    def part_keys(self, region_id: Optional[int] = None) -> list:
        """Resident part-anchored keys (diagnostics + tests)."""
        with self._lock:
            return [k for k in self._lru if k[0] == "part"
                    and (region_id is None or k[1] == region_id)]

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
            PARTIAL_AGG_CACHE_BYTES.set(0.0)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes


_GLOBAL: Optional[PartialAggCache] = None
_GLOBAL_LOCK = threading.Lock()


def global_cache() -> PartialAggCache:
    """The process-wide cache: executors and the datanode fragment seam
    share ONE byte budget (the issue's 'shared byte budget' — per-
    executor budgets would multiply under the threaded servers)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = PartialAggCache()
        return _GLOBAL


def canonical_key(k, kexpr) -> tuple:
    """Canonical form of one group key for the shape fingerprint: tag
    cardinality and bucket base/size are EXCLUDED on purpose — cached
    partials hold decoded VALUES, which are invariant to dictionary
    growth and to the scan extent the dense id spaces derive from.
    Generic ("pre") keys canonicalize by the ORIGINAL expression, not
    the per-scan factorized column name. Only what changes the per-part
    VALUES may enter the fingerprint."""
    if k.kind == "tag":
        return ("tag", k.column)
    if k.kind == "bucket":
        return ("bucket", k.column, k.step)
    return ("pre", repr(kexpr))


def shape_fingerprint(bound_where, keys, key_exprs, arg_exprs, ops,
                      acc_dtype) -> tuple:
    """Canonical plan-shape fingerprint: everything that changes a
    part's [G, F] partial VALUES. `bound_where` reprs with tag literals
    already rewritten to dictionary codes — append-only dictionaries
    keep those codes stable, and TRUNCATE (which resets them) kills the
    region's entries wholesale."""
    return (
        tuple(canonical_key(k, e) for k, e in zip(keys, key_exprs)),
        repr(bound_where),
        tuple(repr(a) for a in arg_exprs),
        tuple(ops),
        str(acc_dtype),
    )
