"""Physical execution: logical plan -> streamed device kernels.

The execution model (TPU-first re-design of the reference's volcano-style
async streams, SURVEY.md §7):

  host scan (pruned, columnar)  ->  fixed-shape padded blocks  ->
  one fused jit kernel per block: filter mask + group ids + segment
  reductions  ->  device partial-aggregate combine across blocks  ->
  tiny host tail (decode group keys, HAVING/ORDER/LIMIT over G rows)

Everything static (expressions, key specs, ops) rides into jit as hashable
static arguments, so each query shape compiles once and is cached by jax.
Dedup (last-write-wins) runs as a whole-scan device sort when the table is
not append-mode.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading
import time
from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.datatypes.types import DataType, SemanticType
from greptimedb_tpu.ops.blocks import DEFAULT_BLOCK_ROWS, block_size_for, pad_rows
from greptimedb_tpu.ops.dedup import sort_dedup
from greptimedb_tpu.ops import sparse_segment as sparse_ops
from greptimedb_tpu.ops.segment import (
    _type_max as _seg_type_max,
    _type_min as _seg_type_min,
    combine_group_ids,
    dense_segment_sum,
    segment_agg,
)
from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query.expr import (
    BindContext,
    PlanError,
    bind_expr,
    collect_columns,
    eval_device,
    eval_host,
)
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.sql import ast
from greptimedb_tpu.storage.engine import RegionEngine
from greptimedb_tpu.storage.region import ScanData
from greptimedb_tpu.utils import device_telemetry
from greptimedb_tpu.utils import flame as _flame

# XLA compile + device memory telemetry rides jax.monitoring: one
# listener covers every jax.jit entry point in this module and ops/
device_telemetry.install()


def _readback(x) -> np.ndarray:
    """D2H result materialization, counted (over a remote accelerator
    link this is THE interactive-latency bottleneck — it must show up
    at /metrics, not only in anecdotes)."""
    arr = np.asarray(x)
    device_telemetry.count_d2h(arr.nbytes)
    return arr

# primitive kernel ops backing each SQL aggregate
# boundary first/last gather only pays when it shrinks the scan: above
# this candidate fraction the subset would roughly duplicate the cached
# columns for no kernel savings (tests patch this to force the path on)
_BOUNDARY_MAX_FRACTION = 0.5

_PRIMITIVES = {
    "sum": ("sum", "count"),  # count detects all-NULL groups -> NULL sum
    "count": ("count",),
    "rows": ("rows",),
    "avg": ("sum", "count"),
    "min": ("min",),
    "max": ("max",),
    "first": ("first",),
    "last": ("last",),
    "stddev": ("sum", "sumsq", "count"),
    "variance": ("sum", "sumsq", "count"),
}


def _needs_host_agg(spec, schema) -> bool:
    """True when a spec cannot ride the numeric device planes: order
    statistics, or first/last/min/max over STRING-typed arguments (tag
    codes are dictionary positions — reducing them yields positions, and
    their order is insertion order, not lexicographic)."""
    from greptimedb_tpu.query.host_agg import HOST_AGGS

    if spec.func in HOST_AGGS or spec.func == "count_distinct":
        return True
    if spec.arg is None:
        return False
    dt = _infer_dtype(spec.arg, schema)
    if dt is None or dt.is_numeric or dt.is_timestamp:
        return False
    if spec.func in ("first", "last", "min", "max"):
        return True
    if spec.func == "count":
        # count over a string TAG rides the device (codes, NULL = -1);
        # a string FIELD scans as decoded objects and must count on host
        from greptimedb_tpu.datatypes.types import SemanticType

        return (isinstance(spec.arg, ast.Column)
                and spec.arg.name in schema.names
                and schema.column(spec.arg.name).semantic
                is not SemanticType.TAG)
    return False


@dataclass(frozen=True)
class DeviceKey:
    """One group-by key computed on device (static under jit)."""

    kind: str  # "tag" | "bucket" | "pre"
    column: str
    size: int
    step: int = 0  # bucket width in the column's storage unit
    base: int = 0  # minimum bucket index (offsets ids to 0)


class _BlockEntry(NamedTuple):
    """One device block of the scan: rows [start, end) padded to
    `block`. `pkey` is the immutable SST part the rows belong to
    ((file_id, ts_range, pred_key) from ScanData.part_keys) or None for
    memtable/synthetic rows; `part_start` anchors the block offset
    inside its part so hot-set keys stay stable across versions."""

    pkey: Optional[tuple]
    part_start: int
    start: int
    end: int
    block: int


#: ceiling on part-aligned plan fan-out: a region with hundreds of tiny
#: unmerged flush files would otherwise unroll hundreds of kernel
#: dispatches into one jit — beyond this the scan falls back to the
#: uniform (version-keyed) block layout and lets compaction catch up
_MAX_PLAN_BLOCKS = 64


def _block_plan(scan) -> list[_BlockEntry]:
    """Part-aligned device block plan: blocks never straddle SST part
    seams, so every block's content is a pure function of its immutable
    file (+ window/predicate key) and its HBM upload survives
    data-version bumps — a flush uploads ONLY its new file's blocks.
    Scans without per-part identity (merged/synthetic/seq-sliced) get
    the classic uniform layout keyed by data version."""
    n = scan.num_rows
    offs = scan.sorted_part_offsets
    pkeys = getattr(scan, "part_keys", ())
    segs: list[tuple] = []
    if pkeys and len(offs) == len(pkeys) + 1 and offs[-1] <= n:
        segs = [(pkeys[i], offs[i], offs[i + 1]) for i in range(len(pkeys))]
        if offs[-1] < n:  # memtable tail: version-keyed, no part identity
            segs.append((None, offs[-1], n))
        est = sum(
            -(-max(s1 - s0, 1) // min(block_size_for(s1 - s0),
                                      DEFAULT_BLOCK_ROWS))
            for _, s0, s1 in segs if s1 > s0)
        if est > _MAX_PLAN_BLOCKS:
            segs = []
    if not segs:
        segs = [(None, 0, n)]
    plan: list[_BlockEntry] = []
    for pk, s0, s1 in segs:
        if s1 <= s0:
            continue
        pb = min(block_size_for(s1 - s0), DEFAULT_BLOCK_ROWS)
        for st in range(s0, s1, pb):
            plan.append(_BlockEntry(pk, s0, st, min(st + pb, s1), pb))
    return plan


# ---- fused per-block kernel ------------------------------------------------


def _value_planes(agg_args, cols, tag_names, schema, shape, acc_dtype):
    """Aggregate value matrix [N, F]. A tag column used as a VALUE maps
    its NULL code (-1) to NaN so count()/min()/... skip NULL tags."""
    vals = []
    for a in agg_args:
        v = eval_device(a, cols, tag_names, schema)
        if jnp.ndim(v) == 0:
            v = jnp.broadcast_to(v, shape)
        v = v.astype(acc_dtype)
        if isinstance(a, ast.Column) and a.name in tag_names:
            v = jnp.where(cols[a.name] < 0, jnp.nan, v)
        vals.append(v)
    return jnp.stack(vals, axis=1)


def _group_ids(cols: dict, keys, n: int) -> jax.Array:
    """Dense group ids from the key columns (shared by every agg path)."""
    if not keys:
        return jnp.zeros(n, dtype=jnp.int32)
    key_arrays = []
    for k in keys:
        c = cols[k.column]
        if k.kind == "tag":
            arr = (c + 1).astype(jnp.int32)
        elif k.kind == "bucket":
            arr = (c // k.step - k.base).astype(jnp.int32)
        else:
            arr = c.astype(jnp.int32)
        key_arrays.append(jnp.clip(arr, 0, k.size - 1))
    return combine_group_ids(key_arrays, tuple(k.size for k in keys))


def _agg_block(
    cols: dict,
    n_valid: jax.Array,  # scalar: rows [0, n_valid) are real, rest padding
    dedup_mask,  # Optional[jax.Array]: survivors of last-write-wins
    *,
    where,
    keys: tuple[DeviceKey, ...],
    agg_args: tuple,
    ops: tuple[str, ...],
    num_segments: int,
    ts_name: str,
    tag_names: frozenset,
    schema,
    need_ts: bool,
    acc_dtype=jnp.float64,
):
    some = next(iter(cols.values()))
    # validity computed on device from a scalar — no host mask transfer
    mask = jnp.arange(some.shape[0]) < n_valid
    if dedup_mask is not None:
        mask = mask & dedup_mask
    return _agg_block_masked(
        cols, mask, where=where, keys=keys, agg_args=agg_args, ops=ops,
        num_segments=num_segments, ts_name=ts_name, tag_names=tag_names,
        schema=schema, need_ts=need_ts, acc_dtype=acc_dtype,
    )


def _agg_block_masked(
    cols: dict,
    mask: jax.Array,  # [N] base validity (padding & dedup), pre-filter
    *,
    where,
    keys: tuple[DeviceKey, ...],
    agg_args: tuple,
    ops: tuple[str, ...],
    num_segments: int,
    ts_name: str,
    tag_names: frozenset,
    schema,
    need_ts: bool,
    acc_dtype=jnp.float64,
):
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    gid = _group_ids(cols, keys, mask.shape[0])
    if agg_args:
        values = _value_planes(agg_args, cols, tag_names, schema,
                               mask.shape, acc_dtype)
    else:
        values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
    ts = cols[ts_name] if need_ts else None
    return segment_agg(values, gid, mask, num_segments, ops=ops, ts=ts)


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "nf", "has_nan", "finite",
                     "num_segments", "tag_names", "schema", "float_ops",
                     "pack_dtype"),
)
def _agg_scan_prepared(
    blocks: tuple,  # per-block col dicts incl. "__prep__"
    n_valids: jax.Array,
    dedup_masks,
    *,
    where, keys, nf, has_nan, finite, num_segments, tag_names, schema,
    float_ops, pack_dtype,
):
    """Dense fast path for sum/count/mean/rows over plain field columns.

    The "__prep__" plane is query-invariant and HBM-cached, so each
    query only computes [N]-shaped masks/keys and runs ONE dead-segment
    segment-sum per block — none of the [N, F] elementwise masking
    passes the general kernel needs (those dominated the profile: a
    masked segment-sum costs ~4x the plain one on this shape).

    Plane layouts (all query-invariant, per reduction class):
    - "__prep__"     [vals0 | valid | ones] (2F+1 with NaNs, F+1 without)
      reduced with segment-sum — feeds sum/count/mean/rows
    - "__prep_min__" vals with NaN -> +inf, reduced with segment-min
    - "__prep_max__" vals with NaN -> -inf, reduced with segment-max
    Empty/all-NULL groups come back as +/-inf and convert to NULL."""
    G = num_segments
    total = tmin = tmax = tsq = None
    for i, cols in enumerate(blocks):
        plane = cols["__prep__"]
        mask = jnp.arange(plane.shape[0]) < n_valids[i]
        if dedup_masks is not None:
            mask = mask & dedup_masks[i]
        if where is not None:
            w = eval_device(where, cols, tag_names, schema)
            mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
        gid = _group_ids(cols, keys, plane.shape[0])
        ids = jnp.where(mask, gid, jnp.int32(G))
        part = dense_segment_sum(plane, ids, G + 1, finite=finite)[:G]
        total = part if total is None else total + part
        if "__prep_min__" in cols:
            p = jax.ops.segment_min(cols["__prep_min__"], ids,
                                    num_segments=G + 1)[:G]
            tmin = p if tmin is None else jnp.minimum(tmin, p)
        if "__prep_max__" in cols:
            p = jax.ops.segment_max(cols["__prep_max__"], ids,
                                    num_segments=G + 1)[:G]
            tmax = p if tmax is None else jnp.maximum(tmax, p)
        if "__prep_sq__" in cols:
            p = dense_segment_sum(cols["__prep_sq__"], ids, G + 1,
                                  finite=finite)[:G]
            tsq = p if tsq is None else tsq + p
    sums = total[:, :nf]
    if has_nan:
        cnts = total[:, nf:2 * nf]
        rows = total[:, 2 * nf:2 * nf + 1]
    else:
        rows = total[:, nf:nf + 1]
        cnts = jnp.broadcast_to(rows, (G, nf))
    packed_f = _pack_float_ops(sums, cnts, rows, tmin, tmax, tsq,
                               float_ops, pack_dtype)
    return packed_f, jnp.zeros((0,), jnp.int64)


def _pack_float_ops(sums, cnts, rows, tmin, tmax, tsq, float_ops,
                    pack_dtype, extra=None):
    """Finalize + pack the prepared/fused accumulator planes into the
    one packed_f matrix both paths ship back over the link. `extra`
    supplies already-finalized planes the kernel can't derive (the
    fused path's first/last value planes)."""
    acc: dict[str, jax.Array] = {}
    for k in float_ops:
        if extra is not None and k in extra:
            acc[k] = extra[k]
        elif k == "sum":
            acc[k] = sums
        elif k == "count":
            acc[k] = cnts
        elif k == "rows":
            acc[k] = rows
        elif k == "min":
            # sentinel semantics identical to segment_agg: floats fill
            # with +/-inf, so an all-+inf group reads as NULL (a known,
            # shared limitation) and jax's +inf empty-segment fill is
            # covered by the same comparison
            big = _seg_type_max(tmin.dtype)
            acc[k] = jnp.where(tmin == big, jnp.nan, tmin)
        elif k == "max":
            small = _seg_type_min(tmax.dtype)
            acc[k] = jnp.where(tmax == small, jnp.nan, tmax)
        elif k == "sumsq":
            acc[k] = tsq
        else:  # mean — same NULL semantics as segment_agg
            denom = jnp.maximum(cnts, 1.0)
            acc[k] = jnp.where(cnts > 0, sums / denom, jnp.nan)
    parts = [acc[k].astype(pack_dtype) for k in float_ops]
    return jnp.concatenate(parts, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "arg_names", "num_segments",
                     "ts_name", "tag_names", "schema", "float_ops",
                     "int_ops", "pack_dtype", "acc_dtype", "want_min",
                     "want_max", "want_sumsq", "interpret"),
)
def _agg_scan_fused(
    blocks: tuple,  # per-block dicts of RAW column arrays (hot set)
    n_valids: jax.Array,
    dedup_masks,
    *,
    where, keys, arg_names, num_segments, ts_name, tag_names, schema,
    float_ops, int_ops, pack_dtype, acc_dtype, want_min, want_max,
    want_sumsq, interpret,
):
    """Fused-kernel twin of _agg_scan_prepared: the hot set holds only
    the RAW value columns — validity masks, the [vals|valid|rows]
    reduction plane, and the min/max identity fills / squared values are
    all built in-register by ops/pallas_segment.pallas_fused_segment_agg,
    so the HBM footprint per block is F lanes instead of 2F+1 (+F per
    min/max/sumsq rider) and each block costs ONE kernel dispatch.
    first/last ride along OUTSIDE the kernel: their (value, ts) pairing
    needs the arg-extreme select segment_agg implements, so each block
    adds one segment_agg over the ts column, folded across blocks with
    the same pairwise _combine_partials the classic dense path uses —
    a lastpoint + sum dashboard panel no longer kicks the whole query
    off the fused kernel."""
    from greptimedb_tpu.ops import pallas_segment as ps

    G = num_segments
    # first/last riders, named by their *_ts int planes
    fl_ops = tuple(sorted(op[:-3] for op in int_ops))
    # smaller row tile when extra lanes ride along: the [Gp, Nb]
    # select temporaries double, so halve Nb to stay inside VMEM
    block_rows = 256 if (want_min or want_max or want_sumsq) else 512
    tsum = tcnt = trow = tmin = tmax = tsq = None
    flacc = None
    for i, cols in enumerate(blocks):
        some = cols[arg_names[0]]
        nrows = some.shape[0]
        mask = jnp.arange(nrows) < n_valids[i]
        if dedup_masks is not None:
            mask = mask & dedup_masks[i]
        if where is not None:
            w = eval_device(where, cols, tag_names, schema)
            mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
        gid = _group_ids(cols, keys, nrows)
        ids = jnp.where(mask, gid, jnp.int32(G))
        vals = jnp.stack([cols[a].astype(acc_dtype) for a in arg_names],
                         axis=1)
        out = ps.pallas_fused_segment_agg(
            vals, ids, G + 1, want_min=want_min, want_max=want_max,
            want_sumsq=want_sumsq, block_rows=block_rows,
            interpret=interpret)
        s, c, r = out["sum"][:G], out["count"][:G], out["rows"][:G][:, None]
        tsum = s if tsum is None else tsum + s
        tcnt = c if tcnt is None else tcnt + c
        trow = r if trow is None else trow + r
        if want_min:
            m = out["min"][:G]
            tmin = m if tmin is None else jnp.minimum(tmin, m)
        if want_max:
            m = out["max"][:G]
            tmax = m if tmax is None else jnp.maximum(tmax, m)
        if want_sumsq:
            q = out["sumsq"][:G]
            tsq = q if tsq is None else tsq + q
        if fl_ops:
            part = segment_agg(vals, gid, mask, G, ops=fl_ops,
                               ts=cols[ts_name])
            flacc = _combine_partials(flacc, part)
    extra = {k: flacc[k] for k in fl_ops} if fl_ops else None
    packed_f = _pack_float_ops(tsum, tcnt, trow, tmin, tmax, tsq,
                               float_ops, pack_dtype, extra=extra)
    if int_ops:
        packed_i = jnp.stack([flacc[k] for k in int_ops], axis=1)
    else:
        packed_i = jnp.zeros((0,), jnp.int64)
    return packed_f, packed_i


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "agg_args", "ops", "num_segments",
                     "ts_name", "tag_names", "schema", "need_ts", "acc_dtype",
                     "float_ops", "int_ops", "pack_dtype"),
)
def _agg_scan(
    blocks: tuple,  # tuple of per-block col dicts (pytree)
    n_valids: jax.Array,  # [nblocks]
    dedup_masks,  # Optional[tuple of per-block masks]
    *,
    where, keys, agg_args, ops, num_segments, ts_name, tag_names, schema,
    need_ts, acc_dtype, float_ops, int_ops, pack_dtype,
):
    """The WHOLE aggregation as one device program: per-block fused
    filter+group+reduce, on-device partial combine, and a packed result —
    exactly one dispatch and one device->host transfer per query."""
    acc = None
    for i, cols in enumerate(blocks):
        partial = _agg_block(
            cols, n_valids[i],
            dedup_masks[i] if dedup_masks is not None else None,
            where=where, keys=keys, agg_args=agg_args, ops=ops,
            num_segments=num_segments, ts_name=ts_name, tag_names=tag_names,
            schema=schema, need_ts=need_ts, acc_dtype=acc_dtype,
        )
        acc = _combine_partials(acc, partial)
    parts = []
    for k in float_ops:
        v = acc[k]
        if v.ndim == 1:
            v = v[:, None]
        parts.append(v.astype(pack_dtype))
    packed_f = jnp.concatenate(parts, axis=1)
    if int_ops:
        packed_i = jnp.stack([acc[k] for k in int_ops], axis=1)
    else:
        packed_i = jnp.zeros((0,), jnp.int64)
    return packed_f, packed_i


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "where", "keys", "agg_args", "ops",
                     "num_segments", "ts_name", "tag_names", "schema",
                     "acc_dtype", "float_ops", "pack_dtype"),
)
def _agg_scan_sharded(
    cols: dict,  # {name: [N_pad] array sharded along "shard"}
    base_mask: jax.Array,  # [N_pad] bool, sharded: padding & dedup survivors
    *,
    mesh, where, keys, agg_args, ops, num_segments, ts_name, tag_names,
    schema, acc_dtype, float_ops, pack_dtype,
):
    """Multi-device aggregation: each shard runs the same fused
    filter+group+reduce over its rows, partials combine with psum/pmin/pmax
    along the "shard" axis — the collective MergeScan (reference
    query/src/dist_plan/analyzer.rs:35 splits plans at commutativity
    boundaries and gathers at merge_scan.rs:122; here the combine rides ICI
    instead of point-to-point Flight). first/last pair (value, ts) and the
    shard with the global extreme ts wins (combine_partial_aggs), so
    lastpoint-class queries stay on the mesh; the *_ts planes never leave
    the collective."""
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import _SHARD_MAP_KW, shard_map

    in_specs = ({k: P("shard") for k in cols}, P("shard"))
    need_ts = bool({"first", "last"} & set(ops))

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=P(), **_SHARD_MAP_KW)
    def step(local_cols, local_mask):
        from greptimedb_tpu.ops.segment import combine_partial_aggs

        part = _agg_block_masked(
            local_cols, local_mask, where=where, keys=keys,
            agg_args=agg_args, ops=ops, num_segments=num_segments,
            ts_name=ts_name, tag_names=tag_names, schema=schema,
            need_ts=need_ts, acc_dtype=acc_dtype,
        )
        part = {op: (v if v.ndim > 1 else v[:, None])
                for op, v in part.items()}
        combined = combine_partial_aggs(part, "shard")
        return jnp.concatenate(
            [combined[k].astype(pack_dtype) for k in float_ops], axis=1)

    return step(cols, base_mask)


def _agg_scan_sharded_sparse(
    cols: dict,  # {name: [N_pad] array sharded along "shard"}
    base_mask: jax.Array,  # [N_pad] bool, sharded
    *,
    mesh, where, keys, agg_args, ops, cap, ts_name, tag_names, schema,
    need_ts, acc_dtype, float_ops, int_ops, pack_dtype,
):
    """Multi-device SPARSE aggregation: each shard sort-compacts the
    group ids IT observes and ships [cap, W] value-keyed partials plus
    its rank -> global-id table. Unlike the dense collective, partials
    cannot psum in place — compact slots don't line up across shards —
    so out_specs stack the per-shard planes along "shard" and the host
    merges them in GID space (combine_sparse_gid_partials; global ids
    are shard-invariant, see _sparse_gid). Per-shard group counts ride
    along so the host can slice each shard's observed prefix."""
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import _SHARD_MAP_KW, shard_map

    in_specs = ({k: P("shard") for k in cols}, P("shard"))
    out_specs = (P("shard"), P("shard"), P("shard"), P("shard"))

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **_SHARD_MAP_KW)
    def step(local_cols, local_mask):
        mask = local_mask
        if where is not None:
            w = eval_device(where, local_cols, tag_names, schema)
            mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
        gid = _sparse_gid(local_cols, keys)
        if agg_args:
            values = _value_planes(agg_args, local_cols, tag_names, schema,
                                   mask.shape, acc_dtype)
        else:
            values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
        ts = local_cols[ts_name] if need_ts else None
        part, uniq, n_groups = sparse_ops.sparse_segment_agg(
            values, gid, mask, cap, ops=ops, ts=ts)
        packed_f, packed_i = _pack_part(part, float_ops, int_ops, pack_dtype)
        return (packed_f, packed_i, uniq,
                n_groups.astype(jnp.int64)[None])

    return step(cols, base_mask)


def _build_prep(scan, arg_names, start, end, out_rows, acc_dtype, has_nan,
                kind) -> np.ndarray:
    """THE prepared-plane builder — rows [start, end) of the scan into a
    plane of `out_rows` rows (the single source of truth for the layout;
    the dense per-block and sharded whole-scan paths both call it).

    kind None -> the sum/count plane: [vals0 | valid | ones] (2F+1) with
    NaNs present, [vals | ones] (F+1) without. kind "min"/"max" ->
    identity-filled value planes for segment-min/max. kind "sq" ->
    squared values with NaN -> 0 (zero contribution), always f64: the
    stddev/variance cancellation needs full precision (see segment_agg).
    Padding rows are excluded by the base mask; extreme planes still get
    the identity fill there for safety."""
    f = len(arg_names)
    m = end - start
    np_acc = np.dtype(str(acc_dtype))
    # layout note: writes go through a feature-major [F, m] staging
    # buffer and ONE transpose-assign into the [rows, width] plane.
    # Column-at-a-time writes (plane[:m, j] = src) touch every 64B cache
    # line of the plane once per field — a read-modify-write of the
    # whole plane F times over; the transpose-assign streams the
    # destination sequentially while reading F sequential sources, so
    # the build runs at copy bandwidth (first-query warm-up was
    # dominated by exactly this at TSBS scale).
    def staged():
        src = np.empty((f, m), dtype=np.float64)
        for j, name in enumerate(arg_names):
            src[j] = scan.columns[name][start:end]
        return src

    if kind is None:
        width = (2 * f + 1) if has_nan else (f + 1)
        plane = np.empty((out_rows, width), dtype=np_acc)
        if out_rows > m:
            plane[m:] = 0.0
        src = staged()
        if has_nan:
            nan = np.isnan(src)
            np.copyto(src, 0.0, where=nan)
            plane[:m, :f] = src.T
            plane[:m, f:2 * f] = (~nan).T
        else:
            plane[:m, :f] = src.T
        plane[:m, width - 1] = 1.0
        return plane
    if kind == "sq":
        plane = np.empty((out_rows, f), dtype=np.float64)
        if out_rows > m:
            plane[m:] = 0.0
        src = staged()
        np.multiply(src, src, out=src)
        np.copyto(src, 0.0, where=np.isnan(src))
        plane[:m] = src.T
        return plane
    fill = np.inf if kind == "min" else -np.inf
    plane = np.empty((out_rows, f), dtype=np_acc)
    if out_rows > m:
        plane[m:] = fill
    src = staged()
    np.copyto(src, fill, where=np.isnan(src))
    plane[:m] = src.T
    return plane


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "where", "keys", "nf", "has_nan",
                     "num_segments", "tag_names", "schema", "float_ops",
                     "pack_dtype"),
)
def _agg_scan_sharded_prepared(
    cols: dict,  # sharded cols incl. "__prep__" (+ optional min/max planes)
    base_mask: jax.Array,
    *,
    mesh, where, keys, nf, has_nan, num_segments, tag_names, schema,
    float_ops, pack_dtype,
):
    """Sharded twin of _agg_scan_prepared: each shard reduces its slice of
    the cached planes with the dead-segment id trick, then partials ride
    ICI (psum/pmin/pmax) — the multi-chip MergeScan with none of the
    per-query [N, F] masking passes."""
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import _SHARD_MAP_KW, shard_map

    G = num_segments
    in_specs = ({k: P("shard") for k in cols}, P("shard"))

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=P(), **_SHARD_MAP_KW)
    def step(local_cols, local_mask):
        plane = local_cols["__prep__"]
        mask = local_mask
        if where is not None:
            w = eval_device(where, local_cols, tag_names, schema)
            mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
        gid = _group_ids(local_cols, keys, plane.shape[0])
        ids = jnp.where(mask, gid, jnp.int32(G))
        total = jax.lax.psum(
            jax.ops.segment_sum(plane, ids, num_segments=G + 1)[:G],
            "shard")
        sums = total[:, :nf]
        if has_nan:
            cnts = total[:, nf:2 * nf]
            rows = total[:, 2 * nf:2 * nf + 1]
        else:
            rows = total[:, nf:nf + 1]
            cnts = jnp.broadcast_to(rows, (G, nf))
        acc: dict[str, jax.Array] = {}
        for k in float_ops:
            if k == "sum":
                acc[k] = sums
            elif k == "count":
                acc[k] = cnts
            elif k == "rows":
                acc[k] = rows
            elif k == "min":
                tmin = jax.lax.pmin(
                    jax.ops.segment_min(local_cols["__prep_min__"], ids,
                                        num_segments=G + 1)[:G], "shard")
                big = _seg_type_max(tmin.dtype)
                acc[k] = jnp.where(tmin == big, jnp.nan, tmin)
            elif k == "max":
                tmax = jax.lax.pmax(
                    jax.ops.segment_max(local_cols["__prep_max__"], ids,
                                        num_segments=G + 1)[:G], "shard")
                small = _seg_type_min(tmax.dtype)
                acc[k] = jnp.where(tmax == small, jnp.nan, tmax)
            elif k == "sumsq":
                acc[k] = jax.lax.psum(
                    jax.ops.segment_sum(local_cols["__prep_sq__"], ids,
                                        num_segments=G + 1)[:G], "shard")
            else:
                denom = jnp.maximum(cnts, 1.0)
                acc[k] = jnp.where(cnts > 0, sums / denom, jnp.nan)
        return jnp.concatenate(
            [acc[k].astype(pack_dtype) for k in float_ops], axis=1)

    return step(cols, base_mask)


def _prep_stream_step_impl(acc, cols, n_valid, *, where, keys, num_segments,
                           tag_names, schema):
    """One streaming step on the PREPARED planes: a single dead-segment
    segment-sum per chunk folded into the device accumulator — the
    streaming twin of _agg_scan_prepared (none of the [N, F] masking
    passes of the general streaming kernel)."""
    G = num_segments
    plane = cols["__prep__"]
    mask = jnp.arange(plane.shape[0]) < n_valid
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    gid = _group_ids(cols, keys, plane.shape[0])
    ids = jnp.where(mask, gid, jnp.int32(G))
    out = {"total": jax.ops.segment_sum(plane, ids, num_segments=G + 1)[:G]}
    if "__prep_min__" in cols:
        out["min"] = jax.ops.segment_min(cols["__prep_min__"], ids,
                                         num_segments=G + 1)[:G]
    if "__prep_max__" in cols:
        out["max"] = jax.ops.segment_max(cols["__prep_max__"], ids,
                                         num_segments=G + 1)[:G]
    if "__prep_sq__" in cols:
        out["sq"] = jax.ops.segment_sum(cols["__prep_sq__"], ids,
                                        num_segments=G + 1)[:G]
    if acc is not None:
        out["total"] = out["total"] + acc["total"]
        if "min" in out:
            out["min"] = jnp.minimum(out["min"], acc["min"])
        if "max" in out:
            out["max"] = jnp.maximum(out["max"], acc["max"])
        if "sq" in out:
            out["sq"] = out["sq"] + acc["sq"]
    return out


_PREP_STREAM_STATICS = ("where", "keys", "num_segments", "tag_names",
                        "schema")
_prep_stream_step = functools.partial(
    jax.jit, static_argnames=_PREP_STREAM_STATICS)(_prep_stream_step_impl)
# donated twin: the chunked bigger-than-HBM fold reuses the accumulator
# AND the spent chunk's upload buffers instead of doubling peak HBM —
# XLA aliases the output planes over the donated inputs and frees the
# chunk at dispatch, so steady-state residency is one chunk + one
# accumulator no matter how many chunks stream through
_prep_stream_step_donated = functools.partial(
    jax.jit, static_argnames=_PREP_STREAM_STATICS,
    donate_argnums=(0, 1))(_prep_stream_step_impl)


def _donate_stream_buffers() -> bool:
    """Buffer donation knob for the streaming folds. Default: on for
    accelerator backends, off on CPU (XLA:CPU cannot alias these
    buffers and warns on every trace). GREPTIMEDB_TPU_DONATE=on forces
    it anywhere (the parity tests); =off pins the copying behavior for
    A/B."""
    env = os.environ.get("GREPTIMEDB_TPU_DONATE")
    if env is not None:
        return env.lower() not in ("0", "false", "off")
    return jax.default_backend() != "cpu"


def _prefetch(items, depth: int = 2):
    """Double-buffered pipeline: a producer thread runs the host-side
    work of the NEXT chunk (SST page reads, plane building, the H2D
    copy) while the device folds the current one. JAX dispatch is
    already async on the device side; this overlaps the HOST side too,
    so streaming wall-clock approaches max(transfer, compute) instead of
    their sum (SURVEY §7 hard part 4 — bigger-than-HBM scans).

    `depth` bounds the queue; up to depth+2 chunks can coexist (queued,
    one blocked in the producer's put, one being folded) — the real
    memory ceiling for 100M+-row scans."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    err: list = []

    def producer():
        try:
            for item in items:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return  # consumer abandoned: skip the rest of the scan
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            err.append(e)
        finally:
            # the sentinel MUST land (a dropped sentinel deadlocks the
            # consumer's get) — retry until it fits or we were cancelled
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=0.1)
            except queue.Empty:
                # deadline checkpoint: a cancelled/expired consumer
                # unwinds typed; the finally stops the producer
                from greptimedb_tpu.utils import deadline as dl

                dl.check("streaming scan wait")
                continue
            if item is sentinel:
                break
            yield item
        if err:
            raise err[0]
    finally:
        # cancel the producer (exception/close downstream): it stops at
        # its next put instead of building the rest of the scan
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        # the producer exits after its CURRENT read; waiting keeps SST
        # file pins valid until no thread touches the files (bounded
        # laps, never abandoned — the pin contract is absolute)
        while t.is_alive():
            t.join(0.1)


class _NotStreamable(Exception):
    """Query shape the streaming path can't serve (generic group keys,
    host-side order statistics); caller falls back to the materialized
    scan."""


_agg_block_jit = functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "agg_args", "ops", "num_segments",
                     "ts_name", "tag_names", "schema", "need_ts",
                     "acc_dtype"),
)(_agg_block)


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "agg_args", "ops", "cap", "ts_name",
                     "tag_names", "schema", "need_ts", "acc_dtype"),
)
def _agg_block_sparse(
    cols: dict,
    n_valid: jax.Array,
    dedup_mask,
    *,
    where, keys, agg_args, ops, cap, ts_name, tag_names, schema, need_ts,
    acc_dtype,
):
    """Sparse twin of _agg_block for the incremental per-part fold:
    sort-compact the part's observed group ids and segment-reduce over
    the static `cap` — the partial carries [cap, F] planes plus the
    rank -> global-id table, and the host keeps only the observed [:U]
    prefix. Replaces the dense [G, F] per-part planes past the partial
    cache's dense group cap."""
    some = next(iter(cols.values()))
    mask = jnp.arange(some.shape[0]) < n_valid
    if dedup_mask is not None:
        mask = mask & dedup_mask
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    gid = _sparse_gid(cols, keys)
    if agg_args:
        values = _value_planes(agg_args, cols, tag_names, schema,
                               mask.shape, acc_dtype)
    else:
        values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
    ts = cols[ts_name] if need_ts else None
    return sparse_ops.sparse_segment_agg(values, gid, mask, cap, ops=ops,
                                         ts=ts)


def _agg_step_impl(acc, cols, n_valid, *, where, keys, agg_args, ops,
                   num_segments, ts_name, tag_names, schema, need_ts,
                   acc_dtype):
    """One streaming step: fold a chunk's partial aggregate into the
    device-resident accumulator (constant HBM; one dispatch per chunk)."""
    part = _agg_block(cols, n_valid, None, where=where, keys=keys,
                      agg_args=agg_args, ops=ops, num_segments=num_segments,
                      ts_name=ts_name, tag_names=tag_names, schema=schema,
                      need_ts=need_ts, acc_dtype=acc_dtype)
    return _combine_partials(acc, part)


_AGG_STEP_STATICS = ("where", "keys", "agg_args", "ops", "num_segments",
                     "ts_name", "tag_names", "schema", "need_ts",
                     "acc_dtype")
_agg_step = functools.partial(
    jax.jit, static_argnames=_AGG_STEP_STATICS)(_agg_step_impl)
# see _prep_stream_step_donated: accumulator + chunk buffers reused
_agg_step_donated = functools.partial(
    jax.jit, static_argnames=_AGG_STEP_STATICS,
    donate_argnums=(0, 1))(_agg_step_impl)


_GID_SENTINEL = sparse_ops.GID_SENTINEL  # > any real combined group id


def _sparse_gid(cols: dict, keys) -> jax.Array:
    """Combined int64 group id per row — shard-invariant (tag dictionary
    codes and bucket bases don't depend on which rows a shard holds), so
    gids computed per shard / per part merge globally."""
    key_arrays, sizes = [], []
    for k in keys:
        c = cols[k.column]
        if k.kind == "tag":
            arr = (c + 1).astype(jnp.int64)
        elif k.kind == "bucket":
            arr = (c // k.step - k.base).astype(jnp.int64)
        else:
            arr = c.astype(jnp.int64)
        key_arrays.append(jnp.clip(arr, 0, k.size - 1))
        sizes.append(k.size)
    return combine_group_ids(key_arrays, tuple(sizes), dtype=jnp.int64)


def _pack_part(part: dict, float_ops, int_ops, pack_dtype):
    """Pack a segment_agg plane dict into the (packed_f, packed_i) pair
    shipped over the link (same layout _unpack_acc splits)."""
    parts = []
    for k in float_ops:
        v = part[k]
        if v.ndim == 1:
            v = v[:, None]
        parts.append(v.astype(pack_dtype))
    packed_f = jnp.concatenate(parts, axis=1)
    if int_ops:
        packed_i = jnp.stack([part[k] for k in int_ops], axis=1)
    else:
        packed_i = jnp.zeros((0,), jnp.int64)
    return packed_f, packed_i


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "agg_args", "ops", "cap", "ts_name",
                     "tag_names", "schema", "need_ts", "acc_dtype",
                     "float_ops", "int_ops", "pack_dtype"),
)
def _agg_scan_sparse(
    cols: dict,  # {name: [N] padded whole-scan arrays}
    base_mask: jax.Array,  # [N] bool: padding & dedup survivors
    *,
    where, keys, agg_args, ops, cap, ts_name, tag_names, schema, need_ts,
    acc_dtype, float_ops, int_ops, pack_dtype,
):
    """Sparse (high-cardinality) aggregation: when the dense key product
    won't fit as [G, F] planes, sort the observed int64 group ids, compact
    them to dense [0, U) ids at segment boundaries, and segment-reduce over
    a static cap — the TPU-native replacement for the reference's hash
    aggregate (DataFusion row-hash; BASELINE config #5: 1M tag combos).
    Sorting is XLA-native and shapes stay static: all arrays are [N] or
    [cap, F]; only the group *count* is dynamic (returned as a scalar).
    The sort-compact core lives in ops/sparse_segment.py, shared with the
    fused/sharded/incremental/vmapped sparse flavors.
    """
    mask = base_mask
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    gid = _sparse_gid(cols, keys)
    if agg_args:
        values = _value_planes(agg_args, cols, tag_names, schema,
                               mask.shape, acc_dtype)
    else:
        values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
    ts = cols[ts_name] if need_ts else None
    part, uniq, n_groups = sparse_ops.sparse_segment_agg(
        values, gid, mask, cap, ops=ops, ts=ts)
    packed_f, packed_i = _pack_part(part, float_ops, int_ops, pack_dtype)
    return packed_f, packed_i, uniq, n_groups


@functools.partial(
    jax.jit,
    static_argnames=("where", "keys", "arg_names", "ops", "cap",
                     "tag_names", "schema", "acc_dtype", "float_ops",
                     "pack_dtype", "interpret"),
)
def _agg_scan_sparse_fused(
    cols: dict,  # {name: [N] padded whole-scan arrays}
    base_mask: jax.Array,
    *,
    where, keys, arg_names, ops, cap, tag_names, schema, acc_dtype,
    float_ops, pack_dtype, interpret,
):
    """Sparse aggregation with the reductions on the fused Pallas kernel:
    sort-compact once, then tile the compacted segment axis in FUSED_TILE
    windows (ops/sparse_segment.fused_sparse_segment_agg). The kernel's
    4096-segment envelope becomes a tile size — date_bin bucket domains
    and tag products far past it stay fused instead of falling back to
    the XLA scatter chain. Eligibility (plain finite field columns, op
    subset, mode gates) is the caller's job, mirroring _fused_ok."""
    mask = base_mask
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    gid = _sparse_gid(cols, keys)
    order, ids, valid_s, uniq, n_groups = sparse_ops.sort_compact(
        gid, mask, cap)
    vals = jnp.stack([cols[a].astype(acc_dtype) for a in arg_names],
                     axis=1)[order]
    out = sparse_ops.fused_sparse_segment_agg(
        vals, ids, cap, want_min="min" in ops, want_max="max" in ops,
        want_sumsq="sumsq" in ops, interpret=interpret)
    packed_f = _pack_float_ops(out["sum"], out["count"],
                               out["rows"][:, None], out.get("min"),
                               out.get("max"), out.get("sumsq"),
                               float_ops, pack_dtype)
    return packed_f, jnp.zeros((0,), jnp.int64), uniq, n_groups


@functools.partial(jax.jit, static_argnames=("where", "tag_names", "schema"))
def _filter_block(cols: dict, n_valid: jax.Array, dedup_mask, *, where,
                  tag_names, schema):
    some = next(iter(cols.values()))
    mask = jnp.arange(some.shape[0]) < n_valid
    if dedup_mask is not None:
        mask = mask & dedup_mask
    if where is not None:
        w = eval_device(where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    return mask


@jax.jit
def _dedup_mask(sid, ts, seq, op_type, valid):
    order, keep = sort_dedup(sid, ts, seq, op_type, valid)
    mask = jnp.zeros(valid.shape, dtype=bool)
    return mask.at[order].set(keep)


def _combine_partials(acc: Optional[dict], p: dict) -> dict:
    if acc is None:
        return p
    out = {}
    for k, v in p.items():
        a = acc[k]
        if k in ("count", "rows"):
            out[k] = a.astype(jnp.int64) + v.astype(jnp.int64)
        elif k in ("sum", "sumsq"):
            out[k] = a + v
        elif k == "min":
            out[k] = jnp.fmin(a, v)
        elif k == "max":
            out[k] = jnp.fmax(a, v)
        elif k in ("last", "last_ts", "first", "first_ts"):
            continue  # handled below as pairs
        else:
            raise PlanError(f"cannot combine partial op {k}")
    if "last" in p:
        newer = p["last_ts"] > acc["last_ts"]
        out["last"] = jnp.where(newer[:, None], p["last"], acc["last"])
        out["last_ts"] = jnp.where(newer, p["last_ts"], acc["last_ts"])
    if "first" in p:
        older = p["first_ts"] < acc["first_ts"]
        out["first"] = jnp.where(older[:, None], p["first"], acc["first"])
        out["first_ts"] = jnp.where(older, p["first_ts"], acc["first_ts"])
    return out


# ---- execution tiers -------------------------------------------------------

#: fused-kernel runtime-failure latch (dict so tests can reset it): one
#: mid-query kernel failure routes this and every later query to the
#: XLA scatter path instead of re-failing per query
_FUSED_DISABLED = {"flag": False}

#: incremental-aggregation runtime-failure latch (same contract): an
#: unexpected per-part fold failure degrades to the classic whole-scan
#: kernels instead of re-failing every query
_PARTIAL_DISABLED = {"flag": False}


def _snap_version(scan) -> tuple:
    """Snapshot identity for snap-anchored hot-set keys: (incarnation,
    data_version). TRUNCATE recreates the region and resets its
    data_version, so the version alone can collide with a pre-truncate
    snapshot taken by a query still in flight; the region incarnation
    (0 for remote/synthetic scans) breaks the tie, and the tuple still
    orders lexicographically for the cache's generation retirement."""
    return (getattr(scan, "incarnation", 0), scan.data_version)

_LINK: Optional[dict] = None
# contextvar, NOT a module global: queries run concurrently under the
# threaded servers, and jax.default_device is itself thread-local — the
# cache-key tier must track the same scope or tiers cross-contaminate
import contextvars as _contextvars

_ACTIVE_TIER_VAR = _contextvars.ContextVar("gtpu_tier", default="device")


def accelerator_link() -> dict:
    """Measured host↔accelerator link profile, probed once per process.

    On co-located hardware (PCIe-attached TPU) compute-result readback
    is sub-ms and D2H runs GB/s. Through a network tunnel (remote chip)
    the same readback costs tens of ms and first-fetch D2H single-digit
    MB/s (measured 2026-07-31 on the axon tunnel: 66 ms RTT, ~11 MB/s) —
    in that regime every INTERACTIVE query is readback-bound, while
    large resident-plane aggregations still amortize the link. The tier
    router consults this instead of assuming the link shape."""
    global _LINK
    if _LINK is not None:
        return _LINK
    backend = jax.default_backend()
    if backend == "cpu":
        _LINK = {"backend": "cpu", "rtt_ms": 0.0,
                 "d2h_mbps": float("inf"), "colocated": True}
        return _LINK
    import time as _t
    try:
        f = jax.jit(lambda x: (x * 2.0).sum())
        x = jnp.ones((8, 128), jnp.float32)
        float(f(x))  # compile outside the clock
        t0 = _t.perf_counter()
        for _ in range(3):
            float(f(x))
        rtt_ms = (_t.perf_counter() - t0) / 3 * 1e3
        # D2H must fetch a freshly COMPUTED array: an uploaded one can
        # be served from a host-side copy the transport kept
        y = jax.jit(lambda v: v + 1.0)(jnp.ones((1 << 20,), jnp.float32))
        y.block_until_ready()
        t0 = _t.perf_counter()
        np.asarray(y)
        d2h_mbps = 4.0 / max(_t.perf_counter() - t0, 1e-9)
    except Exception:  # noqa: BLE001 — probe failure ⇒ assume co-located
        rtt_ms, d2h_mbps = 0.0, float("inf")
    _LINK = {"backend": backend, "rtt_ms": round(rtt_ms, 2),
             "d2h_mbps": round(d2h_mbps, 1),
             "colocated": rtt_ms < 5.0 and d2h_mbps > 500.0}
    return _LINK


_COMPILE_CACHE_WIRED = {"done": False}


def enable_compilation_cache() -> bool:
    """Wire JAX's persistent compilation cache (idempotent). The r05
    capture hid a 27.8 s compile-dominated warmup inside the first
    query; with the cache on, that cost is paid once per cluster, not
    once per process start. Enabled by default on accelerator
    platforms; GREPTIMEDB_TPU_COMPILATION_CACHE_DIR overrides the
    location (off/0/none disables)."""
    if _COMPILE_CACHE_WIRED["done"]:
        return True
    from greptimedb_tpu import config

    d = config.compilation_cache_dir()
    if not d:
        return False
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_enable_compilation_cache", True)
        # cache even fast compiles: the dense path compiles one
        # executable per (block plan, query shape) and the long tail of
        # 1-2 s compiles adds up across a dashboard fleet
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        return False
    _COMPILE_CACHE_WIRED["done"] = True
    return True


@functools.lru_cache(maxsize=1)
def _host_device():
    return jax.local_devices(backend="cpu")[0]


class _TierCtx:
    """Route the enclosed jax work to the host tier: compilations and
    new arrays land on the CPU backend (which coexists with the
    accelerator backend), so small queries skip the link entirely."""

    def __init__(self, tier: str):
        self.tier = tier
        self._dd = None
        self._token = None

    def __enter__(self):
        if self.tier == "host" and jax.default_backend() != "cpu":
            self._token = _ACTIVE_TIER_VAR.set("host")
            self._dd = jax.default_device(_host_device())
            self._dd.__enter__()
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _ACTIVE_TIER_VAR.reset(self._token)
        if self._dd is not None:
            self._dd.__exit__(*exc)
        return False


# ---- executor --------------------------------------------------------------


class PhysicalExecutor:
    def __init__(self, engine: RegionEngine):
        self.engine = engine
        from greptimedb_tpu import config
        from greptimedb_tpu.query.device_cache import DeviceCache

        self.cache = DeviceCache()
        # multi-device: row-shard the scan over the mesh and combine
        # partial aggregates with collectives (None on a single chip)
        self.mesh = config.query_mesh()
        # last_path (which aggregate path served the last query:
        # dense | sparse | sharded | stream) and last_tier live behind
        # thread-local properties below
        # hedged device warm-up: shape keys whose device executable is
        # compiled (first-touch queries serve host-side while the
        # ~25 s accelerator compile runs in the background)
        self._device_warm: set = set()
        self._device_warming: set = set()
        self._device_warm_failed: set = set()
        self._warm_lock = threading.Lock()
        # last_path/last_tier are THREAD-LOCAL: the background warm
        # thread runs the same _stream_agg machinery and must not
        # clobber the foreground query's reported path/tier
        self._tls = threading.local()
        # measured per-tier latency history (the span-ring feed): keyed
        # by (tier, log2 rows bucket) so the router can stop choosing a
        # tier that is measurably losing for a workload class
        from collections import deque as _deque

        self._tier_hist: dict[tuple, "_deque"] = {}
        self._tier_explore: dict[int, int] = {}
        self._tier_lock = threading.Lock()
        # warmup amortization: the persistent XLA compilation cache
        # turns the ~25 s first-compile into a once-per-cluster cost,
        # and a background kernel pre-warm compiles the dominant Pallas
        # shapes at open time instead of under the first query
        enable_compilation_cache()
        if config.prewarm_enabled() and jax.default_backend() != "cpu":
            threading.Thread(target=self._prewarm_kernels,
                             daemon=True,
                             name="gtpu-device-prewarm").start()

    @property
    def last_path(self):
        return getattr(self._tls, "last_path", None)

    @last_path.setter
    def last_path(self, v):
        self._tls.last_path = v
        # the continuous profiler attributes samples by execution path;
        # this setter is the single choke point every path tag flows
        # through (one attribute read when profiling is off)
        if _flame._ENABLED:
            _flame.note_path(v)

    @property
    def last_tier(self):
        return getattr(self._tls, "last_tier", "device")

    @last_tier.setter
    def last_tier(self, v):
        self._tls.last_tier = v

    @property
    def last_partial_stats(self):
        """Incremental-aggregation stats of this thread's last query
        (None when the classic paths served): part hit/miss counts,
        delta rows actually folded vs total scan rows."""
        return getattr(self._tls, "partial_stats", None)

    @last_partial_stats.setter
    def last_partial_stats(self, v):
        self._tls.partial_stats = v

    def _prewarm_kernels(self) -> None:
        """Background compile of the dominant Pallas kernel shapes
        (GREPTIMEDB_TPU_PREWARM_SHAPES, "G,F;G,F" pairs — defaults to
        the single-groupby and double-groupby classes) so the first
        dashboard query pays HLO-level compile only, not Mosaic. Best
        effort: a failing shape flips the canaries and the scatter path
        serves."""
        try:
            from greptimedb_tpu.ops import pallas_segment as ps

            ps.tpu_compile_ok()
            ps.fused_tpu_compile_ok()
            # NB: G is the query's GROUP count — the kernels get G+1
            # segments (dead segment), so the largest routable G is
            # MAX_SEGMENTS-1 (4095), not 4096; an ineligible shape
            # would burn Mosaic compile on an executable _fused_ok can
            # never route
            shapes = os.environ.get("GREPTIMEDB_TPU_PREWARM_SHAPES",
                                    "64,10;4095,10")
            for part in shapes.split(";"):
                g, f = (int(x) for x in part.split(","))
                if ps.fused_eligible(f, g + 1):
                    ps.pallas_fused_segment_agg(
                        jnp.zeros((512, f), jnp.float32),
                        jnp.zeros(512, jnp.int32), g + 1,
                        want_min=True, want_max=True, block_rows=256)
                    ps.pallas_fused_segment_agg(
                        jnp.zeros((512, f), jnp.float32),
                        jnp.zeros(512, jnp.int32), g + 1)
                if ps.eligible((512, 2 * f + 1), g + 1):
                    ps.pallas_dense_segment_sum(
                        jnp.zeros((512, 2 * f + 1), jnp.float32),
                        jnp.zeros(512, jnp.int32), g + 1)
        except Exception:  # noqa: BLE001 — pre-warm must never take a node down
            pass

    def _note_tier(self, tier: str, num_rows: int, seconds: float) -> None:
        """Feed one measured execution into the per-tier history ring
        (the device_agg span's duration, bucketed by scan size)."""
        if tier not in ("device", "host", "mesh"):
            return
        from collections import deque as _deque

        b = max(int(num_rows), 1).bit_length()
        with self._tier_lock:
            self._tier_hist.setdefault((tier, b),
                                       _deque(maxlen=16)).append(seconds)

    def _tier_from_history(self, num_rows: int) -> Optional[str]:
        """Measured-routing verdict for this scan-size class, or None
        when either tier lacks samples. Every 16th decision explores
        the losing tier so a regression (or recovery) on the unused
        tier is re-measured instead of frozen in."""
        from greptimedb_tpu import config

        if not config.tier_adaptive():
            return None
        b = max(int(num_rows), 1).bit_length()
        with self._tier_lock:
            dev = sorted(self._tier_hist.get(("device", b), ()))
            host = sorted(self._tier_hist.get(("host", b), ()))
            if len(dev) < 3 or len(host) < 3:
                return None
            med_d = dev[len(dev) // 2]
            med_h = host[len(host) // 2]
            winner = "device" if med_d <= med_h else "host"
            n = self._tier_explore.get(b, 0) + 1
            self._tier_explore[b] = n
        if n % 16 == 0:
            return "host" if winner == "device" else "device"
        return winner

    def _mesh_from_history(self, num_rows: int) -> str:
        """Measured mesh-vs-single-device verdict for this scan-size
        class. Defaults to "mesh" until both tiers hold >=3 real samples
        (the mesh must get its first measurements from somewhere); every
        16th decision explores the loser so a regression on the unused
        tier is re-measured instead of frozen in. GREPTIMEDB_TPU_
        TIER_ADAPTIVE=off pins the static always-mesh routing."""
        from greptimedb_tpu import config

        if not config.tier_adaptive():
            return "mesh"
        b = max(int(num_rows), 1).bit_length()
        with self._tier_lock:
            mesh = sorted(self._tier_hist.get(("mesh", b), ()))
            dev = sorted(self._tier_hist.get(("device", b), ()))
            n = self._tier_explore.get(("mesh", b), 0) + 1
            self._tier_explore[("mesh", b)] = n
            if len(mesh) < 3 or len(dev) < 3:
                # seed the underfilled ring: mesh-eligible shapes never
                # reach the single-device paths on their own, so without
                # this forced sample the >=3 gate would hold forever and
                # the measured arbitration below would be unreachable
                if len(dev) < 3 and n % 8 == 0:
                    return "device"
                return "mesh"
            med_m = mesh[len(mesh) // 2]
            med_d = dev[len(dev) // 2]
            winner = "mesh" if med_m <= med_d else "device"
        if n % 16 == 0:
            return "device" if winner == "mesh" else "mesh"
        return winner

    def tier_for(self, agg, num_rows: int, streaming: bool = False,
                 scan=None) -> str:
        """Tiered execution (round-5 redesign): over a REMOTE
        accelerator link every interactive query is readback-bound —
        66 ms RTT dwarfs single-digit-ms host execution — so only work
        that amortizes the link belongs on the chip: large aggregations
        whose planes stay HBM-resident and whose results are small.
        Raw row-returning queries ship their whole result over the
        slow D2H path, and STREAMING folds ship every block up the
        link once (H2D-bound), so both stay host-side unless
        co-located. On co-located hardware everything runs on the
        device."""
        from greptimedb_tpu import config

        if self.mesh is not None:
            # measured "mesh" tier: aggregate scans big enough to
            # amortize per-shard dispatch ride the mesh, unless the
            # latency history says single-device wins this size class
            if (agg is not None and not streaming
                    and num_rows >= config.mesh_min_rows()
                    and self._mesh_from_history(num_rows) == "mesh"):
                return "mesh"
            return "device"
        if jax.default_backend() == "cpu":
            return "device"
        mode = config.host_tier_mode()
        if mode == "off":
            return "device"
        if mode == "force":
            return "host"
        # measured routing beats the static heuristic: when both tiers
        # have real samples for this scan-size class, the one that is
        # actually losing stops being chosen (ISSUE 7: the heuristic
        # used to pin double_groupby_all to a device tier that measured
        # slower than its own host tier). GREPTIMEDB_TPU_TIER_ADAPTIVE
        # =off restores the pure heuristic for A/B benching.
        if agg is not None and not streaming:
            # hot-set-aware admission runs BEFORE the latency history:
            # a tier already holding the scan's file-anchored blocks
            # serves warm (zero H2D), which no size-class average sees
            adv = self._hot_set_admission(scan)
            if adv is not None:
                return adv
            adv = self._tier_from_history(num_rows)
            if adv is not None:
                return adv
        if accelerator_link()["colocated"]:
            return "device"
        if not streaming and agg is not None \
                and num_rows >= config.device_tier_rows():
            return "device"
        return "host"

    def _hot_set_admission(self, scan) -> Optional[str]:
        """Hot-set-aware tier admission: which tier's block cache already
        holds this scan's file-anchored blocks? Routing a warm scan to
        the OTHER tier re-uploads the whole working set for nothing —
        the history router can't see that (it averages a size class, not
        a residency state). Returns the hot tier, or None to fall
        through to history/heuristic routing. Decisions are counted on
        greptimedb_tpu_tier_admission_total{reason}; the
        GREPTIMEDB_TPU_TIER_ADMISSION knob is the A/B override."""
        from greptimedb_tpu import config
        from greptimedb_tpu.utils.metrics import TIER_ADMISSION

        if scan is None or getattr(scan, "region_id", -1) < 0:
            return None
        if not config.tier_admission():
            TIER_ADMISSION.inc(reason="off")
            return None
        fids = {e.pkey[0] for e in _block_plan(scan) if e.pkey is not None}
        if not fids:
            return None  # memtable/synthetic-only: nothing file-anchored
        per_tier: dict[str, int] = {}
        try:
            resident = self.cache.file_keys(scan.region_id)
        except Exception:
            return None
        for k in resident:
            if len(k) > 3 and k[2] in fids and k[3] in ("device", "host"):
                per_tier[k[3]] = per_tier.get(k[3], 0) + 1
        if not per_tier:
            TIER_ADMISSION.inc(reason="cold")
            return None
        # ties go to the device tier (its planes also serve the kernels)
        best = max(per_tier, key=lambda t: (per_tier[t], t == "device"))
        TIER_ADMISSION.inc(reason=f"{best}_hot")
        return best

    def execute(self, plan: lp.LogicalPlan) -> QueryResult:
        # unwrap the linear chain
        limit = offset = None
        sort: Optional[lp.Sort] = None
        node = plan
        if isinstance(node, lp.Limit):
            limit, offset = node.limit, node.offset
            node = node.input
        if isinstance(node, lp.Sort):
            sort = node
            node = node.input
        if not isinstance(node, lp.Project):
            raise PlanError(f"unexpected plan root {type(node).__name__}")
        project = node
        node = node.input
        having: Optional[lp.Having] = None
        if isinstance(node, lp.Having):
            having = node
            node = node.input
        agg: Optional[lp.Aggregate] = None
        if isinstance(node, lp.Aggregate):
            agg = node
            node = node.input
        where = None
        if isinstance(node, lp.Filter):
            where = node.predicate
            node = node.input
        if not isinstance(node, lp.Scan):
            raise PlanError(f"unexpected scan node {type(node).__name__}")
        scan_node = node

        table = scan_node.table
        ts_range = _closed_range(scan_node.ts_range)
        # conjunctive tag eq/IN predicates drive inverted-index row-group
        # pruning inside the scan (reference scan_region.rs index applier)
        from greptimedb_tpu.storage.index import extract_tag_predicates

        tag_preds = extract_tag_predicates(where, table.schema) or None
        from greptimedb_tpu.utils import tracing

        def run(ts_range):
            # lastpoint pruning: an all-`last` aggregate grouped by one
            # tag only needs each series' newest rows — the region walks
            # SSTs newest-first and stops early (Region.scan_last) in
            # place of decoding the full table. Falls through to the
            # normal paths whenever the region can't serve it exactly
            # (tombstones, router engines, no files).
            lp_tag = self._lastpoint_tag(table, where, agg, ts_range)
            if (lp_tag is not None and len(table.region_ids) == 1
                    and hasattr(self.engine, "scan_last")):
                with tracing.span("scan", table=table.name, regions=1,
                                  lastpoint=True):
                    pruned = self.engine.scan_last(
                        table.region_ids[0], lp_tag, scan_node.columns)
                if pruned is not None:
                    with tracing.span("aggregate", rows=pruned.num_rows):
                        res = self._execute_agg(
                            pruned, table, where, agg, having, project,
                            sort, limit, offset, scan_node)
                    self.last_path = "lastscan+" + (self.last_path or "")
                    return res

            # distributed plan-fragment pushdown: classify the plan prefix
            # (dist_plan.classify_prefix, the commutativity.rs analog) and
            # ship it as one PlanFragment per region — partial-agg planes,
            # top-k candidates, or filtered rows come back, never raw scans
            if (len(table.region_ids) > 1
                    and hasattr(self.engine, "execute_fragment")):
                res = self._try_fragment_pushdown(
                    table, where, agg, having, project, sort, limit, offset,
                    ts_range, scan_node)
                if res is not None:
                    return res

            # beyond-RAM aggregate scans stream: append-mode (no dedup
            # sort), single region, estimated rows over the threshold
            if (agg is not None and table.append_mode
                    and len(table.region_ids) == 1):
                from greptimedb_tpu import config

                stream = self.engine.scan_stream(
                    table.region_ids[0], ts_range, scan_node.columns,
                    tag_preds)
                if stream is not None:
                    if stream.est_rows >= config.stream_threshold_rows():
                        tier = self.tier_for(agg, stream.est_rows,
                                             streaming=True)
                        self.last_tier = tier
                        try:
                            with _TierCtx(tier):
                                return self._execute_agg_stream(
                                    stream, table, where, agg, having,
                                    project, sort, limit, offset,
                                    scan_node)
                        except _NotStreamable:
                            pass  # materialized fallback below
                        finally:
                            # idempotent: releases SST pins if the stream
                            # was abandoned mid-way (or never started)
                            stream.close()
                    else:
                        stream.close()

            with tracing.span("scan", table=table.name,
                              regions=len(table.region_ids)) as scan_attrs:
                if len(table.region_ids) == 1:
                    scan = self.engine.scan(table.region_ids[0], ts_range,
                                            scan_node.columns, tag_preds)
                else:
                    # distributed fan-out: gather every region's scan
                    # (MergeScan, dist_plan/merge_scan.rs analog)
                    from greptimedb_tpu.storage.merge_scan import merge_scans

                    scan = merge_scans(
                        [
                            self.engine.scan(rid, ts_range,
                                             scan_node.columns, tag_preds)
                            for rid in table.region_ids
                        ]
                    )
                # rows land on the span (and, through it, the resource
                # ledger's rows_scanned)
                scan_attrs["rows"] = 0 if scan is None else scan.num_rows

            nrows = 0 if scan is None else scan.num_rows
            if agg is not None:
                # tier decision happens INSIDE _execute_agg, after the
                # boundary fast path has (possibly) shrunk the scan
                with tracing.span("aggregate", rows=nrows):
                    return self._execute_agg(scan, table, where, agg,
                                             having, project, sort, limit,
                                             offset, scan_node)
            tier = self.tier_for(None, nrows)
            self.last_tier = tier
            with tracing.span("filter_project", rows=nrows, tier=tier), \
                    _TierCtx(tier):
                return self._execute_raw(scan, table, where, project, sort,
                                         limit, offset)

        # bucket-top-k narrowing: ORDER BY <time bucket> DESC/ASC LIMIT k
        # only needs the k newest/oldest buckets — scan those, and widen
        # geometrically if the data is sparse (TSBS groupby-orderby-limit
        # runs its aggregate over 13M rows for 5 output buckets otherwise)
        candidates = self._bucket_topk_ranges(table, agg, sort, limit,
                                              offset, having, ts_range)
        if candidates:
            for cand in candidates[:-1]:
                res = run(cand)
                if res.num_rows >= int(limit):
                    self.last_path = "bucket_topk+" + (self.last_path or "")
                    return res
            return run(candidates[-1])
        return run(ts_range)

    # ---- distributed aggregation pushdown ----------------------------------

    def _lastpoint_tag(self, table, where, agg, ts_range):
        """The group tag name when this query is lastpoint-shaped —
        every aggregate is chronological `last` on the device path, the
        single group key is a plain tag column, and nothing (WHERE,
        time range) restricts the row set the newest-first termination
        argument reasons over. None otherwise."""
        if agg is None or not agg.aggs or where is not None \
                or ts_range is not None:
            return None
        if any(spec.func != "last" or _needs_host_agg(spec, table.schema)
               for spec in agg.aggs):
            return None
        if len(agg.keys) != 1:
            return None
        _, kexpr = agg.keys[0]
        if not isinstance(kexpr, ast.Column):
            return None
        schema = table.schema
        tag_names = {c.name for c in schema.tag_columns}
        return kexpr.name if kexpr.name in tag_names else None

    def _bucket_topk_ranges(self, table, agg, sort, limit, offset, having,
                            ts_range) -> Optional[list]:
        """Candidate scan ranges for the bucket-top-k shape: a single
        date_bin/time_bucket group key, ordered by that key, with LIMIT.
        Only the newest (DESC) or oldest (ASC) k buckets can reach the
        output, so the scan starts at k buckets and widens 4x per attempt
        until the output fills or the original range is covered — every
        attempt is exact because ranges are bucket-aligned (a bucket
        inside the range holds ALL its rows; LWW dedup is ts-local).
        Returns None when the shape doesn't match or narrowing can't
        help. Reference runs the full aggregate then sorts
        (datafusion.rs); a TSDB's time-ordered file metadata makes the
        narrowing free."""
        if (agg is None or sort is None or limit is None
                or having is not None):
            return None
        if len(agg.keys) != 1 or len(sort.keys) != 1:
            return None
        name, kexpr = agg.keys[0]
        ob = sort.keys[0]
        if not (ob.expr == kexpr or (isinstance(ob.expr, ast.Column)
                                     and ob.expr.name == name)):
            return None
        schema = table.schema
        ts_col = schema.time_index
        if not (isinstance(kexpr, ast.FuncCall)
                and kexpr.name in ("date_bin", "time_bucket")
                and len(kexpr.args) == 2
                and isinstance(kexpr.args[0], ast.Interval)
                and isinstance(kexpr.args[1], ast.Column)
                and kexpr.args[1].name == ts_col.name):
            return None
        if not hasattr(self.engine, "ts_extent"):
            return None  # engine without metadata extents (remote proxy)
        unit = ts_col.dtype.time_unit.nanos_per_unit
        step = max(kexpr.args[0].nanos // unit, 1)
        k = int(limit) + int(offset or 0)
        exts = [self.engine.ts_extent(rid) for rid in table.region_ids]
        exts = [e for e in exts if e is not None]
        if not exts:
            return None
        dmin = min(e[0] for e in exts)
        dmax = max(e[1] for e in exts)
        lo0, hi0 = ts_range if ts_range else (-(1 << 62), 1 << 62)
        lo_full = max(lo0, dmin)
        hi_full = min(hi0, dmax + 1)  # half-open upper bound
        if hi_full <= lo_full:
            return None
        full = (lo_full, hi_full)
        desc = not ob.asc
        ranges: list = []
        span = k * step
        while True:
            if desc:
                lo = max((max(hi_full - span, lo_full) // step) * step,
                         lo_full)
                cand = (lo, hi_full)
            else:
                hi = min(-(-(min(lo_full + span, hi_full)) // step) * step,
                         hi_full)
                cand = (lo_full, hi)
            ranges.append(cand)
            if cand == full or len(ranges) > 12:
                break
            span *= 4
        if ranges[-1] != full:
            ranges.append(full)
        return ranges if len(ranges) > 1 else None

    def _try_fragment_pushdown(self, table, where, agg, having, project,
                               sort, limit, offset, ts_range,
                               scan_node) -> Optional[QueryResult]:
        """Classify the plan prefix, fan one PlanFragment out to each
        region's owner, and run the Final step over what returns:
        combine partial planes ("agg"), merge-and-resort candidates
        ("topk"), or treat the filtered-row union as the relation
        ("rows"). Returns None when nothing pushes — caller falls back
        to the gather-rows MergeScan path."""
        from greptimedb_tpu.query.dist_agg import combine_partials, merge_topk
        from greptimedb_tpu.query.dist_plan import classify_prefix
        from greptimedb_tpu.utils import tracing

        out = classify_prefix(table, where, agg, project, sort, limit,
                              offset, ts_range, scan_node,
                              _needs_host_agg, _infer_dtype, _PRIMITIVES)
        if out is None:
            return None
        frag, mode = out
        lp_tag = None
        if mode == "agg" and os.environ.get("GTPU_LASTFRAG", "1") \
                not in ("0", "off"):
            # lastpoint pruning hint: an all-`last` single-tag aggregate
            # lets each region owner serve its partial from the newest-
            # first pruned scan (Region.scan_last) instead of decoding
            # the whole region — cluster mode used to pay the full raw
            # scan per datanode here (ROADMAP item 3 cliff).
            # GTPU_LASTFRAG=0 pins the unhinted fragment for A/B.
            lp_tag = self._lastpoint_tag(table, where, agg, ts_range)
            if lp_tag is not None:
                frag.stages.insert(0, {"op": "lastpoint", "tag": lp_tag})
        from greptimedb_tpu.utils.metrics import FRAGMENT_PUSHDOWNS

        FRAGMENT_PUSHDOWNS.inc(mode="lastpoint" if lp_tag else mode)
        with tracing.span("fragment_pushdown", mode=mode,
                          regions=len(table.region_ids)):
            rids = list(table.region_ids)
            if len(rids) > 1:
                # independent region RPCs: fan out so wall-clock is the
                # slowest region, not the sum (merge_scan polls all
                # region streams concurrently for the same reason)
                from concurrent.futures import ThreadPoolExecutor

                from greptimedb_tpu.utils import deadline as dl

                # the statement's CancelToken rides into every region
                # worker: a stalled region unwinds typed at the deadline
                # instead of pinning the fan-out past it
                one = dl.propagate(tracing.propagate(
                    lambda rid: self.engine.execute_fragment(rid, frag)))

                with ThreadPoolExecutor(
                        max_workers=min(8, len(rids))) as pool:
                    partials = list(pool.map(one, rids))
            else:
                partials = [self.engine.execute_fragment(rids[0], frag)]

        if mode == "agg":
            agg_stage = frag.stage("partial_agg")
            spec_slot: list[Optional[int]] = []
            for spec in agg.aggs:
                spec_slot.append(
                    None if spec.arg is None
                    else agg_stage["args"].index(spec.arg))
            combined = combine_partials(partials, len(agg.keys),
                                        tuple(agg_stage["ops"]))
            self.last_path = "lastfrag+pushdown" if lp_tag else "pushdown"
            return self._finalize_combined_agg(
                combined, table, agg, having, project, sort, limit,
                offset, spec_slot)

        merged = merge_topk(partials)
        if mode == "rows_agg":
            # non-decomposable aggregate over the filtered-row union:
            # regions shipped exactly the needed columns (already
            # LWW-deduped and filtered); re-enter the normal device
            # aggregation with the union as the relation
            if merged is None:
                self.last_path = "rows_agg_pushdown"
                return self._empty_agg_result(table, agg, having, project,
                                              sort, limit, offset)
            scan = _cols_to_scan(table, merged["cols"])
            with tracing.span("aggregate", rows=scan.num_rows):
                res = self._execute_agg(scan, table, None, agg, having,
                                        project, sort, limit, offset,
                                        scan_node)
            self.last_path = "rows_agg+" + (self.last_path or "")
            return res
        self.last_path = "topk_pushdown" if mode == "topk" \
            else "rows_pushdown"
        if merged is None:
            return _project_empty(project, table.schema)
        host_cols = merged["cols"]
        nrows = len(next(iter(host_cols.values()))) if host_cols else 0
        return self._post_process({}, None, None, project, sort, limit,
                                  offset, table, nrows, host_cols=host_cols)

    def _finalize_combined_agg(self, combined, table, agg, having, project,
                               sort, limit, offset,
                               spec_slot) -> QueryResult:
        """Final step over combined [G, F] partial planes — shared by
        the fragment pushdown and the vmapped-fragments member loop."""
        if combined is None:
            return self._empty_agg_result(table, agg, having, project,
                                          sort, limit, offset)
        planes = combined["planes"]
        g = len(combined["keys"][0]) if agg.keys else 1
        present = np.arange(g)
        env: dict = {}
        for i, (name, kexpr) in enumerate(agg.keys):
            env[kexpr] = combined["keys"][i]
        for spec, slot in zip(agg.aggs, spec_slot):
            env[spec.call] = _finalize_agg(spec.func, planes, slot,
                                           present)
        return self._post_process(env, agg, having, project, sort,
                                  limit, offset, table, g)

    def _execute_agg(self, scan, table, where, agg, having, project, sort,
                     limit, offset, scan_node) -> QueryResult:
        schema = table.schema
        ts_name = schema.time_index.name
        self.last_partial_stats = None
        if scan is None:
            return self._empty_agg_result(table, agg, having, project, sort, limit, offset)

        ctx = BindContext(schema, scan.tag_dicts)
        bound_where = bind_expr(where, ctx) if where is not None else None

        # group keys -> DeviceKeys (+ host factorized pre-keys)
        keys: list[DeviceKey] = []
        decoders = []  # per key: fn(int indices) -> value array, dtype
        extra_cols: dict[str, np.ndarray] = {}
        for i, (name, kexpr) in enumerate(agg.keys):
            dk, decode = self._plan_key(i, kexpr, ctx, scan, scan_node, extra_cols)
            keys.append(dk)
            decoders.append(decode)
        from greptimedb_tpu import config

        num_groups = 1
        for k in keys:
            num_groups *= k.size
        if num_groups >= _GID_SENTINEL:
            raise PlanError(
                f"group key space {num_groups} overflows the int64 id "
                "domain; add predicates or reduce keys"
            )
        # dense [G, F] planes up to the configured budget; beyond that the
        # sparse sort-compact path handles arbitrary cardinality.
        # sparse_groups_min (off by default) pulls smaller key products
        # onto the sparse path too — the lever for date_bin domains that
        # fit the dense budget but blow the fused 4096-segment envelope
        sparse = bool(keys) and (
            num_groups > config.dense_groups_max()
            or (config.sparse_groups_min() > 0
                and num_groups >= config.sparse_groups_min()))

        # aggregate args -> values matrix columns (host-computed
        # order-statistic aggs don't consume a device value plane)
        from greptimedb_tpu.query.host_agg import HOST_AGGS

        arg_exprs: list[ast.Expr] = []
        spec_slot: list[Optional[int]] = []
        for spec in agg.aggs:
            if spec.arg is None or _needs_host_agg(spec, schema):
                spec_slot.append(None)
                continue
            b = bind_expr(spec.arg, ctx)
            if b not in arg_exprs:
                arg_exprs.append(b)
            spec_slot.append(arg_exprs.index(b))
        ops: set = {"rows"}
        for spec in agg.aggs:
            if not _needs_host_agg(spec, schema):
                ops.update(_PRIMITIVES[spec.func])
        need_ts = bool({"first", "last"} & ops)

        reduced = self._boundary_firstlast(scan, table, agg, bound_where,
                                           keys, extra_cols)
        # incremental aggregation (ISSUE 13): immutable parts' [G, F]
        # partials come from the partial-aggregate cache; only uncached
        # parts + the memtable delta run kernels. Runs after the
        # boundary first/last reduction (whose candidate gather is
        # already snapshot-memoized) — a reduced scan has no per-part
        # identity and falls through to the classic kernels. Typed
        # fallback (PartialCacheIneligible) lands back here too.
        if reduced is None:
            res = self._try_incremental_agg(
                scan, table, bound_where, keys, decoders, arg_exprs, ops,
                num_groups, ts_name, ctx, extra_cols, agg, having, project,
                sort, limit, offset, spec_slot, sparse)
            if res is not None:
                return res
        if reduced is not None:
            scan = reduced
        # tier re-decision on the POST-reduction row count: the
        # boundary fast path shrinks a 17M-row lastpoint to a few
        # thousand candidate rows — routing those to a remote chip
        # would pay the link RTT for microseconds of compute
        tier = self.tier_for(agg, scan.num_rows, scan=scan)
        stream_args = (scan, table, bound_where, tuple(keys),
                       tuple(arg_exprs), tuple(sorted(ops)), num_groups,
                       ts_name, ctx, extra_cols, sparse)
        tier = self._hedge_device_warmup(tier, stream_args)
        self.last_tier = tier
        t0 = time.perf_counter()
        with _TierCtx(tier):
            acc, sparse_gids = self._stream_agg(*stream_args)
        # measured-routing feed: what this tier actually cost for this
        # scan size (results are materialized host-side by here, so the
        # clock covers upload + kernels + readback). last_tier is the
        # EFFECTIVE tier — a mesh-routed query that degraded to the
        # single-device paths must feed the device history, not mesh's
        self._note_tier(self.last_tier, scan.num_rows,
                        time.perf_counter() - t0)
        if reduced is not None:
            self.last_path = "boundary+" + (self.last_path or "")
        host_info = (scan, extra_cols, bound_where, ctx, num_groups)
        return self._agg_tail(acc, sparse_gids, agg, keys, decoders,
                              spec_slot, host_info, having, project, sort,
                              limit, offset, table)

    # ---- incremental aggregation (partial-aggregate cache) -----------------

    def _try_incremental_agg(self, scan, table, bound_where, keys, decoders,
                             arg_exprs, ops, num_groups, ts_name, ctx,
                             extra_cols, agg, having, project, sort, limit,
                             offset, spec_slot,
                             sparse=False) -> Optional[QueryResult]:
        """Serve this aggregate from per-part cached partials + a
        delta-only fold (query/partial_cache.py module docstring), or
        return None for the classic whole-scan paths. Any gate the
        per-part decomposition cannot prove raises the typed
        PartialCacheIneligible internally and counts one `fallback`."""
        from greptimedb_tpu.query import partial_cache as pc
        from greptimedb_tpu.query.dist_agg import combine_partials
        from greptimedb_tpu.utils import tracing
        from greptimedb_tpu.utils.metrics import PARTIAL_AGG_CACHE_EVENTS

        if not pc.enabled() or _PARTIAL_DISABLED["flag"]:
            return None
        try:
            t0 = time.perf_counter()
            partials, stats, tier = self._incremental_partials(
                scan, table, bound_where, keys, decoders, arg_exprs, ops,
                num_groups, ts_name, ctx, extra_cols, agg, sparse)
        except pc.PartialCacheIneligible:
            PARTIAL_AGG_CACHE_EVENTS.inc(event="fallback")
            return None
        except PlanError:
            # a planning error (e.g. a substituted rollup plan probing a
            # column the companion scan lacks) is the GUARDED-FALLBACK
            # signal upstream relies on — the classic path would raise
            # the identical error here, so propagate it and never latch
            raise
        except Exception:  # noqa: BLE001 — degrade, don't fail the query
            # an unexpected incremental failure (compile, OOM) must not
            # take serving down: latch the path off and let the classic
            # whole-scan kernels answer this and later queries — the
            # same degradation contract as the fused-kernel latch
            import traceback

            traceback.print_exc()
            print("incremental aggregation failed; serving this and "
                  "later queries through the classic paths", flush=True)
            _PARTIAL_DISABLED["flag"] = True
            PARTIAL_AGG_CACHE_EVENTS.inc(event="fallback")
            return None
        with tracing.span("incremental_agg", parts=stats["parts"],
                          part_hits=stats["part_hits"],
                          delta_rows=stats["delta_rows"],
                          total_rows=stats["total_rows"]):
            combined = combine_partials(partials, len(agg.keys),
                                        tuple(sorted(ops)))
        # measured-routing feed: the fold only ran kernels over the
        # DELTA rows — recording a cache-served query against the full
        # scan size would teach the router that this tier folds 17M
        # rows in a millisecond and misroute non-cacheable queries of
        # the same size class. Pure-cache serves feed nothing.
        if stats["delta_rows"]:
            self._note_tier(tier, stats["delta_rows"],
                            time.perf_counter() - t0)
        self.last_path = "incremental_sparse" if stats.get("sparse") \
            else "incremental"
        self.last_partial_stats = stats
        return self._finalize_combined_agg(combined, table, agg, having,
                                           project, sort, limit, offset,
                                           spec_slot)

    def _incremental_partials(self, scan, table, bound_where, keys,
                              decoders, arg_exprs, ops, num_groups, ts_name,
                              ctx, extra_cols, agg, sparse=False):
        """Gather cached part partials, compute the uncached parts and
        the memtable delta with the SAME per-block kernel the classic
        dense path runs, and return the part-ordered partial list (the
        left-fold order combine_partials preserves). Raises
        PartialCacheIneligible when the per-part decomposition is not
        provably exact.

        Past the dense cache cap (or when the query is already sparse),
        the per-part fold sort-compacts instead: partials carry only the
        OBSERVED groups' value-keyed planes ([U, F], U <= part rows) —
        the 64k-group fallback becomes a different per-part kernel, and
        the value-keyed combine (query/dist_agg.py) is cardinality-
        oblivious either way."""
        from collections import OrderedDict as _OrderedDict

        from greptimedb_tpu import config
        from greptimedb_tpu.query import partial_cache as pc
        from greptimedb_tpu.utils.metrics import PARTIAL_AGG_DELTA_ROWS

        schema = table.schema
        if scan.region_id < 0:
            raise pc.PartialCacheIneligible("synthetic scan")
        if any(_needs_host_agg(spec, schema) for spec in agg.aggs):
            raise pc.PartialCacheIneligible("host-side aggregate")
        # past the dense cache cap the fold goes sparse instead of
        # falling back (value-keyed partials never materialize [G, F])
        use_sparse = sparse or num_groups > pc.groups_max()
        # DELETE voids the decomposition exactly like scan_last: a
        # tombstone may mask rows in a different part (memoized on the
        # snapshot, shared with the boundary fast path)
        has_delete = getattr(scan, "_has_delete", None)
        if has_delete is None:
            from greptimedb_tpu.storage.region import OP_PUT

            has_delete = bool((scan.op_type != OP_PUT).any())
            scan._has_delete = has_delete
        if has_delete:
            raise pc.PartialCacheIneligible("tombstones reachable")

        plan = _block_plan(scan)
        parts: "_OrderedDict[tuple, list]" = _OrderedDict()
        mem_entries: list[_BlockEntry] = []
        for e in plan:
            if e.pkey is not None:
                parts.setdefault(e.pkey, []).append(e)
            else:
                mem_entries.append(e)
        if not parts:
            raise pc.PartialCacheIneligible("no immutable parts")
        for pk, es in parts.items():
            if len(es) != 1:
                # one-device-block-per-part gate (the vmapped parity
                # precedent): the cached partial must BE the part's
                # left-fold contribution for combine order to reproduce
                # the classic block-sequential association bit-for-bit
                raise pc.PartialCacheIneligible("multi-block part")
        # LWW dedup is whole-scan: a newer duplicate in part Q can kill
        # a row in part P, so a masked per-part partial is only
        # file-pure when no duplicate can CROSS a part seam. Duplicates
        # share an exact (series, ts) instant, so pairwise-disjoint
        # part/memtable ts extents prove the dedup part-local — the
        # sliced global mask then equals the part's own LWW mask
        # bit-for-bit. Overlapping extents (late writes) fall back.
        dedup_mask = None
        if not table.append_mode and scan.needs_dedup:
            if not self._parts_ts_disjoint(scan, ts_name):
                raise pc.PartialCacheIneligible("cross-part dedup")
            dedup_mask = self._maybe_dedup(scan, table, ctx)

        acc_dtype = jnp.dtype(config.compute_dtype())
        ops_t = tuple(sorted(ops))
        fp = pc.shape_fingerprint(bound_where, keys,
                                  [kexpr for _, kexpr in agg.keys],
                                  arg_exprs, ops_t, acc_dtype)
        if use_sparse:
            # sparse partials fold sorted (different float association
            # than the dense scatter) — never mix with dense cache hits
            fp = fp + ("sparse",)
        cache = pc.global_cache()
        # probe the cache BEFORE routing: only the delta (uncached parts
        # + memtable) runs kernels, and routing a 50-row warm delta to a
        # remote accelerator would pay the link RTT for microseconds of
        # compute — the same argument as the boundary fast path's
        # post-reduction tier re-decision
        probed: list[tuple] = []
        delta_est = sum(e.end - e.start for e in mem_entries)
        first_uncached = None
        for pk, (entry,) in parts.items():
            key = ("part", scan.region_id, pk[0], pk[1], pk[2], fp)
            p = cache.get(key)
            probed.append((key, entry, p))
            if p is None:
                delta_est += entry.end - entry.start
                if first_uncached is None:
                    first_uncached = entry
        tier = self.tier_for(agg, delta_est, scan=scan)
        # first-touch hedge (the classic paths' 40s-cold-start fix must
        # not regress here): until this shape's per-part kernel has
        # compiled on the accelerator, folds serve host-side and a
        # background thread warms the device — same contract as
        # _hedge_device_warmup, keyed by the incremental fingerprint
        hedge = delta_est > 0 and self._incremental_hedge_needed(tier, fp)
        if hedge:
            tier = "host"
        self.last_tier = tier
        place = self._incremental_placement(tier, scan)

        tag_names = frozenset(ctx.tag_names)
        float_fields = {c.name for c in schema.field_columns
                        if c.dtype.is_float}
        col_names = self._device_columns(scan, bound_where, keys, arg_exprs,
                                         ts_name, extra_cols)
        kw = dict(where=bound_where, keys=tuple(keys),
                  agg_args=tuple(arg_exprs), ops=ops_t,
                  num_segments=num_groups, ts_name=ts_name,
                  tag_names=tag_names, schema=schema,
                  need_ts=bool({"first", "last"} & set(ops)),
                  acc_dtype=acc_dtype)
        strides = _strides([k.size for k in keys])

        def fetch_cols(entry):
            return {name: self._device_block(
                        scan, name, entry, extra_cols,
                        acc_dtype if name in float_fields else None)
                    for name in col_names}

        def entry_dmask(entry):
            return None if dedup_mask is None else _pad_device_mask(
                dedup_mask, entry.start, entry.end, entry.block)

        def compute_partial_dense(entry):
            out = _agg_block_jit(fetch_cols(entry),
                                 jnp.asarray(entry.end - entry.start),
                                 entry_dmask(entry), **kw)
            planes = {op: _readback(v) for op, v in out.items()}
            rows = planes["rows"]
            rows1 = rows[:, 0] if rows.ndim == 2 else rows
            # keyed aggregates keep only observed groups (matching the
            # per-region Partial step); a global aggregate keeps its one
            # group even when empty so the combined result has a row
            present = np.flatnonzero(rows1 > 0) if agg.keys \
                else np.arange(1)
            key_cols = []
            for i, decode in enumerate(decoders):
                idx = (present // strides[i]) % keys[i].size
                col, _ = decode(idx)
                key_cols.append(np.asarray(col))
            return {"keys": key_cols,
                    "planes": {op: pl[present]
                               for op, pl in planes.items()}}

        sparse_kw = {k: v for k, v in kw.items() if k != "num_segments"}

        def compute_partial_sparse(entry):
            # sort-compact the part's own rows: the cap is one device
            # block (observed groups can't exceed part rows), so the
            # 64k dense cache ceiling never enters the per-part shapes
            cap = min(entry.block, config.sparse_groups_max())
            out, uniq, n_groups = _agg_block_sparse(
                fetch_cols(entry), jnp.asarray(entry.end - entry.start),
                entry_dmask(entry), cap=cap, **sparse_kw)
            u = int(n_groups)
            if u > cap:
                raise PlanError(
                    f"part observed {u} distinct groups, exceeding the "
                    f"sparse cap {cap}; raise "
                    "GREPTIMEDB_TPU_SPARSE_GROUPS_MAX or add predicates")
            gids = np.asarray(uniq)[:u]
            key_cols = []
            for i, decode in enumerate(decoders):
                idx = (gids // strides[i]) % keys[i].size
                col, _ = decode(idx)
                key_cols.append(np.asarray(col))
            return {"keys": key_cols,
                    "planes": {op: _readback(v)[:u]
                               for op, v in out.items()}}

        compute_partial = compute_partial_sparse if use_sparse \
            else compute_partial_dense

        if hedge:
            self._kick_incremental_warm(
                fp,
                first_uncached if first_uncached is not None
                else mem_entries[0],
                compute_partial)

        partials: list[dict] = []
        hits = misses = 0
        delta_rows = cached_rows = 0
        for key, entry, p in probed:
            if p is None:
                epoch = cache.epoch(scan.region_id)
                with place(key[2]):
                    p = compute_partial(entry)
                cache.put(key, p, epoch=epoch)
                misses += 1
                delta_rows += entry.end - entry.start
            else:
                hits += 1
                cached_rows += entry.end - entry.start
            partials.append(p)
        mem_rows = 0
        for entry in mem_entries:
            with place(None):
                partials.append(compute_partial(entry))
            mem_rows += entry.end - entry.start
        delta_rows += mem_rows
        if delta_rows:
            PARTIAL_AGG_DELTA_ROWS.inc(float(delta_rows), kind="delta")
        if cached_rows:
            PARTIAL_AGG_DELTA_ROWS.inc(float(cached_rows), kind="cached")
        stats = {"parts": len(parts), "part_hits": hits,
                 "part_misses": misses, "delta_rows": delta_rows,
                 "cached_rows": cached_rows, "memtable_rows": mem_rows,
                 "total_rows": scan.num_rows, "sparse": use_sparse}
        if use_sparse:
            from greptimedb_tpu.utils.metrics import SPARSE_DISPATCHES

            SPARSE_DISPATCHES.inc(path="incremental")
        return partials, stats, tier

    def _incremental_hedge_needed(self, tier: str, fp: tuple) -> bool:
        """Whether this incremental fold must serve host-side while the
        accelerator compile of its per-part kernel warms in the
        background (auto host-tier mode on a real accelerator only —
        mode=off means the caller wants the device NOW and will wait,
        and the mesh tier has its own placement)."""
        from greptimedb_tpu import config

        if tier != "device" or jax.default_backend() == "cpu" \
                or self.mesh is not None \
                or config.host_tier_mode() != "auto":
            return False
        with self._warm_lock:
            return fp not in self._device_warm

    def _kick_incremental_warm(self, fp: tuple, entry, compute_partial):
        """Background device compile of the incremental per-part kernel
        for this shape: runs ONE part's fold on the accelerator and
        DISCARDS the result (the host-computed partials are already
        cached — a device-computed twin could differ in the last ulp on
        emulated f64, and warm/cold serves must stay bit-identical).
        Once it lands, the shape joins `_device_warm` and later delta
        folds run on the chip."""
        with self._warm_lock:
            if fp in self._device_warming or fp in self._device_warm \
                    or fp in self._device_warm_failed:
                return
            self._device_warming.add(fp)

        def warm():
            try:
                with _TierCtx("device"):
                    compute_partial(entry)
                with self._warm_lock:
                    self._device_warm.add(fp)
            except Exception:  # noqa: BLE001 — hedge must not raise
                with self._warm_lock:
                    self._device_warm_failed.add(fp)
            finally:
                with self._warm_lock:
                    self._device_warming.discard(fp)

        threading.Thread(target=warm, daemon=True,
                         name="gtpu-incremental-warm").start()

    def _parts_ts_disjoint(self, scan, ts_name: str) -> bool:
        """Whether every SST part's ts extent (and the memtable tail's)
        is pairwise disjoint — the proof that LWW dedup cannot cross a
        part seam. One O(N) min/max pass, memoized on the snapshot."""
        cached = getattr(scan, "_parts_ts_disjoint_cache", None)
        if cached is not None:
            return cached
        offs = list(scan.sorted_part_offsets) or [0]
        if offs[-1] < scan.num_rows:
            offs.append(scan.num_rows)  # memtable tail interval
        ts = scan.columns[ts_name]
        spans = []
        for i in range(len(offs) - 1):
            s0, s1 = offs[i], offs[i + 1]
            if s1 > s0:
                seg = ts[s0:s1]
                spans.append((int(seg.min()), int(seg.max())))
        spans.sort()
        ok = all(spans[i][1] < spans[i + 1][0]
                 for i in range(len(spans) - 1))
        scan._parts_ts_disjoint_cache = ok
        return ok

    def _incremental_placement(self, tier: str, scan):
        """Compute-placement context per part for the incremental fold:
        host tier pins the CPU backend; the mesh tier computes each
        part's partial on the shard `plan_shards` assigns the part's
        FIRST chunk to (the dispatch's deterministic greedy balance, so
        uncached folds spread across the mesh the way the classic
        dispatch's load does). The per-block uploads key under
        tier="mesh" — a namespace deliberately distinct from both the
        single-device tiers and the classic dispatch's per-segment
        "mshard" entries (which chunk parts ACROSS shards and can't be
        reused at part granularity); all classes share the one
        DeviceCache byte budget, so duplicates are bounded by LRU, not
        leaked. Cached partials are host numpy either way — the warm
        path never touches a device."""
        if tier == "mesh" and self.mesh is not None:
            from greptimedb_tpu.parallel import sharded_dispatch as sd

            if sd.eligible(self.mesh):
                devs = sd.shard_devices(self.mesh)
                plan = sd.plan_shards(scan, len(devs))
                owner_of = {}
                for s, segs in enumerate(plan.segs):
                    for seg in segs:
                        if seg.pkey is not None and seg.start == \
                                seg.part_start:
                            owner_of[seg.pkey[0]] = s
                tok = _ACTIVE_TIER_VAR

                class _OnShard:
                    def __init__(self, fid):
                        owner = owner_of.get(fid, 0) if fid is not None \
                            else 0
                        self._dd = jax.default_device(devs[owner])
                        self._token = None

                    def __enter__(self):
                        self._token = tok.set("mesh")
                        self._dd.__enter__()
                        return self

                    def __exit__(self, *exc):
                        self._dd.__exit__(*exc)
                        tok.reset(self._token)
                        return False

                return _OnShard
        return lambda fid: _TierCtx(tier)

    def _agg_tail(self, acc, sparse_gids, agg, keys, decoders, spec_slot,
                  host_info, having, project, sort, limit, offset,
                  table) -> QueryResult:
        """Shared host tail: decode present groups' keys, finalize
        aggregates, run HAVING/ORDER/LIMIT over the G-row result."""
        from greptimedb_tpu.query.host_agg import HOST_AGGS

        rows = acc["rows"][:, 0] if acc["rows"].ndim == 2 else acc["rows"]
        if sparse_gids is not None:
            # sparse: acc rows [0, U) are the observed groups, in
            # ascending global-id order
            present = np.arange(len(sparse_gids))
            present_gids = sparse_gids
        elif agg.keys:
            present = np.flatnonzero(rows > 0)
            present_gids = present
        else:
            present = np.arange(1)
            present_gids = present
        env: dict = {}
        # decode group key columns
        strides = _strides([k.size for k in keys])
        key_cols: dict[str, tuple[np.ndarray, Optional[DataType]]] = {}
        for i, ((name, kexpr), decode) in enumerate(zip(agg.keys, decoders)):
            idx = (present_gids // strides[i]) % keys[i].size
            col, dtype = decode(idx)
            env[kexpr] = col
            key_cols[name] = (col, dtype)
        # aggregate outputs
        host_specs = [s for s in agg.aggs
                      if _needs_host_agg(s, table.schema)]
        for spec, slot in zip(agg.aggs, spec_slot):
            if _needs_host_agg(spec, table.schema):
                continue
            env[spec.call] = _finalize_agg(spec.func, acc, slot, present)
        if host_specs:
            scan, extra_cols, bound_where, ctx, num_groups = host_info
            self._host_aggs(host_specs, keys, scan, extra_cols, bound_where,
                            table, ctx, num_groups, present, env,
                            sparse_gids)

        return self._post_process(env, agg, having, project, sort, limit, offset,
                                  table, len(present))

    def _hedge_device_warmup(self, tier: str, stream_args) -> str:
        """First-touch hedge: an accelerator's first compile of a query
        shape costs tens of seconds (measured ~25 s on v5e through the
        remote compile helper) — blocking the first query on it is the
        round-4 verdict's 40 s cold-start. Instead, kick the device
        fold on a background thread and serve THIS query host-side;
        once the background compile lands, the shape joins
        `_device_warm` and later queries run on the chip. Applies only
        in auto mode on a real accelerator backend (explicit mode=off
        means the caller wants the device NOW and will wait)."""
        from greptimedb_tpu import config

        if tier != "device" or jax.default_backend() == "cpu" \
                or self.mesh is not None \
                or config.host_tier_mode() != "auto":
            return tier
        scan = stream_args[0]
        # repr() folds the full query shape in: WHERE expression, group
        # keys, and arg expressions each change the compiled HLO — a
        # key missing them would declare a DIFFERENT program warm and
        # block the foreground on its cold compile
        wkey = (scan.region_id, scan.data_version, scan.scan_fingerprint,
                repr(stream_args[2]), repr(stream_args[3]),
                repr(stream_args[4]), stream_args[5], stream_args[6],
                stream_args[10])
        with self._warm_lock:
            if wkey in self._device_warm:
                return "device"
            if wkey in self._device_warm_failed:
                return "host"  # don't re-kick a known-failing compile
            already = wkey in self._device_warming
            if not already:
                self._device_warming.add(wkey)
        if not already:
            def warm():
                try:
                    t0 = time.perf_counter()
                    with _TierCtx("device"):
                        self._stream_agg(*stream_args)
                    # first device sample includes the compile; later
                    # foreground runs will pull the median down — but a
                    # device tier that stays slow now shows up in the
                    # router's history instead of being assumed fast
                    self._note_tier("device", stream_args[0].num_rows,
                                    time.perf_counter() - t0)
                    with self._warm_lock:
                        self._device_warm.add(wkey)
                except Exception:  # noqa: BLE001 — hedge must not raise
                    import traceback

                    traceback.print_exc()
                    print("device warm-up failed for this query shape; "
                          "it stays on the host tier", flush=True)
                    with self._warm_lock:
                        self._device_warm_failed.add(wkey)
                finally:
                    with self._warm_lock:
                        self._device_warming.discard(wkey)

            threading.Thread(target=warm, daemon=True).start()
        return "host"

    def _boundary_firstlast(self, scan, table, agg, bound_where, keys,
                            extra_cols) -> Optional[ScanData]:
        """Lastpoint-class fast path: when every aggregate is first/last
        (by time index) and grouping is by tag columns only, the winners
        can only sit at per-series run boundaries of the (tags..., ts,
        seq)-sorted SST segments — gather those few rows on host and run
        the normal kernel over the tiny subset instead of reducing the
        whole scan (reference reads the same order per file,
        mito2/src/read/merge.rs; TSBS `lastpoint` is the headline user).

        Correctness sketch (LWW): within one sorted segment the last row
        of a series' run carries its max ts and, among duplicates of that
        ts, the max seq; the global max-seq version of the max-ts instant
        lives in SOME segment where it is that segment's boundary row, so
        the candidate set always contains the LWW winner and the subset
        dedup selects it. Mirrored for `first` via the end of the first
        (tags, ts) sub-run. Memtable rows are unsorted and are included
        wholesale. DELETE tombstones void the argument (the newest row
        may be a tombstone, making an interior row the answer) — any
        tombstone in the scan disables the path."""
        offsets = scan.sorted_part_offsets
        if len(offsets) < 2 or offsets[-1] == 0:
            return None
        if bound_where is not None or extra_cols:
            return None
        if not agg.aggs or any(
                spec.func not in ("first", "last")
                or _needs_host_agg(spec, table.schema)
                for spec in agg.aggs):
            return None
        if not all(k.kind == "tag" for k in keys):
            return None
        cached = getattr(scan, "_boundary_fl_cache", None)
        if cached is not None:
            return cached if cached is not False else None
        has_delete = getattr(scan, "_has_delete", None)
        if has_delete is None:
            from greptimedb_tpu.storage.region import OP_PUT

            has_delete = bool((scan.op_type != OP_PUT).any())
            scan._has_delete = has_delete
        if has_delete:
            scan._boundary_fl_cache = False
            return None

        n = scan.num_rows
        send = offsets[-1]  # end of the sorted region
        # row i starts a new series run when any tag code differs from
        # row i-1, or i is a segment seam (sortedness restarts there)
        new_run = np.zeros(send, dtype=bool)
        new_run[0] = True
        for c in table.schema.tag_columns:
            col = scan.columns[c.name]
            new_run[1:] |= col[1:send] != col[: send - 1]
        seams = np.asarray(offsets[1:-1], dtype=np.int64)
        new_run[seams[seams < send]] = True
        ts = scan.columns[table.schema.time_index.name]
        new_sub = new_run.copy()
        new_sub[1:] |= ts[1:send] != ts[: send - 1]
        run_start = np.flatnonzero(new_run)
        run_end = np.append(run_start[1:] - 1, send - 1)
        # ends of (tags, ts) sub-runs: max-seq row of each instant
        sub_end = np.flatnonzero(np.append(new_sub[1:], True))
        # `first` winner candidate: end of the FIRST sub-run in each run
        first_end = sub_end[np.searchsorted(sub_end, run_start)]
        parts = [run_start, run_end, first_end]
        if send < n:
            parts.append(np.arange(send, n))
        idx = np.unique(np.concatenate(parts))
        if idx.size >= n * _BOUNDARY_MAX_FRACTION:
            scan._boundary_fl_cache = False
            return None
        reduced = ScanData(
            schema=scan.schema,
            columns={k: v[idx] for k, v in scan.columns.items()},
            seq=scan.seq[idx],
            op_type=scan.op_type[idx],
            tag_dicts=scan.tag_dicts,
            num_rows=idx.size,
            needs_dedup=scan.needs_dedup,
            region_id=scan.region_id,
            data_version=scan.data_version,
            scan_fingerprint=scan.scan_fingerprint + ("__boundary_fl__",),
        )
        scan._boundary_fl_cache = reduced
        return reduced

    def _execute_agg_stream(self, stream, table, where, agg, having, project,
                            sort, limit, offset, scan_node) -> QueryResult:
        """Bounded-memory aggregation: lazy scan chunks fold into a
        device-resident accumulator (see ScanStream). Raises _NotStreamable
        for shapes that need the whole scan on host (generic keys, host
        order statistics, sparse cardinality)."""
        from greptimedb_tpu import config
        from greptimedb_tpu.query.host_agg import HOST_AGGS

        schema = table.schema
        ts_name = schema.time_index.name
        ctx = BindContext(schema, stream.tag_dicts)
        bound_where = bind_expr(where, ctx) if where is not None else None

        keys: list[DeviceKey] = []
        decoders = []
        for i, (name, kexpr) in enumerate(agg.keys):
            dk, decode = self._plan_key_stream(i, kexpr, ctx, stream, scan_node)
            keys.append(dk)
            decoders.append(decode)
        num_groups = 1
        for k in keys:
            num_groups *= k.size
        if num_groups > config.dense_groups_max():
            raise _NotStreamable("sparse cardinality")

        arg_exprs: list[ast.Expr] = []
        spec_slot: list[Optional[int]] = []
        for spec in agg.aggs:
            if _needs_host_agg(spec, schema):
                raise _NotStreamable(f"host aggregate {spec.func}")
            if spec.arg is None:
                spec_slot.append(None)
                continue
            b = bind_expr(spec.arg, ctx)
            if b not in arg_exprs:
                arg_exprs.append(b)
            spec_slot.append(arg_exprs.index(b))
        ops: set = {"rows"}
        for spec in agg.aggs:
            ops.update(_PRIMITIVES[spec.func])
        need_ts = bool({"first", "last"} & ops)

        self.last_path = "stream"
        acc = self._fold_stream(stream, table, bound_where, tuple(keys),
                                tuple(arg_exprs), tuple(sorted(ops)),
                                num_groups, ts_name, ctx, need_ts,
                                len(arg_exprs))
        return self._agg_tail(acc, None, agg, keys, decoders, spec_slot,
                              None, having, project, sort, limit, offset,
                              table)

    def _fold_stream(self, stream, table, bound_where, keys, arg_exprs, ops,
                     num_groups, ts_name, ctx, need_ts, nf):
        from greptimedb_tpu import config

        schema = table.schema
        acc_dtype = jnp.dtype(config.compute_dtype())
        tag_names = frozenset(ctx.tag_names)
        float_fields = {c.name for c in schema.field_columns if c.dtype.is_float}
        from greptimedb_tpu.query.expr import collect_columns

        needed: set[str] = set()
        collect_columns(bound_where, needed)
        for a in arg_exprs:
            collect_columns(a, needed)
        for k in keys:
            needed.add(k.column)
        needed.add(ts_name)
        names = sorted(needed)

        block = config.stream_block_rows()
        if not need_ts and self._prepared_ok(arg_exprs, ops, (), schema, {}):
            # streaming twin of the prepared dense path: the chunk's
            # value/validity plane is built once host-side and folded with
            # ONE dead-segment segment-sum — no per-query [N, F] masking
            self.last_path = "stream_prepared"
            return self._fold_stream_prepared(
                stream, bound_where, keys, arg_exprs, ops, num_groups,
                tag_names, float_fields, schema, block, acc_dtype,
                max(nf, 1))
        kw = dict(where=bound_where, keys=keys, agg_args=arg_exprs, ops=ops,
                  num_segments=num_groups, ts_name=ts_name,
                  tag_names=tag_names, schema=schema, need_ts=need_ts,
                  acc_dtype=acc_dtype)
        def build_blocks():
            for cols_np, nrows in stream.chunks():
                for start in range(0, nrows, block):
                    end = min(start + block, nrows)
                    dev = {}
                    for name in names:
                        arr = pad_rows(np.asarray(cols_np[name][start:end]),
                                       block)
                        if name in float_fields and arr.dtype != acc_dtype:
                            arr = arr.astype(acc_dtype)
                        dev[name] = jnp.asarray(arr)
                    yield dev, jnp.asarray(end - start)

        acc_dev = None
        step = _agg_step_donated if _donate_stream_buffers() else _agg_step
        gen = _prefetch(build_blocks())
        try:
            for dev, n_valid in gen:
                device_telemetry.count_h2d(
                    sum(a.nbytes for a in dev.values()))
                if acc_dev is None:
                    acc_dev = _agg_block_jit(dev, n_valid, None, **kw)
                else:
                    acc_dev = step(acc_dev, dev, n_valid, **kw)
        finally:
            # stop the producer BEFORE the caller's stream.close() drops
            # SST pins: a generator left suspended would only clean up at
            # GC, racing the producer's reads against file purge
            gen.close()
        nf = max(nf, 1)
        if acc_dev is None:
            # pruned-empty stream: identity planes
            acc = {}
            for op in ops:
                if op == "rows":
                    acc[op] = np.zeros((num_groups, 1), dtype=np.int64)
                elif op == "count":
                    acc[op] = np.zeros((num_groups, nf), dtype=np.int64)
                elif op in ("sum", "sumsq"):
                    acc[op] = np.zeros((num_groups, nf))
                elif op in ("min", "max", "first", "last"):
                    acc[op] = np.full((num_groups, nf), np.nan)
                    if op in ("first", "last"):
                        acc[op + "_ts"] = np.zeros(num_groups, dtype=np.int64)
            return acc
        acc = {k: _readback(v) for k, v in acc_dev.items()}
        for k in ("count", "rows"):
            if k in acc:
                acc[k] = acc[k].astype(np.int64)
        return acc

    def _fold_stream_prepared(self, stream, bound_where, keys, arg_exprs,
                              ops, num_groups, tag_names, float_fields,
                              schema, block, acc_dtype, nf):
        """Prepared-plane streaming fold (see _prep_stream_step). Plane
        NaN-handling is conservative (`has_nan=True`): a stream can't
        pre-scan its chunks for NULLs the way the materialized path can."""
        from types import SimpleNamespace

        from greptimedb_tpu.query.expr import collect_columns

        arg_names = tuple(a.name for a in arg_exprs)
        aux: set[str] = set()
        collect_columns(bound_where, aux)
        for k in keys:
            aux.add(k.column)
        aux_names = sorted(aux)
        prep_dtype = jnp.dtype(jnp.float64) if "sumsq" in ops else acc_dtype
        kw = dict(where=bound_where, keys=keys, num_segments=num_groups,
                  tag_names=tag_names, schema=schema)
        def build_blocks():
            for cols_np, nrows in stream.chunks():
                shim = SimpleNamespace(columns=cols_np)
                for start in range(0, nrows, block):
                    end = min(start + block, nrows)
                    dev = {}
                    for name in aux_names:
                        arr = pad_rows(np.asarray(cols_np[name][start:end]),
                                       block)
                        if name in float_fields and arr.dtype != acc_dtype:
                            arr = arr.astype(acc_dtype)
                        dev[name] = jnp.asarray(arr)
                    dev["__prep__"] = jnp.asarray(_build_prep(
                        shim, arg_names, start, end, block, prep_dtype,
                        True, None))
                    if "min" in ops:
                        dev["__prep_min__"] = jnp.asarray(_build_prep(
                            shim, arg_names, start, end, block, acc_dtype,
                            False, "min"))
                    if "max" in ops:
                        dev["__prep_max__"] = jnp.asarray(_build_prep(
                            shim, arg_names, start, end, block, acc_dtype,
                            False, "max"))
                    if "sumsq" in ops:
                        dev["__prep_sq__"] = jnp.asarray(_build_prep(
                            shim, arg_names, start, end, block, prep_dtype,
                            False, "sq"))
                    yield dev, jnp.asarray(end - start)

        acc_dev = None
        step = _prep_stream_step_donated if _donate_stream_buffers() \
            else _prep_stream_step
        # double-buffered: the next chunk's SST read + plane build + H2D
        # copy overlap the device fold of the current one
        gen = _prefetch(build_blocks())
        try:
            for dev, n_valid in gen:
                device_telemetry.count_h2d(
                    sum(a.nbytes for a in dev.values()))
                acc_dev = step(acc_dev, dev, n_valid, **kw)
        finally:
            gen.close()  # see _fold_stream: producer must die before unpin
        G = num_groups
        acc: dict[str, np.ndarray] = {}
        if acc_dev is None:
            # pruned-empty stream: identity planes
            for op in ops:
                if op == "rows":
                    acc[op] = np.zeros((G, 1), dtype=np.int64)
                elif op == "count":
                    acc[op] = np.zeros((G, nf), dtype=np.int64)
                elif op in ("sum", "sumsq"):
                    acc[op] = np.zeros((G, nf))
                else:
                    acc[op] = np.full((G, nf), np.nan)
            return acc
        total = _readback(acc_dev["total"])
        sums = total[:, :nf]
        cnts = total[:, nf:2 * nf]
        rows = total[:, 2 * nf:2 * nf + 1]
        for op in ops:
            if op == "sum":
                acc[op] = sums
            elif op == "count":
                acc[op] = cnts.astype(np.int64)
            elif op == "rows":
                acc[op] = rows.astype(np.int64)
            elif op == "min":
                tmin = np.asarray(acc_dev["min"])
                acc[op] = np.where(np.isposinf(tmin), np.nan, tmin)
            elif op == "max":
                tmax = np.asarray(acc_dev["max"])
                acc[op] = np.where(np.isneginf(tmax), np.nan, tmax)
            elif op == "sumsq":
                acc[op] = np.asarray(acc_dev["sq"])
        return acc

    def _plan_key_stream(self, i, kexpr, ctx, stream, scan_node):
        """Key planning against stream metadata only (no data columns):
        tag keys decode from the registry dictionaries; time buckets get
        their extent from pruned-file stats. Anything needing the actual
        rows (generic expressions) is not streamable."""
        schema = ctx.schema
        ts_col = schema.time_index
        if isinstance(kexpr, ast.Column) and kexpr.name in ctx.tag_names:
            name = kexpr.name
            values = stream.tag_dicts[name]

            def decode_tag(idx, values=values):
                out = np.empty(len(idx), dtype=object)
                codes = idx - 1
                valid = codes >= 0
                out[valid] = values[codes[valid]]
                out[~valid] = None
                return out, DataType.STRING

            return DeviceKey("tag", name, len(values) + 1), decode_tag
        if (isinstance(kexpr, ast.FuncCall) and kexpr.name in ("date_bin", "time_bucket")
                and isinstance(kexpr.args[0], ast.Interval)
                and isinstance(kexpr.args[1], ast.Column)
                and kexpr.args[1].name == ts_col.name):
            unit = ts_col.dtype.time_unit.nanos_per_unit
            step = max(kexpr.args[0].nanos // unit, 1)
            lo, hi = self._ts_bounds(scan_node, None,
                                     fallback=(stream.ts_min, stream.ts_max))
            base = int(np.floor_divide(lo, step))
            size = int(np.floor_divide(hi, step)) - base + 1

            def decode_bucket(idx, step=step, base=base, dtype=ts_col.dtype):
                return (idx.astype(np.int64) + base) * step, dtype

            return DeviceKey("bucket", ts_col.name, size, step=step,
                             base=base), decode_bucket
        raise _NotStreamable(f"group key {kexpr!r} needs materialized scan")

    def _host_aggs(self, host_specs, keys, scan, extra_cols, bound_where,
                   table, ctx, num_groups, present, env, sparse_gids=None):
        """Order-statistic aggregates (argmax/percentile/…) over host
        columns — see host_agg.py for the sort-based group pass. Uses the
        BOUND where/arg exprs (tag literals → codes, ts literals coerced),
        so host evaluation over the raw scan columns matches the device
        semantics exactly."""
        from greptimedb_tpu.query import host_agg as ha
        from greptimedb_tpu.query.expr import bind_expr, eval_host

        strides = _strides([k.size for k in keys])
        gid = ha.row_group_ids(keys, strides, scan, extra_cols)
        if sparse_gids is not None:
            # map global ids onto the compact [0, U) slots the device
            # kernel assigned (ascending global-id order); rows whose
            # group isn't observed are already masked out below
            num_groups = len(sparse_gids)
            gid = np.clip(np.searchsorted(sparse_gids, gid), 0,
                          max(num_groups - 1, 0))
        n = scan.num_rows
        dmask = self._maybe_dedup(scan, table, ctx)
        mask = ha.host_row_mask(
            scan, bound_where, table.schema, n,
            np.asarray(dmask)[:n] if dmask is not None else None)
        ts_name = table.schema.time_index.name
        for spec in host_specs:
            if spec.func not in ha.HOST_AGGS:
                # string-typed first/last/min/max: decode the argument to
                # real values and pick per group on host
                from greptimedb_tpu.datatypes.vector import DictVector

                if isinstance(spec.arg, ast.Column) and \
                        spec.arg.name in scan.tag_dicts:
                    vals = DictVector(
                        scan.columns[spec.arg.name],
                        scan.tag_dicts[spec.arg.name]).decode()
                else:
                    vals = np.asarray(eval_host(
                        spec.arg, scan.columns, table.schema, None, n),
                        dtype=object)
                vals = np.broadcast_to(vals, (n,))
                per_group = ha.compute_host_agg_str(
                    spec.func, gid, vals,
                    scan.columns[ts_name], mask, num_groups)
                env[spec.call] = per_group[present]
                continue
            bound_arg = bind_expr(spec.arg, ctx)
            vals = eval_host(bound_arg, scan.columns, table.schema, None, n)
            vals = np.broadcast_to(
                np.asarray(vals, dtype=np.float64), (n,))
            per_group = ha.compute_host_agg(
                spec.func, gid, vals, mask, num_groups, spec.extra_args)
            env[spec.call] = per_group[present]

    def _plan_key(self, i, kexpr, ctx, scan: ScanData, scan_node, extra_cols):
        schema = ctx.schema
        ts_col = schema.time_index
        if isinstance(kexpr, ast.Column) and kexpr.name in ctx.tag_names:
            name = kexpr.name
            card = len(scan.tag_dicts[name])
            values = scan.tag_dicts[name]

            def decode_tag(idx, values=values):
                out = np.empty(len(idx), dtype=object)
                codes = idx - 1
                valid = codes >= 0
                out[valid] = values[codes[valid]]
                out[~valid] = None
                return out, DataType.STRING

            return DeviceKey("tag", name, card + 1), decode_tag
        if (isinstance(kexpr, ast.FuncCall) and kexpr.name in ("date_bin", "time_bucket")
                and isinstance(kexpr.args[0], ast.Interval)
                and isinstance(kexpr.args[1], ast.Column)
                and kexpr.args[1].name == ts_col.name):
            unit = ts_col.dtype.time_unit.nanos_per_unit
            step = max(kexpr.args[0].nanos // unit, 1)
            ts_arr = scan.columns[ts_col.name]
            lo, hi = self._ts_bounds(scan_node, ts_arr)
            base = lo // step - (1 if lo % step and lo < 0 else 0)
            base = int(np.floor_divide(lo, step))
            size = int(np.floor_divide(hi, step)) - base + 1

            def decode_bucket(idx, step=step, base=base, dtype=ts_col.dtype):
                return (idx.astype(np.int64) + base) * step, dtype

            return DeviceKey("bucket", ts_col.name, size, step=step, base=base), decode_bucket
        # generic expression: factorize on host
        host_cols = dict(scan.columns)
        for c in schema.tag_columns:
            if c.name in host_cols:
                from greptimedb_tpu.datatypes.vector import DictVector
                host_cols[c.name] = DictVector(
                    scan.columns[c.name], scan.tag_dicts[c.name]
                ).decode()
        vals = np.asarray(eval_host(kexpr, host_cols, schema))
        if np.ndim(vals) == 0:
            vals = np.broadcast_to(vals, (scan.num_rows,))
        uniq, inverse = np.unique(vals, return_inverse=True)
        colname = f"__key_{i}"
        extra_cols[colname] = inverse.astype(np.int32)
        out_dtype = None
        if isinstance(kexpr, ast.Column) and kexpr.name in schema.names:
            out_dtype = schema.column(kexpr.name).dtype

        def decode_pre(idx, uniq=uniq, out_dtype=out_dtype):
            return uniq[idx], out_dtype

        return DeviceKey("pre", colname, max(len(uniq), 1)), decode_pre

    def _ts_bounds(self, scan_node, ts_arr, fallback=None) -> tuple[int, int]:
        lo = hi = None
        if scan_node.ts_range is not None:
            lo, hi0 = scan_node.ts_range
            hi = None if hi0 is None else hi0 - 1
        if lo is None:
            lo = int(ts_arr.min()) if ts_arr is not None else fallback[0]
        if hi is None:
            hi = int(ts_arr.max()) if ts_arr is not None else fallback[1]
        return lo, hi

    def _stream_agg(self, scan: ScanData, table, bound_where, keys, arg_exprs,
                    ops, num_groups, ts_name, ctx, extra_cols, sparse=False):
        """Run the device aggregation; returns (acc planes, sparse group
        ids or None). Dense: planes indexed by global group id. Sparse:
        planes indexed by compact slot, plus the observed global ids."""
        from greptimedb_tpu.utils import tracing

        with tracing.span("device_agg", rows=scan.num_rows,
                          groups=num_groups):
            return self._stream_agg_inner(
                scan, table, bound_where, keys, arg_exprs, ops, num_groups,
                ts_name, ctx, extra_cols, sparse)

    def _stream_agg_inner(self, scan, table, bound_where, keys, arg_exprs,
                          ops, num_groups, ts_name, ctx, extra_cols,
                          sparse=False):
        from greptimedb_tpu import config

        schema = table.schema
        acc_dtype = jnp.dtype(config.compute_dtype())
        device_col_names = self._device_columns(
            scan, bound_where, keys, arg_exprs, ts_name, extra_cols
        )
        n = scan.num_rows
        dedup_mask = self._maybe_dedup(scan, table, ctx)
        tag_names = frozenset(ctx.tag_names)
        float_fields = {
            c.name for c in schema.field_columns if c.dtype.is_float
        }

        # output layout (static): which float/int planes the kernel packs
        nf = max(len(arg_exprs), 1)
        produced_f, produced_i = [], []
        widths = {}
        for op in ops:
            if op in ("first", "last"):
                produced_f.append(op)
                widths[op] = nf
                produced_i.append(op + "_ts")
            elif op == "rows":
                produced_f.append(op)
                widths[op] = 1
            else:
                produced_f.append(op)
                widths[op] = nf
        float_ops = tuple(sorted(produced_f))
        int_ops = tuple(sorted(produced_i))
        pack_dtype = jnp.dtype(jnp.float64) if num_groups <= 4096 else acc_dtype
        if not jnp.issubdtype(pack_dtype, jnp.floating):
            pack_dtype = jnp.dtype(jnp.float64)
        if "sumsq" in float_ops:
            # f32 packing would destroy the precision the f64 moment
            # accumulation just preserved (see segment_agg)
            pack_dtype = jnp.dtype(jnp.float64)

        from greptimedb_tpu.parallel.mesh import COLLECTIVE_OPS

        if sparse:
            self.last_path = "sparse"
            if self.last_tier == "mesh":
                from greptimedb_tpu.parallel.sharded_dispatch import (
                    MeshIneligible,
                )

                try:
                    # per-shard sort-compact + gid-space combine: the
                    # compact slots differ per shard but the global ids
                    # they decode to don't, so the host merge is exact
                    return self._sparse_sharded_scan(
                        scan, self.mesh, device_col_names, extra_cols,
                        float_fields, acc_dtype, dedup_mask, bound_where,
                        keys, arg_exprs, ops, ts_name, tag_names, schema,
                        float_ops, int_ops, widths, pack_dtype)
                except MeshIneligible:
                    self.last_tier = "device"
            return self._sparse_scan(
                scan, device_col_names, extra_cols, float_fields, acc_dtype,
                dedup_mask, bound_where, keys, arg_exprs, ops, ts_name,
                tag_names, schema, float_ops, int_ops, widths, pack_dtype)

        mesh = self.mesh
        # first/last produce int *_ts planes, but those are consumed
        # INSIDE the collective combine — only value planes leave the mesh
        ts_only_ints = bool(int_ops) and all(k.endswith("_ts")
                                             for k in int_ops)
        mesh_shape_ok = (mesh is not None and (not int_ops or ts_only_ints)
                         and set(ops) <= set(COLLECTIVE_OPS))
        if mesh_shape_ok and self.last_tier == "mesh":
            from greptimedb_tpu.parallel.sharded_dispatch import (
                MeshIneligible,
            )

            try:
                self.last_path = "sharded"
                packed_f = self._sharded_scan(
                    scan, mesh, device_col_names, extra_cols, float_fields,
                    acc_dtype, dedup_mask, bound_where, keys, arg_exprs,
                    ops, num_groups, ts_name, tag_names, schema, float_ops,
                    pack_dtype)
                return (_unpack_acc(packed_f, None, float_ops, (),
                                    widths), None)
            except MeshIneligible:
                # typed degradation: a plan/shape the shard dispatch
                # cannot serve falls back to the single-device paths
                self.last_tier = "device"
        elif self.last_tier == "mesh":
            # the router picked the mesh before seeing the op set; a
            # non-collective shape runs single-device and must report so
            self.last_tier = "device"
        prepared = self._prepared_ok(arg_exprs, ops, int_ops, schema,
                                     extra_cols)
        # first/last can't ride the PREPARED planes (no ts pairing) but
        # CAN ride the fused kernel: the kernel covers the other ops and
        # a per-block segment_agg folds the (value, ts) pairs alongside
        fused_extra = (not prepared and bool(int_ops)
                       and all(k.endswith("_ts") for k in int_ops)
                       and self._prepared_ok(
                           arg_exprs, set(ops) - {"first", "last"}, (),
                           schema, extra_cols))
        if prepared or fused_extra:
            arg_names = tuple(a.name for a in arg_exprs)
            aux_names = self._device_columns(
                scan, bound_where, keys, (), ts_name, extra_cols)
            plan = _block_plan(scan)
            if self._fused_ok(ops, arg_names, num_groups, scan):
                # fused Pallas path: ONE kernel per block over the RAW
                # hot-set columns — mask/validity/plane assembly never
                # touch HBM (ops/pallas_segment.py); degrades to the
                # prepared scatter path below on any kernel failure
                res = self._dense_fused_scan(
                    scan, plan, aux_names, arg_names, extra_cols,
                    float_fields, acc_dtype, dedup_mask, bound_where,
                    keys, ops, num_groups, ts_name, tag_names, schema,
                    float_ops, int_ops, pack_dtype)
                if res is not None:
                    packed_f, packed_i = res
                    self.last_path = "dense_fused"
                    return (_unpack_acc(packed_f, packed_i, float_ops,
                                        int_ops, widths), None)
        if prepared:
            # fast dense path: query-invariant [N, 2F+1] value/validity
            # planes are HBM-cached; per query only [N] masks/keys run
            self.last_path = "dense_prepared"
            has_nan = self._scan_has_nan(scan, arg_names)
            # variance/stddev difference two moments: BOTH must carry f64
            # even on the f32 fast path (see segment_agg) — the sum plane
            # included, or the cancellation eats the f64 sq plane's work
            prep_dtype = jnp.dtype(jnp.float64) if "sumsq" in ops \
                else acc_dtype

            def fetch_block(entry, prefetch_only=False):
                cols = {}
                for name in aux_names:
                    cols[name] = self._device_block(
                        scan, name, entry, extra_cols,
                        acc_dtype if name in float_fields else None,
                        prefetch_only=prefetch_only,
                    )
                cols["__prep__"] = self._prep_plane(
                    scan, arg_names, entry, prep_dtype,
                    has_nan, prefetch_only=prefetch_only)
                if "min" in ops:
                    cols["__prep_min__"] = self._prep_extreme_plane(
                        scan, arg_names, entry, acc_dtype,
                        "min", prefetch_only=prefetch_only)
                if "max" in ops:
                    cols["__prep_max__"] = self._prep_extreme_plane(
                        scan, arg_names, entry, acc_dtype,
                        "max", prefetch_only=prefetch_only)
                if "sumsq" in ops:
                    cols["__prep_sq__"] = self._prep_extreme_plane(
                        scan, arg_names, entry, prep_dtype,
                        "sq", prefetch_only=prefetch_only)
                return cols

            blocks, n_valids, dmasks = self._gather_blocks(
                scan, plan, fetch_block, dedup_mask)
            packed_f, packed_i = _agg_scan_prepared(
                tuple(blocks), jnp.asarray(np.asarray(n_valids)),
                tuple(dmasks) if dmasks is not None else None,
                where=bound_where, keys=keys, nf=nf, has_nan=has_nan,
                finite=not self._scan_has_inf(scan, arg_names,
                                              dtype=prep_dtype),
                num_segments=num_groups,
                tag_names=tag_names, schema=schema, float_ops=float_ops,
                pack_dtype=pack_dtype,
            )
            return (_unpack_acc(packed_f, packed_i, float_ops, int_ops,
                                widths), None)
        else:
            self.last_path = "dense"
            plan = _block_plan(scan)

            def fetch_block(entry, prefetch_only=False):
                cols = {}
                for name in device_col_names:
                    cols[name] = self._device_block(
                        scan, name, entry, extra_cols,
                        acc_dtype if name in float_fields else None,
                        prefetch_only=prefetch_only,
                    )
                return cols

            blocks, n_valids, dmasks = self._gather_blocks(
                scan, plan, fetch_block, dedup_mask)
            packed_f, packed_i = _agg_scan(
                tuple(blocks), jnp.asarray(np.asarray(n_valids)),
                tuple(dmasks) if dmasks is not None else None,
                where=bound_where, keys=keys, agg_args=arg_exprs, ops=ops,
                num_segments=num_groups, ts_name=ts_name, tag_names=tag_names,
                schema=schema, need_ts=bool({"first", "last"} & set(ops)),
                acc_dtype=acc_dtype, float_ops=float_ops, int_ops=int_ops,
                pack_dtype=pack_dtype,
            )
        return _unpack_acc(packed_f, packed_i, float_ops, int_ops, widths), None

    def _sparse_scan(self, scan, device_col_names, extra_cols, float_fields,
                     acc_dtype, dedup_mask, bound_where, keys, arg_exprs,
                     ops, ts_name, tag_names, schema, float_ops, int_ops,
                     widths, pack_dtype):
        """High-cardinality aggregation over the whole scan as one padded
        device program (sort-compact; see _agg_scan_sparse). Routes the
        reductions through the tiled fused kernel when eligible
        (_sparse_fused_ok), degrading to the XLA scatter chain on any
        kernel failure — same latch as the dense fused path."""
        from greptimedb_tpu import config
        from greptimedb_tpu.utils.metrics import (
            SPARSE_COMPACTION_RATIO,
            SPARSE_DISPATCHES,
        )

        n = scan.num_rows
        n_pad = block_size_for(n)
        cap = min(n_pad, config.sparse_groups_max())
        cols = {}
        for name in device_col_names:
            cast = acc_dtype if name in float_fields else None

            def build(name=name, cast=cast):
                src = extra_cols[name] if name in extra_cols \
                    else scan.columns[name]
                arr = pad_rows(src, n_pad)
                if cast is not None and arr.dtype != cast:
                    arr = arr.astype(cast)
                return jnp.asarray(arr)

            if scan.region_id < 0 or name in extra_cols:
                cols[name] = build()
            else:
                # whole-scan arrays cannot be file-anchored: snapshot key
                key = ("snap", scan.region_id, _snap_version(scan),
                       _ACTIVE_TIER_VAR.get(), scan.scan_fingerprint,
                       name, "whole", n_pad, str(cast))
                cols[name] = self.cache.get(key, build)
        base = np.arange(n_pad) < n
        if dedup_mask is not None:
            base[:n] &= np.asarray(dedup_mask)[:n]
        packed = None
        if self._sparse_fused_ok(ops, arg_exprs, scan, schema, extra_cols,
                                 acc_dtype):
            from greptimedb_tpu.utils.metrics import PALLAS_DISPATCHES

            try:
                packed_f, packed_i, uniq, n_groups = _agg_scan_sparse_fused(
                    cols, jnp.asarray(base), where=bound_where, keys=keys,
                    arg_names=tuple(a.name for a in arg_exprs), ops=ops,
                    cap=cap, tag_names=tag_names, schema=schema,
                    acc_dtype=acc_dtype, float_ops=float_ops,
                    pack_dtype=pack_dtype,
                    interpret=jax.default_backend() != "tpu")
                packed_f.block_until_ready()
                packed = (packed_f, packed_i, uniq, n_groups)
                self.last_path = "sparse_fused"
                PALLAS_DISPATCHES.inc(kernel="sparse_fused_agg")
                SPARSE_DISPATCHES.inc(path="fused")
            except Exception:  # noqa: BLE001 — degrade, never fail the query
                import traceback

                traceback.print_exc()
                print("sparse fused pallas kernel failed; serving this and "
                      "later queries through the XLA scatter path",
                      flush=True)
                _FUSED_DISABLED["flag"] = True
                PALLAS_DISPATCHES.inc(kernel="fused_agg_failed")
        if packed is None:
            packed = _agg_scan_sparse(
                cols, jnp.asarray(base), where=bound_where, keys=keys,
                agg_args=arg_exprs, ops=ops, cap=cap, ts_name=ts_name,
                tag_names=tag_names, schema=schema,
                need_ts=bool({"first", "last"} & set(ops)),
                acc_dtype=acc_dtype, float_ops=float_ops, int_ops=int_ops,
                pack_dtype=pack_dtype)
            SPARSE_DISPATCHES.inc(path="classic")
        packed_f, packed_i, uniq, n_groups = packed
        u = int(n_groups)
        if u > cap:
            raise PlanError(
                f"query observed {u} distinct groups, exceeding the sparse "
                f"cap {cap}; raise GREPTIMEDB_TPU_SPARSE_GROUPS_MAX or add "
                "predicates")
        SPARSE_COMPACTION_RATIO.set(sparse_ops.compaction_ratio(u, n))
        acc = _unpack_acc(packed_f, packed_i, float_ops, int_ops, widths)
        acc = {k: v[:u] for k, v in acc.items()}
        return acc, np.asarray(uniq)[:u]

    def _sparse_fused_ok(self, ops, arg_exprs, scan, schema, extra_cols,
                         acc_dtype) -> bool:
        """Route the sparse scan through the tiled fused kernel? Mirrors
        _fused_ok (mode/backend gates, finite proof, failure latch) with
        the sparse twists: the segment count is a tile size so no group
        envelope applies, sumsq rides only when the accumulator already
        carries f64 (the tiled fold can't upcast moments the way
        segment_agg does), and first/last stay on the XLA path (the
        kernel has no ts pairing)."""
        from greptimedb_tpu.ops import pallas_segment as ps
        from greptimedb_tpu.ops.segment import _pallas_mode

        if _FUSED_DISABLED["flag"]:
            return False
        if not set(ops) <= {"sum", "count", "mean", "rows", "min", "max",
                            "sumsq"}:
            return False
        if "sumsq" in ops and acc_dtype != jnp.dtype(jnp.float64):
            return False
        if not self._prepared_ok(arg_exprs, ops, (), schema, extra_cols):
            return False  # plain field columns only (same as dense fused)
        if not ps.fused_eligible(len(arg_exprs), ps.MAX_SEGMENTS,
                                 want_sumsq="sumsq" in ops):
            return False
        if acc_dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
            return False
        arg_names = tuple(a.name for a in arg_exprs)
        if self._scan_has_inf(scan, arg_names, dtype=acc_dtype):
            return False
        mode = _pallas_mode()
        if mode == "on":
            return True
        return (mode == "auto" and jax.default_backend() == "tpu"
                and _ACTIVE_TIER_VAR.get() != "host"
                and ps.fused_tpu_compile_ok())

    def _sparse_sharded_scan(self, scan, mesh, device_col_names, extra_cols,
                             float_fields, acc_dtype, dedup_mask,
                             bound_where, keys, arg_exprs, ops, ts_name,
                             tag_names, schema, float_ops, int_ops, widths,
                             pack_dtype):
        """High-cardinality aggregation on the mesh: part-aligned column
        placement (same file-anchored per-shard uploads as the dense
        collective), per-shard sort-compact, host-side GID-space merge
        (_agg_scan_sharded_sparse has the why). Raises MeshIneligible for
        shapes the shard dispatch can't serve — caller falls back to the
        single-device sparse scan."""
        from greptimedb_tpu import config
        from greptimedb_tpu.parallel import sharded_dispatch as sd
        from greptimedb_tpu.utils.metrics import (
            SPARSE_COMPACTION_RATIO,
            SPARSE_DISPATCHES,
        )

        if not sd.eligible(mesh):
            raise sd.MeshIneligible("sparse path needs part-aligned dispatch")
        n_shard = mesh.shape["shard"]
        plan = sd.plan_shards(scan, n_shard)
        tier = _ACTIVE_TIER_VAR.get()
        snap_v = _snap_version(scan)
        cols = {}
        for name in device_col_names:
            cast = acc_dtype if name in float_fields else None

            def build_slice(start, end, out_rows, name=name, cast=cast):
                src = extra_cols[name] if name in extra_cols \
                    else scan.columns[name]
                arr = pad_rows(src[start:end], out_rows)
                if cast is not None and arr.dtype != cast:
                    arr = arr.astype(cast)
                return arr

            cols[name] = sd.sharded_column(
                None if name in extra_cols else self.cache,
                mesh, plan, scan, name, build_slice, tier=tier,
                snap_version=snap_v, extra=(str(cast),))
        base_s = sd.sharded_mask(mesh, plan, scan, dedup_mask,
                                 cache=self.cache, tier=tier,
                                 snap_version=snap_v)
        shard_rows = base_s.shape[0] // n_shard
        cap = min(shard_rows, config.sparse_groups_max())
        sd.note_dispatch("sharded_sparse", plan)
        packed_f, packed_i, uniqs, ns = _agg_scan_sharded_sparse(
            cols, base_s, mesh=mesh, where=bound_where, keys=keys,
            agg_args=arg_exprs, ops=ops, cap=cap, ts_name=ts_name,
            tag_names=tag_names, schema=schema,
            need_ts=bool({"first", "last"} & set(ops)),
            acc_dtype=acc_dtype, float_ops=float_ops, int_ops=int_ops,
            pack_dtype=pack_dtype)
        host_un = np.asarray(uniqs)
        host_ns = np.asarray(ns)
        parts = []
        for s in range(n_shard):
            u_s = int(host_ns[s])
            if u_s > cap:
                raise PlanError(
                    f"shard {s} observed {u_s} distinct groups, exceeding "
                    f"the sparse cap {cap}; raise "
                    "GREPTIMEDB_TPU_SPARSE_GROUPS_MAX or add predicates")
            pf_s = packed_f[s * cap:(s + 1) * cap]
            pi_s = packed_i[s * cap:(s + 1) * cap] if int_ops else None
            acc_s = _unpack_acc(pf_s, pi_s, float_ops, int_ops, widths)
            parts.append({
                "gids": host_un[s * cap:s * cap + u_s],
                "planes": {op: v[:u_s] for op, v in acc_s.items()},
            })
        gids, planes = sparse_ops.combine_sparse_gid_partials(parts)
        total = len(gids)
        self.last_path = "sparse_sharded"
        SPARSE_DISPATCHES.inc(path="sharded")
        SPARSE_COMPACTION_RATIO.set(
            sparse_ops.compaction_ratio(total, scan.num_rows))
        if not total:
            # no shard observed a group: empty keyed result with the
            # same plane layout _unpack_acc would produce
            planes = {op: np.zeros((0, widths[op])) for op in float_ops}
            for op in int_ops:
                planes[op] = np.zeros((0,), np.int64)
        return planes, gids

    def _sharded_scan(self, scan, mesh, device_col_names, extra_cols,
                      float_fields, acc_dtype, dedup_mask, bound_where, keys,
                      arg_exprs, ops, num_groups, ts_name, tag_names, schema,
                      float_ops, pack_dtype):
        """Place the scan's columns across the mesh's "shard" axis and run
        the collective aggregation — the integrated multi-chip MergeScan.
        Part-aligned dispatch (parallel/sharded_dispatch.py) is the
        default: per-segment uploads are file-anchored on their owning
        shard, so a flush transfers only its new file. Meshes with a real
        field axis keep the legacy whole-scan device_put placement."""
        from greptimedb_tpu.parallel import sharded_dispatch as sd

        if sd.eligible(mesh):
            return self._sharded_scan_parts(
                scan, mesh, device_col_names, extra_cols, float_fields,
                acc_dtype, dedup_mask, bound_where, keys, arg_exprs, ops,
                num_groups, ts_name, tag_names, schema, float_ops,
                pack_dtype)
        return self._sharded_scan_even(
            scan, mesh, device_col_names, extra_cols, float_fields,
            acc_dtype, dedup_mask, bound_where, keys, arg_exprs, ops,
            num_groups, ts_name, tag_names, schema, float_ops, pack_dtype)

    def _sharded_scan_parts(self, scan, mesh, device_col_names, extra_cols,
                            float_fields, acc_dtype, dedup_mask, bound_where,
                            keys, arg_exprs, ops, num_groups, ts_name,
                            tag_names, schema, float_ops, pack_dtype):
        """Part-aligned mesh dispatch: the shard plan assigns immutable
        SST segments to shards (prefix-stable greedy balance), per-
        segment uploads land file-anchored on the owning shard's device,
        and the assembled per-shard buffers form the global array with
        zero cross-device traffic (sharded_dispatch module docstring)."""
        from greptimedb_tpu.parallel import sharded_dispatch as sd

        n_shard = mesh.shape["shard"]
        plan = sd.plan_shards(scan, n_shard)
        tier = _ACTIVE_TIER_VAR.get()
        snap_v = _snap_version(scan)
        cache = self.cache
        prepared = self._prepared_ok(arg_exprs, ops, (), schema, extra_cols)
        names = device_col_names
        if prepared:
            names = self._device_columns(scan, bound_where, keys, (),
                                         ts_name, extra_cols)
        cols = {}
        for name in names:
            cast = acc_dtype if name in float_fields else None

            def build_slice(start, end, out_rows, name=name, cast=cast):
                src = extra_cols[name] if name in extra_cols \
                    else scan.columns[name]
                arr = pad_rows(src[start:end], out_rows)
                if cast is not None and arr.dtype != cast:
                    arr = arr.astype(cast)
                return arr

            cols[name] = sd.sharded_column(
                # extra_cols hold query-specific factorized keys: their
                # content is not a pure function of the file — never
                # cache them under file/snapshot identity
                None if name in extra_cols else cache,
                mesh, plan, scan, name, build_slice, tier=tier,
                snap_version=snap_v, extra=(str(cast),))
        base_s = sd.sharded_mask(mesh, plan, scan, dedup_mask, cache=cache,
                                 tier=tier, snap_version=snap_v)
        if prepared:
            self.last_path = "sharded_prepared"
            arg_names = tuple(a.name for a in arg_exprs)
            has_nan = self._scan_has_nan(scan, arg_names)
            nf = len(arg_names)
            # sum + sq moments both need f64 for stddev/variance (see the
            # dense branch note)
            prep_dtype = jnp.dtype(jnp.float64) if "sumsq" in ops \
                else acc_dtype
            plane_kinds = [("__prep__", None, prep_dtype, 0.0)]
            if "min" in ops:
                plane_kinds.append(("__prep_min__", "min", acc_dtype,
                                    np.inf))
            if "max" in ops:
                plane_kinds.append(("__prep_max__", "max", acc_dtype,
                                    -np.inf))
            if "sumsq" in ops:
                plane_kinds.append(("__prep_sq__", "sq", prep_dtype, 0.0))
            for plane_name, kind, pdt, fill in plane_kinds:
                def build_plane_slice(start, end, out_rows, kind=kind,
                                      pdt=pdt):
                    return _build_prep(scan, arg_names, start, end,
                                       out_rows, pdt, has_nan, kind)

                cols[plane_name] = sd.sharded_column(
                    cache, mesh, plan, scan,
                    (plane_name,) + arg_names, build_plane_slice,
                    tier=tier, snap_version=snap_v,
                    extra=(str(pdt), has_nan), pad_fill=fill)
            sd.note_dispatch("sharded_prepared", plan)
            return _agg_scan_sharded_prepared(
                cols, base_s, mesh=mesh, where=bound_where, keys=keys,
                nf=nf, has_nan=has_nan, num_segments=num_groups,
                tag_names=tag_names, schema=schema, float_ops=float_ops,
                pack_dtype=pack_dtype)
        sd.note_dispatch("sharded", plan)
        return _agg_scan_sharded(
            cols, base_s, mesh=mesh, where=bound_where, keys=keys,
            agg_args=arg_exprs, ops=ops, num_segments=num_groups,
            ts_name=ts_name, tag_names=tag_names, schema=schema,
            acc_dtype=acc_dtype, float_ops=float_ops, pack_dtype=pack_dtype)

    def _sharded_scan_even(self, scan, mesh, device_col_names, extra_cols,
                           float_fields, acc_dtype, dedup_mask, bound_where,
                           keys, arg_exprs, ops, num_groups, ts_name,
                           tag_names, schema, float_ops, pack_dtype):
        """Legacy whole-scan placement (one device_put over the
        NamedSharding): kept for meshes with a real field axis, where the
        per-shard committed-buffer assembly would need replicated
        placement. Snapshot-anchored only — a flush re-uploads."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = scan.num_rows
        n_shard = mesh.shape["shard"]
        n_pad = ((n + n_shard - 1) // n_shard) * n_shard
        sharding = NamedSharding(mesh, P("shard"))
        prepared = self._prepared_ok(arg_exprs, ops, (), schema, extra_cols)
        names = device_col_names
        if prepared:
            names = self._device_columns(scan, bound_where, keys, (),
                                         ts_name, extra_cols)
        cols = {}
        for name in names:
            cast = acc_dtype if name in float_fields else None

            def build(name=name, cast=cast):
                src = extra_cols[name] if name in extra_cols \
                    else scan.columns[name]
                arr = pad_rows(src, n_pad)
                if cast is not None and arr.dtype != cast:
                    arr = arr.astype(cast)
                return jax.device_put(arr, sharding)

            if scan.region_id < 0 or name in extra_cols:
                cols[name] = build()
                device_telemetry.count_h2d(cols[name].nbytes)
            else:
                key = ("snap", scan.region_id, _snap_version(scan),
                       _ACTIVE_TIER_VAR.get(), scan.scan_fingerprint,
                       name, "sharded", n_pad, n_shard, str(cast))
                cols[name] = self.cache.get(key, build)
        base = np.arange(n_pad) < n
        if dedup_mask is not None:
            base[:n] &= np.asarray(dedup_mask)[:n]
        base_s = jax.device_put(base, sharding)
        if prepared:
            self.last_path = "sharded_prepared"
            arg_names = tuple(a.name for a in arg_exprs)
            has_nan = self._scan_has_nan(scan, arg_names)
            nf = len(arg_names)
            # sum + sq moments both need f64 for stddev/variance (see the
            # dense branch note)
            prep_dtype = jnp.dtype(jnp.float64) if "sumsq" in ops \
                else acc_dtype
            plane_kinds = [("__prep__", None, prep_dtype)]
            if "min" in ops:
                plane_kinds.append(("__prep_min__", "min", acc_dtype))
            if "max" in ops:
                plane_kinds.append(("__prep_max__", "max", acc_dtype))
            if "sumsq" in ops:
                plane_kinds.append(("__prep_sq__", "sq", prep_dtype))
            for plane_name, kind, pdt in plane_kinds:
                def build_plane(kind=kind, pdt=pdt):
                    whole = _build_prep(scan, arg_names, 0, n, n_pad,
                                        pdt, has_nan, kind)
                    return jax.device_put(whole, sharding)

                if scan.region_id < 0:
                    cols[plane_name] = build_plane()
                else:
                    key = ("snap", scan.region_id, _snap_version(scan),
                           _ACTIVE_TIER_VAR.get(), scan.scan_fingerprint,
                           plane_name, arg_names, "sharded", n_pad,
                           n_shard, str(pdt), has_nan)
                    cols[plane_name] = self.cache.get(key, build_plane)
            return _agg_scan_sharded_prepared(
                cols, base_s, mesh=mesh, where=bound_where, keys=keys,
                nf=nf, has_nan=has_nan, num_segments=num_groups,
                tag_names=tag_names, schema=schema, float_ops=float_ops,
                pack_dtype=pack_dtype)
        return _agg_scan_sharded(
            cols, base_s, mesh=mesh, where=bound_where, keys=keys,
            agg_args=arg_exprs, ops=ops, num_segments=num_groups,
            ts_name=ts_name, tag_names=tag_names, schema=schema,
            acc_dtype=acc_dtype, float_ops=float_ops, pack_dtype=pack_dtype)

    def _upload_prefetch_ok(self, scan) -> bool:
        """Whether the dense block loops should double-buffer uploads:
        the knob is on, the scan is cacheable (prefetch parks results in
        the HBM cache), and the host tier is not active — the tier's
        jax.default_device context is thread-scoped, so a background
        build would land on the wrong device."""
        from greptimedb_tpu.query.device_cache import upload_prefetch_enabled

        return (upload_prefetch_enabled() and scan.region_id >= 0
                and _ACTIVE_TIER_VAR.get() != "host")

    def _gather_blocks(self, scan, plan, fetch, dedup_mask):
        """Walk the block plan through `fetch`, double-buffering block
        i+1's host build + H2D behind block i's assembly (the upload
        prefetch worker). Returns (blocks, n_valids, dedup block masks)."""
        from greptimedb_tpu.utils import deadline as dl

        blocks, n_valids = [], []
        dmasks = [] if dedup_mask is not None else None
        do_prefetch = self._upload_prefetch_ok(scan)
        for i, entry in enumerate(plan):
            # host-level deadline checkpoint per device block: the
            # jitted kernels below can't be interrupted, but a streamed
            # scan crosses here once per block — an expired or killed
            # query stops dispatching instead of walking the whole plan
            dl.check("device dispatch")
            if do_prefetch and i + 1 < len(plan):
                # double buffering: the background worker builds and
                # uploads block i+1 while this thread assembles
                # block i (and the device chews on what's queued)
                fetch(plan[i + 1], prefetch_only=True)
            blocks.append(fetch(entry))
            n_valids.append(entry.end - entry.start)
            if dmasks is not None:
                dmasks.append(_pad_device_mask(dedup_mask, entry.start,
                                               entry.end, entry.block))
        return blocks, n_valids, dmasks

    def _fused_ok(self, ops, arg_names, num_groups, scan) -> bool:
        """Route to the fused Pallas kernel? Mode/backend gates mirror
        dense_segment_sum (on = force incl. interpret mode off-TPU, how
        the CPU differential tests drive it; auto = real TPU device
        tier only, behind the Mosaic canary), plus the kernel's own
        shape envelope, a finite-values proof (Inf would poison the
        0*x matmul), and the runtime-failure latch the chaos test
        trips."""
        from greptimedb_tpu import config
        from greptimedb_tpu.ops import pallas_segment as ps
        from greptimedb_tpu.ops.segment import _pallas_mode

        if _FUSED_DISABLED["flag"]:
            return False
        if not set(ops) <= {"sum", "count", "mean", "rows", "min", "max",
                            "sumsq", "first", "last"}:
            return False
        acc_dtype = jnp.dtype(config.compute_dtype())
        if "sumsq" in ops and acc_dtype != jnp.dtype(jnp.float64):
            # the kernel accumulates moments in the compute dtype; only
            # f64 carries the variance cancellation (see segment_agg)
            return False
        if not ps.fused_eligible(len(arg_names), num_groups + 1,
                                 want_sumsq="sumsq" in ops):
            return False
        if acc_dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
            return False
        if self._scan_has_inf(scan, arg_names, dtype=acc_dtype):
            return False
        mode = _pallas_mode()
        if mode == "on":
            return True
        backend = jax.default_backend()
        return (mode == "auto" and backend == "tpu"
                and _ACTIVE_TIER_VAR.get() != "host"
                and ps.fused_tpu_compile_ok())

    def _dense_fused_scan(self, scan, plan, aux_names, arg_names,
                          extra_cols, float_fields, acc_dtype, dedup_mask,
                          bound_where, keys, ops, num_groups, ts_name,
                          tag_names, schema, float_ops, int_ops,
                          pack_dtype):
        """Run the fused-kernel aggregation; returns (packed_f,
        packed_i), or None after latching the kernel off when anything
        in the fused program fails (trace, Mosaic compile, or execution)
        — the caller then serves the same query through the XLA scatter
        path, so a kernel regression degrades throughput, never
        availability."""
        from greptimedb_tpu.utils.metrics import PALLAS_DISPATCHES

        need_cols = sorted(set(aux_names) | set(arg_names)
                           | ({ts_name} if int_ops else set()))

        def fetch_block(entry, prefetch_only=False):
            cols = {}
            for name in need_cols:
                cols[name] = self._device_block(
                    scan, name, entry, extra_cols,
                    acc_dtype if name in float_fields else None,
                    prefetch_only=prefetch_only,
                )
            return cols

        blocks, n_valids, dmasks = self._gather_blocks(
            scan, plan, fetch_block, dedup_mask)
        try:
            packed_f, packed_i = _agg_scan_fused(
                tuple(blocks), jnp.asarray(np.asarray(n_valids)),
                tuple(dmasks) if dmasks is not None else None,
                where=bound_where, keys=keys, arg_names=arg_names,
                num_segments=num_groups, ts_name=ts_name,
                tag_names=tag_names, schema=schema, float_ops=float_ops,
                int_ops=int_ops, pack_dtype=pack_dtype,
                acc_dtype=acc_dtype, want_min="min" in ops,
                want_max="max" in ops, want_sumsq="sumsq" in ops,
                interpret=jax.default_backend() != "tpu")
            # surface async execution errors HERE, inside the latch —
            # the result is consumed immediately downstream anyway
            packed_f.block_until_ready()
        except Exception:  # noqa: BLE001 — any kernel failure must degrade
            import traceback

            traceback.print_exc()
            print("fused pallas kernel failed; serving this and later "
                  "queries through the XLA scatter path", flush=True)
            _FUSED_DISABLED["flag"] = True
            PALLAS_DISPATCHES.inc(kernel="fused_agg_failed")
            return None
        PALLAS_DISPATCHES.inc(float(len(blocks)), kernel="fused_agg")
        return packed_f, packed_i

    def _device_block(self, scan: ScanData, name, entry: _BlockEntry,
                      extra_cols, cast_dtype, prefetch_only=False):
        """Fetch one padded column block through the HBM hot set.
        Blocks of an immutable SST part are keyed by the FILE identity
        (entry.pkey) and survive flushes/data-version bumps; memtable
        and synthetic rows key by snapshot version. `prefetch_only`:
        schedule the build on the cache's background worker
        (upload/compute double buffering) and return None."""
        start, end, block = entry.start, entry.end, entry.block

        def build():
            src = extra_cols[name] if name in extra_cols else scan.columns[name]
            arr = pad_rows(src[start:end], block)
            if cast_dtype is not None and arr.dtype != cast_dtype:
                arr = arr.astype(cast_dtype)
            return jnp.asarray(arr)

        if scan.region_id < 0 or name in extra_cols:
            if prefetch_only:
                return None  # uncacheable: nowhere to park the result
            out = build()
            # uncached upload (the cache counts its own miss-builds)
            device_telemetry.count_h2d(out.nbytes)
            return out
        key = self._hot_key(scan, entry, name, str(cast_dtype))
        if prefetch_only:
            self.cache.prefetch(key, build)
            return None
        return self.cache.get(key, build)

    def _hot_key(self, scan, entry: _BlockEntry, name, extra) -> tuple:
        """Hot-set key for one block. File-anchored blocks carry the
        (file_id, ts_range, pred_key) part identity + the block offset
        INSIDE the part, so a dashboard's steady-state uploads are
        invalidated by file death (compaction/expiry/DROP), not by every
        memtable write; everything else is snapshot-anchored and retires
        with its data version."""
        tier = _ACTIVE_TIER_VAR.get()
        if entry.pkey is not None:
            fid, ts_r, pred_key = entry.pkey
            return ("file", scan.region_id, fid, tier, ts_r, pred_key,
                    name, entry.start - entry.part_start, entry.block,
                    extra)
        return ("snap", scan.region_id, _snap_version(scan), tier,
                scan.scan_fingerprint, name, entry.start, entry.block,
                extra)

    def _prepared_ok(self, arg_exprs, ops, int_ops, schema,
                     extra_cols) -> bool:
        """Eligibility for the prepared dense path: plain float/int FIELD
        columns aggregated with sum/count/mean/rows/min/max/sumsq
        (min/max ride identity-filled planes, sumsq a squared-values
        plane; first/last still need the ts pairing the planes can't
        encode)."""
        if int_ops or not arg_exprs:
            return False
        if not set(ops) <= {"mean", "sum", "count", "rows", "min", "max",
                            "sumsq"}:
            return False
        field_names = {c.name for c in schema.field_columns}
        return all(
            isinstance(a, ast.Column) and a.name in field_names
            and a.name not in extra_cols
            for a in arg_exprs
        )

    def _scan_has_nan(self, scan, arg_names: tuple) -> bool:
        """Whether any aggregated column holds NULLs — decides the
        prepared plane layout. Memoized on the ScanData snapshot (one
        pass at first query, free afterwards)."""
        flags = getattr(scan, "_nan_flags", None)
        if flags is None:
            flags = {}
            scan._nan_flags = flags
        out = False
        for name in arg_names:
            f = flags.get(name)
            if f is None:
                col = np.asarray(scan.columns[name])
                f = bool(np.isnan(col).any()) \
                    if col.dtype.kind == "f" else False
                flags[name] = f
            out = out or f
        return out

    def _scan_has_inf(self, scan, arg_names: tuple, dtype=None) -> bool:
        """Whether any aggregated column holds +/-Inf — the pallas
        one-hot matmul kernel would turn one Inf into NaN for every
        group (0*inf), so only provably finite planes may ride it.
        `dtype` is the dtype the kernel will actually compute in: a
        finite f64 value that overflows the f64->f32 cast reaches the
        matmul as Inf all the same, so the proof must run post-cast.
        Memoized on the ScanData snapshot like _scan_has_nan."""
        flags = getattr(scan, "_inf_flags", None)
        if flags is None:
            flags = {}
            scan._inf_flags = flags
        dt = np.dtype(dtype) if dtype is not None else None
        out = False
        for name in arg_names:
            key = (name, dt.str if dt is not None else None)
            f = flags.get(key)
            if f is None:
                col = np.asarray(scan.columns[name])
                if col.dtype.kind == "f":
                    if (dt is not None and dt.kind == "f"
                            and dt.itemsize < col.dtype.itemsize):
                        with np.errstate(over="ignore"):
                            col = col.astype(dt)
                    f = bool(np.isinf(col).any())
                else:
                    f = False
                flags[key] = f
            out = out or f
        return out

    def _prep_plane(self, scan, arg_names, entry: _BlockEntry, acc_dtype,
                    has_nan: bool, prefetch_only=False):
        """Query-invariant value plane for the prepared path, cached in
        HBM alongside the raw column blocks (layout: _build_prep)."""

        def build():
            return jnp.asarray(_build_prep(scan, arg_names, entry.start,
                                           entry.end, entry.block,
                                           acc_dtype, has_nan, None))

        if scan.region_id < 0:
            return None if prefetch_only else build()
        key = self._hot_key(scan, entry, ("__prep__",) + arg_names,
                            (str(acc_dtype), has_nan))
        if prefetch_only:
            self.cache.prefetch(key, build)
            return None
        return self.cache.get(key, build)

    def _prep_extreme_plane(self, scan, arg_names, entry: _BlockEntry,
                            acc_dtype, kind: str, prefetch_only=False):
        """min/max/sq companion plane: values with NaN (and padding)
        replaced by the reduction's identity (±inf for extremes, 0 for
        the squared-sum plane), so the dead-segment id trick is the only
        masking the query needs."""

        def build():
            return jnp.asarray(_build_prep(scan, arg_names, entry.start,
                                           entry.end, entry.block,
                                           acc_dtype, False, kind))

        if scan.region_id < 0:
            return None if prefetch_only else build()
        key = self._hot_key(scan, entry, (f"__prep_{kind}__",) + arg_names,
                            str(acc_dtype))
        if prefetch_only:
            self.cache.prefetch(key, build)
            return None
        return self.cache.get(key, build)

    def _device_columns(self, scan, bound_where, keys, arg_exprs, ts_name, extra_cols):
        from greptimedb_tpu.query.expr import collect_columns

        needed: set[str] = set()
        collect_columns(bound_where, needed)
        for a in arg_exprs:
            collect_columns(a, needed)
        for k in keys:
            needed.add(k.column)
        needed.add(ts_name)
        avail = set(scan.columns) | set(extra_cols)
        missing = needed - avail
        if missing:
            raise PlanError(f"columns missing from scan: {sorted(missing)}")
        return sorted(needed)

    def _maybe_dedup(self, scan: ScanData, table, ctx) -> Optional[jax.Array]:
        """Device-resident last-write-wins mask (stays on device; sliced
        per block without a host round-trip). Memoized per ScanData so a
        query mixing device and host aggregates computes it once."""
        if table.append_mode or not scan.needs_dedup:
            return None
        cached = getattr(scan, "_dedup_mask_cache", None)
        if cached is not None:
            return cached
        mask = self._compute_dedup(scan, table)
        scan._dedup_mask_cache = mask
        return mask

    def _compute_dedup(self, scan: ScanData, table) -> jax.Array:
        tag_names = [c.name for c in table.schema.tag_columns]
        if tag_names:
            sizes = [len(scan.tag_dicts[t]) + 1 for t in tag_names]
            sid = combine_group_ids(
                [jnp.asarray(scan.columns[t]) + 1 for t in tag_names],
                sizes, dtype=jnp.int64,
            )
        else:
            sid = jnp.zeros(scan.num_rows, dtype=jnp.int64)
        ts = jnp.asarray(scan.columns[table.schema.time_index.name])
        return _dedup_mask(sid, ts, jnp.asarray(scan.seq),
                           jnp.asarray(scan.op_type),
                           jnp.ones(scan.num_rows, dtype=bool))

    # ---- raw (non-aggregate) path ------------------------------------------

    def _filtered_row_indices(self, scan, table, ctx, bound_where,
                              where_unbound=None) -> np.ndarray:
        """Row indices surviving WHERE + LWW dedup, computed blockwise on
        device (shared by the raw scan and RANGE-select paths).

        String FIELD columns (non-tag, so not dict-coded) cannot become
        device blocks; they stay host-side. A WHERE referencing one flips
        the whole filter to host numpy evaluation — correct, just not
        device-accelerated (string fields are metadata-shaped, e.g. the
        OTLP trace table's span attributes)."""
        schema = table.schema
        dedup_mask = self._maybe_dedup(scan, table, ctx)
        n = scan.num_rows
        obj_cols = {name for name, arr in scan.columns.items()
                    if arr.dtype == object and name not in scan.tag_dicts}
        referenced: set = set()
        collect_columns(bound_where, referenced)
        if not referenced & obj_cols:
            try:
                return self._device_filtered_indices(
                    scan, schema, ctx, bound_where, dedup_mask, obj_cols, n)
            except PlanError:
                # a WHERE construct the device evaluator doesn't cover
                # (e.g. a plugin scalar function): host filter below
                pass
        return self._host_filtered_indices(
            scan, schema, bound_where, where_unbound, dedup_mask,
            referenced, n)

    def _device_filtered_indices(self, scan, schema, ctx, bound_where,
                                 dedup_mask, obj_cols, n) -> np.ndarray:
        tag_names = frozenset(ctx.tag_names)
        picked: list[np.ndarray] = []
        for entry in _block_plan(scan):
            start, end, block = entry.start, entry.end, entry.block
            cols = {
                name: self._device_block(scan, name, entry, {}, None)
                for name in scan.columns
                if name not in obj_cols
            }
            dmask = None
            if dedup_mask is not None:
                dmask = _pad_device_mask(dedup_mask, start, end, block)
            mask = _filter_block(cols, jnp.asarray(end - start), dmask,
                                 where=bound_where,
                                 tag_names=tag_names, schema=schema)
            picked.append(np.flatnonzero(np.asarray(mask)) + start)
        return np.concatenate(picked) if picked else np.empty(0, dtype=np.int64)

    def _host_filtered_indices(self, scan, schema, bound_where,
                               where_unbound, dedup_mask, referenced,
                               n) -> np.ndarray:
        """Numpy filter over host columns: tags referenced by the WHERE
        decode to strings (the bound expression's code rewriting doesn't
        apply here, but timestamp-literal coercion still must — see
        bind_host_expr)."""
        from greptimedb_tpu.datatypes.vector import DictVector
        from greptimedb_tpu.query.expr import bind_host_expr

        host_cols = {}
        for name, arr in scan.columns.items():
            if name in scan.tag_dicts:
                if name not in referenced:
                    continue  # decoding is O(n) python objects — skip
                host_cols[name] = DictVector(
                    arr, scan.tag_dicts[name]).decode()
            else:
                host_cols[name] = arr
        w = bind_host_expr(where_unbound, schema) \
            if where_unbound is not None else bound_where
        if w is None:
            m = np.ones(n, dtype=bool)
        else:
            m = np.asarray(eval_host(w, host_cols, schema))
            m = (m if m.dtype == bool else m != 0)
            m = np.broadcast_to(m, (n,)).copy()
        if dedup_mask is not None:
            m &= np.asarray(dedup_mask)[:n]
        return np.flatnonzero(m)

    def _execute_raw(self, scan, table, where, project, sort, limit, offset) -> QueryResult:
        schema = table.schema
        if scan is None:
            return _project_empty(project, schema)
        ctx = BindContext(schema, scan.tag_dicts)
        bound_where = bind_expr(where, ctx) if where is not None else None
        idx = self._filtered_row_indices(scan, table, ctx, bound_where,
                                         where_unbound=where)

        # gather + decode on host
        host_cols: dict[str, np.ndarray] = {}
        for name, arr in scan.columns.items():
            taken = arr[idx]
            if name in scan.tag_dicts:
                from greptimedb_tpu.datatypes.vector import DictVector
                taken = DictVector(taken, scan.tag_dicts[name]).decode()
            host_cols[name] = taken

        env: dict = {}
        return self._post_process(env, None, None, project, sort, limit, offset,
                                  table, len(idx), host_cols=host_cols)

    # ---- shared tail: project/having/sort/limit over host arrays -----------

    def _post_process(self, env, agg, having, project, sort, limit, offset,
                      table, nrows, host_cols=None) -> QueryResult:
        schema = table.schema
        host_cols = host_cols or {}

        if having is not None:
            m = np.asarray(eval_host(having.predicate, host_cols, schema, env))
            m = m if m.dtype == bool else m != 0
            m = np.broadcast_to(m, (nrows,))
            env = {k: v[m] if isinstance(v, np.ndarray) and v.ndim >= 1 and len(v) == nrows else v
                   for k, v in env.items()}
            host_cols = {k: v[m] for k, v in host_cols.items()}
            nrows = int(m.sum())

        out_cols: list[np.ndarray] = []
        out_names: list[str] = []
        out_dtypes: list[Optional[DataType]] = []
        for name, e in project.items:
            v = eval_host(e, host_cols, schema, env)
            arr = np.asarray(v)
            if arr.ndim == 0:
                arr = np.broadcast_to(arr, (nrows,)).copy()
            out_cols.append(arr)
            out_names.append(name)
            out_dtypes.append(_infer_dtype(e, schema))

        if sort is not None and nrows > 1:
            order = _host_sort_order(sort.keys, project, out_names, out_cols,
                                     host_cols, schema, env)
            out_cols = [c[order] for c in out_cols]
        if offset:
            out_cols = [c[offset:] for c in out_cols]
        if limit is not None:
            out_cols = [c[:limit] for c in out_cols]
        return QueryResult(out_names, out_dtypes, out_cols)

    def _empty_agg_result(self, table, agg, having, project, sort, limit, offset):
        # no data: global aggregates still yield one row
        env: dict = {}
        nrows = 0 if agg.keys else 1
        for name, kexpr in agg.keys:
            env[kexpr] = np.empty(0, dtype=object)
        for spec in agg.aggs:
            if spec.func in ("count", "rows"):
                env[spec.call] = np.zeros(nrows, dtype=np.int64)
            else:
                env[spec.call] = np.full(nrows, np.nan)
        return self._post_process(env, agg, having, project, sort, limit, offset,
                                  table, nrows)


# ---- helpers ---------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("start", "end", "block"))
def _pad_device_mask(mask: jax.Array, start: int, end: int, block: int) -> jax.Array:
    sl = jax.lax.dynamic_slice_in_dim(mask, start, end - start)
    return jnp.pad(sl, (0, block - (end - start)), constant_values=False)


def _unpack_acc(packed_f, packed_i, float_ops, int_ops, widths):
    """Split the kernel's packed output matrix back into per-op planes.
    This is the dense/prepared paths' D2H readback boundary."""
    host_f = _readback(packed_f)
    acc: dict[str, np.ndarray] = {}
    off = 0
    for k in float_ops:
        w = widths[k]
        sl = host_f[:, off:off + w]
        off += w
        if k in ("count", "rows"):
            sl = sl.astype(np.int64)
        acc[k] = sl
    if int_ops:
        host_i = _readback(packed_i)
        for j, k in enumerate(int_ops):
            acc[k] = host_i[:, j]
    return acc


def _closed_range(ts_range):
    if ts_range is None:
        return None
    lo, hi = ts_range
    return (lo if lo is not None else -(1 << 62), hi if hi is not None else (1 << 62))


def _strides(sizes: list[int]) -> list[int]:
    strides = [1] * len(sizes)
    for i in range(len(sizes) - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]
    return strides


def _finalize_agg(func: str, acc: dict, slot: Optional[int], present: np.ndarray):
    def get(op):
        v = acc[op]
        if v.ndim == 2:
            v = v[:, slot if slot is not None else 0]
        return v[present]

    if func == "rows":
        return get("rows").astype(np.int64)
    if func == "count":
        return get("count").astype(np.int64)
    if func == "sum":
        s, c = get("sum"), get("count")
        return np.where(c > 0, s, np.nan)
    if func == "avg":
        s, c = get("sum"), get("count")
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(c > 0, s / np.maximum(c, 1), np.nan)
    if func in ("min", "max", "first", "last"):
        return get(func)
    if func in ("stddev", "variance"):
        s, ss, c = get("sum"), get("sumsq"), get("count")
        with np.errstate(invalid="ignore", divide="ignore"):
            var = (ss - s * s / np.maximum(c, 1)) / np.maximum(c - 1, 1)
            var = np.where(c > 1, np.maximum(var, 0.0), np.nan)
        return np.sqrt(var) if func == "stddev" else var
    raise PlanError(f"unknown aggregate {func}")


def _infer_dtype(e: ast.Expr, schema) -> Optional[DataType]:
    if isinstance(e, ast.Column) and e.name in schema.names:
        return schema.column(e.name).dtype
    if isinstance(e, ast.FuncCall):
        if e.name in ("date_bin", "time_bucket", "date_trunc"):
            ts_arg = e.args[1] if len(e.args) > 1 else None
            if isinstance(ts_arg, ast.Column) and ts_arg.name in schema.names:
                return schema.column(ts_arg.name).dtype
        if e.name == "count":
            return DataType.INT64
        if e.name in ("min", "max", "first", "last", "first_value", "last_value"):
            arg = e.args[0] if e.args else None
            if isinstance(arg, ast.Column) and arg.name in schema.names:
                dt = schema.column(arg.name).dtype
                if dt.is_timestamp:
                    return dt
            return DataType.FLOAT64
        return DataType.FLOAT64
    if isinstance(e, ast.Literal):
        if isinstance(e.value, bool):
            return DataType.BOOL
        if isinstance(e.value, int):
            return DataType.INT64
        if isinstance(e.value, float):
            return DataType.FLOAT64
        if isinstance(e.value, str):
            return DataType.STRING
    return None


def _host_sort_order(keys, project, out_names, out_cols, host_cols, schema, env):
    sort_arrays = []
    nrows = len(out_cols[0]) if out_cols else 0
    by_name = dict(zip(out_names, out_cols))
    for k in reversed(keys):  # lexsort: primary key last
        if isinstance(k.expr, ast.Column) and k.expr.name in by_name:
            arr = by_name[k.expr.name]
        else:
            arr = np.asarray(eval_host(k.expr, host_cols, schema, env))
            if arr.ndim == 0:
                arr = np.broadcast_to(arr, (nrows,))
        arr = _sortable(arr, k.asc, k.nulls_first)
        sort_arrays.append(arr)
    return np.lexsort(sort_arrays)


def _sortable(arr: np.ndarray, asc: bool, nulls_first: Optional[bool]) -> np.ndarray:
    if arr.dtype == object or arr.dtype.kind in ("U", "S"):
        mask = np.asarray([v is None for v in arr]) \
            if arr.dtype == object else np.zeros(len(arr), dtype=bool)
        filled = np.where(mask, "", arr.astype(str))
        uniq, codes = np.unique(filled, return_inverse=True)
        key = codes.astype(np.float64)
        key[mask] = np.nan
    else:
        key = arr.astype(np.float64)
    isnan = np.isnan(key)
    if not asc:
        key = -key
    # SQL default: NULLS LAST for ASC, NULLS FIRST for DESC
    nf = nulls_first if nulls_first is not None else (not asc)
    key = np.where(isnan, -np.inf if nf else np.inf, key)
    return key


_ROWS_AGG_SEQ = itertools.count(1)


def _cols_to_scan(table, cols: dict) -> ScanData:
    """Re-encode a rows-mode fragment union (decoded host columns) as a
    ScanData so `_execute_agg` runs the normal device aggregation over
    it — the Final step for non-decomposable aggregates. Rows arrived
    already LWW-deduped and filtered region-side, so no seq/op_type
    machinery applies; the unique data_version keeps the ephemeral
    relation out of every persistent device-cache lineage."""
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.storage.region import OP_PUT

    schema = table.schema
    n = len(next(iter(cols.values()))) if cols else 0
    columns: dict[str, np.ndarray] = {}
    tag_dicts: dict[str, np.ndarray] = {}
    for name, arr in cols.items():
        arr = np.asarray(arr)
        if arr.dtype == object:
            dv = DictVector.encode(arr)
            columns[name] = dv.codes
            tag_dicts[name] = dv.values
        else:
            columns[name] = arr
    return ScanData(
        schema=schema, columns=columns,
        seq=np.zeros(n, dtype=np.int64),
        op_type=np.full(n, OP_PUT, dtype=np.int8),
        tag_dicts=tag_dicts, num_rows=n, needs_dedup=False,
        region_id=-1, data_version=next(_ROWS_AGG_SEQ))


def _project_empty(project, schema) -> QueryResult:
    names = [n for n, _ in project.items]
    dtypes = [_infer_dtype(e, schema) for _, e in project.items]
    cols = [np.empty(0) for _ in project.items]
    return QueryResult(names, dtypes, cols)
