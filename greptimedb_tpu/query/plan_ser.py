"""Plan/expression serialization — the substrait analog.

The reference ships DataFusion plans between frontend and datanode as
substrait bytes (src/common/substrait/src/df_substrait.rs,
datanode/src/region_server.rs:623-660). Here the exchanged unit is a
PlanFragment — an ordered stage pipeline (filter / prune / sort / limit
/ partial-agg) covering the region-side-commutative prefix of the plan —
encoded as JSON over the expression AST (every node is a frozen
dataclass, so encoding is structural and round-trips exactly).

Security note: `expr_from_json` only instantiates ast.* dataclasses by
whitelisted name — never arbitrary classes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

from greptimedb_tpu.sql import ast

_NODE_TYPES = {
    name: cls
    for name, cls in vars(ast).items()
    if isinstance(cls, type) and dataclasses.is_dataclass(cls)
}


def expr_to_json(e: Optional[ast.Expr]) -> Any:
    """Expression AST -> JSON-serializable structure."""
    if e is None:
        return None
    if isinstance(e, (str, int, float, bool)):
        return e
    if isinstance(e, (list, tuple)):
        return [expr_to_json(x) for x in e]
    if dataclasses.is_dataclass(e):
        out: dict = {"_t": type(e).__name__}
        for f in dataclasses.fields(e):
            out[f.name] = expr_to_json(getattr(e, f.name))
        return out
    raise TypeError(f"unserializable plan node {type(e).__name__}")


def expr_from_json(obj: Any) -> Any:
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, list):
        return tuple(expr_from_json(x) for x in obj)
    if isinstance(obj, dict):
        t = obj.get("_t")
        cls = _NODE_TYPES.get(t)
        if cls is None:
            raise ValueError(f"unknown plan node type {t!r}")
        kwargs = {k: expr_from_json(v) for k, v in obj.items() if k != "_t"}
        return cls(**kwargs)
    raise ValueError(f"bad plan JSON {obj!r}")


#: stage shapes of the plan IR — each stage is a plain dict whose expr
#: fields are AST nodes host-side and expr_to_json structures on the wire:
#:   {"op": "filter",      "expr": Expr}
#:   {"op": "prune",       "columns": [name, ...]}         # col projection
#:   {"op": "sort",        "keys": [(Expr, asc), ...]}
#:   {"op": "limit",       "k": int}
#:   {"op": "partial_agg", "keys": [(name, Expr)], "args": [Expr],
#:                         "ops": [primitive op]}           # terminal
#:   {"op": "window",      "calls": [(name, FuncCall-with-over)]}
#:       — window functions whose PARTITION BY covers the table's
#:       partition-rule columns: each region holds its partitions whole,
#:       so the whole window computation commutes with MergeScan


def _stage_to_json(st: dict) -> dict:
    op = st["op"]
    out = {"op": op}
    if op == "filter":
        out["expr"] = expr_to_json(st["expr"])
    elif op == "prune":
        out["columns"] = list(st["columns"])
    elif op == "sort":
        out["keys"] = [[expr_to_json(e), bool(asc)]
                       for e, asc in st["keys"]]
    elif op == "limit":
        out["k"] = int(st["k"])
    elif op == "partial_agg":
        out["keys"] = [[n, expr_to_json(e)] for n, e in st["keys"]]
        out["args"] = [expr_to_json(a) for a in st["args"]]
        out["ops"] = list(st["ops"])
    elif op == "window":
        out["calls"] = [[n, expr_to_json(e)] for n, e in st["calls"]]
    elif op == "lastpoint":
        # pruning HINT for a partial_agg terminal: the region may serve
        # the partial from its newest-first lastpoint scan
        # (Region.scan_last) instead of decoding the full region
        out["tag"] = st["tag"]
    elif op == "vmapped_agg":
        # a BATCH of parameter-sibling partial aggregates: member
        # parameter values stack into one region-side vmapped dispatch
        # (query/vmapped.run_vmapped_region_partial); per-member
        # {keys, planes} partials return — terminal
        out["keys"] = [[n, expr_to_json(e)] for n, e in st["keys"]]
        out["args"] = [expr_to_json(a) for a in st["args"]]
        out["ops"] = list(st["ops"])
        out["shared_where"] = expr_to_json(st.get("shared_where"))
        out["params"] = [[c, o] for c, o in st["params"]]
        out["values"] = [list(v) for v in st["values"]]
    else:
        raise ValueError(f"unknown fragment stage {op!r}")
    return out


def _stage_from_json(d: dict) -> dict:
    op = d["op"]
    if op == "filter":
        return {"op": op, "expr": expr_from_json(d["expr"])}
    if op == "prune":
        return {"op": op, "columns": list(d["columns"])}
    if op == "sort":
        return {"op": op, "keys": [(expr_from_json(e), bool(asc))
                                   for e, asc in d["keys"]]}
    if op == "limit":
        return {"op": op, "k": int(d["k"])}
    if op == "partial_agg":
        return {"op": op,
                "keys": [(n, expr_from_json(e)) for n, e in d["keys"]],
                "args": [expr_from_json(a) for a in d["args"]],
                "ops": list(d["ops"])}
    if op == "window":
        return {"op": op,
                "calls": [(n, expr_from_json(e)) for n, e in d["calls"]]}
    if op == "lastpoint":
        return {"op": op, "tag": d["tag"]}
    if op == "vmapped_agg":
        sw = d.get("shared_where")
        return {"op": op,
                "keys": [(n, expr_from_json(e)) for n, e in d["keys"]],
                "args": [expr_from_json(a) for a in d["args"]],
                "ops": list(d["ops"]),
                "shared_where": expr_from_json(sw) if sw is not None
                else None,
                "params": [(c, o) for c, o in d["params"]],
                "values": [list(v) for v in d["values"]]}
    raise ValueError(f"unknown fragment stage {op!r}")


@dataclasses.dataclass
class PlanFragment:
    """The unit shipped to a datanode: an ordered pipeline of plan
    stages the region executes over its own rows, classified by the
    frontend as region-side-commutative (the reference classifies every
    plan node the same way and pushes the whole commutative prefix,
    query/src/dist_plan/analyzer.rs:35 + commutativity.rs:27-52):

    - filter / prune are Commutative: they run fully region-side
    - sort + limit are PartialCommutative: regions pre-truncate to k
      candidates, the frontend re-sorts and re-limits the union
    - partial_agg is the Partial half of the Partial/Final aggregate
      split: regions return primitive planes, the frontend combines

    What returns over the wire is the terminal stage's output — partial
    planes, k candidate rows, or filtered/pruned rows — never a raw
    region scan."""

    stages: list          # ordered stage dicts, see _stage_to_json
    ts_range: Optional[tuple] = None
    append_mode: bool = False  # skip LWW dedup on append-only tables
    tz: Optional[str] = None  # session timezone for naive ts literals

    def stage(self, op: str) -> Optional[dict]:
        for st in self.stages:
            if st["op"] == op:
                return st
        return None

    def to_json(self) -> str:
        return json.dumps({
            "stages": [_stage_to_json(st) for st in self.stages],
            "ts_range": list(self.ts_range) if self.ts_range else None,
            "append_mode": self.append_mode,
            "tz": self.tz,
        })

    @staticmethod
    def from_json(s: str) -> "PlanFragment":
        d = json.loads(s)
        return PlanFragment(
            stages=[_stage_from_json(st) for st in d["stages"]],
            ts_range=tuple(d["ts_range"]) if d["ts_range"] else None,
            append_mode=bool(d.get("append_mode", False)),
            tz=d.get("tz"),
        )
