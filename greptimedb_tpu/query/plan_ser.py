"""Plan/expression serialization — the substrait analog.

The reference ships DataFusion plans between frontend and datanode as
substrait bytes (src/common/substrait/src/df_substrait.rs,
datanode/src/region_server.rs:623-660). Here the exchanged fragment is
an *aggregation pushdown*: WHERE + group keys + decomposed aggregate
specs, encoded as JSON over the expression AST (every node is a frozen
dataclass, so encoding is structural and round-trips exactly).

Security note: `expr_from_json` only instantiates ast.* dataclasses by
whitelisted name — never arbitrary classes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

from greptimedb_tpu.sql import ast

_NODE_TYPES = {
    name: cls
    for name, cls in vars(ast).items()
    if isinstance(cls, type) and dataclasses.is_dataclass(cls)
}


def expr_to_json(e: Optional[ast.Expr]) -> Any:
    """Expression AST -> JSON-serializable structure."""
    if e is None:
        return None
    if isinstance(e, (str, int, float, bool)):
        return e
    if isinstance(e, (list, tuple)):
        return [expr_to_json(x) for x in e]
    if dataclasses.is_dataclass(e):
        out: dict = {"_t": type(e).__name__}
        for f in dataclasses.fields(e):
            out[f.name] = expr_to_json(getattr(e, f.name))
        return out
    raise TypeError(f"unserializable plan node {type(e).__name__}")


def expr_from_json(obj: Any) -> Any:
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, list):
        return tuple(expr_from_json(x) for x in obj)
    if isinstance(obj, dict):
        t = obj.get("_t")
        cls = _NODE_TYPES.get(t)
        if cls is None:
            raise ValueError(f"unknown plan node type {t!r}")
        kwargs = {k: expr_from_json(v) for k, v in obj.items() if k != "_t"}
        return cls(**kwargs)
    raise ValueError(f"bad plan JSON {obj!r}")


@dataclasses.dataclass
class AggFragment:
    """The unit shipped to a datanode: compute per-region PARTIAL
    aggregates (primitive planes, not finalized values) grouped by the
    evaluated key expressions. Mirrors the reference's commutativity
    split (query/src/dist_plan/analyzer.rs:35): Partial runs on the
    region, Final combines on the frontend."""

    keys: list            # [(name, Expr)]
    args: list            # positional aggregate argument Exprs
    ops: list             # primitive op names for segment_agg
    where: Optional[ast.Expr] = None
    ts_range: Optional[tuple] = None
    append_mode: bool = False  # skip LWW dedup on append-only tables
    tz: Optional[str] = None  # session timezone for naive ts literals

    def to_json(self) -> str:
        return json.dumps({
            "keys": [[n, expr_to_json(e)] for n, e in self.keys],
            "args": [expr_to_json(a) for a in self.args],
            "ops": list(self.ops),
            "where": expr_to_json(self.where),
            "ts_range": list(self.ts_range) if self.ts_range else None,
            "append_mode": self.append_mode,
            "tz": self.tz,
        })

    @staticmethod
    def from_json(s: str) -> "AggFragment":
        d = json.loads(s)
        return AggFragment(
            keys=[(n, expr_from_json(e)) for n, e in d["keys"]],
            args=[expr_from_json(a) for a in d["args"]],
            ops=list(d["ops"]),
            where=expr_from_json(d["where"]),
            ts_range=tuple(d["ts_range"]) if d["ts_range"] else None,
            append_mode=bool(d.get("append_mode", False)),
            tz=d.get("tz"),
        )


@dataclasses.dataclass
class TopkFragment:
    """Sort/limit pushdown for non-aggregate scans: each region filters,
    sorts by `sort_keys` and returns only its top `k` rows; the frontend
    merges the per-region candidates and applies the final sort+limit.
    Mirrors the reference's commutativity classification — Sort+Limit
    commute with MergeScan when every region pre-truncates to k
    (query/src/dist_plan/commutativity.rs:27-52: Limit is
    PartialCommutative)."""

    sort_keys: list       # [(Expr, asc: bool)]
    k: int                # limit + offset: candidates each region returns
    columns: Optional[list] = None  # projection (None = all)
    where: Optional[ast.Expr] = None
    ts_range: Optional[tuple] = None
    append_mode: bool = False
    tz: Optional[str] = None  # session timezone for naive ts literals

    def to_json(self) -> str:
        return json.dumps({
            "sort_keys": [[expr_to_json(e), asc] for e, asc in self.sort_keys],
            "k": self.k,
            "columns": list(self.columns) if self.columns else None,
            "where": expr_to_json(self.where),
            "ts_range": list(self.ts_range) if self.ts_range else None,
            "append_mode": self.append_mode,
            "tz": self.tz,
        })

    @staticmethod
    def from_json(s: str) -> "TopkFragment":
        d = json.loads(s)
        return TopkFragment(
            sort_keys=[(expr_from_json(e), bool(asc))
                       for e, asc in d["sort_keys"]],
            k=int(d["k"]),
            columns=list(d["columns"]) if d["columns"] else None,
            where=expr_from_json(d["where"]),
            ts_range=tuple(d["ts_range"]) if d["ts_range"] else None,
            append_mode=bool(d.get("append_mode", False)),
            tz=d.get("tz"),
        )
