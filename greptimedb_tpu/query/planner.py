"""SQL SELECT -> logical plan (mirrors reference DfLogicalPlanner +
the optimizer's pushdown rules: projection pruning and time-predicate
extraction happen here at plan build, SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Optional

from greptimedb_tpu.catalog.catalog import TableInfo
from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query.expr import (
    AGG_FUNCS,
    PlanError,
    collect_aggregates,
    collect_columns,
    extract_ts_bounds,
    has_aggregate,
)
from greptimedb_tpu.sql import ast

_FUNC_CANON = {
    "avg": "avg", "mean": "avg", "sum": "sum", "count": "count",
    "min": "min", "max": "max",
    "first": "first", "first_value": "first",
    "last": "last", "last_value": "last",
    "stddev": "stddev", "variance": "variance",
    # order-statistic UDAFs (reference common/function scalars/aggregate)
    "argmax": "argmax", "argmin": "argmin", "median": "median",
    "percentile": "percentile", "approx_percentile_cont": "percentile",
    "polyval": "polyval",
}

#: funcs taking a literal parameter after the column arg
_PARAM_AGGS = {"percentile", "polyval"}


def plan_select(sel: ast.Select, table: TableInfo) -> lp.LogicalPlan:
    schema = table.schema
    # 1. expand stars, name items
    items: list[tuple[str, ast.Expr]] = []
    for it in sel.items:
        if isinstance(it.expr, ast.Star):
            for c in schema.columns:
                items.append((c.name, ast.Column(c.name)))
        else:
            items.append((it.alias or _default_name(it.expr), it.expr))

    alias_map = {name: expr for name, expr in items}

    # 2. resolve group-by ordinals and aliases
    group_exprs: list[ast.Expr] = []
    for g in sel.group_by:
        group_exprs.append(_resolve_group_expr(g, items, alias_map))

    # DISTINCT == group by all items with no aggregates
    if sel.distinct and not group_exprs and not any(has_aggregate(e) for _, e in items):
        group_exprs = [e for _, e in items]

    order_keys = [
        ast.OrderByItem(_resolve_group_expr(o.expr, items, alias_map), o.asc, o.nulls_first)
        for o in sel.order_by
    ]
    having = _substitute_aliases(sel.having, alias_map) if sel.having else None

    # 3. aggregates across select/having/order
    agg_calls: list[ast.FuncCall] = []
    for _, e in items:
        collect_aggregates(e, agg_calls)
    collect_aggregates(having, agg_calls)
    for o in order_keys:
        collect_aggregates(o.expr, agg_calls)
    is_agg = bool(agg_calls) or bool(group_exprs)

    # 4. referenced storage columns
    needed: set[str] = set()
    for _, e in items:
        collect_columns(e, needed)
    collect_columns(sel.where, needed)
    for g in group_exprs:
        collect_columns(g, needed)
    collect_columns(having, needed)
    for o in order_keys:
        collect_columns(o.expr, needed)
    unknown = needed - set(schema.names) - set(alias_map)
    if unknown:
        raise PlanError(f"unknown column(s) {sorted(unknown)} in table {table.name}")
    storage_cols = [n for n in schema.names if n in needed]

    ts_col = schema.time_index
    ts_range = extract_ts_bounds(sel.where, ts_col.name, ts_col.dtype)

    plan: lp.LogicalPlan = lp.Scan(table, columns=storage_cols or None, ts_range=ts_range)
    if sel.where is not None:
        plan = lp.Filter(plan, sel.where)

    if is_agg:
        keys = [(_key_name(g, items), g) for g in group_exprs]
        specs = []
        for call in agg_calls:
            func = _FUNC_CANON.get(call.name)
            if func is None:
                raise PlanError(f"unsupported aggregate {call.name!r}")
            if call.distinct:
                # COUNT(DISTINCT x) needs the full value multiset per
                # group → host pass (reference: DataFusion distinct agg)
                if func != "count":
                    raise PlanError(
                        "DISTINCT is only supported for COUNT(DISTINCT x)")
                func = "count_distinct"
            arg: Optional[ast.Expr]
            if len(call.args) == 1 and isinstance(call.args[0], ast.Star):
                if func != "count":
                    raise PlanError(f"{func}(*) is not valid")
                func, arg = "rows", None
            elif len(call.args) == 0:
                raise PlanError(f"{call.name} needs an argument")
            else:
                arg = call.args[0]
            extra: tuple = ()
            if func in _PARAM_AGGS:
                if len(call.args) != 2 or not isinstance(call.args[1], ast.Literal):
                    raise PlanError(
                        f"{call.name} needs (column, <numeric literal>)")
                try:
                    p = float(call.args[1].value)
                except (TypeError, ValueError) as exc:
                    raise PlanError(
                        f"{call.name} parameter must be numeric, got "
                        f"{call.args[1].value!r}") from exc
                if call.name == "approx_percentile_cont":
                    # standard signature takes a FRACTION in [0, 1]
                    if not 0.0 <= p <= 1.0:
                        raise PlanError(
                            "approx_percentile_cont fraction must be in [0, 1]")
                    p *= 100.0
                extra = (p,)
            if call.order_within is not None:
                oexpr, asc = call.order_within
                if func not in ("first", "last"):
                    raise PlanError(
                        f"ORDER BY inside {call.name}() is only supported "
                        "for first_value/last_value")
                if not (isinstance(oexpr, ast.Column)
                        and oexpr.table in (None, table.name)
                        and oexpr.name == schema.time_index.name):
                    raise PlanError(
                        f"{call.name}(... ORDER BY x): only the time "
                        f"index {schema.time_index.name!r} is supported")
                if not asc:
                    # last-by-descending-time IS the chronological first
                    func = "first" if func == "last" else "last"
            specs.append(lp.AggSpec(_default_name(call), func, arg, call,
                                    extra_args=extra))
        plan = lp.Aggregate(plan, keys, specs)
        _validate_agg_items(items, group_exprs, agg_calls)
        if having is not None:
            plan = lp.Having(plan, having)
    plan = lp.Project(plan, items)
    if order_keys:
        plan = lp.Sort(plan, order_keys)
    if sel.limit is not None or sel.offset:
        plan = lp.Limit(plan, sel.limit, sel.offset or 0)
    return plan


def _default_name(e: ast.Expr) -> str:
    if isinstance(e, ast.Column):
        return e.name
    if isinstance(e, ast.FuncCall):
        args = ",".join(_default_name(a) for a in e.args)
        if e.order_within is not None:
            # the ORDER BY variant must not share a name with (and thus
            # silently shadow) the plain aggregate in the projection
            oexpr, asc = e.order_within
            direction = "" if asc else " desc"
            return (f"{e.name}({args} order by "
                    f"{_default_name(oexpr)}{direction})")
        return f"{e.name}({args})"
    if isinstance(e, ast.Literal):
        return str(e.value)
    if isinstance(e, ast.Star):
        return "*"
    if isinstance(e, ast.BinaryOp):
        return f"{_default_name(e.left)} {e.op} {_default_name(e.right)}"
    if isinstance(e, ast.Interval):
        return f"interval '{e.text}'"
    if isinstance(e, ast.Cast):
        return _default_name(e.expr)
    return type(e).__name__.lower()


def _resolve_group_expr(g: ast.Expr, items, alias_map) -> ast.Expr:
    # ordinal: GROUP BY 1
    if isinstance(g, ast.Literal) and isinstance(g.value, int) and not isinstance(g.value, bool):
        idx = g.value - 1
        if 0 <= idx < len(items):
            return items[idx][1]
        raise PlanError(f"GROUP BY position {g.value} out of range")
    # alias of a select item
    if isinstance(g, ast.Column) and g.name in alias_map:
        return alias_map[g.name]
    return g


def _substitute_aliases(e: Optional[ast.Expr], alias_map) -> Optional[ast.Expr]:
    if e is None:
        return None
    if isinstance(e, ast.Column) and e.name in alias_map and not isinstance(alias_map[e.name], ast.Column):
        return alias_map[e.name]
    if isinstance(e, ast.BinaryOp):
        return ast.BinaryOp(e.op, _substitute_aliases(e.left, alias_map),
                            _substitute_aliases(e.right, alias_map))
    if isinstance(e, ast.UnaryOp):
        return ast.UnaryOp(e.op, _substitute_aliases(e.operand, alias_map))
    if isinstance(e, ast.FuncCall):
        return ast.FuncCall(e.name, tuple(_substitute_aliases(a, alias_map) for a in e.args),
                            e.distinct, order_within=e.order_within)
    if isinstance(e, ast.Between):
        return ast.Between(_substitute_aliases(e.expr, alias_map),
                           _substitute_aliases(e.low, alias_map),
                           _substitute_aliases(e.high, alias_map), e.negated)
    return e


def _key_name(g: ast.Expr, items) -> str:
    for name, expr in items:
        if expr == g:
            return name
    return _default_name(g)


def _validate_agg_items(items, group_exprs, agg_calls):
    """Every select item must be derivable from group keys + aggregates."""
    group_set = set(group_exprs)

    def ok(e: ast.Expr) -> bool:
        if e in group_set:
            return True
        if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS:
            return True
        if isinstance(e, ast.Literal) or isinstance(e, ast.Interval):
            return True
        if isinstance(e, ast.Column):
            return False
        if isinstance(e, ast.BinaryOp):
            return ok(e.left) and ok(e.right)
        if isinstance(e, ast.UnaryOp):
            return ok(e.operand)
        if isinstance(e, ast.FuncCall):
            return all(ok(a) for a in e.args)
        if isinstance(e, ast.Cast):
            return ok(e.expr)
        return False

    for name, e in items:
        if not ok(e):
            raise PlanError(
                f"select item {name!r} is neither a group key nor an aggregate"
            )
