"""RANGE ... ALIGN execution — time-windowed aggregation with overlap.

Mirrors reference src/query/src/range_select/plan.rs semantics
(plan.rs:1049-1070): an output point at aligned timestamp T aggregates
rows with `T <= ts < T + range`, output points step every ALIGN interval,
series are keyed by the BY columns (default: the table's primary-key
tags). `RANGE` may exceed `ALIGN` (overlapping sliding windows).

TPU-first design: instead of the reference's per-row hash-map of
accumulators, each row is replicated across `S = ceil(range/align)`
static slots — slot j assigns the row to window `T_j = align_slot(ts) -
j*align` — and ONE masked segment reduction over the [N*S] replicated
rows produces every window's primitives in a single fused device kernel
(ops/segment.segment_agg). S, the bucket capacity, and the series
capacity are rounded to powers of two so XLA compilations cache across
query shapes.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.catalog.catalog import TableInfo
from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query.expr import (
    PlanError,
    collect_aggregates,
    collect_columns,
    eval_host,
    extract_ts_bounds,
    _interval_in_col_unit,
)
from greptimedb_tpu.sql import ast


@dataclass
class RangeAgg:
    func: str               # canonical primitive-decomposable aggregate
    arg: Optional[ast.Expr]
    key: ast.Expr           # unique marker node — the env key: the same
    #                         FuncCall may appear with different RANGEs
    range_steps: int        # window width, in align steps (>= 1)
    fill: Optional[object]  # None | 'null' | 'prev' | 'linear' | float


@dataclass
class RangePlan:
    table: TableInfo
    where: Optional[ast.Expr]
    align_step: int         # in ts-column units
    origin: int             # ALIGN TO, in ts-column units
    by: list[ast.Expr]
    aggs: list[RangeAgg]
    items: list[tuple[str, ast.Expr]]
    order_keys: list[ast.OrderByItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


_RANGE_FUNCS = {
    "avg": "avg", "mean": "avg", "sum": "sum", "count": "count",
    "min": "min", "max": "max", "first": "first", "last": "last",
    "first_value": "first", "last_value": "last",
    "stddev": "stddev", "variance": "variance",
}


def is_range_select(sel: ast.Select) -> bool:
    return sel.align is not None or any(
        getattr(it, "range_interval", None) is not None for it in sel.items
    )


def plan_range_select(sel: ast.Select, table: TableInfo) -> RangePlan:
    """Validate + lower a RANGE select (reference plan_rewrite.rs
    RangePlanRewriter)."""
    schema = table.schema
    ts_col = schema.time_index
    ts_expr = ast.Column(ts_col.name)
    if sel.align is None:
        raise PlanError("RANGE aggregates need an ALIGN clause")
    # clauses the range path does not implement are rejected, not
    # silently dropped (reference range_select has the same restrictions)
    if sel.group_by:
        raise PlanError(
            "GROUP BY is not valid in a RANGE query; series are keyed by "
            "the ALIGN BY clause")
    if sel.having is not None:
        raise PlanError("HAVING is not supported in RANGE queries")
    if sel.distinct:
        raise PlanError("DISTINCT is not supported in RANGE queries")
    align_step = _interval_in_col_unit(sel.align, ts_expr, schema)
    origin = 0
    if sel.align_to is not None:
        if not (isinstance(sel.align_to, ast.Literal)
                and isinstance(sel.align_to.value, (int, float))):
            raise PlanError("ALIGN TO expects a numeric timestamp literal")
        origin = int(sel.align_to.value)
    by = list(sel.align_by) if sel.align_by else [
        ast.Column(c.name) for c in schema.tag_columns
    ]
    default_fill = sel.range_fill

    items: list[tuple[str, ast.Expr]] = []
    aggs: list[RangeAgg] = []
    # dedupe aggregates by (call, range, fill) — the SAME avg(v) node with
    # two different RANGEs is two different computations, so each gets a
    # unique marker column that replaces it inside that item's expression
    marker_of: dict[tuple, ast.Column] = {}
    for it in sel.items:
        if isinstance(it.expr, ast.Star):
            raise PlanError("SELECT * is not valid in a RANGE query")
        name = it.alias or _item_name(it.expr)
        calls: list[ast.FuncCall] = []
        collect_aggregates(it.expr, calls)
        rng = it.range_interval
        steps = align_step if rng is None else \
            _interval_in_col_unit(rng, ts_expr, schema)
        if steps % align_step:
            raise PlanError(
                f"RANGE ({steps}) must be a multiple of ALIGN ({align_step})")
        range_steps = max(steps // align_step, 1)
        fill = it.fill if it.fill is not None else default_fill
        subst: dict[ast.FuncCall, ast.Column] = {}
        for call in calls:
            dedup_key = (call, range_steps, fill)
            marker = marker_of.get(dedup_key)
            if marker is None:
                func = _RANGE_FUNCS.get(call.name)
                if func is None:
                    raise PlanError(
                        f"aggregate {call.name!r} is not supported in "
                        "RANGE queries")
                arg: Optional[ast.Expr]
                if len(call.args) == 1 and isinstance(call.args[0], ast.Star):
                    if func != "count":
                        raise PlanError(f"{func}(*) is not valid")
                    func, arg = "rows", None
                elif len(call.args) != 1:
                    raise PlanError(f"{call.name} takes one argument")
                else:
                    arg = call.args[0]
                marker = ast.Column(f"__range_agg_{len(aggs)}")
                marker_of[dedup_key] = marker
                aggs.append(RangeAgg(func, arg, marker, range_steps, fill))
            subst[call] = marker
        items.append((name, _subst_calls(it.expr, subst)))
    if not aggs:
        raise PlanError("a RANGE query needs at least one aggregate")

    # every non-aggregate column reference must be the time index or a BY key
    allowed = {ts_col.name}
    for b in by:
        collect_columns(b, allowed)
    outside: set[str] = set()
    for _, e in items:
        _collect_nonagg_columns(e, outside)
    bad = {c for c in outside - allowed if not c.startswith("__range_agg_")}
    if bad:
        raise PlanError(
            f"column(s) {sorted(bad)} must appear in the ALIGN BY clause")

    return RangePlan(
        table=table, where=sel.where, align_step=align_step, origin=origin,
        by=by, aggs=aggs, items=items, order_keys=list(sel.order_by),
        limit=sel.limit, offset=sel.offset or 0,
    )


def _item_name(e: ast.Expr) -> str:
    from greptimedb_tpu.query.planner import _default_name
    return _default_name(e)


def _subst_calls(e: ast.Expr, subst: dict) -> ast.Expr:
    """Structurally replace aggregate FuncCalls with their marker columns."""
    if isinstance(e, ast.FuncCall) and e in subst:
        return subst[e]
    if isinstance(e, ast.BinaryOp):
        return ast.BinaryOp(e.op, _subst_calls(e.left, subst),
                            _subst_calls(e.right, subst))
    if isinstance(e, ast.UnaryOp):
        return ast.UnaryOp(e.op, _subst_calls(e.operand, subst))
    if isinstance(e, ast.FuncCall):
        return ast.FuncCall(
            e.name, tuple(_subst_calls(a, subst) for a in e.args),
            e.distinct, order_within=e.order_within)
    if isinstance(e, ast.Cast):
        return ast.Cast(_subst_calls(e.expr, subst), e.type_name)
    return e


def _collect_nonagg_columns(e: ast.Expr, out: set) -> None:
    if isinstance(e, ast.FuncCall) and e.name in _RANGE_FUNCS:
        return
    if isinstance(e, ast.Column):
        out.add(e.name)
        return
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, ast.Expr):
            _collect_nonagg_columns(v, out)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.Expr):
                    _collect_nonagg_columns(x, out)


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def execute_range_select(executor, rp: RangePlan):
    """Run a RangePlan through the executor's storage + device substrate."""
    from greptimedb_tpu import config
    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.query.physical import (
        BindContext,
        _PRIMITIVES,
        _closed_range,
        _finalize_agg,
        bind_expr,
    )
    from greptimedb_tpu.storage.index import extract_tag_predicates
    from greptimedb_tpu.storage.merge_scan import merge_scans
    from greptimedb_tpu.utils import tracing

    table = rp.table
    schema = table.schema
    ts_name = schema.time_index.name
    ts_range = _closed_range(
        extract_ts_bounds(rp.where, ts_name, schema.time_index.dtype))
    tag_preds = extract_tag_predicates(rp.where, schema) or None

    # projection pruning: only ts, WHERE, BY, and aggregate-arg columns
    needed: set[str] = {ts_name}
    collect_columns(rp.where, needed)
    for b in rp.by:
        collect_columns(b, needed)
    for a in rp.aggs:
        collect_columns(a.arg, needed)
    proj_cols = [c for c in schema.names if c in needed]

    with tracing.span("scan", table=table.name,
                      regions=len(table.region_ids)):
        if len(table.region_ids) == 1:
            scan = executor.engine.scan(table.region_ids[0], ts_range,
                                        proj_cols, tag_preds)
        else:
            scan = merge_scans([
                executor.engine.scan(rid, ts_range, proj_cols, tag_preds)
                for rid in table.region_ids
            ])
    project = lp.Project(None, rp.items)
    sort = lp.Sort(None, rp.order_keys) if rp.order_keys else None

    def empty_result():
        # zero windows: every projected expression still needs a binding
        env0: dict = {ast.Column(ts_name): np.empty(0, dtype=np.int64)}
        for b in rp.by:
            env0[b] = np.empty(0, dtype=object)
        for a in rp.aggs:
            env0[a.key] = np.empty(0, dtype=np.float64)
        return executor._post_process(env0, None, None, project, sort,
                                      rp.limit, rp.offset, table, 0)

    if scan is None or scan.num_rows == 0:
        return empty_result()

    ctx = BindContext(schema, scan.tag_dicts)
    bound_where = bind_expr(rp.where, ctx) if rp.where is not None else None
    idx = executor._filtered_row_indices(scan, table, ctx, bound_where,
                                         where_unbound=rp.where)
    if len(idx) == 0:
        return empty_result()

    # host gather of surviving rows
    host: dict[str, np.ndarray] = {}
    for name, arr in scan.columns.items():
        taken = arr[idx]
        if name in scan.tag_dicts:
            taken = DictVector(taken, scan.tag_dicts[name]).decode()
        host[name] = taken
    ts = host[ts_name].astype(np.int64)
    n = len(ts)

    # BY-key factorization -> one dense series code
    by_values: list[np.ndarray] = []
    by_codes = np.zeros(n, dtype=np.int64)
    n_series = 1
    for b in rp.by:
        vals = np.asarray(eval_host(b, host, schema))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (n,))
        uniq, codes = np.unique(vals, return_inverse=True)
        by_values.append(uniq)
        by_codes = by_codes * len(uniq) + codes
        n_series *= len(uniq)
    # compact the combined code (the cross product may have holes)
    series_uniq, series_code = (np.unique(by_codes, return_inverse=True)
                                if rp.by else
                                (np.zeros(1, dtype=np.int64),
                                 np.zeros(n, dtype=np.int64)))

    align, origin = rp.align_step, rp.origin
    base_slot = (ts - origin) // align
    n_slots = max(a.range_steps for a in rp.aggs)
    # the grid extends n_slots-1 below the earliest data slot: a window
    # starting before the first row still covers it when range > align
    # (reference emits those leading partial windows)
    slot_lo = int(base_slot.min()) - (n_slots - 1)
    slot_span = int(base_slot.max()) - slot_lo + 1
    cap_buckets = _pow2(slot_span)
    cap_series = _pow2(len(series_uniq))
    num_groups = cap_series * cap_buckets
    if num_groups > config.dense_groups_max() * 4:
        raise PlanError(
            f"RANGE query group space {num_groups} too large; narrow the "
            "time window or coarsen ALIGN")

    # aggregate value planes
    arg_exprs: list[Optional[ast.Expr]] = []
    slots: list[Optional[int]] = []
    for a in rp.aggs:
        if a.arg is None:
            slots.append(None)
            continue
        if a.arg not in arg_exprs:
            arg_exprs.append(a.arg)
        slots.append(arg_exprs.index(a.arg))
    if arg_exprs:
        planes = [
            np.asarray(eval_host(e, host, schema), dtype=np.float64)
            for e in arg_exprs
        ]
        vals = np.stack([np.broadcast_to(p, (n,)) for p in planes], axis=1)
    else:
        vals = np.zeros((n, 1), dtype=np.float64)

    ops: set = {"rows"}
    for a in rp.aggs:
        ops.update(_PRIMITIVES[a.func])
    ranges = tuple(sorted({a.range_steps for a in rp.aggs}))
    need_ts = bool({"first", "last"} & ops)

    with tracing.span("range_agg", rows=n, slots=n_slots,
                      groups=num_groups):
        accs = _range_kernel(
            jnp.asarray(ts), jnp.asarray(series_code.astype(np.int32)),
            jnp.asarray(vals), jnp.asarray(base_slot - slot_lo),
            align=align, n_slots=n_slots, cap_buckets=cap_buckets,
            num_groups=num_groups, ranges=ranges,
            ops=tuple(sorted(ops)), need_ts=need_ts,
        )
    accs = {r: {k: np.asarray(v) for k, v in acc.items()}
            for r, acc in accs.items()}

    # windows observed by ANY aggregate's range
    present_mask = np.zeros(num_groups, dtype=bool)
    for r in ranges:
        rows_r = accs[r]["rows"]
        rows_r = rows_r[:, 0] if rows_r.ndim == 2 else rows_r
        present_mask |= rows_r > 0
    present = np.flatnonzero(present_mask)

    env: dict = {}
    series_idx = present // cap_buckets
    bucket_idx = present % cap_buckets
    align_ts = (bucket_idx + slot_lo) * align + origin
    env[ast.Column(ts_name)] = align_ts
    # decode BY values for the present windows
    gcodes = series_uniq[series_idx] if rp.by else series_idx
    for b, uniq in zip(reversed(rp.by), reversed(by_values)):
        env[b] = uniq[gcodes % len(uniq)]
        gcodes = gcodes // len(uniq)
    for a, slot in zip(rp.aggs, slots):
        env[a.key] = _finalize_agg(a.func, accs[a.range_steps], slot,
                                    present)

    nrows = len(present)
    env, nrows = _apply_fill(rp, env, series_idx, bucket_idx, align_ts,
                             slot_lo, align, origin, ts_name, nrows)
    return executor._post_process(env, None, None, project, sort, rp.limit,
                                  rp.offset, table, nrows)


def _range_kernel(ts, series_code, vals, rel_slot, *, align, n_slots,
                  cap_buckets, num_groups, ranges, ops, need_ts):
    """One fused device reduction over slot-replicated rows. Returns
    {range_steps: {op: [G(,F)]}}."""
    return _range_kernel_jit(ts, series_code, vals, rel_slot, align,
                             n_slots, cap_buckets, num_groups, ranges,
                             ops, need_ts)


@functools.partial(
    jax.jit,
    static_argnums=(4, 5, 6, 7, 8, 9, 10),
)
def _range_kernel_jit(ts, series_code, vals, rel_slot, align, n_slots,
                      cap_buckets, num_groups, ranges, ops, need_ts):
    n, f = vals.shape
    # replicate rows across slots: slot j -> window starting j*align earlier
    j = jnp.arange(n_slots, dtype=rel_slot.dtype)[:, None]       # [S, 1]
    cand = rel_slot[None, :] - j                                  # [S, N]
    in_grid = (cand >= 0) & (cand < cap_buckets)
    gid = (series_code.astype(jnp.int64)[None, :] * cap_buckets
           + jnp.clip(cand, 0, cap_buckets - 1))                  # [S, N]
    gid_flat = gid.reshape(-1).astype(jnp.int32)
    vals_rep = jnp.broadcast_to(vals[None], (n_slots, n, f)).reshape(-1, f)
    ts_rep = (jnp.broadcast_to(ts[None], (n_slots, n)).reshape(-1)
              if need_ts else None)
    out = {}
    for r in ranges:
        # row in window iff its slot distance j < range_steps
        valid = (in_grid & (j < r)).reshape(-1)
        out[r] = segment_agg(vals_rep, gid_flat, valid, num_groups,
                             ops=ops, ts=ts_rep)
    return out


def _apply_fill(rp, env, series_idx, bucket_idx, align_ts, slot_lo, align,
                origin, ts_name, nrows):
    """FILL NULL/PREV/LINEAR/<const> densify the per-series time grid
    between the globally observed first and last windows
    (reference range_select FILL, plan.rs RangeFn::fill)."""
    if not any(a.fill is not None for a in rp.aggs) or nrows == 0:
        return env, nrows
    b_lo, b_hi = int(bucket_idx.min()), int(bucket_idx.max())
    span = b_hi - b_lo + 1
    series = np.unique(series_idx)
    dense_n = len(series) * span
    # position of each present window in the dense grid
    s_pos = np.searchsorted(series, series_idx)
    pos = s_pos * span + (bucket_idx - b_lo)
    out_env: dict = {}
    dense_buckets = np.tile(np.arange(b_lo, b_hi + 1), len(series))
    new_align_ts = (dense_buckets + slot_lo) * align + origin
    for key, arr in env.items():
        if arr is align_ts:
            out_env[key] = new_align_ts
            continue
        if key in rp.by:
            continue  # densified from the series blocks below
        if np.issubdtype(np.asarray(arr).dtype, np.number):
            dense = np.full(dense_n, np.nan)
        else:
            dense = np.empty(dense_n, dtype=object)
        dense[pos] = arr
        out_env[key] = dense
    # BY columns must be total on the dense grid: each series block gets
    # its decoded value
    for b in rp.by:
        arr = env[b]
        per_series = {}
        for sp, v in zip(s_pos, arr):
            per_series.setdefault(sp, v)
        col = np.empty(dense_n, dtype=object)
        for k in range(len(series)):
            col[k * span:(k + 1) * span] = per_series.get(k)
        out_env[b] = col
    # per-aggregate fill policies
    have = np.zeros(dense_n, dtype=bool)
    have[pos] = True
    for a in rp.aggs:
        arr = out_env[a.key]
        if a.fill in (None, "null"):
            continue
        if isinstance(a.fill, float):
            arr = np.where(have, arr, a.fill)
        elif a.fill == "prev":
            arr = arr.copy()
            for k in range(len(series)):
                seg = arr[k * span:(k + 1) * span]
                for i in range(1, span):
                    if not have[k * span + i]:
                        seg[i] = seg[i - 1]
        elif a.fill == "linear":
            arr = arr.copy()
            for k in range(len(series)):
                seg = arr[k * span:(k + 1) * span]
                hs = have[k * span:(k + 1) * span]
                xs = np.flatnonzero(hs)
                if len(xs) >= 2:
                    miss = np.flatnonzero(~hs)
                    seg[miss] = np.interp(miss, xs,
                                          seg[xs].astype(np.float64))
        out_env[a.key] = arr
    return out_env, dense_n
