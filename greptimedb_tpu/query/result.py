"""Query results: a loose column container (query output needn't have a
time index, unlike storage RecordBatch)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from greptimedb_tpu.datatypes.types import DataType
from greptimedb_tpu.utils.time import format_ts


@dataclass
class QueryResult:
    names: list[str] = field(default_factory=list)
    dtypes: list[Optional[DataType]] = field(default_factory=list)
    columns: list[np.ndarray] = field(default_factory=list)
    affected_rows: Optional[int] = None  # set for DML/DDL

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def is_query(self) -> bool:
        return self.affected_rows is None

    @staticmethod
    def of_affected(n: int) -> "QueryResult":
        return QueryResult(affected_rows=n)

    def _format_col(self, dt, col, format_timestamps: bool) -> list:
        if format_timestamps and dt is not None and dt.is_timestamp:
            return [None if v is None else format_ts(v, dt)
                    for v in col.tolist()]
        return [None if _is_nan(v) else v for v in col.tolist()]

    def to_pydict(self, format_timestamps: bool = False) -> dict[str, list]:
        return {name: self._format_col(dt, col, format_timestamps)
                for name, dt, col in zip(self.names, self.dtypes,
                                         self.columns)}

    def rows(self) -> list[list]:
        # no dict round-trip: duplicate output names (SELECT a.x, b.x)
        # must stay distinct columns
        cols = [self._format_col(dt, col, False)
                for dt, col in zip(self.dtypes, self.columns)]
        return [list(r) for r in zip(*cols)] if cols else []

    def column(self, name: str) -> np.ndarray:
        return self.columns[self.names.index(name)]


def _is_nan(v) -> bool:
    return isinstance(v, float) and v != v
