"""vmap'd multi-query execution: a batch of parameter-sibling queries
as ONE device program.

The cross-query batcher (concurrency/batcher.py) collects SELECTs that
share a plan shape but differ in parameter literals — which host, which
datacenter, which time window. The previous stacked path rewrote the
group into one IN-list query and demultiplexed the combined result;
that only covers a single tag-equality selector and forces every member
onto the same time window. Here the members' parameters become a
STACKED AXIS instead: the scan, group ids, and value planes are built
once (they are member-invariant), each member contributes only its
per-row predicate mask, and `jax.vmap` maps the masked segment
reduction over the member axis — one dispatch computes an [M, G, F]
accumulator whose member slices are separated by construction. No
rewrite, no demux.

Bit-for-bit parity with serial execution is by masking identity, not by
approximation: the kernel scans the region's full row set and routes
every row a member's WHERE rejects into the dead segment — exactly what
the serial kernels do with their own masks — so a member's per-segment
fold visits precisely the rows its serial run would, in the same order.
Two structural conditions keep the fold association identical too, and
`run_vmapped` refuses (raises `VmapIneligible`, the batcher falls back
to the stacked/serial paths) when they don't hold:

- every scan part maps to ONE device block (so a serial scan of any
  sub-window, which decodes a row-subset of each part, splits partials
  at the same part seams — inserting identity elements into a left fold
  preserves every partial sum exactly);
- the member's whole predicate decomposes into shared conjuncts plus
  `column <op> literal` parameter conjuncts the kernel can evaluate
  from a stacked array (tag equality by dictionary code, time-index
  comparisons in storage units — bound through the SAME `bind_expr`
  the serial path uses, so literal coercion cannot drift).

Window-union batching falls out for free: members with different time
windows share the one full scan and differ only in their ts-comparison
parameters; multi-tag selectors are just several tag parameters.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query import physical as ph
from greptimedb_tpu.query.expr import (
    BindContext,
    bind_expr,
    eval_device,
    extract_ts_bounds,
    split_conjuncts,
)
from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.sql import ast


class VmapIneligible(Exception):
    """This batch group cannot ride the vmapped kernel with provable
    serial parity — the batcher falls back to stacked/serial paths."""


#: member-axis padding buckets: compile one executable per (shape,
#: width bucket) instead of one per batch width
_WIDTH_BUCKETS = (2, 4, 8, 16, 32, 64, 128)


def _pad_width(m: int) -> int:
    for b in _WIDTH_BUCKETS:
        if m <= b:
            return b
    return m


def _rebuild_conjunction(conjuncts: list) -> Optional[ast.Expr]:
    if not conjuncts:
        return None
    e = conjuncts[0]
    for c in conjuncts[1:]:
        e = ast.BinaryOp("and", e, c)
    return e


def _member_mask(cols, base_mask, shared_where, param_specs, pvals,
                 tag_names, schema):
    """One member's row mask: shared conjuncts plus its stacked
    parameter comparisons (shared by the single-region and region-
    partial kernels)."""
    mask = base_mask
    if shared_where is not None:
        w = eval_device(shared_where, cols, tag_names, schema)
        mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
    for (name, op), pv in zip(param_specs, pvals):
        c = cols[name]
        if op == "=":
            mask = mask & (c == pv)
        elif op == "<":
            mask = mask & (c < pv)
        elif op == "<=":
            mask = mask & (c <= pv)
        elif op == ">":
            mask = mask & (c > pv)
        else:  # ">="
            mask = mask & (c >= pv)
    return mask


@functools.partial(
    jax.jit,
    static_argnames=("shared_where", "param_specs", "keys", "agg_args",
                     "ops", "num_segments", "ts_name", "need_ts",
                     "tag_names", "schema", "acc_dtype", "float_ops",
                     "pack_dtype"),
)
def _vmapped_agg_scan(
    blocks: tuple,  # per-block col dicts (member-invariant)
    n_valids: jax.Array,
    dedup_masks,
    params: tuple,  # per-spec [M] stacked parameter arrays
    *,
    shared_where, param_specs, keys, agg_args, ops, num_segments,
    ts_name, need_ts, tag_names, schema, acc_dtype, float_ops,
    pack_dtype,
):
    """One dispatch for M parameter-sibling queries. Everything that
    does not depend on the member parameters (group ids, value planes,
    the shared-predicate mask) is traced once and stays unbatched;
    only the per-member mask and the segment reductions carry the
    vmapped leading axis. first/last ride as ts-paired planes: the
    companion *_ts planes drive the cross-block combine on device and
    never leave the kernel (the value planes are what the host reads)."""

    def member(pvals):
        acc = None
        for i, cols in enumerate(blocks):
            some = next(iter(cols.values()))
            mask = jnp.arange(some.shape[0]) < n_valids[i]
            if dedup_masks is not None:
                mask = mask & dedup_masks[i]
            mask = _member_mask(cols, mask, shared_where, param_specs,
                                pvals, tag_names, schema)
            gid = ph._group_ids(cols, keys, mask.shape[0])
            if agg_args:
                values = ph._value_planes(agg_args, cols, tag_names,
                                          schema, mask.shape, acc_dtype)
            else:
                values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
            part = segment_agg(values, gid, mask, num_segments, ops=ops,
                               ts=cols[ts_name] if need_ts else None)
            acc = ph._combine_partials(acc, part)
        parts = []
        for k in float_ops:
            v = acc[k]
            if v.ndim == 1:
                v = v[:, None]
            parts.append(v.astype(pack_dtype))
        return jnp.concatenate(parts, axis=1)

    return jax.vmap(member)(params)


@functools.partial(
    jax.jit,
    static_argnames=("shared_where", "param_specs", "keys", "agg_args",
                     "ops", "cap", "ts_name", "need_ts", "tag_names",
                     "schema", "acc_dtype", "float_ops", "pack_dtype"),
)
def _vmapped_sparse_agg_scan(
    cols: dict,  # whole-scan padded col arrays (member-invariant)
    base_mask: jax.Array,  # [N] padding & dedup survivors
    params: tuple,  # per-spec [M] stacked parameter arrays
    *,
    shared_where, param_specs, keys, agg_args, ops, cap, ts_name,
    need_ts, tag_names, schema, acc_dtype, float_ops, pack_dtype,
):
    """Sparse (sort-compact) twin of _vmapped_agg_scan: ONE shared
    compaction over the member-invariant base mask (padding, dedup,
    shared conjuncts — every member's rows are a subset), then each
    member's parameter mask rides the vmapped axis as the segment_agg
    validity over the already-sorted rows. The compact ranks cover the
    UNION of observed groups; a member's unobserved ranks come back
    with rows == 0 and the host drops them, so each member sees exactly
    the groups its serial sparse run would. Parity is the masking
    identity again: the shared sort is stable, so a member's surviving
    rows keep their serial fold order, and masked rows contribute fold
    identities."""
    from greptimedb_tpu.ops import sparse_segment as sparse_ops

    mask0 = base_mask
    if shared_where is not None:
        w = eval_device(shared_where, cols, tag_names, schema)
        mask0 = mask0 & (w if w.dtype == jnp.bool_ else w != 0)
    gid = ph._sparse_gid(cols, keys)
    order, ids, valid_s, uniq, n_groups = sparse_ops.sort_compact(
        gid, mask0, cap)
    if agg_args:
        values = ph._value_planes(agg_args, cols, tag_names, schema,
                                  mask0.shape, acc_dtype)
    else:
        values = jnp.zeros((mask0.shape[0], 1), dtype=acc_dtype)
    values_s = values[order]
    ts_s = cols[ts_name][order] if need_ts else None
    param_cols_s = {name: cols[name][order]
                    for name, _op in dict.fromkeys(param_specs)}

    def member(pvals):
        mask = _member_mask(param_cols_s, valid_s, None, param_specs,
                            pvals, tag_names, schema)
        part = segment_agg(values_s, ids, mask, cap, ops=ops, ts=ts_s,
                           indices_are_sorted=True)
        parts = []
        for k in float_ops:
            v = part[k]
            if v.ndim == 1:
                v = v[:, None]
            parts.append(v.astype(pack_dtype))
        return jnp.concatenate(parts, axis=1)

    return jax.vmap(member)(params), uniq, n_groups


def _bind_param(pspec, value, bctx) -> tuple:
    """One member's value for one parameter conjunct, bound through the
    engine's own literal coercion. Returns (device column name, op,
    bound int). Tag equality binds to a dictionary code, time-index
    comparisons coerce to storage units — identical to what the serial
    path's bound WHERE would compare against."""
    conj = ast.BinaryOp(pspec.op, ast.Column(pspec.col), ast.Literal(value))
    bound = bind_expr(conj, bctx)
    if not (isinstance(bound, ast.BinaryOp)
            and isinstance(bound.left, ast.Column)
            and isinstance(bound.right, ast.Literal)
            and isinstance(bound.right.value, (int, np.integer))
            and not isinstance(bound.right.value, bool)):
        raise VmapIneligible(f"unbindable parameter {pspec.col} {pspec.op}")
    return bound.left.name, bound.op, int(bound.right.value)


def run_vmapped(executor, sel: ast.Select, info, pspecs,
                member_values: list) -> list:
    """Execute `sel`'s shape once for every member value tuple; returns
    QueryResults aligned with `member_values`. Raises VmapIneligible
    when the shape/scan cannot guarantee bit-for-bit serial parity."""
    from greptimedb_tpu import config
    from greptimedb_tpu.query.planner import plan_select

    plan = plan_select(sel, info)
    node = plan
    if not isinstance(node, lp.Project):
        raise VmapIneligible("plan root is not a projection")
    project = node
    node = node.input
    if not isinstance(node, lp.Aggregate):
        raise VmapIneligible("not an aggregate shape")
    agg = node
    node = node.input
    if not isinstance(node, lp.Filter):
        raise VmapIneligible("no predicate to parameterize")
    template_where = node.predicate
    node = node.input
    if not isinstance(node, lp.Scan):
        raise VmapIneligible("unexpected scan node")
    scan_node = node
    table = scan_node.table
    schema = table.schema
    ts_name = schema.time_index.name

    if any(ph._needs_host_agg(spec, schema) for spec in agg.aggs):
        raise VmapIneligible("host-side aggregate in batch shape")
    if len(table.region_ids) != 1:
        # cluster frontend: the members execute as ONE vmapped_agg
        # fragment per region — per-member [G, F] partials come back
        # and combine like the serial pushdown's Final step (no raw
        # rows, no IN-list/serial fallback)
        if hasattr(executor.engine, "execute_fragment"):
            return _run_vmapped_fragments(
                executor, sel, info, pspecs, member_values, project, agg,
                template_where)
        raise VmapIneligible("multi-region scans gather via fragments")
    if not hasattr(executor.engine, "scan"):
        raise VmapIneligible("engine has no materialized scan")

    # split the predicate: parameter conjuncts out, shared rest stays.
    # plan_select passes sel.where through by reference, so the
    # batcher-identified conjunct objects are found by identity.
    param_ids = {id(p.conjunct) for p in pspecs}
    shared = [c for c in split_conjuncts(template_where)
              if id(c) not in param_ids]
    if len(shared) + len(pspecs) != len(split_conjuncts(template_where)):
        raise VmapIneligible("parameter conjuncts lost in planning")
    shared_where_ast = _rebuild_conjunction(shared)

    # union time range (drives the bucket-key domain and the scan's
    # coarse pruning; member masks carve exact slices on device)
    union_range = _union_member_range(template_where, pspecs,
                                      member_values, ts_name,
                                      schema.time_index.dtype)

    # one scan covering the UNION of the member windows (tag predicates
    # stay None: every member's rows must be present); member masks
    # carve their slices on device. Region.scan's own covering-range
    # widening keeps the parity cases aligned: if any member's serial
    # scan would widen to the full region, the union (a superset range)
    # widens too, so the one-block-per-part gate below always runs over
    # a superset of every member's decoded parts.
    scan = executor.engine.scan(table.region_ids[0],
                                ph._closed_range(union_range),
                                scan_node.columns, None)
    if scan is None or scan.num_rows == 0:
        raise VmapIneligible("empty scan: serial path settles it")
    if table.append_mode and \
            scan.num_rows >= config.stream_threshold_rows():
        raise VmapIneligible("serial path would stream this scan")
    if executor.mesh is not None and \
            scan.num_rows >= config.mesh_min_rows():
        raise VmapIneligible("serial path would shard over the mesh")

    # parity gate: one device block per part seam (see module docstring)
    block_plan = ph._block_plan(scan)
    seen: set = set()
    for entry in block_plan:
        seam = (entry.pkey, entry.part_start)
        if seam in seen:
            raise VmapIneligible("a scan part spans multiple blocks")
        seen.add(seam)

    bctx = BindContext(schema, scan.tag_dicts)
    bound_shared = bind_expr(shared_where_ast, bctx) \
        if shared_where_ast is not None else None

    # stacked parameter matrix: [n_specs][M] bound ints
    cols_ops: list[tuple] = []
    matrix: list[list[int]] = [[] for _ in pspecs]
    for values in member_values:
        for j, (p, v) in enumerate(zip(pspecs, values)):
            name, op, bval = _bind_param(p, v, bctx)
            if len(cols_ops) <= j:
                cols_ops.append((name, op))
            elif cols_ops[j] != (name, op):
                raise VmapIneligible("parameter spec drift across members")
            matrix[j].append(bval)

    # group keys over the union scan; decode is value-based, so a base
    # shift against a member's narrower serial window is invisible
    scan_node_u = lp.Scan(table, scan_node.columns, union_range)
    keys: list = []
    decoders: list = []
    extra_cols: dict[str, np.ndarray] = {}
    for i, (name, kexpr) in enumerate(agg.keys):
        dk, decode = executor._plan_key(i, kexpr, bctx, scan, scan_node_u,
                                        extra_cols)
        keys.append(dk)
        decoders.append(decode)
    num_groups = 1
    for k in keys:
        num_groups *= k.size
    if not keys:
        raise VmapIneligible("global aggregate has no group axis")
    if num_groups >= ph._GID_SENTINEL:
        raise VmapIneligible(f"group domain {num_groups} overflows gid space")
    # past the dense envelope the members ride the sparse (sort-compact)
    # twin instead of falling back to serial — the batch's accumulator
    # is [M, cap, F] over OBSERVED groups, not the key-domain product
    sparse = num_groups > config.dense_groups_max() or (
        config.sparse_groups_min() > 0
        and num_groups >= config.sparse_groups_min())
    cap = min(ph.block_size_for(scan.num_rows), config.sparse_groups_max())
    # the stacked axis multiplies the accumulator: bound M*G by the
    # budget one serial query of the same flavor is allowed (dense key
    # domain, or sparse compact cap), so a wide batch over a near-max
    # group domain can't ask XLA for a multi-GB output
    budget = config.sparse_groups_max() if sparse \
        else config.dense_groups_max()
    if _pad_width(len(member_values)) * (cap if sparse else num_groups) \
            > budget:
        raise VmapIneligible("stacked accumulator exceeds group budget")

    # aggregate layout (mirrors _stream_agg_inner's dense packing)
    arg_exprs: list = []
    spec_slot: list = []
    for spec in agg.aggs:
        if spec.arg is None:
            spec_slot.append(None)
            continue
        b = bind_expr(spec.arg, bctx)
        if b not in arg_exprs:
            arg_exprs.append(b)
        spec_slot.append(arg_exprs.index(b))
    ops: set = {"rows"}
    for spec in agg.aggs:
        ops.update(ph._PRIMITIVES[spec.func])
    # first/last batch too (ROADMAP item 1 rung): the kernel pairs each
    # group's value with its timestamp, so lastpoint-class dashboards
    # ride the stacked axis like every other aggregate
    need_ts = bool({"first", "last"} & ops)

    acc_dtype = jnp.dtype(config.compute_dtype())
    nf = max(len(arg_exprs), 1)
    float_ops_l, widths = [], {}
    for op in sorted(ops):
        if op.endswith("_ts"):
            continue  # companion planes stay inside the kernel
        float_ops_l.append(op)
        widths[op] = 1 if op == "rows" else nf
    float_ops = tuple(float_ops_l)
    pack_dtype = jnp.dtype(jnp.float64) if num_groups <= 4096 else acc_dtype
    if not jnp.issubdtype(pack_dtype, jnp.floating):
        pack_dtype = jnp.dtype(jnp.float64)
    if "sumsq" in float_ops:
        pack_dtype = jnp.dtype(jnp.float64)

    dedup_mask = executor._maybe_dedup(scan, table, bctx)
    tag_names = frozenset(bctx.tag_names)
    float_fields = {c.name for c in schema.field_columns
                    if c.dtype.is_float}
    device_col_names = executor._device_columns(
        scan, bound_shared, keys, tuple(arg_exprs), ts_name, extra_cols)
    for name, _op in cols_ops:
        if name not in device_col_names:
            device_col_names.append(name)

    tier = executor.tier_for(agg, scan.num_rows, scan=scan)
    executor.last_tier = tier

    def fetch_block(entry, prefetch_only=False):
        out = {}
        for name in device_col_names:
            out[name] = executor._device_block(
                scan, name, entry, extra_cols,
                acc_dtype if name in float_fields else None,
                prefetch_only=prefetch_only)
        return out

    m = len(member_values)
    mp = _pad_width(m)
    params = []
    for j, (name, _op) in enumerate(cols_ops):
        dt = np.int64 if name == ts_name else np.int32
        vals = matrix[j] + [matrix[j][-1]] * (mp - m)
        params.append(jnp.asarray(np.asarray(vals, dtype=dt)))

    if sparse:
        return _run_vmapped_sparse(
            executor, scan, agg, project, table, keys, decoders, spec_slot,
            extra_cols, bound_shared, bctx, cols_ops, params, m,
            device_col_names, float_fields, acc_dtype, dedup_mask,
            tag_names, schema, ts_name, need_ts, arg_exprs, ops, cap,
            float_ops, widths, pack_dtype, tier, num_groups)

    with ph._TierCtx(tier):
        blocks, n_valids, dmasks = executor._gather_blocks(
            scan, block_plan, fetch_block, dedup_mask)
        packed = _vmapped_agg_scan(
            tuple(blocks), jnp.asarray(np.asarray(n_valids)),
            tuple(dmasks) if dmasks is not None else None,
            tuple(params),
            shared_where=bound_shared, param_specs=tuple(cols_ops),
            keys=tuple(keys), agg_args=tuple(arg_exprs),
            ops=tuple(sorted(ops)), num_segments=num_groups,
            ts_name=ts_name, need_ts=need_ts,
            tag_names=tag_names, schema=schema, acc_dtype=acc_dtype,
            float_ops=float_ops, pack_dtype=pack_dtype)
        host = ph._readback(packed)

    results = []
    host_info = (scan, extra_cols, bound_shared, bctx, num_groups)
    for i in range(m):
        acc: dict = {}
        off = 0
        for k in float_ops:
            w = widths[k]
            sl = host[i][:, off:off + w]
            off += w
            if k in ("count", "rows"):
                sl = sl.astype(np.int64)
            acc[k] = sl
        results.append(executor._agg_tail(
            acc, None, agg, keys, decoders, spec_slot, host_info,
            None, project, None, None, None, table))
    executor.last_path = "dense_vmapped"
    return results


def _run_vmapped_sparse(executor, scan, agg, project, table, keys, decoders,
                        spec_slot, extra_cols, bound_shared, bctx, cols_ops,
                        params, m, device_col_names, float_fields, acc_dtype,
                        dedup_mask, tag_names, schema, ts_name, need_ts,
                        arg_exprs, ops, cap, float_ops, widths, pack_dtype,
                        tier, num_groups) -> list:
    """Sparse execution tail of run_vmapped: whole-scan padded columns
    (sharing the serial sparse path's snapshot cache keys, so a batch
    after a serial high-card query reuses its uploads), ONE stacked
    sort-compact dispatch, then a per-member demux that keeps only the
    compact ranks the member actually observed (rows > 0) before the
    shared gid-decoding tail."""
    from greptimedb_tpu.ops import sparse_segment as sparse_ops
    from greptimedb_tpu.utils.metrics import (
        SPARSE_COMPACTION_RATIO,
        SPARSE_DISPATCHES,
    )

    n = scan.num_rows
    n_pad = ph.block_size_for(n)
    cols = {}
    for name in device_col_names:
        cast = acc_dtype if name in float_fields else None

        def build(name=name, cast=cast):
            src = extra_cols[name] if name in extra_cols \
                else scan.columns[name]
            arr = ph.pad_rows(src, n_pad)
            if cast is not None and arr.dtype != cast:
                arr = arr.astype(cast)
            return jnp.asarray(arr)

        if scan.region_id < 0 or name in extra_cols:
            cols[name] = build()
        else:
            key = ("snap", scan.region_id, ph._snap_version(scan),
                   ph._ACTIVE_TIER_VAR.get(), scan.scan_fingerprint,
                   name, "whole", n_pad, str(cast))
            cols[name] = executor.cache.get(key, build)
    base = np.arange(n_pad) < n
    if dedup_mask is not None:
        base[:n] &= np.asarray(dedup_mask)[:n]

    with ph._TierCtx(tier):
        packed, uniq, n_obs = _vmapped_sparse_agg_scan(
            cols, jnp.asarray(base), tuple(params),
            shared_where=bound_shared, param_specs=tuple(cols_ops),
            keys=tuple(keys), agg_args=tuple(arg_exprs),
            ops=tuple(sorted(ops)), cap=cap, ts_name=ts_name,
            need_ts=need_ts, tag_names=tag_names, schema=schema,
            acc_dtype=acc_dtype, float_ops=float_ops,
            pack_dtype=pack_dtype)
        host = ph._readback(packed)
        host_uniq = np.asarray(uniq)
    u = int(n_obs)
    if u > cap:
        # the UNION of member windows overflowed the sparse cap; each
        # member alone may still fit, so hand back to the serial paths
        raise VmapIneligible(
            f"batch observed {u} distinct groups over sparse cap {cap}")
    SPARSE_DISPATCHES.inc(path="vmapped")
    SPARSE_COMPACTION_RATIO.set(sparse_ops.compaction_ratio(u, n))

    results = []
    host_info = (scan, extra_cols, bound_shared, bctx, num_groups)
    gids_u = host_uniq[:u]
    for i in range(m):
        acc: dict = {}
        off = 0
        for k in float_ops:
            w = widths[k]
            sl = host[i][:u, off:off + w]
            off += w
            if k in ("count", "rows"):
                sl = sl.astype(np.int64)
            acc[k] = sl
        rows = acc["rows"][:, 0] if acc["rows"].ndim == 2 else acc["rows"]
        present = np.flatnonzero(rows > 0)
        acc = {k: v[present] for k, v in acc.items()}
        results.append(executor._agg_tail(
            acc, gids_u[present], agg, keys, decoders, spec_slot,
            host_info, None, project, None, None, None, table))
    executor.last_path = "sparse_vmapped"
    return results


def _union_member_range(template_where, pspecs, member_values, ts_name,
                        ts_dtype):
    """(lo, hi) covering every member's ts bounds, or None when any
    member is unbounded on either side. Scanning the union is the
    parity-preserving coarse prune: rows outside a member's own window
    are masked by its bound ts parameters on device."""
    lo = hi = None
    lo_open = hi_open = False
    for values in member_values:
        repl = {id(p.conjunct): ast.BinaryOp(
            p.op, ast.Column(p.col), ast.Literal(v))
            for p, v in zip(pspecs, values)}
        member_where = _replace_by_id(template_where, repl)
        r = extract_ts_bounds(member_where, ts_name, ts_dtype)
        mlo, mhi = r if r is not None else (None, None)
        if mlo is None:
            lo_open = True
        elif lo is None or mlo < lo:
            lo = mlo
        if mhi is None:
            hi_open = True
        elif hi is None or mhi > hi:
            hi = mhi
    if lo_open and hi_open:
        return None
    union_range = (None if lo_open else lo, None if hi_open else hi)
    return None if union_range == (None, None) else union_range


# ---- multi-region: vmapped partials over plan fragments ---------------------


@functools.partial(
    jax.jit,
    static_argnames=("shared_where", "param_specs", "keys", "agg_args",
                     "ops", "num_segments", "ts_name", "need_ts",
                     "tag_names", "schema", "acc_dtype"),
)
def _vmapped_partial_scan(
    cols: dict,  # whole-scan padded column arrays (member-invariant)
    base_mask: jax.Array,
    params: tuple,
    *,
    shared_where, param_specs, keys, agg_args, ops, num_segments,
    ts_name, need_ts, tag_names, schema, acc_dtype,
):
    """Region-side member batch: ONE whole-scan segment reduction per
    member over the stacked axis. Deliberately not block-split: the
    serial cluster partial (`partial_region_agg`) reduces the region's
    filtered rows with a single segment_agg, and a masked whole-scan
    fold visits the same rows in the same order with identity elements
    interleaved — bit-for-bit the same per-group result."""

    def member(pvals):
        mask = _member_mask(cols, base_mask, shared_where, param_specs,
                            pvals, tag_names, schema)
        gid = ph._group_ids(cols, keys, mask.shape[0])
        if agg_args:
            values = ph._value_planes(agg_args, cols, tag_names, schema,
                                      mask.shape, acc_dtype)
        else:
            values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
        return segment_agg(values, gid, mask, num_segments, ops=ops,
                           ts=cols[ts_name] if need_ts else None)

    return jax.vmap(member)(params)


def run_vmapped_region_partial(executor, region_id: int, vm: dict,
                               schema=None, *, where=None, ts_range=None,
                               append_mode=False, tz=None):
    """Execute a `vmapped_agg` fragment stage against ONE local region:
    all members' partial aggregates in a single stacked dispatch.
    Returns {"members": [per-member {"keys", "planes"} | None]} — the
    per-member twin of `partial_region_agg`'s output, combined by the
    frontend with the same `combine_partials` Final step — or
    {"vmap_ineligible": reason} when this region cannot serve the batch
    with provable serial parity (the frontend then falls back to
    serial/stacked member execution; typed, never an error)."""
    from greptimedb_tpu.query.expr import reset_session_tz, set_session_tz

    token = set_session_tz(tz)
    try:
        return _region_partial_inner(executor, region_id, vm, schema,
                                     append_mode, ts_range)
    except VmapIneligible as e:
        return {"vmap_ineligible": str(e)}
    finally:
        reset_session_tz(token)


def _region_partial_inner(executor, region_id, vm, schema, append_mode,
                          ts_range=None):
    from types import SimpleNamespace

    from greptimedb_tpu import config
    from greptimedb_tpu.ops.blocks import block_size_for, pad_rows
    from greptimedb_tpu.query.expr import collect_columns

    eng = executor.engine
    probe = eng.region(region_id)
    schema = schema or probe.schema
    ts_name = schema.time_index.name
    keys_spec = list(vm["keys"])
    args = list(vm["args"])
    ops = tuple(sorted(vm["ops"]))
    pspecs = [tuple(p) for p in vm["params"]]
    values = vm["values"]
    m = len(values)
    need_ts = bool({"first", "last"} & set(ops))

    needed: set = {ts_name}
    collect_columns(vm.get("shared_where"), needed)
    for _, kexpr in keys_spec:
        collect_columns(kexpr, needed)
    for a in args:
        collect_columns(a, needed)
    for col, _op in pspecs:
        needed.add(col)
    proj = [c for c in schema.names if c in needed]
    # the fragment's ts_range is the UNION of member windows: index-
    # pruned like the serial per-member pushdown scan; rows outside a
    # member's own window are masked by its ts parameters below
    scan = eng.scan(region_id, ph._closed_range(ts_range), proj, None)
    if scan is None or scan.num_rows == 0:
        return {"members": [None] * m}
    n = scan.num_rows
    bctx = BindContext(schema, scan.tag_dicts)
    shared_ast = vm.get("shared_where")
    bound_shared = bind_expr(shared_ast, bctx) \
        if shared_ast is not None else None

    # stacked parameters bound through the engine's own literal
    # coercion (identical to what each member's serial WHERE would
    # compare against on THIS region's dictionaries)
    cols_ops: list[tuple] = []
    matrix: list[list[int]] = [[] for _ in pspecs]
    for vals in values:
        for j, ((col, op), v) in enumerate(zip(pspecs, vals)):
            name, bop, bval = _bind_param(
                SimpleNamespace(col=col, op=op), v, bctx)
            if len(cols_ops) <= j:
                cols_ops.append((name, bop))
            elif cols_ops[j] != (name, bop):
                raise VmapIneligible("parameter spec drift across members")
            matrix[j].append(bval)

    shim_node = SimpleNamespace(ts_range=None, columns=proj)
    keys: list = []
    decoders: list = []
    extra_cols: dict[str, np.ndarray] = {}
    for i, (name, kexpr) in enumerate(keys_spec):
        dk, decode = executor._plan_key(i, kexpr, bctx, scan, shim_node,
                                        extra_cols)
        keys.append(dk)
        decoders.append(decode)
    num_groups = 1
    for k in keys:
        num_groups *= k.size
    if num_groups > config.dense_groups_max() \
            or num_groups >= ph._GID_SENTINEL:
        raise VmapIneligible(f"group domain {num_groups} needs sparse path")
    mp = _pad_width(m)
    if keys and mp * num_groups > config.dense_groups_max():
        raise VmapIneligible("stacked accumulator exceeds dense budget")

    bound_args = [bind_expr(a, bctx) for a in args]
    for b in bound_args:
        if ph._needs_host_agg(SimpleNamespace(func="sum", arg=b), schema):
            raise VmapIneligible("non-numeric aggregate argument")
    tshim = SimpleNamespace(schema=schema, append_mode=append_mode)
    dedup_mask = executor._maybe_dedup(scan, tshim, bctx)

    # the serial partial computes in float64 (partial_region_agg casts
    # eval_host planes to f64) — match it exactly, even on f32 backends
    acc_dtype = jnp.dtype(jnp.float64)
    tag_names = frozenset(bctx.tag_names)
    names = executor._device_columns(scan, bound_shared, keys,
                                     tuple(bound_args), ts_name,
                                     extra_cols)
    for pname, _op in cols_ops:
        if pname not in names:
            names.append(pname)
    n_pad = block_size_for(n)
    float_fields = {c.name for c in schema.field_columns
                    if c.dtype.is_float}
    dev_cols = {}
    for name in names:
        src = extra_cols[name] if name in extra_cols else scan.columns[name]
        arr = pad_rows(np.asarray(src), n_pad)
        if name in float_fields and arr.dtype != acc_dtype:
            arr = arr.astype(acc_dtype)
        dev_cols[name] = jnp.asarray(arr)
    base = np.arange(n_pad) < n
    base = jnp.asarray(base)
    if dedup_mask is not None:
        base = base & jnp.concatenate(
            [dedup_mask, jnp.zeros(n_pad - n, dtype=bool)])
    params = []
    for j, (pname, _op) in enumerate(cols_ops):
        dt = np.int64 if pname == ts_name else np.int32
        vals = matrix[j] + [matrix[j][-1]] * (mp - m)
        params.append(jnp.asarray(np.asarray(vals, dtype=dt)))

    out = _vmapped_partial_scan(
        dev_cols, base, tuple(params),
        shared_where=bound_shared, param_specs=tuple(cols_ops),
        keys=tuple(keys), agg_args=tuple(bound_args), ops=ops,
        num_segments=num_groups, ts_name=ts_name, need_ts=need_ts,
        tag_names=tag_names, schema=schema, acc_dtype=acc_dtype)
    host = {op: np.asarray(v) for op, v in out.items()}

    strides = ph._strides([k.size for k in keys])
    members = []
    for i in range(m):
        rows = host["rows"][i].reshape(-1)
        if keys:
            present = np.flatnonzero(rows > 0)
            if present.size == 0:
                members.append(None)
                continue
            key_cols = []
            for j, decode in enumerate(decoders):
                idx = (present // strides[j]) % keys[j].size
                col, _dt = decode(idx)
                key_cols.append(np.asarray(col))
        else:
            if rows[0] <= 0:
                members.append(None)
                continue
            present = np.arange(1)
            key_cols = []
        planes = {}
        for op, plane in host.items():
            p = plane[i]
            planes[op] = p[present] if p.ndim >= 1 else p
        members.append({"keys": key_cols, "planes": planes})
    return {"members": members}


_JSON_LITERALS = (str, int, float, bool, type(None))


def _coerce_partial(part: dict) -> dict:
    """Normalize a per-member partial from either transport (in-process
    numpy or JSON lists over Flight) into combine_partials' shape."""
    keys = []
    for k in part["keys"]:
        if isinstance(k, np.ndarray):
            keys.append(k)
            continue
        arr = np.asarray(k, dtype=object)
        vals = arr.tolist()
        if len(vals) and all(
                isinstance(x, (int, np.integer)) and not isinstance(x, bool)
                for x in vals):
            arr = arr.astype(np.int64)  # bucket keys stay int64
        keys.append(arr)
    planes = {}
    for op, v in part["planes"].items():
        planes[op] = v if isinstance(v, np.ndarray) else np.asarray(v)
    return {"keys": keys, "planes": planes}


def _run_vmapped_fragments(executor, sel, info, pspecs, member_values,
                           project, agg, template_where) -> list:
    """Cluster-mode member batch: ship ONE `vmapped_agg` fragment per
    region, combine each member's per-region [G, F] partials with the
    SAME Final step the serial pushdown uses (`combine_partials`), and
    post-process per member. What crosses the wire is partial planes
    per member — today's fallback was IN-list/serial per member over
    the same regions."""
    from concurrent.futures import ThreadPoolExecutor

    from greptimedb_tpu.query.dist_agg import combine_partials
    from greptimedb_tpu.query.expr import current_session_tz
    from greptimedb_tpu.query.plan_ser import PlanFragment
    from greptimedb_tpu.utils import tracing
    from greptimedb_tpu.utils.metrics import FRAGMENT_PUSHDOWNS

    table = info
    for vals in member_values:
        for v in vals:
            if not isinstance(v, _JSON_LITERALS):
                raise VmapIneligible("non-literal member parameter")
    param_ids = {id(p.conjunct) for p in pspecs}
    shared = [c for c in split_conjuncts(template_where)
              if id(c) not in param_ids]
    if len(shared) + len(pspecs) != len(split_conjuncts(template_where)):
        raise VmapIneligible("parameter conjuncts lost in planning")
    shared_where_ast = _rebuild_conjunction(shared)

    arg_exprs: list = []
    spec_slot: list = []
    for spec in agg.aggs:
        if spec.arg is None:
            spec_slot.append(None)
            continue
        if spec.arg not in arg_exprs:
            arg_exprs.append(spec.arg)
        spec_slot.append(arg_exprs.index(spec.arg))
    ops: set = {"rows"}
    for spec in agg.aggs:
        ops.update(ph._PRIMITIVES[spec.func])

    schema = table.schema
    union_range = _union_member_range(
        template_where, pspecs, member_values,
        schema.time_index.name, schema.time_index.dtype)
    stage = {"op": "vmapped_agg",
             "keys": list(agg.keys),
             "args": arg_exprs,
             "ops": sorted(ops),
             "shared_where": shared_where_ast,
             "params": [(p.col, p.op) for p in pspecs],
             "values": [list(vals) for vals in member_values]}
    frag = PlanFragment(stages=[stage], ts_range=union_range,
                        append_mode=table.append_mode,
                        tz=current_session_tz())
    FRAGMENT_PUSHDOWNS.inc(mode="vmapped")
    rids = list(table.region_ids)
    m = len(member_values)
    with tracing.span("vmapped_fragments", regions=len(rids), members=m):
        from greptimedb_tpu.utils import deadline as dl

        one = dl.propagate(tracing.propagate(
            lambda rid: executor.engine.execute_fragment(rid, frag)))
        if len(rids) > 1:
            with ThreadPoolExecutor(
                    max_workers=min(8, len(rids))) as pool:
                resps = list(pool.map(one, rids))
        else:
            resps = [one(rids[0])]

    per_member: list = [[] for _ in range(m)]
    for resp in resps:
        if resp is None:
            continue  # empty region contributes nothing
        if "vmap_ineligible" in resp:
            raise VmapIneligible(str(resp["vmap_ineligible"]))
        members = resp.get("members")
        if members is None or len(members) != m:
            raise VmapIneligible("member count drift across regions")
        for i, part in enumerate(members):
            if part is not None:
                per_member[i].append(_coerce_partial(part))

    results = []
    sorted_ops = tuple(sorted(ops))
    for i in range(m):
        combined = combine_partials(per_member[i], len(agg.keys),
                                    sorted_ops)
        results.append(executor._finalize_combined_agg(
            combined, table, agg, None, project, None, None, None,
            spec_slot))
    executor.last_path = "vmapped_fragments"
    return results


def _replace_by_id(e, repl: dict):
    """Rebuild `e` with nodes replaced by identity (id(node) -> new)."""
    r = repl.get(id(e))
    if r is not None:
        return r
    if isinstance(e, (list, tuple)):
        return type(e)(_replace_by_id(x, repl) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _replace_by_id(v, repl)
                if nv is not v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e
