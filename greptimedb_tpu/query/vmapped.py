"""vmap'd multi-query execution: a batch of parameter-sibling queries
as ONE device program.

The cross-query batcher (concurrency/batcher.py) collects SELECTs that
share a plan shape but differ in parameter literals — which host, which
datacenter, which time window. The previous stacked path rewrote the
group into one IN-list query and demultiplexed the combined result;
that only covers a single tag-equality selector and forces every member
onto the same time window. Here the members' parameters become a
STACKED AXIS instead: the scan, group ids, and value planes are built
once (they are member-invariant), each member contributes only its
per-row predicate mask, and `jax.vmap` maps the masked segment
reduction over the member axis — one dispatch computes an [M, G, F]
accumulator whose member slices are separated by construction. No
rewrite, no demux.

Bit-for-bit parity with serial execution is by masking identity, not by
approximation: the kernel scans the region's full row set and routes
every row a member's WHERE rejects into the dead segment — exactly what
the serial kernels do with their own masks — so a member's per-segment
fold visits precisely the rows its serial run would, in the same order.
Two structural conditions keep the fold association identical too, and
`run_vmapped` refuses (raises `VmapIneligible`, the batcher falls back
to the stacked/serial paths) when they don't hold:

- every scan part maps to ONE device block (so a serial scan of any
  sub-window, which decodes a row-subset of each part, splits partials
  at the same part seams — inserting identity elements into a left fold
  preserves every partial sum exactly);
- the member's whole predicate decomposes into shared conjuncts plus
  `column <op> literal` parameter conjuncts the kernel can evaluate
  from a stacked array (tag equality by dictionary code, time-index
  comparisons in storage units — bound through the SAME `bind_expr`
  the serial path uses, so literal coercion cannot drift).

Window-union batching falls out for free: members with different time
windows share the one full scan and differ only in their ts-comparison
parameters; multi-tag selectors are just several tag parameters.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.query import logical as lp
from greptimedb_tpu.query import physical as ph
from greptimedb_tpu.query.expr import (
    BindContext,
    bind_expr,
    eval_device,
    extract_ts_bounds,
    split_conjuncts,
)
from greptimedb_tpu.ops.segment import segment_agg
from greptimedb_tpu.sql import ast


class VmapIneligible(Exception):
    """This batch group cannot ride the vmapped kernel with provable
    serial parity — the batcher falls back to stacked/serial paths."""


#: member-axis padding buckets: compile one executable per (shape,
#: width bucket) instead of one per batch width
_WIDTH_BUCKETS = (2, 4, 8, 16, 32, 64, 128)


def _pad_width(m: int) -> int:
    for b in _WIDTH_BUCKETS:
        if m <= b:
            return b
    return m


def _rebuild_conjunction(conjuncts: list) -> Optional[ast.Expr]:
    if not conjuncts:
        return None
    e = conjuncts[0]
    for c in conjuncts[1:]:
        e = ast.BinaryOp("and", e, c)
    return e


@functools.partial(
    jax.jit,
    static_argnames=("shared_where", "param_specs", "keys", "agg_args",
                     "ops", "num_segments", "tag_names", "schema",
                     "acc_dtype", "float_ops", "pack_dtype"),
)
def _vmapped_agg_scan(
    blocks: tuple,  # per-block col dicts (member-invariant)
    n_valids: jax.Array,
    dedup_masks,
    params: tuple,  # per-spec [M] stacked parameter arrays
    *,
    shared_where, param_specs, keys, agg_args, ops, num_segments,
    tag_names, schema, acc_dtype, float_ops, pack_dtype,
):
    """One dispatch for M parameter-sibling queries. Everything that
    does not depend on the member parameters (group ids, value planes,
    the shared-predicate mask) is traced once and stays unbatched;
    only the per-member mask and the segment reductions carry the
    vmapped leading axis."""

    def member(pvals):
        acc = None
        for i, cols in enumerate(blocks):
            some = next(iter(cols.values()))
            mask = jnp.arange(some.shape[0]) < n_valids[i]
            if dedup_masks is not None:
                mask = mask & dedup_masks[i]
            if shared_where is not None:
                w = eval_device(shared_where, cols, tag_names, schema)
                mask = mask & (w if w.dtype == jnp.bool_ else w != 0)
            for (name, op), pv in zip(param_specs, pvals):
                c = cols[name]
                if op == "=":
                    mask = mask & (c == pv)
                elif op == "<":
                    mask = mask & (c < pv)
                elif op == "<=":
                    mask = mask & (c <= pv)
                elif op == ">":
                    mask = mask & (c > pv)
                else:  # ">="
                    mask = mask & (c >= pv)
            gid = ph._group_ids(cols, keys, mask.shape[0])
            if agg_args:
                values = ph._value_planes(agg_args, cols, tag_names,
                                          schema, mask.shape, acc_dtype)
            else:
                values = jnp.zeros((mask.shape[0], 1), dtype=acc_dtype)
            part = segment_agg(values, gid, mask, num_segments, ops=ops)
            acc = ph._combine_partials(acc, part)
        parts = []
        for k in float_ops:
            v = acc[k]
            if v.ndim == 1:
                v = v[:, None]
            parts.append(v.astype(pack_dtype))
        return jnp.concatenate(parts, axis=1)

    return jax.vmap(member)(params)


def _bind_param(pspec, value, bctx) -> tuple:
    """One member's value for one parameter conjunct, bound through the
    engine's own literal coercion. Returns (device column name, op,
    bound int). Tag equality binds to a dictionary code, time-index
    comparisons coerce to storage units — identical to what the serial
    path's bound WHERE would compare against."""
    conj = ast.BinaryOp(pspec.op, ast.Column(pspec.col), ast.Literal(value))
    bound = bind_expr(conj, bctx)
    if not (isinstance(bound, ast.BinaryOp)
            and isinstance(bound.left, ast.Column)
            and isinstance(bound.right, ast.Literal)
            and isinstance(bound.right.value, (int, np.integer))
            and not isinstance(bound.right.value, bool)):
        raise VmapIneligible(f"unbindable parameter {pspec.col} {pspec.op}")
    return bound.left.name, bound.op, int(bound.right.value)


def run_vmapped(executor, sel: ast.Select, info, pspecs,
                member_values: list) -> list:
    """Execute `sel`'s shape once for every member value tuple; returns
    QueryResults aligned with `member_values`. Raises VmapIneligible
    when the shape/scan cannot guarantee bit-for-bit serial parity."""
    from greptimedb_tpu import config
    from greptimedb_tpu.query.planner import plan_select

    plan = plan_select(sel, info)
    node = plan
    if not isinstance(node, lp.Project):
        raise VmapIneligible("plan root is not a projection")
    project = node
    node = node.input
    if not isinstance(node, lp.Aggregate):
        raise VmapIneligible("not an aggregate shape")
    agg = node
    node = node.input
    if not isinstance(node, lp.Filter):
        raise VmapIneligible("no predicate to parameterize")
    template_where = node.predicate
    node = node.input
    if not isinstance(node, lp.Scan):
        raise VmapIneligible("unexpected scan node")
    scan_node = node
    table = scan_node.table
    schema = table.schema
    ts_name = schema.time_index.name

    if len(table.region_ids) != 1 or not hasattr(executor.engine, "scan"):
        raise VmapIneligible("multi-region scans gather via fragments")
    if any(ph._needs_host_agg(spec, schema) for spec in agg.aggs):
        raise VmapIneligible("host-side aggregate in batch shape")

    # split the predicate: parameter conjuncts out, shared rest stays.
    # plan_select passes sel.where through by reference, so the
    # batcher-identified conjunct objects are found by identity.
    param_ids = {id(p.conjunct) for p in pspecs}
    shared = [c for c in split_conjuncts(template_where)
              if id(c) not in param_ids]
    if len(shared) + len(pspecs) != len(split_conjuncts(template_where)):
        raise VmapIneligible("parameter conjuncts lost in planning")
    shared_where_ast = _rebuild_conjunction(shared)

    # union time range (drives only the bucket-key domain; the scan
    # itself reads the full region so every member's serial scan is a
    # per-part row-subset of it)
    lo = hi = None
    lo_open = hi_open = False
    for values in member_values:
        repl = {id(p.conjunct): ast.BinaryOp(
            p.op, ast.Column(p.col), ast.Literal(v))
            for p, v in zip(pspecs, values)}
        member_where = _replace_by_id(template_where, repl)
        r = extract_ts_bounds(member_where, ts_name,
                              schema.time_index.dtype)
        mlo, mhi = r if r is not None else (None, None)
        if mlo is None:
            lo_open = True
        elif lo is None or mlo < lo:
            lo = mlo
        if mhi is None:
            hi_open = True
        elif hi is None or mhi > hi:
            hi = mhi
    union_range = None
    if not (lo_open and hi_open):
        union_range = (None if lo_open else lo, None if hi_open else hi)
        if union_range == (None, None):
            union_range = None

    # one scan covering the UNION of the member windows (tag predicates
    # stay None: every member's rows must be present); member masks
    # carve their slices on device. Region.scan's own covering-range
    # widening keeps the parity cases aligned: if any member's serial
    # scan would widen to the full region, the union (a superset range)
    # widens too, so the one-block-per-part gate below always runs over
    # a superset of every member's decoded parts.
    scan = executor.engine.scan(table.region_ids[0],
                                ph._closed_range(union_range),
                                scan_node.columns, None)
    if scan is None or scan.num_rows == 0:
        raise VmapIneligible("empty scan: serial path settles it")
    if table.append_mode and \
            scan.num_rows >= config.stream_threshold_rows():
        raise VmapIneligible("serial path would stream this scan")
    if executor.mesh is not None and \
            scan.num_rows >= config.mesh_min_rows():
        raise VmapIneligible("serial path would shard over the mesh")

    # parity gate: one device block per part seam (see module docstring)
    block_plan = ph._block_plan(scan)
    seen: set = set()
    for entry in block_plan:
        seam = (entry.pkey, entry.part_start)
        if seam in seen:
            raise VmapIneligible("a scan part spans multiple blocks")
        seen.add(seam)

    bctx = BindContext(schema, scan.tag_dicts)
    bound_shared = bind_expr(shared_where_ast, bctx) \
        if shared_where_ast is not None else None

    # stacked parameter matrix: [n_specs][M] bound ints
    cols_ops: list[tuple] = []
    matrix: list[list[int]] = [[] for _ in pspecs]
    for values in member_values:
        for j, (p, v) in enumerate(zip(pspecs, values)):
            name, op, bval = _bind_param(p, v, bctx)
            if len(cols_ops) <= j:
                cols_ops.append((name, op))
            elif cols_ops[j] != (name, op):
                raise VmapIneligible("parameter spec drift across members")
            matrix[j].append(bval)

    # group keys over the union scan; decode is value-based, so a base
    # shift against a member's narrower serial window is invisible
    scan_node_u = lp.Scan(table, scan_node.columns, union_range)
    keys: list = []
    decoders: list = []
    extra_cols: dict[str, np.ndarray] = {}
    for i, (name, kexpr) in enumerate(agg.keys):
        dk, decode = executor._plan_key(i, kexpr, bctx, scan, scan_node_u,
                                        extra_cols)
        keys.append(dk)
        decoders.append(decode)
    num_groups = 1
    for k in keys:
        num_groups *= k.size
    if not keys or num_groups > config.dense_groups_max() \
            or num_groups >= ph._GID_SENTINEL:
        raise VmapIneligible(f"group domain {num_groups} needs sparse path")
    # the stacked axis multiplies the accumulator: bound M*G by the
    # same dense budget one serial query is allowed, so a wide batch
    # over a near-max group domain can't ask XLA for a multi-GB output
    if _pad_width(len(member_values)) * num_groups \
            > config.dense_groups_max():
        raise VmapIneligible("stacked accumulator exceeds dense budget")

    # aggregate layout (mirrors _stream_agg_inner's dense packing)
    arg_exprs: list = []
    spec_slot: list = []
    for spec in agg.aggs:
        if spec.arg is None:
            spec_slot.append(None)
            continue
        b = bind_expr(spec.arg, bctx)
        if b not in arg_exprs:
            arg_exprs.append(b)
        spec_slot.append(arg_exprs.index(b))
    ops: set = {"rows"}
    for spec in agg.aggs:
        ops.update(ph._PRIMITIVES[spec.func])
    if {"first", "last"} & ops:
        raise VmapIneligible("first/last need the ts-paired planes")

    acc_dtype = jnp.dtype(config.compute_dtype())
    nf = max(len(arg_exprs), 1)
    float_ops_l, widths = [], {}
    for op in sorted(ops):
        float_ops_l.append(op)
        widths[op] = 1 if op == "rows" else nf
    float_ops = tuple(float_ops_l)
    pack_dtype = jnp.dtype(jnp.float64) if num_groups <= 4096 else acc_dtype
    if not jnp.issubdtype(pack_dtype, jnp.floating):
        pack_dtype = jnp.dtype(jnp.float64)
    if "sumsq" in float_ops:
        pack_dtype = jnp.dtype(jnp.float64)

    dedup_mask = executor._maybe_dedup(scan, table, bctx)
    tag_names = frozenset(bctx.tag_names)
    float_fields = {c.name for c in schema.field_columns
                    if c.dtype.is_float}
    device_col_names = executor._device_columns(
        scan, bound_shared, keys, tuple(arg_exprs), ts_name, extra_cols)
    for name, _op in cols_ops:
        if name not in device_col_names:
            device_col_names.append(name)

    tier = executor.tier_for(agg, scan.num_rows)
    executor.last_tier = tier

    def fetch_block(entry, prefetch_only=False):
        out = {}
        for name in device_col_names:
            out[name] = executor._device_block(
                scan, name, entry, extra_cols,
                acc_dtype if name in float_fields else None,
                prefetch_only=prefetch_only)
        return out

    m = len(member_values)
    mp = _pad_width(m)
    params = []
    for j, (name, _op) in enumerate(cols_ops):
        dt = np.int64 if name == ts_name else np.int32
        vals = matrix[j] + [matrix[j][-1]] * (mp - m)
        params.append(jnp.asarray(np.asarray(vals, dtype=dt)))

    with ph._TierCtx(tier):
        blocks, n_valids, dmasks = executor._gather_blocks(
            scan, block_plan, fetch_block, dedup_mask)
        packed = _vmapped_agg_scan(
            tuple(blocks), jnp.asarray(np.asarray(n_valids)),
            tuple(dmasks) if dmasks is not None else None,
            tuple(params),
            shared_where=bound_shared, param_specs=tuple(cols_ops),
            keys=tuple(keys), agg_args=tuple(arg_exprs),
            ops=tuple(sorted(ops)), num_segments=num_groups,
            tag_names=tag_names, schema=schema, acc_dtype=acc_dtype,
            float_ops=float_ops, pack_dtype=pack_dtype)
        host = ph._readback(packed)

    results = []
    host_info = (scan, extra_cols, bound_shared, bctx, num_groups)
    for i in range(m):
        acc: dict = {}
        off = 0
        for k in float_ops:
            w = widths[k]
            sl = host[i][:, off:off + w]
            off += w
            if k in ("count", "rows"):
                sl = sl.astype(np.int64)
            acc[k] = sl
        results.append(executor._agg_tail(
            acc, None, agg, keys, decoders, spec_slot, host_info,
            None, project, None, None, None, table))
    executor.last_path = "dense_vmapped"
    return results


def _replace_by_id(e, repl: dict):
    """Rebuild `e` with nodes replaced by identity (id(node) -> new)."""
    r = repl.get(id(e))
    if r is not None:
        return r
    if isinstance(e, (list, tuple)):
        return type(e)(_replace_by_id(x, repl) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type) \
            and not isinstance(e, ast.Statement):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) or (
                    dataclasses.is_dataclass(v)
                    and not isinstance(v, (type, ast.Statement))):
                nv = _replace_by_id(v, repl)
                if nv is not v:
                    changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    return e
