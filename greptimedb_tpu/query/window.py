"""Window function evaluation over in-memory columns.

Mirrors the reference's window-function capability (DataFusion
WindowAggExec behind the forked sqlparser-rs OVER clause,
reference src/query/src/datafusion.rs:66 planner). The TPU-first design
runs windows on host over the materialized relation: the scan + filter
still use the device path, and window output sizes are the post-filter
row counts (dashboards: thousands, not the raw scan).

Semantics implemented:
- ranking: row_number, rank, dense_rank, ntile(k)
- navigation: lag(x[,k[,default]]), lead, first_value, last_value,
  nth_value(x, k)
- aggregates over the window: count, sum, avg/mean, min, max
- frames: the SQL defaults — whole-partition when there is no ORDER BY,
  running-to-current-row (RANGE, peer-sharing) when there is — plus
  explicit `ROWS|RANGE` frames with `UNBOUNDED PRECEDING`, `k PRECEDING`
  (numeric, or an INTERVAL for RANGE over a timestamp order key),
  `CURRENT ROW` and `UNBOUNDED FOLLOWING` bounds. Sliding aggregates run
  as cumulative-sum differences; sliding min/max as a vectorized sparse
  table — no per-row Python, so moving averages over a million rows stay
  array-speed (reference gets the same frames from DataFusion's
  WindowAggExec).
- windows over GROUP BY output in the same SELECT (SQL evaluation
  order: aggregate first, windows over the grouped relation) — see
  split_groupby_window.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np

from greptimedb_tpu.query.expr import PlanError, eval_host
from greptimedb_tpu.sql import ast

_RANKING = {"row_number", "rank", "dense_rank", "ntile"}
_NAV = {"lag", "lead", "first_value", "last_value", "nth_value"}
_WAGGS = {"count", "sum", "avg", "mean", "min", "max"}
SUPPORTED = _RANKING | _NAV | _WAGGS


def contains_window(e) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.over is not None:
            return True
        return any(contains_window(a) for a in e.args)
    if isinstance(e, (list, tuple)):
        return any(contains_window(x) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) and contains_window(v):
                return True
    return False


def select_has_window(sel: ast.Select) -> bool:
    return (any(contains_window(it.expr) for it in sel.items)
            or any(contains_window(ob.expr) for ob in sel.order_by))


def rewrite_select(sel: ast.Select, cols: dict, n: int, resolve,
                   dtypes: Optional[dict] = None):
    """Compute every window call in `sel` over `cols` (mutated: one
    `__win_i` array per distinct call is added) and return a copy of
    `sel` with those calls replaced by column references. The caller's
    normal projection/order machinery then just reads the arrays.
    `dtypes` (column name -> DataType) lets INTERVAL frame offsets
    resolve against timestamp order keys. A SELECT that still carries
    GROUP BY must go through split_groupby_window first."""
    if sel.group_by:
        raise PlanError(
            "window functions cannot be combined with GROUP BY in one "
            "SELECT; aggregate in a subquery or CTE first")

    def dtype_of(e):
        r = resolve(e)
        if isinstance(r, ast.Column) and dtypes:
            return dtypes.get(r.name)
        return None

    calls = collect_window_calls(sel)
    if not calls:
        return sel
    mapping: list[tuple[ast.FuncCall, ast.Column]] = []
    for i, fc in enumerate(calls):
        name = f"__win_{i}"
        cols[name] = _eval_window(fc, cols, n, resolve, dtype_of)
        mapping.append((fc, ast.Column(name)))
    return substitute_window_calls(sel, mapping)


def collect_window_calls(sel: ast.Select) -> list:
    """Distinct window calls in SELECT items and ORDER BY, in first-seen
    order (window args cannot themselves be windows, per SQL)."""
    calls: list[ast.FuncCall] = []

    def collect(e):
        if isinstance(e, ast.FuncCall) and e.over is not None:
            if e not in calls:
                calls.append(e)
            return
        if isinstance(e, (list, tuple)):
            for x in e:
                collect(x)
        elif dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    collect(v)

    for it in sel.items:
        collect(it.expr)
    for ob in sel.order_by:
        collect(ob.expr)
    return calls


def substitute_window_calls(sel: ast.Select, mapping) -> ast.Select:
    """Replace each (call, column) pair in items/ORDER BY, keeping the
    user-visible header when an unaliased call collapses to an internal
    column reference."""

    def replace(e):
        if isinstance(e, ast.FuncCall) and e.over is not None:
            for fc, col in mapping:
                if e == fc:
                    return col
            return e
        if isinstance(e, (list, tuple)):
            return type(e)(replace(x) for x in e)
        if dataclasses.is_dataclass(e) and not isinstance(e, type) \
                and isinstance(e, ast.Expr):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    nv = replace(v)
                    if nv != v:
                        changes[f.name] = nv
            if changes:
                return dataclasses.replace(e, **changes)
        return e

    from greptimedb_tpu.query.join import _expr_name

    items = []
    for it in sel.items:
        ne = replace(it.expr)
        alias = it.alias
        if alias is None and ne != it.expr:
            alias = _expr_name(it.expr)
        items.append(dataclasses.replace(it, expr=ne, alias=alias))
    order_by = [dataclasses.replace(ob, expr=replace(ob.expr))
                for ob in sel.order_by]
    return dataclasses.replace(sel, items=items, order_by=order_by)


# ---- core ------------------------------------------------------------------


def _is_nan(v) -> bool:
    return isinstance(v, float) and v != v


def _factorize(arr) -> np.ndarray:
    """Order-preserving integer codes: codes compare exactly like the
    values, with NULL (None/NaN) sorting last."""
    a = np.asarray(arr)
    if a.dtype == object:
        uniq: dict = {}
        for v in a:
            k = None if v is None or _is_nan(v) else v
            if k not in uniq:
                uniq[k] = None
        keys = sorted((k for k in uniq if k is not None)) + \
            ([None] if None in uniq else [])
        remap = {k: i for i, k in enumerate(keys)}
        return np.asarray(
            [remap[None if v is None or _is_nan(v) else v] for v in a],
            dtype=np.int64)
    if a.dtype.kind == "f":
        b = np.where(np.isnan(a), np.inf, a)
        _, codes = np.unique(b, return_inverse=True)
        return codes.astype(np.int64)
    _, codes = np.unique(a, return_inverse=True)
    return codes.astype(np.int64)


def _composite(codes_list: list[np.ndarray], n: int) -> np.ndarray:
    if not codes_list:
        return np.zeros(n, dtype=np.int64)
    pid = codes_list[0].astype(np.int64)
    for c in codes_list[1:]:
        width = int(c.max()) + 1 if len(c) else 1
        _, pid = np.unique(pid * width + c, return_inverse=True)
        pid = pid.astype(np.int64)
    return pid


def _as_column(v, n: int) -> np.ndarray:
    arr = np.asarray(v)
    if arr.ndim == 0:
        return np.broadcast_to(arr, (n,)).copy()
    return arr


def _eval_window(fc: ast.FuncCall, cols: dict, n: int, resolve,
                 dtype_of=None) -> np.ndarray:
    name = fc.name
    if name not in SUPPORTED:
        raise PlanError(f"unsupported window function {name!r}")
    spec = fc.over

    def ev(e):
        return _as_column(eval_host(resolve(e), cols, None, None, n), n)

    pcodes = [_factorize(ev(p)) for p in spec.partition_by]
    pid = _composite(pcodes, n)
    ocodes = []
    for oexpr, asc in spec.order_by:
        c = _factorize(ev(oexpr))
        ocodes.append(c if asc else -c)
    # lexsort: last key is primary → (order keys reversed, then pid last)
    order = np.lexsort(tuple(reversed(ocodes)) + (pid,)) if ocodes \
        else np.lexsort((pid,))
    pid_s = pid[order]
    new_seg = np.empty(n, dtype=bool)
    if n:
        new_seg[0] = True
        new_seg[1:] = pid_s[1:] != pid_s[:-1]
    # peer rows: same partition AND equal on every order key
    new_peer = new_seg.copy()
    for c in ocodes:
        cs = c[order]
        if n:
            new_peer[1:] |= cs[1:] != cs[:-1]
    seg_id = np.cumsum(new_seg) - 1 if n else np.zeros(0, dtype=np.int64)
    run_id = np.cumsum(new_peer) - 1 if n else np.zeros(0, dtype=np.int64)
    seg_starts = np.flatnonzero(new_seg)
    run_starts = np.flatnonzero(new_peer)
    run_ends = np.append(run_starts[1:] - 1, n - 1) if n else run_starts
    # row number within segment, 1-based
    rn = (np.arange(n) - seg_starts[seg_id] + 1) if n \
        else np.zeros(0, dtype=np.int64)

    unit, fstart, fend = _parse_frame(spec.frame, bool(spec.order_by))
    seg_ends = np.append(seg_starts[1:] - 1, n - 1) if n else seg_starts
    idx = np.arange(n)
    # per-row frame bounds [st, en] (inclusive, sorted positions)
    if fstart[0] == "unbounded":
        st = seg_starts[seg_id] if n else idx
    elif unit == "rows":
        if isinstance(fstart[1], tuple):
            raise PlanError("ROWS frames take a row count, not an INTERVAL")
        st = np.maximum(seg_starts[seg_id], idx - int(fstart[1]))
    else:
        st = _range_frame_starts(spec, fstart[1], ev, order, seg_starts,
                                 seg_id, n, dtype_of)
    if fend[0] == "unbounded":
        en = seg_ends[seg_id] if n else idx
    elif unit == "rows":
        en = idx
    else:
        # RANGE ... CURRENT ROW includes the current row's peers
        en = run_ends[run_id] if n else idx

    out_s = _compute(fc, name, ev, order, n, pid_s, seg_id, run_id,
                     seg_starts, run_starts, seg_ends, rn, st, en)
    out = np.empty(n, dtype=out_s.dtype)
    out[order] = out_s
    return out


_BOUND_RE = re.compile(r"^(.*?)\s+(preceding|following)$")


def _parse_frame(frame: Optional[str], has_order: bool):
    """Frame text -> (unit, start, end). unit "rows"|"range"; start
    ("unbounded",) or ("preceding", k) with k a number or ("interval",
    nanos); end ("current",) or ("unbounded",). No frame text means the
    SQL defaults: whole partition without ORDER BY, RANGE UNBOUNDED
    PRECEDING .. CURRENT ROW with it. Unsupported shapes raise — running
    a moving average as a running sum would be silently wrong."""
    if not frame:
        return (("range", ("unbounded",), ("current",)) if has_order
                else ("rows", ("unbounded",), ("unbounded",)))
    text = " ".join(frame.split())
    m = re.match(r"^(rows|range|groups)\s+(.*)$", text)
    if not m:
        raise PlanError(f"unsupported window frame {frame!r}")
    unit, rest = m.group(1), m.group(2)
    if unit == "groups":
        raise PlanError("GROUPS window frames are not supported")
    if rest.startswith("between "):
        m2 = re.match(r"^between\s+(.*?)\s+and\s+(.*)$", rest)
        if m2 is None:
            raise PlanError(f"unsupported window frame {frame!r}")
        b1, b2 = m2.group(1), m2.group(2)
    else:
        b1, b2 = rest, "current row"
    start = _parse_bound(b1, frame, is_end=False)
    end = _parse_bound(b2, frame, is_end=True)
    if start[0] == "preceding" and not has_order:
        raise PlanError(
            "a window frame with an offset requires ORDER BY")
    return unit, start, end


def _parse_bound(s: str, frame: str, is_end: bool):
    s = s.strip()
    if s == "unbounded preceding" and not is_end:
        return ("unbounded",)
    if s == "current row" and is_end:
        return ("current",)
    if s == "unbounded following" and is_end:
        return ("unbounded",)
    if not is_end:
        m = _BOUND_RE.match(s)
        if m is not None and m.group(2) == "preceding":
            val = m.group(1).strip()
            im = re.match(r"^interval\s+'([^']*)'$", val)
            if im is not None:
                from greptimedb_tpu.sql.parser import Parser

                iv = Parser(f"INTERVAL '{im.group(1)}'").parse_expr()
                return ("preceding", ("interval", iv.nanos))
            try:
                return ("preceding", float(val))
            except ValueError:
                pass
    raise PlanError(
        f"unsupported window frame bound {s!r} in {frame!r}; supported: "
        "UNBOUNDED PRECEDING / <n> PRECEDING / INTERVAL '...' PRECEDING "
        "starts and CURRENT ROW / UNBOUNDED FOLLOWING ends")


def _range_frame_starts(spec, value, ev, order, seg_starts, seg_id, n,
                        dtype_of):
    """Window start indices for RANGE <delta> PRECEDING: first row of the
    current segment whose order-key value >= current - delta. Order keys
    are ascending within each sorted segment, so one global searchsorted
    over a segment-shifted encoding answers every row at once."""
    if len(spec.order_by) != 1:
        raise PlanError(
            "RANGE offset frames require exactly one ORDER BY key")
    oexpr, asc = spec.order_by[0]
    if isinstance(value, tuple):  # ("interval", nanos)
        dt = dtype_of(oexpr) if dtype_of is not None else None
        if dt is None or not getattr(dt, "is_timestamp", False):
            raise PlanError(
                "INTERVAL frame offsets need a timestamp ORDER BY key "
                "of known type; use a numeric offset instead")
        delta = float(value[1] // dt.time_unit.nanos_per_unit)
    else:
        delta = float(value)
    if delta < 0:
        raise PlanError("window frame offsets must be non-negative")
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    vals = np.asarray(ev(oexpr))
    if vals.dtype == object or vals.dtype.kind not in "iuf":
        raise PlanError("RANGE offset frames need a numeric or timestamp "
                        "ORDER BY key")
    # integer order keys (timestamps) stay in int64: a float64 detour
    # loses sub-256ns resolution at epoch-ns magnitudes and the
    # segment-shift encoding compounds it
    exact = vals.dtype.kind in "iu" and float(delta).is_integer()
    v = vals[order].astype(np.int64 if exact else np.float64)
    if not exact and np.isnan(v).any():
        raise PlanError("RANGE offset frames need a non-NULL ORDER BY key")
    if not asc:
        v = -v  # descending: preceding means larger values
    # segment-shifted monotone encoding: strictly increasing across
    # segment seams because the shift exceeds the global value span
    nseg = int(seg_id[-1]) + 1
    if exact:
        d = int(delta)
        # Python-int arithmetic: an int64 subtraction could itself wrap
        span = (int(v.max()) - int(v.min())) if n else 0
        shift = span + d + 1
        if nseg * shift < (1 << 62):  # headroom against int64 overflow
            base = v - int(v.min())
            b = base + seg_id * shift
            starts = np.searchsorted(b, b - d, side="left")
            return np.maximum(starts, seg_starts[seg_id])
        v = v.astype(np.float64)  # astronomically wide: approximate
    delta = float(delta)
    span = float(v.max() - v.min()) if n else 0.0
    shift = span + delta + 1.0
    b = v + seg_id.astype(np.float64) * shift
    starts = np.searchsorted(b, b - delta, side="left")
    return np.maximum(starts, seg_starts[seg_id])


def _arg_values(fc, ev, order, n):
    if not fc.args or isinstance(fc.args[0], ast.Star):
        return None
    return ev(fc.args[0])[order]


def _lit(e, default=None):
    if e is None:
        return default
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.UnaryOp) and e.op == "-" \
            and isinstance(e.operand, ast.Literal):
        return -e.operand.value
    raise PlanError("window offset/default arguments must be literals")


def _range_extreme(mv: np.ndarray, st: np.ndarray, en: np.ndarray, op):
    """min/max over arbitrary inclusive index ranges [st, en] via a
    sparse table: level j holds op over blocks of 2^j, a query combines
    the two blocks covering the range — O(n log n) build, O(n) query,
    all vectorized (the frame machinery's RMQ; no per-row Python)."""
    n = len(mv)
    if n == 0:
        return mv
    length = en - st + 1
    max_level = max(int(np.max(length)).bit_length() - 1, 0)
    tables = [mv]
    for j in range(1, max_level + 1):
        prev = tables[-1]
        half = 1 << (j - 1)
        m_len = len(prev) - half  # level j covers n - 2^j + 1 positions
        tables.append(op(prev[:m_len], prev[half:half + m_len]))
    j = np.maximum(
        np.frexp(length.astype(np.float64))[1] - 1, 0).astype(np.int64)
    out = np.empty(n, dtype=mv.dtype)
    for lvl in range(max_level + 1):
        rows = np.flatnonzero(j == lvl)
        if rows.size == 0:
            continue
        t = tables[lvl]
        a = st[rows]
        b = en[rows] - (1 << lvl) + 1
        out[rows] = op(t[a], t[b])
    return out


def _compute(fc, name, ev, order, n, pid_s, seg_id, run_id, seg_starts,
             run_starts, seg_ends, rn, st, en):
    if name == "row_number":
        return rn.astype(np.int64)
    if name == "rank":
        return rn[run_starts][run_id].astype(np.int64)
    if name == "dense_rank":
        return (run_id - run_id[seg_starts][seg_id] + 1).astype(np.int64)
    if name == "ntile":
        k = int(_lit(fc.args[0] if fc.args else None, 1))
        if k <= 0:
            raise PlanError("ntile() requires a positive bucket count")
        seg_len = (seg_ends - seg_starts + 1)[seg_id]
        # SQL ntile: first (len % k) buckets get ceil(len/k) rows
        base, rem = seg_len // k, seg_len % k
        big = (base + 1) * rem
        r0 = rn - 1
        out = np.where(
            (base > 0) & (r0 < big), r0 // np.maximum(base + 1, 1) + 1,
            np.where(base > 0, (r0 - big) // np.maximum(base, 1) + rem + 1,
                     r0 + 1))
        return np.minimum(out, seg_len).astype(np.int64)

    vals = _arg_values(fc, ev, order, n)
    if vals is None and name != "count":
        raise PlanError(f"window function {name}() requires an argument")
    if name in ("lag", "lead"):
        k = int(_lit(fc.args[1] if len(fc.args) > 1 else None, 1))
        default = _lit(fc.args[2] if len(fc.args) > 2 else None, None)
        if name == "lead":
            k = -k
        idx = np.arange(n) - k
        valid = (idx >= 0) & (idx < n)
        src = np.clip(idx, 0, max(n - 1, 0))
        valid &= pid_s[src] == pid_s  # stay within the partition
        out = np.asarray(vals, dtype=object)[src]
        out[~valid] = default
        return out
    if n == 0:
        return np.empty(0, dtype=object)
    # frame-positional navigation: first/last/nth read directly at the
    # frame bounds (with the default frames these reduce to the classic
    # partition-start / running-end behaviors)
    if name == "first_value":
        return np.asarray(vals, dtype=object)[st]
    if name == "last_value":
        return np.asarray(vals, dtype=object)[en]
    if name == "nth_value":
        k = int(_lit(fc.args[1] if len(fc.args) > 1 else None, 1))
        if k < 1:
            raise PlanError("nth_value() position must be >= 1")
        pos = st + (k - 1)
        ok = pos <= en
        out = np.asarray(vals, dtype=object)[np.minimum(pos, en)]
        out[~ok] = None
        return out

    # windowed aggregates over [st, en]: cumulative-sum differences for
    # sum/count/avg, sparse-table range queries for min/max
    if name == "count" and vals is None:
        fv = np.ones(n, dtype=np.float64)
        valid = np.ones(n, dtype=bool)
    else:
        if vals.dtype == object:
            fv = np.asarray(
                [np.nan if v is None or _is_nan(v) else float(v)
                 for v in vals], dtype=np.float64)
        else:
            fv = vals.astype(np.float64)
        valid = ~np.isnan(fv)
        fv = np.where(valid, fv, 0.0)
    if name in ("min", "max"):
        op = np.minimum if name == "min" else np.maximum
        init = np.inf if name == "min" else -np.inf
        mv = np.where(valid, fv, init)
        m = _range_extreme(mv, st, en, op)
        has = _range_extreme(valid.astype(np.float64), st, en, np.maximum)
        return np.where(has > 0, m, np.nan)
    csum = np.concatenate([[0.0], np.cumsum(fv)])
    ccnt = np.concatenate([[0.0], np.cumsum(valid.astype(np.float64))])
    wsum = csum[en + 1] - csum[st]
    wcnt = ccnt[en + 1] - ccnt[st]
    if name == "count":
        return wcnt.astype(np.int64)
    if name == "sum":
        return np.where(wcnt > 0, wsum, np.nan)
    return np.where(wcnt > 0, wsum / np.maximum(wcnt, 1), np.nan)
