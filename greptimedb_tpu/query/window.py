"""Window function evaluation over in-memory columns.

Mirrors the reference's window-function capability (DataFusion
WindowAggExec behind the forked sqlparser-rs OVER clause,
reference src/query/src/datafusion.rs:66 planner). The TPU-first design
runs windows on host over the materialized relation: the scan + filter
still use the device path, and window output sizes are the post-filter
row counts (dashboards: thousands, not the raw scan).

Semantics implemented:
- ranking: row_number, rank, dense_rank, ntile(k)
- navigation: lag(x[,k[,default]]), lead, first_value, last_value,
  nth_value(x, k)
- aggregates over the window: count, sum, avg/mean, min, max
- frames: the two SQL defaults — whole-partition when there is no ORDER
  BY, running-to-current-row (RANGE, peer-sharing) when there is — plus
  an explicit `... BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING`
  (treated as whole-partition) and `ROWS` (strict per-row running).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from greptimedb_tpu.query.expr import PlanError, eval_host
from greptimedb_tpu.sql import ast

_SUPPORTED_FRAMES = {
    f"{u} {b}" for u in ("rows", "range")
    for b in ("unbounded preceding",
              "between unbounded preceding and current row",
              "between unbounded preceding and unbounded following")
}

_RANKING = {"row_number", "rank", "dense_rank", "ntile"}
_NAV = {"lag", "lead", "first_value", "last_value", "nth_value"}
_WAGGS = {"count", "sum", "avg", "mean", "min", "max"}
SUPPORTED = _RANKING | _NAV | _WAGGS


def contains_window(e) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.over is not None:
            return True
        return any(contains_window(a) for a in e.args)
    if isinstance(e, (list, tuple)):
        return any(contains_window(x) for x in e)
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)) and contains_window(v):
                return True
    return False


def select_has_window(sel: ast.Select) -> bool:
    return (any(contains_window(it.expr) for it in sel.items)
            or any(contains_window(ob.expr) for ob in sel.order_by))


def rewrite_select(sel: ast.Select, cols: dict, n: int, resolve):
    """Compute every window call in `sel` over `cols` (mutated: one
    `__win_i` array per distinct call is added) and return a copy of
    `sel` with those calls replaced by column references. The caller's
    normal projection/order machinery then just reads the arrays."""
    if sel.group_by:
        raise PlanError(
            "window functions cannot be combined with GROUP BY in one "
            "SELECT; aggregate in a subquery or CTE first")
    calls: list[ast.FuncCall] = []

    def collect(e):
        if isinstance(e, ast.FuncCall) and e.over is not None:
            if e not in calls:
                calls.append(e)
            return  # window args cannot themselves be windows (SQL)
        if isinstance(e, (list, tuple)):
            for x in e:
                collect(x)
        elif dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    collect(v)

    for it in sel.items:
        collect(it.expr)
    for ob in sel.order_by:
        collect(ob.expr)
    if not calls:
        return sel
    mapping: list[tuple[ast.FuncCall, ast.Column]] = []
    for i, fc in enumerate(calls):
        name = f"__win_{i}"
        cols[name] = _eval_window(fc, cols, n, resolve)
        mapping.append((fc, ast.Column(name)))

    def replace(e):
        if isinstance(e, ast.FuncCall) and e.over is not None:
            for fc, col in mapping:
                if e == fc:
                    return col
            return e
        if isinstance(e, (list, tuple)):
            return type(e)(replace(x) for x in e)
        if dataclasses.is_dataclass(e) and not isinstance(e, type) \
                and isinstance(e, ast.Expr):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (ast.Expr, list, tuple)):
                    nv = replace(v)
                    if nv != v:
                        changes[f.name] = nv
            if changes:
                return dataclasses.replace(e, **changes)
        return e

    items = [dataclasses.replace(it, expr=replace(it.expr))
             for it in sel.items]
    order_by = [dataclasses.replace(ob, expr=replace(ob.expr))
                for ob in sel.order_by]
    return dataclasses.replace(sel, items=items, order_by=order_by)


# ---- core ------------------------------------------------------------------


def _is_nan(v) -> bool:
    return isinstance(v, float) and v != v


def _factorize(arr) -> np.ndarray:
    """Order-preserving integer codes: codes compare exactly like the
    values, with NULL (None/NaN) sorting last."""
    a = np.asarray(arr)
    if a.dtype == object:
        uniq: dict = {}
        for v in a:
            k = None if v is None or _is_nan(v) else v
            if k not in uniq:
                uniq[k] = None
        keys = sorted((k for k in uniq if k is not None)) + \
            ([None] if None in uniq else [])
        remap = {k: i for i, k in enumerate(keys)}
        return np.asarray(
            [remap[None if v is None or _is_nan(v) else v] for v in a],
            dtype=np.int64)
    if a.dtype.kind == "f":
        b = np.where(np.isnan(a), np.inf, a)
        _, codes = np.unique(b, return_inverse=True)
        return codes.astype(np.int64)
    _, codes = np.unique(a, return_inverse=True)
    return codes.astype(np.int64)


def _composite(codes_list: list[np.ndarray], n: int) -> np.ndarray:
    if not codes_list:
        return np.zeros(n, dtype=np.int64)
    pid = codes_list[0].astype(np.int64)
    for c in codes_list[1:]:
        width = int(c.max()) + 1 if len(c) else 1
        _, pid = np.unique(pid * width + c, return_inverse=True)
        pid = pid.astype(np.int64)
    return pid


def _as_column(v, n: int) -> np.ndarray:
    arr = np.asarray(v)
    if arr.ndim == 0:
        return np.broadcast_to(arr, (n,)).copy()
    return arr


def _eval_window(fc: ast.FuncCall, cols: dict, n: int, resolve) -> np.ndarray:
    name = fc.name
    if name not in SUPPORTED:
        raise PlanError(f"unsupported window function {name!r}")
    spec = fc.over

    def ev(e):
        return _as_column(eval_host(resolve(e), cols, None, None, n), n)

    pcodes = [_factorize(ev(p)) for p in spec.partition_by]
    pid = _composite(pcodes, n)
    ocodes = []
    for oexpr, asc in spec.order_by:
        c = _factorize(ev(oexpr))
        ocodes.append(c if asc else -c)
    # lexsort: last key is primary → (order keys reversed, then pid last)
    order = np.lexsort(tuple(reversed(ocodes)) + (pid,)) if ocodes \
        else np.lexsort((pid,))
    pid_s = pid[order]
    new_seg = np.empty(n, dtype=bool)
    if n:
        new_seg[0] = True
        new_seg[1:] = pid_s[1:] != pid_s[:-1]
    # peer rows: same partition AND equal on every order key
    new_peer = new_seg.copy()
    for c in ocodes:
        cs = c[order]
        if n:
            new_peer[1:] |= cs[1:] != cs[:-1]
    seg_id = np.cumsum(new_seg) - 1 if n else np.zeros(0, dtype=np.int64)
    run_id = np.cumsum(new_peer) - 1 if n else np.zeros(0, dtype=np.int64)
    seg_starts = np.flatnonzero(new_seg)
    run_starts = np.flatnonzero(new_peer)
    run_ends = np.append(run_starts[1:] - 1, n - 1) if n else run_starts
    # row number within segment, 1-based
    rn = (np.arange(n) - seg_starts[seg_id] + 1) if n \
        else np.zeros(0, dtype=np.int64)

    frame = " ".join((spec.frame or "").split())
    if frame and frame not in _SUPPORTED_FRAMES:
        # executing an unsupported frame as a different one would return
        # silently wrong numbers (e.g. a moving average as a running sum)
        raise PlanError(
            f"unsupported window frame {spec.frame!r}; supported: "
            "default, [ROWS|RANGE] UNBOUNDED PRECEDING, and "
            "[ROWS|RANGE] BETWEEN UNBOUNDED PRECEDING AND "
            "[CURRENT ROW|UNBOUNDED FOLLOWING]")
    whole = (not spec.order_by) or "unbounded following" in frame
    rows_frame = frame.startswith("rows")

    out_s = _compute(fc, name, ev, order, n, pid_s, new_seg, seg_id,
                     run_id, seg_starts, run_starts, run_ends, rn,
                     whole, rows_frame)
    out = np.empty(n, dtype=out_s.dtype)
    out[order] = out_s
    return out


def _arg_values(fc, ev, order, n):
    if not fc.args or isinstance(fc.args[0], ast.Star):
        return None
    return ev(fc.args[0])[order]


def _lit(e, default=None):
    if e is None:
        return default
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.UnaryOp) and e.op == "-" \
            and isinstance(e.operand, ast.Literal):
        return -e.operand.value
    raise PlanError("window offset/default arguments must be literals")


def _compute(fc, name, ev, order, n, pid_s, new_seg, seg_id, run_id,
             seg_starts, run_starts, run_ends, rn, whole, rows_frame):
    if name == "row_number":
        return rn.astype(np.int64)
    if name == "rank":
        return rn[run_starts][run_id].astype(np.int64)
    if name == "dense_rank":
        return (run_id - run_id[seg_starts][seg_id] + 1).astype(np.int64)
    if name == "ntile":
        k = int(_lit(fc.args[0] if fc.args else None, 1))
        if k <= 0:
            raise PlanError("ntile() requires a positive bucket count")
        seg_ends = np.append(seg_starts[1:] - 1, n - 1) if n else seg_starts
        seg_len = (seg_ends - seg_starts + 1)[seg_id]
        # SQL ntile: first (len % k) buckets get ceil(len/k) rows
        base, rem = seg_len // k, seg_len % k
        big = (base + 1) * rem
        r0 = rn - 1
        out = np.where(
            (base > 0) & (r0 < big), r0 // np.maximum(base + 1, 1) + 1,
            np.where(base > 0, (r0 - big) // np.maximum(base, 1) + rem + 1,
                     r0 + 1))
        return np.minimum(out, seg_len).astype(np.int64)

    vals = _arg_values(fc, ev, order, n)
    if vals is None and name != "count":
        raise PlanError(f"window function {name}() requires an argument")
    if name in ("lag", "lead"):
        k = int(_lit(fc.args[1] if len(fc.args) > 1 else None, 1))
        default = _lit(fc.args[2] if len(fc.args) > 2 else None, None)
        if name == "lead":
            k = -k
        out = np.empty(n, dtype=object)
        idx = np.arange(n) - k
        valid = (idx >= 0) & (idx < n)
        src = np.clip(idx, 0, max(n - 1, 0))
        valid &= pid_s[src] == pid_s  # stay within the partition
        for i in range(n):
            out[i] = vals[src[i]] if valid[i] else default
        return out
    if name == "first_value":
        return np.asarray(vals, dtype=object)[seg_starts[seg_id]] if n \
            else np.empty(0, dtype=object)
    if name == "nth_value":
        k = int(_lit(fc.args[1] if len(fc.args) > 1 else None, 1))
        if k < 1:
            raise PlanError("nth_value() position must be >= 1")
        pos = seg_starts[seg_id] + (k - 1)
        seg_ends = np.append(seg_starts[1:] - 1, n - 1) if n else seg_starts
        ok = pos <= seg_ends[seg_id]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = vals[pos[i]] if ok[i] else None
        return out
    if name == "last_value":
        if n == 0:
            return np.empty(0, dtype=object)
        seg_ends = np.append(seg_starts[1:] - 1, n - 1)
        if whole:
            return np.asarray(vals, dtype=object)[seg_ends[seg_id]]
        if rows_frame:
            return np.asarray(vals, dtype=object)
        return np.asarray(vals, dtype=object)[run_ends[run_id]]

    # windowed aggregates
    if name == "count" and vals is None:
        fv = np.ones(n, dtype=np.float64)
        valid = np.ones(n, dtype=bool)
    else:
        fv = np.asarray(
            [np.nan if v is None or _is_nan(v) else float(v)
             for v in vals], dtype=np.float64)
        valid = ~np.isnan(fv)
        fv = np.where(valid, fv, 0.0)
    if whole:
        nseg = len(seg_starts)
        s = np.zeros(nseg)
        cnt = np.zeros(nseg)
        np.add.at(s, seg_id, fv)
        np.add.at(cnt, seg_id, valid.astype(np.float64))
        if name == "count":
            return cnt[seg_id].astype(np.int64)
        if name == "sum":
            return np.where(cnt[seg_id] > 0, s[seg_id], np.nan)
        if name in ("avg", "mean"):
            return np.where(cnt[seg_id] > 0,
                            s[seg_id] / np.maximum(cnt[seg_id], 1), np.nan)
        # min / max per segment
        init = np.inf if name == "min" else -np.inf
        m = np.full(nseg, init)
        mv = np.where(valid, fv, init)
        (np.minimum if name == "min" else np.maximum).at(m, seg_id, mv)
        return np.where(cnt[seg_id] > 0, m[seg_id], np.nan)
    # running frame: cumulative within segment (peer-shared unless ROWS)
    csum = np.cumsum(fv)
    ccnt = np.cumsum(valid.astype(np.float64))
    base_sum = np.where(seg_starts > 0, csum[seg_starts - 1], 0.0)
    base_cnt = np.where(seg_starts > 0, ccnt[seg_starts - 1], 0.0)
    run_sum = csum - base_sum[seg_id]
    run_cnt = ccnt - base_cnt[seg_id]
    if name in ("min", "max"):
        op = np.minimum if name == "min" else np.maximum
        init = np.inf if name == "min" else -np.inf
        mv = np.where(valid, fv, init)
        run_m = np.empty(n, dtype=np.float64)
        for s0 in seg_starts:
            e0 = n
            nxt = np.searchsorted(seg_starts, s0 + 1)
            if nxt < len(seg_starts):
                e0 = seg_starts[nxt]
            run_m[s0:e0] = op.accumulate(mv[s0:e0])
        run_val = np.where(np.isfinite(run_m), run_m, np.nan)
    elif name == "count":
        run_val = run_cnt
    elif name == "sum":
        run_val = np.where(run_cnt > 0, run_sum, np.nan)
    else:  # avg / mean
        run_val = np.where(run_cnt > 0, run_sum / np.maximum(run_cnt, 1),
                           np.nan)
    if not rows_frame:
        # RANGE default frame: peers share the value at the peer-run end
        run_val = run_val[run_ends[run_id]]
    if name == "count":
        return run_val.astype(np.int64)
    return run_val
