"""Script engine: Python coprocessors (mirrors reference `src/script`:
the `@coprocessor` decorator binding query columns to function arguments,
scripts-table persistence, and the /v1/scripts + /v1/run-script HTTP
endpoints — src/script/src/python/, manager.rs).

The reference embeds a Python *guest* VM (RustPython / PyO3) inside a
Rust host. Here the host tier is already Python, so scripts execute
natively in a scoped namespace with numpy + jax available — coprocessor
bodies can jit straight onto the TPU device, which is strictly more
powerful than the reference's vector API.

A coprocessor:

    @coprocessor(args=["host", "usage"], returns=["host", "doubled"],
                 sql="SELECT host, usage FROM cpu")
    def double(host, usage):
        return host, usage * 2

`args` bind the SQL result's columns (numpy arrays) to parameters;
returned arrays (tuple, or single value) become the result columns named
by `returns`. Scripts persist in the catalog kv (the reference's
scripts table, src/script/src/manager.rs).
"""

from __future__ import annotations

import builtins
import ctypes
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from greptimedb_tpu.query.result import QueryResult

SCRIPT_PREFIX = "__script/"


class ScriptError(Exception):
    pass


class ScriptTimeout(ScriptError):
    pass


class _Killed(BaseException):
    """Injected into a runaway script thread. Derives BaseException so a
    script's own `except Exception` handler cannot swallow it and keep
    spinning."""


# ---- sandbox ---------------------------------------------------------------
#
# The reference embeds a RustPython VM, which is a hard boundary
# (src/script/Cargo.toml:9-20). Executing natively we settle for
# defense-in-depth: a curated builtins table (no open/exec/eval, no
# arbitrary __import__) plus a wall-clock limit. This blocks the
# straightforward file/network/runaway-loop abuse an authenticated
# script user could attempt; it is NOT a security boundary against a
# determined attacker (CPython introspection escapes exist), which is
# why script endpoints also sit behind auth. Opt out with
# GREPTIMEDB_TPU_SCRIPT_SANDBOX=off for trusted deployments that want
# full-power scripts.

_ALLOWED_MODULES = {
    "numpy", "jax", "math", "statistics", "json", "datetime", "itertools",
    "functools", "collections", "re", "bisect", "heapq", "random",
}

_SAFE_BUILTIN_NAMES = (
    "abs", "all", "any", "bool", "bytes", "callable", "chr", "complex",
    "dict", "divmod", "enumerate", "filter", "float", "format",
    "frozenset", "hash", "hex", "int", "isinstance", "issubclass", "iter",
    "len", "list", "map", "max", "min", "next", "object", "oct", "ord",
    "pow", "print", "range", "repr", "reversed", "round", "set", "slice",
    "sorted", "str", "sum", "tuple", "zip",
    # exceptions scripts legitimately raise/catch
    "ArithmeticError", "AttributeError", "BaseException", "Exception",
    "IndexError", "KeyError", "LookupError", "NameError",
    "NotImplementedError", "OverflowError", "RuntimeError",
    "StopIteration", "TypeError", "ValueError", "ZeroDivisionError",
)


def _guarded_import(name, globals=None, locals=None, fromlist=(), level=0):
    root = name.split(".")[0]
    if root not in _ALLOWED_MODULES:
        raise ScriptError(
            f"import of {name!r} is not allowed in scripts (allowed: "
            f"{', '.join(sorted(_ALLOWED_MODULES))})")
    return __import__(name, globals, locals, fromlist, level)


def _safe_builtins() -> dict:
    table = {n: getattr(builtins, n) for n in _SAFE_BUILTIN_NAMES}
    table["__import__"] = _guarded_import
    return table


def _sandbox_enabled() -> bool:
    return os.environ.get("GREPTIMEDB_TPU_SCRIPT_SANDBOX", "on").lower() \
        not in ("off", "0", "false", "no", "disabled")


def _script_timeout_s() -> float:
    return float(os.environ.get("GREPTIMEDB_TPU_SCRIPT_TIMEOUT_S", "30"))


def _run_limited(fn, timeout_s: float):
    """Run `fn` under a wall-clock cap. A runaway pure-Python loop is
    interrupted with an async exception (PyThreadState_SetAsyncExc);
    code stuck inside a C call cannot be interrupted and the worker
    thread is abandoned (daemon) after the caller gets its timeout."""
    out: dict = {}

    def worker():
        try:
            out["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — reported to caller
            out["error"] = e

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        tid = t.ident
        if tid is not None:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(tid), ctypes.py_object(_Killed))
        t.join(1.0)
        raise ScriptTimeout(
            f"script exceeded the {timeout_s:.0f}s wall-clock limit")
    if "error" in out:
        err = out["error"]
        if isinstance(err, _Killed):
            raise ScriptTimeout("script exceeded the wall-clock limit")
        raise err
    return out.get("value")


@dataclass
class Coprocessor:
    fn: Callable
    args: list[str] = field(default_factory=list)
    returns: list[str] = field(default_factory=list)
    sql: Optional[str] = None


def coprocessor(args=None, returns=None, sql=None, backend=None):
    """The @coprocessor / @copr decorator (reference
    src/script/src/python/ffi_types/copr.rs)."""

    def deco(fn):
        fn.__coprocessor__ = Coprocessor(
            fn, list(args or []), list(returns or []), sql)
        return fn

    return deco


copr = coprocessor


class ScriptEngine:
    """Compile, persist, and run scripts against the query engine.

    Sandboxed (default): scripts execute in a separate worker PROCESS
    (script/worker.py) — the address-space boundary the reference gets
    from its embedded RustPython VM. A CPython introspection escape
    lands in the worker, which holds no engine state; a timeout kills
    the worker outright, so no runaway loop survives. The worker stays
    warm between runs and is respawned after a kill. `query(...)` calls
    from scripts are serviced by the parent over the pipe. Sandbox off
    (GREPTIMEDB_TPU_SCRIPT_SANDBOX=off): scripts run in-process with
    full power (direct accelerator access)."""

    def __init__(self, query_engine):
        self.qe = query_engine
        self.kv = query_engine.catalog.kv
        self._worker = None  # (Process, Connection)
        self._worker_lock = threading.Lock()

    # ---- sandbox worker lifecycle ------------------------------------------

    def _ensure_worker(self):
        if self._worker is not None and self._worker[0].poll() is None:
            return self._worker
        # an explicit subprocess (`python -m greptimedb_tpu.script.worker`)
        # rather than multiprocessing spawn: spawn re-imports the parent's
        # __main__, which re-runs CLI entrypoints and breaks entirely for
        # stdin-launched servers; a fork would inherit the initialized
        # jax/XLA runtime whose threads don't survive forking. The worker
        # dials back over an authenticated unix socket.
        import subprocess
        import sys
        import tempfile
        import uuid
        from multiprocessing.connection import Listener

        addr = os.path.join(tempfile.gettempdir(),
                            f"gtpu_script_{os.getpid()}_{uuid.uuid4().hex}")
        authkey = os.urandom(16)
        listener = Listener(addr, family="AF_UNIX", authkey=authkey)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ,
                   GTPU_SCRIPT_AUTHKEY=authkey.hex(),
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       p for p in (repo_root,
                                   os.environ.get("PYTHONPATH")) if p))
        proc = subprocess.Popen(
            [sys.executable, "-m", "greptimedb_tpu.script.worker", addr,
             str(_script_timeout_s())],
            env=env)
        try:
            listener._listener._socket.settimeout(60)
            conn = listener.accept()
        except Exception as e:
            proc.kill()
            raise ScriptError(
                f"script worker failed to start: {e}") from e
        finally:
            listener.close()
            try:
                os.unlink(addr)
            except OSError:
                pass
        self._worker = (proc, conn)
        return self._worker

    def _kill_worker(self):
        if self._worker is None:
            return
        proc, conn = self._worker
        self._worker = None
        try:
            conn.close()
        except OSError:
            pass
        proc.kill()
        try:
            proc.wait(5)
        except Exception:  # noqa: BLE001 — best-effort reap
            pass

    def close(self):
        self._kill_worker()

    def _rpc(self, msg, db: str):
        """One request to the sandbox worker under the wall-clock cap,
        servicing `query` callbacks; kills the worker on timeout (the
        post-timeout CPU-burn fix — a dead process cannot spin)."""
        import time as _time

        from greptimedb_tpu.session import Channel, QueryContext

        timeout_s = _script_timeout_s()
        with self._worker_lock:
            proc, conn = self._ensure_worker()
            deadline = _time.monotonic() + timeout_s
            try:
                conn.send(msg)
            except (OSError, ValueError) as e:
                self._kill_worker()
                raise ScriptError(f"script worker unavailable: {e}") from e
            while True:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    self._kill_worker()
                    raise ScriptTimeout(
                        f"script exceeded the {timeout_s:.0f}s "
                        "wall-clock limit")
                if proc.poll() is not None:
                    self._kill_worker()
                    raise ScriptError("script worker died")
                if not conn.poll(min(remaining, 0.05)):
                    continue
                try:
                    resp = conn.recv()
                except (EOFError, OSError):
                    self._kill_worker()
                    raise ScriptError("script worker died")
                if resp[0] == "query":
                    ctx = QueryContext(db=db, channel=Channel.HTTP)
                    try:
                        r = self.qe.execute_one(resp[1], ctx)
                        conn.send(("cols", dict(zip(r.names, r.columns))))
                    except Exception as e:  # noqa: BLE001 — reported into the script
                        conn.send(("err", str(e)))
                    continue
                return resp

    # ---- persistence (reference scripts table, manager.rs) -----------------

    def insert_script(self, db: str, name: str, code: str) -> None:
        # validate before persisting — in the sandbox worker, because
        # validation EXECUTES the script's top level
        if _sandbox_enabled():
            resp = self._rpc(("validate", code), db)
            if resp[0] == "err":
                raise ScriptError(f"script failed to compile/run: {resp[1]}")
        else:
            self._compile(code)
        self.kv.put(f"{SCRIPT_PREFIX}{db}/{name}", json.dumps({"code": code}))

    def get_script(self, db: str, name: str) -> Optional[str]:
        raw = self.kv.get(f"{SCRIPT_PREFIX}{db}/{name}")
        return json.loads(raw)["code"] if raw else None

    def list_scripts(self, db: str) -> list[str]:
        prefix = f"{SCRIPT_PREFIX}{db}/"
        return sorted(k[len(prefix):] for k, _ in self.kv.range(prefix))

    def delete_script(self, db: str, name: str) -> None:
        self.kv.delete(f"{SCRIPT_PREFIX}{db}/{name}")

    # ---- execution ---------------------------------------------------------

    def run_script(self, db: str, name: str,
                   params: Optional[dict] = None) -> QueryResult:
        code = self.get_script(db, name)
        if code is None:
            raise ScriptError(f"script {db}.{name} not found")
        return self.execute(code, db=db, params=params)

    def execute(self, code: str, db: str = "public",
                params: Optional[dict] = None) -> QueryResult:
        if _sandbox_enabled():
            resp = self._rpc(("run", code, params), db)
            if resp[0] == "err":
                raise ScriptError(f"script failed: {resp[1]}")
            _, out, returns = resp
            return self._wrap(out, returns)
        copr_meta = self._compile(code)
        from greptimedb_tpu.session import Channel, QueryContext

        ctx = QueryContext(db=db, channel=Channel.HTTP)
        # bind args from the coprocessor's SQL (or params only)
        arg_values = []
        if copr_meta.sql:
            result = self.qe.execute_one(copr_meta.sql, ctx)
            cols = dict(zip(result.names, result.columns))
            for a in copr_meta.args:
                if a not in cols:
                    raise ScriptError(
                        f"arg {a!r} not in SQL result columns {result.names}")
                arg_values.append(cols[a])
        elif copr_meta.args:
            params = params or {}
            for a in copr_meta.args:
                if a not in params:
                    raise ScriptError(f"missing param {a!r}")
                arg_values.append(params[a])
        try:
            out = copr_meta.fn(*arg_values)
        except ScriptError:
            raise
        except Exception as e:  # noqa: BLE001 — user code boundary
            raise ScriptError(f"script failed: {e}") from e
        return self._wrap(out, copr_meta.returns)

    def _compile(self, code: str) -> Coprocessor:
        import jax
        import jax.numpy as jnp

        namespace = {
            "coprocessor": coprocessor, "copr": coprocessor,
            "np": np, "numpy": np, "jax": jax, "jnp": jnp,
            "query": self._query_api,
        }
        sandboxed = _sandbox_enabled()
        if sandboxed:
            # restricted builtins bind to the module namespace, so the
            # coprocessor function body stays restricted when it runs
            # later (its __globals__ IS this namespace)
            namespace["__builtins__"] = _safe_builtins()

        def run():
            exec(compile(code, "<script>", "exec"), namespace)  # noqa: S102 — server-side scripting is the feature

        try:
            if sandboxed:
                _run_limited(run, _script_timeout_s())
            else:
                run()
        except ScriptError:
            raise
        except Exception as e:  # noqa: BLE001 — user code boundary
            raise ScriptError(f"script failed to compile/run: {e}") from e
        for v in namespace.values():
            meta = getattr(v, "__coprocessor__", None)
            if meta is not None:
                return meta
        raise ScriptError("script defines no @coprocessor function")

    def _query_api(self, sql: str, db: str = "public") -> dict:
        """`query("SELECT ...")` inside scripts → dict of numpy columns
        (reference exposes a query engine handle to scripts the same way)."""
        result = self.qe.execute_one(sql)
        return dict(zip(result.names, result.columns))

    def _wrap(self, out, returns) -> QueryResult:
        if isinstance(out, QueryResult):
            return out
        if not isinstance(out, tuple):
            out = (out,)
        cols = []
        n = None
        for v in out:
            arr = np.asarray(v)
            if arr.ndim == 0:
                arr = arr.reshape(1)
            cols.append(arr)
            n = max(n or 0, len(arr))
        cols = [np.resize(c, n) if len(c) != n else c for c in cols]
        names = returns or [f"col{i}" for i in range(len(cols))]
        if len(names) != len(cols):
            raise ScriptError(
                f"script returned {len(cols)} columns, "
                f"`returns` names {len(names)}")
        return QueryResult(names, [None] * len(cols), cols)
