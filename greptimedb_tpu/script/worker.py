"""Script sandbox worker: the child-process side of the script engine.

The reference embeds a RustPython guest VM (src/script/Cargo.toml:9-20) —
a real address-space boundary between user scripts and the database. The
analog here is a separate OS process: scripts compile and run INSIDE this
worker, so a CPython introspection escape
(().__class__.__mro__[1].__subclasses__() → os) lands in a throwaway
process that holds no engine state, no credentials, and no server memory;
a runaway loop dies with the process when the parent kills it on timeout
(no abandoned daemon threads burning CPU).

Protocol (multiprocessing Pipe, pickle framing), parent-driven:
  ("validate", code)      -> ("meta", args, returns, sql) | ("err", msg)
  ("run", code, params)   -> ("ok", out, returns) | ("err", msg)
  while running, the worker may issue ("query", sql) upward; the parent
  answers with ("cols", {name: ndarray}) | ("err", msg).

Kept import-light: numpy only. Scripts may import jax (allowlist), which
initializes a fresh CPU backend in this process — device scripting wants
the sandbox off (trusted deployments)."""

from __future__ import annotations

import os
import resource


def _set_limits(timeout_s: float) -> None:
    """Belt-and-braces CPU ceiling: the parent's wall-clock kill is the
    primary control; RLIMIT_CPU catches a worker whose parent died. Soft
    limit tracks CPU already spent so a long-lived warm worker is not
    progressively starved."""
    try:
        used = resource.getrusage(resource.RUSAGE_SELF).ru_utime
        budget = int(used + timeout_s + 10)
        _, hard = resource.getrlimit(resource.RLIMIT_CPU)
        if hard != resource.RLIM_INFINITY:
            budget = min(budget, hard)
        resource.setrlimit(resource.RLIMIT_CPU, (budget, hard))
    except (ValueError, OSError):
        pass  # limits are advisory hardening, never a crash


def worker_main(conn, timeout_s: float) -> None:
    # the sandbox must not inherit a live accelerator tunnel: a hung TPU
    # init inside a user script would wedge the worker inside a C call
    os.environ["JAX_PLATFORMS"] = "cpu"
    from greptimedb_tpu.script import (
        ScriptError,
        _safe_builtins,
        coprocessor,
    )

    import numpy as np

    def remote_query(sql: str, db: str = "public") -> dict:
        conn.send(("query", sql))
        kind, payload = conn.recv()
        if kind == "err":
            raise ScriptError(payload)
        return payload

    def compile_script(code: str):
        import jax

        # the env var alone is overridden by the host's sitecustomize at
        # interpreter start; config.update is what actually pins CPU
        # (same recipe as tests/conftest.py) — without it a jax-using
        # script would hang on the accelerator tunnel inside the sandbox
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        namespace = {
            "coprocessor": coprocessor, "copr": coprocessor,
            "np": np, "numpy": np, "jax": jax, "jnp": jnp,
            "query": remote_query,
            "__builtins__": _safe_builtins(),
        }
        exec(compile(code, "<script>", "exec"), namespace)  # noqa: S102 — the sandboxed scripting feature itself
        for v in namespace.values():
            meta = getattr(v, "__coprocessor__", None)
            if meta is not None:
                return meta
        raise ScriptError("script defines no @coprocessor function")

    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        _set_limits(timeout_s)
        try:
            if msg[0] == "validate":
                meta = compile_script(msg[1])
                conn.send(("meta", meta.args, meta.returns, meta.sql))
            elif msg[0] == "run":
                _, code, params = msg
                meta = compile_script(code)
                if meta.sql:
                    cols = remote_query(meta.sql)
                    for a in meta.args:
                        if a not in cols:
                            raise ScriptError(
                                f"arg {a!r} not in SQL result columns "
                                f"{sorted(cols)}")
                    args = [cols[a] for a in meta.args]
                elif meta.args:
                    params = params or {}
                    for a in meta.args:
                        if a not in params:
                            raise ScriptError(f"missing param {a!r}")
                    args = [params[a] for a in meta.args]
                else:
                    args = []
                out = meta.fn(*args)
                if not isinstance(out, tuple):
                    out = (out,)
                conn.send(("ok", tuple(np.asarray(v) for v in out),
                           meta.returns))
            else:
                conn.send(("err", f"unknown op {msg[0]!r}"))
        except BaseException as e:  # noqa: BLE001 — everything reports upward
            try:
                conn.send(("err", f"{type(e).__name__}: {e}"))
            except (OSError, ValueError):
                return


if __name__ == "__main__":
    import sys
    from multiprocessing.connection import Client

    _addr, _timeout = sys.argv[1], float(sys.argv[2])
    _key = bytes.fromhex(os.environ.pop("GTPU_SCRIPT_AUTHKEY"))
    worker_main(Client(_addr, authkey=_key), _timeout)
