"""Protocol servers (mirrors reference src/servers, ~23k LoC: axum HTTP,
tonic gRPC/Flight, MySQL, Postgres wire...).

Round 1 surface: the HTTP server — /v1/sql, the Prometheus query API,
InfluxDB line-protocol and OpenTSDB ingestion, /metrics. gRPC/Flight and
the MySQL/Postgres wire protocols follow in later rounds.
"""

from greptimedb_tpu.servers.http import HttpServer

__all__ = ["HttpServer"]
