"""Protocol servers (mirrors reference src/servers, ~23k LoC: axum HTTP,
tonic gRPC/Flight, MySQL, Postgres wire...).

Round 1 surface: the HTTP server — /v1/sql, the Prometheus query API,
InfluxDB line-protocol and OpenTSDB ingestion, /metrics. gRPC/Flight and
the MySQL/Postgres wire protocols follow in later rounds.

`HttpServer` is exported lazily (PEP 562): the HTTP frontend imports
the full query engine (jax + kernels), but a storage-only datanode
imports only the sibling `servers.flight` — executing `servers.http`
from this package init would drag the device stack into every datanode
child (gtpu-lint `jax-import` guards this).
"""

__all__ = ["HttpServer"]


def __getattr__(name: str):
    if name == "HttpServer":
        from greptimedb_tpu.servers.http import HttpServer

        return HttpServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
