"""Embedded web dashboard (reference: servers/src/http dashboard feature
serving the bundled GreptimeDB dashboard UI). A single self-contained
page: SQL/PromQL query box, results table, and a canvas chart for
timestamp+numeric result shapes — no external assets (zero-egress
deployments included)."""

PAGE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>greptimedb_tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: ui-monospace, SFMono-Regular, Menlo, monospace;
         margin: 2rem auto; max-width: 72rem; padding: 0 1rem; }
  h1 { font-size: 1.1rem; }
  textarea { width: 100%; height: 6rem; font: inherit; padding: .5rem;
             box-sizing: border-box; }
  .row { display: flex; gap: .5rem; margin: .5rem 0; align-items: center; }
  button { font: inherit; padding: .35rem 1rem; cursor: pointer; }
  table { border-collapse: collapse; margin-top: 1rem; font-size: .85rem; }
  th, td { border: 1px solid #8884; padding: .25rem .6rem; text-align: left; }
  th { background: #8881; }
  #meta { opacity: .7; font-size: .8rem; }
  #err { color: #c33; white-space: pre-wrap; }
  canvas { width: 100%; height: 260px; margin-top: 1rem; }
  select, input[type=text] { font: inherit; padding: .3rem; }
</style>
</head>
<body>
<h1>greptimedb_tpu</h1>
<div class="row">
  <select id="mode">
    <option value="sql">SQL</option>
    <option value="promql">PromQL</option>
  </select>
  <input type="text" id="db" value="public" size="10" title="database">
  <span id="meta"></span>
</div>
<textarea id="q" spellcheck="false">SELECT * FROM information_schema.tables LIMIT 20</textarea>
<div class="row">
  <button onclick="run()">Run (Ctrl-Enter)</button>
  <label>start <input type="text" id="start" size="12" placeholder="promql"></label>
  <label>end <input type="text" id="end" size="12"></label>
  <label>step <input type="text" id="step" size="6" value="60s"></label>
</div>
<div id="err"></div>
<div id="out"></div>
<canvas id="chart" width="1100" height="260" style="display:none"></canvas>
<script>
const $ = (id) => document.getElementById(id);
$("q").addEventListener("keydown", (e) => {
  if ((e.ctrlKey || e.metaKey) && e.key === "Enter") run();
});
async function run() {
  $("err").textContent = ""; $("out").innerHTML = "";
  $("chart").style.display = "none";
  const q = $("q").value, t0 = performance.now();
  let url;
  if ($("mode").value === "sql") {
    url = "/v1/sql?" + new URLSearchParams({sql: q, db: $("db").value});
  } else {
    url = "/v1/prometheus/api/v1/query_range?" + new URLSearchParams({
      query: q, start: $("start").value || "0",
      end: $("end").value || String(Math.floor(Date.now()/1000)),
      step: $("step").value || "60s", db: $("db").value});
  }
  let body;
  try { body = await (await fetch(url)).json(); }
  catch (e) { $("err").textContent = String(e); return; }
  const ms = (performance.now() - t0).toFixed(1);
  if ($("mode").value === "sql") renderSql(body, ms); else renderProm(body, ms);
}
function renderSql(body, ms) {
  if (body.error) { $("err").textContent = body.error; return; }
  const out = body.output && body.output[0];
  if (!out) return;
  if (out.affectedrows !== undefined) {
    $("out").textContent = `OK, ${out.affectedrows} rows affected (${ms} ms)`;
    return;
  }
  const rec = out.records, cols = rec.schema.column_schemas.map(c => c.name);
  $("meta").textContent = `${rec.rows.length} rows in ${ms} ms`;
  const tbl = document.createElement("table");
  tbl.innerHTML = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") +
    "</tr>" + rec.rows.map(r => "<tr>" +
      r.map(v => `<td>${esc(v)}</td>`).join("") + "</tr>").join("");
  $("out").appendChild(tbl);
  chartIfSeries(cols, rec.rows, rec.schema.column_schemas);
}
function renderProm(body, ms) {
  if (body.status !== "success") {
    $("err").textContent = JSON.stringify(body); return;
  }
  const result = body.data.result || [];
  $("meta").textContent = `${result.length} series in ${ms} ms`;
  const series = result.map(s => ({
    label: JSON.stringify(s.metric),
    pts: (s.values || [s.value]).map(([t, v]) => [Number(t)*1000, Number(v)]),
  }));
  drawChart(series);
  const tbl = document.createElement("table");
  tbl.innerHTML = "<tr><th>series</th><th>points</th></tr>" +
    result.map(s => `<tr><td>${esc(JSON.stringify(s.metric))}</td>` +
      `<td>${(s.values||[]).length}</td></tr>`).join("");
  $("out").appendChild(tbl);
}
function chartIfSeries(cols, rows, schemas) {
  const ti = schemas.findIndex(c => (c.data_type||"").startsWith("timestamp"));
  const vi = schemas.findIndex(c => ["float64","float32","int64","int32"]
    .includes(c.data_type));
  if (ti < 0 || vi < 0 || rows.length < 2) return;
  drawChart([{label: cols[vi],
              pts: rows.map(r => [Date.parse(r[ti]) || Number(r[ti]),
                                  Number(r[vi])])}]);
}
function drawChart(series) {
  if (!series.length || !series[0].pts.length) return;
  const cv = $("chart"), ctx = cv.getContext("2d");
  cv.style.display = "block";
  ctx.clearRect(0, 0, cv.width, cv.height);
  let xs = [], ys = [];
  series.forEach(s => s.pts.forEach(([x, y]) => {
    if (isFinite(x) && isFinite(y)) { xs.push(x); ys.push(y); }}));
  if (!xs.length) return;
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || x0 + 1;
  const y0 = Math.min(...ys), y1 = Math.max(...ys) || y0 + 1;
  const X = x => 40 + (x - x0) / (x1 - x0 || 1) * (cv.width - 60);
  const Y = y => cv.height - 20 - (y - y0) / (y1 - y0 || 1) * (cv.height - 40);
  ctx.strokeStyle = "#8886"; ctx.strokeRect(40, 10, cv.width - 60, cv.height - 30);
  const hues = [210, 30, 120, 280, 0, 60];
  series.slice(0, 12).forEach((s, i) => {
    ctx.strokeStyle = `hsl(${hues[i % 6]} 70% 50%)`;
    ctx.beginPath();
    s.pts.forEach(([x, y], j) =>
      j ? ctx.lineTo(X(x), Y(y)) : ctx.moveTo(X(x), Y(y)));
    ctx.stroke();
  });
  ctx.fillStyle = "#888"; ctx.font = "11px monospace";
  ctx.fillText(String(y1), 2, Y(y1) + 4);
  ctx.fillText(String(y0), 2, Y(y0) + 4);
}
function esc(v) {
  return String(v === null ? "NULL" : v)
    .replace(/&/g, "&amp;").replace(/</g, "&lt;");
}
</script>
</body>
</html>
"""
