"""Columnar result serialization for the protocol servers.

Deliberately light on imports (json/math/numpy only): the encode pool's
process mode (spawn) imports this module in its workers, and pulling
the engine or JAX into an encode worker would cost seconds of startup
for a serialization job.

Two properties the tier-1 parity tests pin down:

- **byte identity**: the columnar fast path produces exactly the bytes
  the per-value path produced (same null mapping: NaN/Inf -> null, same
  C `json.dumps` on native Python objects), so responses are identical
  whether encoding runs inline, on a pool thread, or in a worker
  process;
- **one materialization per batch group**: results that came out of the
  cross-query batcher share an `encode_memo` dict — the first encoder
  to run stores the materialized row list, the other members of the
  coalesced group reuse it instead of re-walking the columns.
"""

from __future__ import annotations

import json
import math
import struct

import numpy as np

from greptimedb_tpu.utils.metrics import ENCODE_SECONDS


def _json_safe(v):
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def json_rows(r) -> list:
    """`r.rows()` with JSON-safe values, built column-wise: numeric
    columns convert through ONE numpy object cast (a C loop yielding
    native Python scalars) + a vectorized non-finite -> None mask,
    instead of a Python-level `_json_safe` call per value. Object/
    string columns keep the per-value loop (they may hold anything).
    Memoized in the result's batch-group `encode_memo` when present."""
    memo = getattr(r, "encode_memo", None)
    if memo is not None:
        rows = memo.get("json_rows")
        if rows is not None:
            return rows
    cols = []
    for col in r.columns:
        a = np.asarray(col)
        if a.dtype.kind == "f":
            o = a.astype(object)
            bad = ~np.isfinite(a)
            if bad.any():
                o[bad] = None
            cols.append(o.tolist())
        elif a.dtype.kind in "iub":
            cols.append(a.astype(object).tolist())
        else:
            cols.append([_json_safe(v) for v in a.tolist()])
    rows = [list(t) for t in zip(*cols)] if cols else []
    if memo is not None:
        # benign race: concurrent encoders compute identical values
        memo["json_rows"] = rows
    return rows


def records_json(r) -> dict:
    schema = {"column_schemas": [
        {"name": n, "data_type": (dt.value if dt else "string")}
        for n, dt in zip(r.names, r.dtypes)
    ]}
    return {"schema": schema, "rows": json_rows(r),
            "total_rows": r.num_rows}


#: memoized pre-serialized schema headers, keyed by the result shape —
#: dashboards repeat a handful of shapes, and re-dumping the identical
#: column_schemas fragment per response was pure per-request overhead.
#: Plain dict under the GIL (benign race: equal values); bounded by a
#: wholesale clear.
_SCHEMA_CACHE: dict = {}


def schema_header_json(names, dtypes) -> str:
    key = (tuple(names),
           tuple(dt.value if dt else None for dt in dtypes))
    cached = _SCHEMA_CACHE.get(key)
    if cached is None:
        cached = json.dumps({"column_schemas": [
            {"name": n, "data_type": (dt.value if dt else "string")}
            for n, dt in zip(names, dtypes)]})
        if len(_SCHEMA_CACHE) > 512:
            _SCHEMA_CACHE.clear()
        _SCHEMA_CACHE[key] = cached
    return cached


def encode_sql_payload(results, elapsed_ms: float) -> bytes:
    """The full /v1/sql response body — built and dumped in one place
    so the pool can run it off the request thread. Assembled from the
    memoized schema-header fragment + one C `json.dumps` of the rows;
    byte-identical to dumping the whole document (json.dumps emits
    `", "`/`": "` separators — pinned by the tier-1 parity test)."""
    with ENCODE_SECONDS.time(protocol="http"):
        out = []
        for r in results:
            if not r.is_query:
                out.append('{"affectedrows": %d}' % r.affected_rows)
            else:
                out.append(
                    '{"records": {"schema": %s, "rows": %s, '
                    '"total_rows": %d}}'
                    % (schema_header_json(r.names, r.dtypes),
                       json.dumps(json_rows(r)), r.num_rows))
        return ('{"code": 0, "output": [%s], "execution_time_ms": %s}'
                % (", ".join(out), json.dumps(elapsed_ms))).encode()


# ---- MySQL wire fragments --------------------------------------------------
# (moved here from servers/mysql.py so the resultset encoding can run on
# encode-pool workers without importing the engine)

MYSQL_TYPE_VAR_STRING = 253


def lenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenc_str(s: bytes) -> bytes:
    return lenc_int(len(s)) + s


def _eof() -> bytes:
    return b"\xfe" + struct.pack("<H", 0) + struct.pack("<H", 0x0002)


def _coldef(name: str, ftype: int) -> bytes:
    return (
        lenc_str(b"def")
        + lenc_str(b"")  # schema
        + lenc_str(b"")  # table
        + lenc_str(b"")  # org_table
        + lenc_str(name.encode())
        + lenc_str(name.encode())
        + bytes([0x0C])  # fixed-length fields length
        + struct.pack("<H", 0x21)  # charset utf8
        + struct.pack("<I", 1024)  # column length
        + bytes([ftype])
        + struct.pack("<H", 0)  # flags
        + bytes([0x1F])  # decimals
        + b"\x00\x00"
    )


def _fmt(v) -> str:
    if isinstance(v, (bool, np.bool_)):
        return "1" if v else "0"
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    return str(v)


def memo_rows(result) -> list:
    """`QueryResult.rows()` through the batch-group memo: coalesced
    members materialize the Python row objects once."""
    memo = getattr(result, "encode_memo", None)
    if memo is not None:
        rows = memo.get("rows")
        if rows is not None:
            return rows
    rows = result.rows()
    if memo is not None:
        memo["rows"] = rows
    return rows


def encode_mysql_result(result, binary: bool = False) -> list[bytes]:
    """Resultset packets straight from a QueryResult: the row
    materialization (`memo_rows` — the GIL-heaviest half of MySQL
    serialization) runs HERE, so offloading this function moves it off
    the session thread along with the packet assembly."""
    return encode_mysql_rows(list(result.names), memo_rows(result),
                             binary)


#: memoized resultset header packets (column count + column definitions
#: + EOF) keyed by the column-name tuple — every repeat of a dashboard
#: shape re-encoded identical coldef packets. Benign-race dict, bounded
#: by a wholesale clear.
_HEADER_CACHE: dict = {}


def mysql_header_packets(names) -> list[bytes]:
    key = tuple(names)
    cached = _HEADER_CACHE.get(key)
    if cached is None:
        cached = [lenc_int(len(names))] \
            + [_coldef(n, MYSQL_TYPE_VAR_STRING) for n in names] \
            + [_eof()]
        if len(_HEADER_CACHE) > 512:
            _HEADER_CACHE.clear()
        _HEADER_CACHE[key] = cached
    return list(cached)


def encode_mysql_rows(names, rows, binary: bool = False) -> list[bytes]:
    """Resultset packet payloads for one query result (column count,
    column definitions, EOF, row packets, EOF) — the session loop only
    stamps sequence numbers and writes. Row payloads accumulate in a
    reusable bytearray (amortized append) instead of quadratic bytes
    concatenation; the emitted packets are byte-identical."""
    with ENCODE_SECONDS.time(protocol="mysql"):
        packets = mysql_header_packets(names)
        for row in rows:
            payload = bytearray()
            if binary:
                # binary row: 0x00 header + null bitmap (offset 2) + values
                nb = bytearray((len(row) + 7 + 2) // 8)
                for i, v in enumerate(row):
                    if v is None or (isinstance(v, float) and np.isnan(v)):
                        nb[(i + 2) // 8] |= 1 << ((i + 2) % 8)
                    else:
                        s = _fmt(v).encode()
                        payload += lenc_int(len(s))
                        payload += s
                packets.append(b"\x00" + bytes(nb) + bytes(payload))
            else:
                for v in row:
                    if v is None or (isinstance(v, float) and np.isnan(v)):
                        payload += b"\xfb"  # NULL
                    else:
                        s = _fmt(v).encode()
                        payload += lenc_int(len(s))
                        payload += s
                packets.append(bytes(payload))
        packets.append(_eof())
        return packets
