"""gRPC data plane via Arrow Flight (mirrors reference servers::grpc:
`GreptimeDatabase` service + Arrow Flight `do_get`,
src/servers/src/grpc/{greptime_handler.rs:42,flight.rs:45-115}, and the
datanode region Flight service, src/servers/src/grpc/region_server.rs:39-92).

Two services on one Flight endpoint:

- **Query service** (frontend analog): `do_get` with a ticket
  `{"sql": ..., "db": ...}` streams the result as Arrow record batches;
  `do_put` bulk-ingests Arrow batches into a table (the row-insert path);
  `do_action` carries DDL/DML and health checks.
- **Region service** (datanode analog): `do_get` with
  `{"region_scan": {"region_id": ..., ...}}` streams one region's raw scan
  (tag codes as dictionary arrays, `__seq`/`__op_type` sideband columns) —
  the distributed MergeScan transport. The client reassembles `ScanData`
  and feeds the same device merge/dedup kernels as a local scan
  (SURVEY.md §2.6: Flight is the reference's data-movement fabric).

Auth: Flight handshake with Basic credentials when a UserProvider is
installed (the reference authenticates Flight calls the same way,
servers/src/grpc/flight.rs).
"""

from __future__ import annotations

import json
import os
import secrets
from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.fault import FAULTS, local_node, retry_call
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.session import Channel, QueryContext
from greptimedb_tpu.storage.region import ScanData

SEQ_COL = "__seq"
OP_COL = "__op_type"

#: Flight errors the shared RetryPolicy may fix (server briefly away,
#: timeout, transient internal) — auth/arg errors surface immediately
RETRYABLE_FLIGHT = (fl.FlightUnavailableError, fl.FlightTimedOutError,
                    fl.FlightInternalError)


def _call_options() -> Optional[fl.FlightCallOptions]:
    """Per-call gRPC deadline from the active query token: a stalled
    peer then fails the call locally (FlightTimedOutError) right at the
    query deadline instead of blocking in read_all() forever — the
    retry loop's deadline check converts that into typed
    DeadlineExceeded. Recomputed per attempt so retries ride the
    shrinking budget. Floor keeps an almost-spent budget from turning
    into timeout=0 (gRPC treats that as already-expired)."""
    from greptimedb_tpu.utils import deadline as dl

    token = dl.current()
    if token is None:
        return None
    remaining = token.remaining_s()
    if remaining is None:
        return None
    return fl.FlightCallOptions(timeout=max(0.05, remaining))


# ---- QueryResult ⇄ Arrow: shared converters live in datasource ------------

from greptimedb_tpu.datasource import result_to_table, table_to_result  # noqa: E402,F401


# ---- ScanData ⇄ Arrow (region service wire format) --------------------------


def scan_to_table(scan: ScanData) -> pa.Table:
    arrays, fields = [], []
    for name, col in scan.columns.items():
        if name in scan.tag_dicts:
            codes = np.asarray(col, dtype=np.int32)
            dict_vals = pa.array(scan.tag_dicts[name].astype(str))
            arr = pa.DictionaryArray.from_arrays(
                pa.array(np.where(codes < 0, None, codes), type=pa.int32()),
                dict_vals)
        else:
            arr = pa.array(col)
        arrays.append(arr)
        fields.append(pa.field(name, arr.type))
    arrays.append(pa.array(scan.seq))
    fields.append(pa.field(SEQ_COL, pa.int64()))
    arrays.append(pa.array(scan.op_type))
    fields.append(pa.field(OP_COL, pa.int8()))
    meta = {
        b"schema": json.dumps(scan.schema.to_dict()).encode(),
        b"needs_dedup": b"1" if scan.needs_dedup else b"0",
        b"region_id": str(scan.region_id).encode(),
        b"data_version": str(scan.data_version).encode(),
    }
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields, metadata=meta))


def partial_to_table(part: dict) -> pa.Table:
    """Partial-aggregate result ⇄ Arrow (the wire format of the Final
    combine's input). Key columns are `__key_<i>`; each primitive plane
    flattens to `__plane_<op>` FixedSizeList-free float64 columns with
    the field count in metadata."""
    arrays, fields = [], []
    for i, kc in enumerate(part["keys"]):
        arr = pa.array(kc)
        arrays.append(arr)
        fields.append(pa.field(f"__key_{i}", arr.type))
    meta = {b"n_keys": str(len(part["keys"])).encode()}
    for op, plane in part["planes"].items():
        plane2 = plane if plane.ndim == 2 else plane[:, None]
        meta[f"f_{op}".encode()] = str(plane2.shape[1]).encode()
        for j in range(plane2.shape[1]):
            arr = pa.array(plane2[:, j])
            arrays.append(arr)
            fields.append(pa.field(f"__plane_{op}_{j}", arr.type))
    return pa.Table.from_arrays(arrays,
                                schema=pa.schema(fields, metadata=meta))


def table_to_partial(t: pa.Table) -> dict:
    meta = t.schema.metadata or {}
    n_keys = int(meta[b"n_keys"])
    keys = []
    for i in range(n_keys):
        col = t.column(f"__key_{i}")
        arr = col.to_numpy(zero_copy_only=False)
        if arr.dtype.kind == "f" and np.isnan(arr).any():
            # Arrow materialized a NULL key as NaN; the in-process path
            # yields None — normalize so results don't depend on transport
            arr = np.array([None if (isinstance(x, float) and x != x)
                            else x for x in arr], dtype=object)
        keys.append(arr)
    planes: dict = {}
    for k, v in meta.items():
        if not k.startswith(b"f_"):
            continue
        op = k[2:].decode()
        f = int(v)
        cols = [t.column(f"__plane_{op}_{j}").to_numpy(zero_copy_only=False)
                for j in range(f)]
        planes[op] = np.stack(cols, axis=1)
    return {"keys": keys, "planes": planes}


def vmapped_part_to_wire(part: dict) -> dict:
    """JSON-safe form of a vmapped_agg fragment result ({"members":
    [...]} or {"vmap_ineligible": reason}). float64 round-trips exactly
    through Python json (shortest-repr), int64 stays int, NULL keys stay
    null — the frontend re-materializes numpy arrays."""
    if "members" not in part:
        return {"vmap_ineligible": str(part.get("vmap_ineligible", ""))}
    out = []
    for p in part["members"]:
        if p is None:
            out.append(None)
            continue
        keys = []
        for k in p["keys"]:
            vals = np.asarray(k, dtype=object).tolist()
            keys.append([None if (isinstance(x, float) and x != x) else x
                         for x in vals])
        planes = {op: np.asarray(v).tolist()
                  for op, v in p["planes"].items()}
        out.append({"keys": keys, "planes": planes})
    return {"members": out}


def table_to_scan(t: pa.Table) -> ScanData:
    meta = t.schema.metadata or {}
    schema = Schema.from_dict(json.loads(meta[b"schema"].decode()))
    columns: dict[str, np.ndarray] = {}
    tag_dicts: dict[str, np.ndarray] = {}
    seq = op = None
    for field in t.schema:
        col = t.column(field.name)
        if field.name == SEQ_COL:
            seq = col.to_numpy(zero_copy_only=False).astype(np.int64)
        elif field.name == OP_COL:
            op = col.to_numpy(zero_copy_only=False).astype(np.int8)
        elif pa.types.is_dictionary(field.type):
            combined = col.combine_chunks() if isinstance(col, pa.ChunkedArray) \
                else col
            if isinstance(combined, pa.ChunkedArray):
                combined = combined.chunk(0)
            codes = combined.indices.to_numpy(zero_copy_only=False)
            codes = np.where(np.isnan(codes.astype(np.float64)), -1,
                             codes).astype(np.int32) \
                if codes.dtype.kind == "f" else codes.astype(np.int32)
            columns[field.name] = codes
            tag_dicts[field.name] = np.asarray(
                combined.dictionary.to_pylist(), dtype=object)
        else:
            columns[field.name] = col.to_numpy(zero_copy_only=False)
    return ScanData(
        schema=schema, columns=columns, seq=seq, op_type=op,
        tag_dicts=tag_dicts, num_rows=t.num_rows,
        needs_dedup=meta.get(b"needs_dedup", b"1") == b"1",
        region_id=int(meta.get(b"region_id", b"-1")),
        data_version=int(meta.get(b"data_version", b"0")),
    )


# ---- auth handlers ----------------------------------------------------------


class _BasicServerAuth(fl.ServerAuthHandler):
    """Flight handshake: client sends 'user:password', server returns an
    opaque session token validated on every call."""

    MAX_TOKENS = 1024  # LRU bound: oldest sessions re-handshake

    def __init__(self, user_provider):
        from collections import OrderedDict

        super().__init__()
        self.user_provider = user_provider
        self._tokens: "OrderedDict[bytes, str]" = OrderedDict()
        # username -> authenticated UserInfo (with grants), resolved by
        # FlightServer handlers from context.peer_identity()
        self._identities: dict[str, object] = {}

    def authenticate(self, outgoing, incoming):
        from greptimedb_tpu.auth import AuthError

        raw = incoming.read()
        user, _, pwd = raw.decode().partition(":")
        try:
            info = self.user_provider.authenticate(user, pwd)
        except AuthError as e:
            raise fl.FlightUnauthenticatedError(str(e)) from e
        self._identities[user] = info
        token = secrets.token_bytes(16)
        self._tokens[token] = user
        while len(self._tokens) > self.MAX_TOKENS:
            self._tokens.popitem(last=False)
        outgoing.write(token)

    def is_valid(self, token):
        if token not in self._tokens:
            raise fl.FlightUnauthenticatedError("invalid token")
        return self._tokens[token].encode()


class _BasicClientAuth(fl.ClientAuthHandler):
    def __init__(self, user: str, password: str):
        super().__init__()
        self._cred = f"{user}:{password}".encode()
        self._token = b""

    def authenticate(self, outgoing, incoming):
        outgoing.write(self._cred)
        self._token = incoming.read()

    def get_token(self):
        return self._token


# ---- server -----------------------------------------------------------------


class FlightServer(fl.FlightServerBase):
    """Frontend + region Flight services on one port.

    Two deployment shapes (reference: frontend gRPC service vs the
    datanode region server, servers/src/grpc/region_server.rs:39-92):
    - frontend: pass `query_engine` — SQL/TQL over do_get, bulk ingest
      over do_put, plus the region service against its region engine.
    - datanode: pass `region_engine` only — region scan/write/DDL
      actions; no SQL surface.
    """

    def __init__(self, query_engine, host: str = "127.0.0.1", port: int = 0,
                 user_provider=None, region_engine=None,
                 node_id: Optional[str] = None):
        self.qe = query_engine
        self.engine = region_engine if region_engine is not None \
            else (query_engine.region_engine if query_engine else None)
        auth = _BasicServerAuth(user_provider) if user_provider else None
        self._auth = auth
        # lazy executor for partial-aggregate pushdown tickets
        self._agg_executor = None
        location = f"grpc://{host}:{port}"
        super().__init__(location, auth_handler=auth)
        self.host = host
        # identity stamped on piggybacked spans so a distributed EXPLAIN
        # ANALYZE attributes each stage to its process (reference tags
        # RecordBatchMetrics per peer, merge_scan.rs:245-259)
        self.node_id = node_id or os.environ.get("GTPU_NODE_ID") \
            or f"{host}:{self.port}"

    def _resolve_user(self, context):
        """Map the Flight peer identity (set by _BasicServerAuth.is_valid)
        back to the authenticated UserInfo so PermissionChecker sees the
        same principal gRPC authenticated — without this, grants and
        protected-schema rules were silently skipped over Flight."""
        if self._auth is None:
            return None
        ident = context.peer_identity()
        if not ident:
            return None
        name = ident.decode() if isinstance(ident, bytes) else str(ident)
        info = self._auth._identities.get(name)
        if info is None:
            from greptimedb_tpu.auth import UserInfo
            info = UserInfo(name)
        return info

    # -- query service --------------------------------------------------------

    def do_get(self, context, ticket):
        req = json.loads(ticket.ticket.decode())
        if "region_scan" in req:
            user = self._resolve_user(context)
            if user is not None and not user.can("read"):
                raise fl.FlightUnauthorizedError(
                    f"user {user.username!r} lacks read permission")
            return self._region_scan(req["region_scan"])
        if "region_frag" in req:
            user = self._resolve_user(context)
            if user is not None and not user.can("read"):
                raise fl.FlightUnauthorizedError(
                    f"user {user.username!r} lacks read permission")
            return self._region_frag(req["region_frag"])
        if self.qe is None:
            raise fl.FlightServerError("datanode service: region tickets only")
        from greptimedb_tpu.utils import tracing

        if "sql" not in req and "tql" not in req:
            raise fl.FlightServerError("ticket needs 'sql', 'tql' or 'region_scan'")
        # request-root span for the Flight SQL surface: adopt the
        # caller's trace context when the ticket carries one (the
        # region_server.rs:74 re-attach analog), else mint a fresh trace
        with tracing.adopt_remote(req.get("trace_id")
                                  or tracing.new_trace_id(),
                                  req.get("parent_span")):
            ctx = QueryContext(db=req.get("db", "public"),
                               channel=Channel.GRPC,
                               user=self._resolve_user(context),
                               trace_id=tracing.current_trace_id())
            if "sql" in req:
                with tracing.span("flight:sql"):
                    result = self.qe.execute_one(req["sql"], ctx)
            else:
                t = req["tql"]
                from greptimedb_tpu.promql.engine import PromqlEngine
                with tracing.span("flight:tql"):
                    result = PromqlEngine(self.qe).eval_range(
                        t["query"], t["start"], t["end"], t["step"], ctx)
        if not result.is_query:
            # DML/DDL ack: flagged via schema metadata, not column names
            # (a SELECT could legitimately project `affected_rows`)
            table = pa.Table.from_arrays(
                [pa.array([result.affected_rows], type=pa.int64())],
                schema=pa.schema([pa.field("affected_rows", pa.int64())],
                                 metadata={b"affected": b"1"}))
        else:
            table = result_to_table(result)
        return fl.RecordBatchStream(table)

    def _piggyback(self, table: pa.Table, sink) -> pa.Table:
        """Attach this request's spans (+ the serving node's identity) to
        the response schema metadata — the RecordBatchMetrics piggyback
        (merge_scan.rs:245-259): the caller merges them into its own ring
        so one EXPLAIN ANALYZE covers every process the query touched."""
        from greptimedb_tpu.utils import tracing

        meta = dict(table.schema.metadata or {})
        meta[b"spans"] = json.dumps(tracing.spans_to_wire(sink)).encode()
        meta[b"node"] = str(self.node_id).encode()
        # continuous-profiling rollup rides the same seam: a compact
        # flame/ledger digest per response, so the frontend's
        # /v1/profile/cluster view covers every datanode it talked to
        # without a second RPC
        from greptimedb_tpu.utils import flame

        if flame.running():
            meta[b"profile"] = json.dumps(
                flame.summary(node=f"datanode-{self.node_id}")).encode()
        return table.replace_schema_metadata(meta)

    def _region_scan(self, req: dict):
        """Datanode region service (reference region_server.rs:39-92 —
        Substrait plan in, Flight stream out; here the scan spec is the
        plan fragment)."""
        from greptimedb_tpu.utils import tracing

        region_id = req["region_id"]
        ts_range = tuple(req["ts_range"]) if req.get("ts_range") else None
        projection = req.get("projection")
        from greptimedb_tpu.storage.index import deserialize_predicates
        preds = deserialize_predicates(
            req.get("tag_predicates_v2") or req.get("tag_predicates"))
        from greptimedb_tpu.utils import deadline as dl
        from greptimedb_tpu.utils.metrics import REQUEST_BUDGET_REMAINING

        budget = req.get("budget_ms")
        if budget is not None:
            REQUEST_BUDGET_REMAINING.observe(float(budget))
        # adopt the caller's trace AND parent span (region_server.rs:74
        # analog): this datanode's region_scan re-parents under the
        # frontend span that issued the RPC, so the merged ANALYZE tree
        # nests across the process hop. The ticket's remaining budget
        # becomes a local token: a scan whose frontend already gave up
        # unwinds typed here instead of burning datanode workers.
        with dl.activate(dl.token_for_budget(budget)), \
                tracing.adopt_remote(req.get("trace_id"),
                                     req.get("parent_span")), \
                tracing.collect_spans() as sink:
            with tracing.span("region_scan", region=region_id) as attrs:
                # server-side injection INSIDE the scan span: latency
                # armed here (e.g. via GTPU_CHAOS inherited by a child
                # datanode, @side:server) lands in the span duration the
                # frontend's merged tree renders — the end-to-end proof
                # the ROADMAP fault-matrix item asked for
                FAULTS.fire("flight.do_get", side="server",
                            node=local_node(), op="region_scan")
                scan = self.engine.scan(
                    region_id, ts_range=ts_range, projection=projection,
                    tag_predicates=preds, seq_min=req.get("seq_min"))
                # scan stats ride the span: rows served, SST pruning,
                # host scan-cache reuse (reference RecordBatchMetrics
                # carries the same per-stage counters)
                attrs["rows"] = 0 if scan is None else scan.num_rows
                if scan is not None and scan.stats:
                    attrs.update(scan.stats)
            if scan is None:
                # empty marker: zero-column table with metadata flag
                table = pa.Table.from_arrays(
                    [], schema=pa.schema([], metadata={b"empty": b"1"}))
            else:
                table = scan_to_table(scan)
                attrs["bytes"] = table.nbytes
        return fl.RecordBatchStream(self._piggyback(table, sink))

    def _region_frag(self, req: dict):
        """Plan-fragment pushdown: the PlanFragment (the substrait
        analog) executes against the LOCAL region and only the terminal
        stage's output crosses the wire — partial planes (tagged
        kind=partial) or candidate/filtered rows (kind=rows), never the
        raw scan (reference dist_plan Partial step, analyzer.rs:35)."""
        from greptimedb_tpu.query.dist_agg import execute_region_fragment
        from greptimedb_tpu.query.plan_ser import PlanFragment
        from greptimedb_tpu.utils import tracing

        region_id = req["region_id"]
        frag = PlanFragment.from_json(req["fragment"])
        if self._agg_executor is None:
            from greptimedb_tpu.query.physical import PhysicalExecutor
            self._agg_executor = PhysicalExecutor(self.engine)
        from greptimedb_tpu.utils import deadline as dl
        from greptimedb_tpu.utils.metrics import REQUEST_BUDGET_REMAINING

        budget = req.get("budget_ms")
        if budget is not None:
            REQUEST_BUDGET_REMAINING.observe(float(budget))
        with dl.activate(dl.token_for_budget(budget)), \
                tracing.adopt_remote(req.get("trace_id"),
                                     req.get("parent_span")), \
                tracing.collect_spans() as sink:
            with tracing.span("region_frag", region=region_id,
                              stages=len(frag.stages)):
                FAULTS.fire("flight.do_get", side="server",
                            node=local_node(), op="region_frag")
                part = execute_region_fragment(self._agg_executor,
                                               region_id, frag)
            if part is None:
                table = pa.Table.from_arrays(
                    [], schema=pa.schema([], metadata={b"empty": b"1"}))
            elif "members" in part or "vmap_ineligible" in part:
                # vmapped_agg terminal: per-member partials (or the
                # typed ineligibility marker) ride schema metadata
                table = pa.Table.from_arrays([], schema=pa.schema(
                    [], metadata={
                        b"kind": b"vmapped",
                        b"payload": json.dumps(
                            vmapped_part_to_wire(part)).encode()}))
            elif "planes" in part:
                table = partial_to_table(part)
            else:
                cols = part["cols"]
                arrays = [pa.array(cols[name]) for name in cols]
                table = pa.Table.from_arrays(
                    arrays,
                    schema=pa.schema(
                        [pa.field(name, a.type)
                         for name, a in zip(cols, arrays)],
                        metadata={b"kind": b"rows"}))
        return fl.RecordBatchStream(self._piggyback(table, sink))

    # -- ingest ----------------------------------------------------------------

    def do_put(self, context, descriptor, reader, writer):
        """Bulk Arrow ingest into an existing table (the reference's row
        insert gRPC, greptime_handler.rs:62 — here columnar end-to-end).
        Path ["__region__", <rid>, put|delete] is the datanode write path
        (region_server.rs handle_request analog)."""
        path = [p.decode() for p in descriptor.path]
        if not path:
            raise fl.FlightServerError("descriptor path must be [db.]table")
        if path[0] == "__region__":
            user = self._resolve_user(context)
            if user is not None and not user.can("write"):
                raise fl.FlightUnauthorizedError(
                    f"user {user.username!r} lacks write permission")
            from greptimedb_tpu.utils import tracing

            rid = int(path[1])
            op = path[2] if len(path) > 2 else "put"
            # the caller's trace id (and parent span id, one element
            # further) ride the descriptor path tail so write-side
            # spans join — and nest under — the same trace (do_get
            # carries them in the ticket; do_put has only the
            # descriptor). Old peers sent shorter paths; extras are
            # ignored both ways.
            tid_p = path[3] if len(path) > 3 and path[3] else None
            par_p = path[4] if len(path) > 4 and path[4] else None
            with tracing.adopt_remote(tid_p, par_p), \
                    tracing.collect_spans() as sink:
                with tracing.span("region_write", region=rid,
                                  op=op) as attrs:
                    # server-side seam inside the write span (the do_put
                    # mirror of the do_get scan-span injection);
                    # @side:server opts in, plain schedules stay
                    # client-only
                    FAULTS.fire("flight.do_put", side="server",
                                node=local_node(), op="region_write")
                    t = reader.read_all()
                    from greptimedb_tpu.datatypes.recordbatch import RecordBatch

                    region = self.engine.region(rid)
                    if t.num_rows:
                        arrow = t.combine_chunks().to_batches()[0]
                    else:
                        arrow = pa.RecordBatch.from_pydict(
                            {f.name: [] for f in t.schema}, schema=t.schema)
                    batch = RecordBatch.from_arrow(arrow, region.schema)
                    if op == "delete":
                        n = self.engine.delete(rid, batch)
                    else:
                        n = self.engine.put(rid, batch)
                    attrs["rows"] = n
            writer.write(json.dumps({
                "affected_rows": n, "node": self.node_id,
                "spans": tracing.spans_to_wire(sink)}).encode())
            return
        if self.qe is None:
            raise fl.FlightServerError("datanode service: region writes only")
        table_name = path[-1]
        db = path[0] if len(path) > 1 else "public"
        ctx = QueryContext(db=db, channel=Channel.GRPC,
                           user=self._resolve_user(context))
        from greptimedb_tpu.auth import AuthError
        try:
            # full write authorization (grants + protected schema), same
            # rules the SQL INSERT path applies
            self.qe.permission_checker.check_access(ctx.user, "write", db)
        except AuthError as e:
            raise fl.FlightUnauthorizedError(str(e)) from e
        arrow_table = reader.read_all()
        n = self._insert_arrow(table_name, arrow_table, ctx)
        writer.write(json.dumps({"affected_rows": n}).encode())

    def _insert_arrow(self, table_name: str, t: pa.Table, ctx) -> int:
        from greptimedb_tpu.datasource import insert_arrow_table

        return insert_arrow_table(self.qe, table_name, t, ctx)

    # -- control ----------------------------------------------------------------

    def do_action(self, context, action):
        if action.type == "health":
            return [json.dumps({"status": "ok"}).encode()]
        if action.type == "region_admin":
            # datanode control plane (region_server.rs handle_request:
            # create/open/close/drop/flush/compact + existence probe)
            req = json.loads(action.body.to_pybytes().decode())
            rid = req["region_id"]
            op = req["op"]
            user = self._resolve_user(context)
            needed = "read" if op in ("exists", "info") else "write"
            if user is not None and not user.can(needed):
                raise fl.FlightUnauthorizedError(
                    f"user {user.username!r} lacks {needed} permission")
            from greptimedb_tpu.storage.engine import RegionRequest, RequestType

            if op == "chaos_reset":
                # chaos-harness control: clear THIS process's fault
                # registry (schedules + partitions) so an explorer run's
                # final verification reads the cluster chaos-free; a
                # no-op when nothing is armed
                FAULTS.reset()
                return [b'{"ok": true}']
            if op == "info":
                region = self.engine.region(rid)
                return [json.dumps(
                    {"data_version": region.data_version}).encode()]
            if op == "alter":
                from greptimedb_tpu.datatypes.schema import Schema as _S
                self.engine.alter_region_schema(
                    rid, _S.from_dict(req["schema"]))
                return [b'{"ok": true}']
            if op == "create":
                from greptimedb_tpu.datatypes.schema import Schema as _S
                self.engine.create_region(rid, _S.from_dict(req["schema"]))
            elif op == "open":
                self.engine.open_region(rid)
            elif op == "exists":
                try:
                    self.engine.region(rid)
                    return [b'{"exists": true}']
                except KeyError:
                    return [b'{"exists": false}']
            elif op == "flush":
                self.engine.flush(rid)
            elif op == "compact":
                self.engine.compact(rid)
            elif op in ("close", "drop", "truncate"):
                self.engine.handle_request(
                    RegionRequest(RequestType[op.upper()], rid))
            else:
                raise fl.FlightServerError(f"unknown region op {op!r}")
            return [b'{"ok": true}']
        if action.type == "rollup_probe":
            # cluster-mode rollup substitution, eligibility half: which
            # rules fully cover [lo, hi) on this region (the frontend
            # intersects per-region answers and re-plans over the
            # companion plane regions — maintenance/rollup.py)
            req = json.loads(action.body.to_pybytes().decode())
            user = self._resolve_user(context)
            if user is not None and not user.can("read"):
                raise fl.FlightUnauthorizedError(
                    f"user {user.username!r} lacks read permission")
            from greptimedb_tpu.maintenance.rollup import (
                probe_region_rollups,
            )

            out = probe_region_rollups(self.engine, req["region_id"],
                                       int(req["lo"]), int(req["hi"]))
            return [json.dumps(out).encode()]
        if action.type == "sql":
            req = json.loads(action.body.to_pybytes().decode())
            ctx = QueryContext(db=req.get("db", "public"), channel=Channel.GRPC,
                               user=self._resolve_user(context))
            results = self.qe.execute_sql(req["sql"], ctx)
            out = []
            for r in results:
                if r.is_query:
                    out.append(json.dumps(
                        {"rows": r.rows(), "names": r.names}).encode())
                else:
                    out.append(json.dumps(
                        {"affected_rows": r.affected_rows}).encode())
            return out
        raise fl.FlightServerError(f"unknown action {action.type!r}")

    def list_actions(self, context):
        return [("health", "liveness check"),
                ("sql", "execute SQL, results as JSON")]

    def list_flights(self, context, criteria):
        ctx = QueryContext()
        for db in self.qe.catalog.list_databases():
            for name in self.qe.catalog.list_tables(db):
                info = self.qe.catalog.table(db, name)
                fields = [pa.field(c.name, c.dtype.to_arrow())
                          for c in info.schema.columns]
                desc = fl.FlightDescriptor.for_path(db, name)
                yield fl.FlightInfo(pa.schema(fields), desc, [], -1, -1)


# ---- client -----------------------------------------------------------------


class FlightQueryClient:
    """Client for the query service (SQL over Flight)."""

    def __init__(self, addr: str, user: Optional[str] = None,
                 password: Optional[str] = None):
        self.client = fl.FlightClient(f"grpc://{addr}")
        if user is not None:
            self.client.authenticate(_BasicClientAuth(user, password or ""))

    def sql(self, sql: str, db: str = "public") -> QueryResult:
        ticket = fl.Ticket(json.dumps({"sql": sql, "db": db}).encode())
        t = self.client.do_get(ticket).read_all()
        if (t.schema.metadata or {}).get(b"affected") == b"1":
            return QueryResult.of_affected(t.column(0)[0].as_py())
        return table_to_result(t)

    def insert(self, table: str, data: pa.Table, db: str = "public") -> int:
        desc = fl.FlightDescriptor.for_path(db, table)
        writer, reader = self.client.do_put(desc, data.schema)
        writer.write_table(data)
        writer.done_writing()
        ack_buf = reader.read()
        if ack_buf is None:
            # server errored before acking — close() raises the Flight error
            writer.close()
            raise fl.FlightServerError("no ack from server")
        ack = json.loads(ack_buf.to_pybytes().decode())
        writer.close()
        return ack["affected_rows"]

    def health(self) -> bool:
        res = list(self.client.do_action(fl.Action("health", b"")))
        return json.loads(res[0].body.to_pybytes().decode())["status"] == "ok"

    def close(self):
        self.client.close()


class RemoteRegionEngine:
    """The RegionEngine surface over the Flight region service — the real
    network data plane between a frontend and its datanodes (reference:
    frontends reach regions via serialized plans + Flight streams,
    datanode/src/region_server.rs:623-660; cluster mode routes every
    region request through this client instead of in-process calls)."""

    def __init__(self, addr: str, user: Optional[str] = None,
                 password: Optional[str] = None,
                 peer: Optional[str] = None):
        self.addr = addr
        #: the peer's NODE identity (dn-N): with it, every RPC carries a
        #: (src, dst) edge the fault layer can match or partition; an
        #: addr-only client still works, it just has no edge
        self.peer = peer
        self.client = fl.FlightClient(f"grpc://{addr}")
        if user is not None:
            self.client.authenticate(_BasicClientAuth(user, password or ""))

    def _rpc(self, point: str, fn):
        """Every wire call crosses here: chaos injection point + the
        shared retry policy over transient Flight errors. Writes retried
        after a mid-stream failure are at-least-once; the LSM's
        key+timestamp LWW collapses the duplicates (append-mode tables
        trade exactness for availability, as the reference's gRPC retry
        does). The span makes the wire+retry cost visible as self-time
        under the enclosing remote_region_* span."""
        from greptimedb_tpu.utils import tracing

        with tracing.span("flight_rpc", point=point, dst=self.peer
                          or self.addr):
            def op():
                FAULTS.fire(point, addr=self.addr, side="client",
                            src=local_node(), dst=self.peer or self.addr)
                return fn()
            try:
                return retry_call(op, point=point,
                                  retryable=RETRYABLE_FLIGHT)
            except Exception as e:
                from greptimedb_tpu.fault.retry import (
                    Cancelled,
                    DeadlineExceeded,
                )
                from greptimedb_tpu.utils import deadline as dl

                if isinstance(e, (DeadlineExceeded, Cancelled)):
                    raise
                # the datanode enforcing the ticket's budget raises its
                # own typed error, but it crosses the wire as an opaque
                # FlightServerError — once OUR budget is spent, the
                # typed deadline outranks whichever wire error the race
                # produced (gRPC timeout vs server-side unwind)
                dl.check(point)
                raise

    def _merge_remote_spans(self, meta) -> None:
        """Fold the response's piggybacked datanode spans into the local
        ring, tagged with the source node (merge_scan.rs:245-259 analog:
        sub-stage metrics ride the Flight stream back). `meta` is either
        a pa.Table schema-metadata dict or a decoded JSON ack."""
        from greptimedb_tpu.utils import tracing

        if meta is None:
            return
        try:
            if isinstance(meta, dict) and b"spans" in meta:
                wire = json.loads(meta[b"spans"].decode())
                node = meta.get(b"node", b"").decode() or self.addr
                prof = meta.get(b"profile")
                prof = json.loads(prof.decode()) if prof else None
            elif isinstance(meta, dict) and "spans" in meta:
                wire = meta["spans"]
                node = meta.get("node") or self.addr
                prof = meta.get("profile")
            else:
                return
            tracing.merge_spans(wire, node=node)
            if prof:
                from greptimedb_tpu.utils import flame

                flame.note_node_summary(prof.get("node") or node, prof)
        except (ValueError, KeyError, AttributeError):
            pass  # a mangled piggyback must never fail the query

    # -- control -------------------------------------------------------------

    def _admin(self, op: str, region_id: int, **extra) -> dict:
        body = json.dumps({"op": op, "region_id": region_id, **extra}).encode()
        point = "flight.do_get" if op in ("exists", "info") \
            else "flight.do_put"
        res = self._rpc(point, lambda: list(
            self.client.do_action(fl.Action("region_admin", body))))
        return json.loads(res[0].body.to_pybytes().decode())

    def create_region(self, region_id: int, schema) -> None:
        self._admin("create", region_id, schema=schema.to_dict())

    def open_region(self, region_id: int) -> None:
        self._admin("open", region_id)

    def region(self, region_id: int):
        """Existence probe (KeyError contract of the local engine). The
        returned proxy carries identity + remote-backed metadata; schema
        mutations go through alter_region_schema, a dedicated RPC."""
        if not self._admin("exists", region_id).get("exists"):
            raise KeyError(f"region {region_id} not found on {self.addr}")
        return _RemoteRegionProxy(region_id, self)

    def alter_region_schema(self, region_id: int, schema) -> None:
        self._admin("alter", region_id, schema=schema.to_dict())

    def flush(self, region_id: int) -> None:
        self._admin("flush", region_id)

    def compact(self, region_id: int) -> None:
        self._admin("compact", region_id)

    def chaos_reset(self) -> None:
        """Disarm the remote process's fault registry (chaos harness:
        the explorer verifies invariants chaos-free after the workload).
        region_id 0 — the op is process-scoped, not region-scoped."""
        self._admin("chaos_reset", 0)

    def handle_request(self, req) -> int:
        from greptimedb_tpu.storage.engine import RequestType

        if req.kind is RequestType.PUT:
            return self.put(req.region_id, req.batch)
        if req.kind is RequestType.DELETE:
            return self.delete(req.region_id, req.batch)
        self._admin(req.kind.value, req.region_id)
        return 0

    # -- write ---------------------------------------------------------------

    def _write(self, region_id: int, batch, op: str) -> int:
        from greptimedb_tpu.utils import tracing

        tid = tracing.current_trace_id()
        with tracing.span("remote_region_write", region=region_id,
                          op=op, addr=self.addr):
            # trace id + parent span id ride the descriptor path tail
            # (do_put has no ticket); the datanode's region_write span
            # re-parents under THIS span. Old servers ignore extras.
            path = ["__region__", str(region_id), op] + \
                ([tid, tracing.current_span_id() or ""] if tid else [])
            desc = fl.FlightDescriptor.for_path(*path)
            arrow = batch.to_arrow()

            def put_once():
                writer, reader = self.client.do_put(desc, arrow.schema)
                try:
                    writer.write_batch(arrow)
                    writer.done_writing()
                    ack_buf = reader.read()
                    if ack_buf is None:
                        raise fl.FlightServerError("no ack from region server")
                    ack = json.loads(ack_buf.to_pybytes().decode())
                    self._merge_remote_spans(ack)
                    return ack["affected_rows"]
                finally:
                    # close on EVERY path: a failed put that leaks its
                    # stream would accumulate one half-open stream per
                    # retry attempt
                    try:
                        writer.close()
                    except Exception:  # noqa: BLE001 — stream already dead
                        pass
            return self._rpc("flight.do_put", put_once)

    def put(self, region_id: int, batch) -> int:
        return self._write(region_id, batch, "put")

    def delete(self, region_id: int, batch) -> int:
        return self._write(region_id, batch, "delete")

    # -- read ----------------------------------------------------------------

    def scan(self, region_id: int, ts_range=None, projection=None,
             tag_predicates=None, seq_min=None) -> Optional[ScanData]:
        from greptimedb_tpu.utils import tracing

        spec = {"region_id": region_id}
        if seq_min is not None:
            spec["seq_min"] = int(seq_min)
        if ts_range is not None:
            spec["ts_range"] = list(ts_range)
        if projection is not None:
            spec["projection"] = list(projection)
        if tag_predicates:
            from greptimedb_tpu.storage.index import (
                serialize_predicates,
                serialize_predicates_legacy,
            )
            legacy = serialize_predicates_legacy(tag_predicates)
            if legacy:  # shape old peers can parse (InSets only)
                spec["tag_predicates"] = legacy
            spec["tag_predicates_v2"] = serialize_predicates(tag_predicates)
        from greptimedb_tpu.utils import deadline as dl

        budget = dl.budget_ms()
        if budget is not None:
            # remaining budget rides the ticket so the datanode enforces
            # the deadline server-side (the frontend token can't cross
            # the process boundary)
            spec["budget_ms"] = budget
        tid = tracing.current_trace_id()
        if tid:
            # W3C-style propagation: the frontend's trace id crosses the
            # wire inside the request (merge_scan.rs:185-201 analog)
            spec["trace_id"] = tid
        with tracing.span("remote_region_scan", region=region_id,
                          addr=self.addr):
            if tid:
                # parent linkage: the datanode's region_scan span nests
                # under THIS span in the merged tree
                spec["parent_span"] = tracing.current_span_id()
            ticket = fl.Ticket(json.dumps({"region_scan": spec}).encode())
            t = self._rpc("flight.do_get", lambda: self.client.do_get(
                ticket, _call_options()).read_all())
        self._merge_remote_spans(t.schema.metadata)
        if (t.schema.metadata or {}).get(b"empty") == b"1":
            return None
        return table_to_scan(t)

    def execute_fragment(self, region_id: int, frag) -> Optional[dict]:
        """Ship a PlanFragment; receive the terminal stage's output —
        partial planes or candidate/filtered rows, distinguished by the
        response's kind metadata (reference region_server.rs:623-660 —
        substrait plan in, stream out; raw scans never cross here)."""
        from greptimedb_tpu.utils import tracing

        spec = {"region_id": region_id, "fragment": frag.to_json()}
        from greptimedb_tpu.utils import deadline as dl

        budget = dl.budget_ms()
        if budget is not None:
            spec["budget_ms"] = budget
        tid = tracing.current_trace_id()
        if tid:
            spec["trace_id"] = tid
        with tracing.span("remote_region_frag", region=region_id,
                          addr=self.addr):
            if tid:
                spec["parent_span"] = tracing.current_span_id()
            ticket = fl.Ticket(json.dumps({"region_frag": spec}).encode())
            t = self._rpc("flight.do_get", lambda: self.client.do_get(
                ticket, _call_options()).read_all())
        self._merge_remote_spans(t.schema.metadata)
        md = t.schema.metadata or {}
        if md.get(b"empty") == b"1":
            return None
        if md.get(b"kind") == b"vmapped":
            return json.loads(md[b"payload"].decode())
        if md.get(b"kind") == b"rows":
            t = t.combine_chunks()
            cols = {}
            for i, name in enumerate(t.column_names):
                col = t.column(i)
                cols[name] = col.to_numpy(zero_copy_only=False)
            return {"cols": cols}
        return table_to_partial(t)

    def rollup_probe(self, region_id: int, lo: int, hi: int) -> list:
        """Rollup-coverage probe on the region's owner (the cluster
        substitution eligibility RPC; see the server's rollup_probe
        action)."""
        body = json.dumps({"region_id": region_id, "lo": int(lo),
                           "hi": int(hi)}).encode()
        res = self._rpc("flight.do_get", lambda: list(
            self.client.do_action(fl.Action("rollup_probe", body),
                                  _call_options())))
        return json.loads(res[0].body.to_pybytes().decode())

    def scan_stream(self, region_id: int, ts_range=None, projection=None,
                    tag_predicates=None):
        # remote streaming scan not implemented yet: fall back to the
        # materialized wire scan (executor handles None)
        return None

    def close(self) -> None:
        self.client.close()


class _RemoteRegionProxy:
    def __init__(self, region_id: int, client: RemoteRegionEngine):
        self.region_id = region_id
        self._client = client

    def flush(self) -> None:
        self._client.flush(self.region_id)

    @property
    def data_version(self) -> int:
        return self._client._admin("info", self.region_id)["data_version"]


class RegionFlightClient:
    """Client for the region service — the distributed MergeScan transport
    (reference query/src/dist_plan/merge_scan.rs:198-259 streams each
    region over Flight and concatenates; here the reassembled ScanData
    feeds the device merge kernels)."""

    def __init__(self, addr: str, user: Optional[str] = None,
                 password: Optional[str] = None):
        self.client = fl.FlightClient(f"grpc://{addr}")
        if user is not None:
            self.client.authenticate(_BasicClientAuth(user, password or ""))

    def scan(self, region_id: int, ts_range=None, projection=None,
             tag_predicates=None) -> Optional[ScanData]:
        spec = {"region_id": region_id}
        if ts_range is not None:
            spec["ts_range"] = list(ts_range)
        if projection is not None:
            spec["projection"] = list(projection)
        if tag_predicates:
            from greptimedb_tpu.storage.index import (
                serialize_predicates,
                serialize_predicates_legacy,
            )
            legacy = serialize_predicates_legacy(tag_predicates)
            if legacy:
                spec["tag_predicates"] = legacy
            spec["tag_predicates_v2"] = serialize_predicates(tag_predicates)
        ticket = fl.Ticket(json.dumps({"region_scan": spec}).encode())
        t = self.client.do_get(ticket).read_all()
        if (t.schema.metadata or {}).get(b"empty") == b"1":
            return None
        return table_to_scan(t)

    def close(self):
        self.client.close()
