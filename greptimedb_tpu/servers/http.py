"""HTTP server (mirrors reference servers::http `HttpServer::make_app`,
src/servers/src/http.rs:625-801): /v1/sql, the Prometheus HTTP API,
InfluxDB/OpenTSDB write endpoints, /metrics, /health.

stdlib ThreadingHTTPServer — the host tier serves protocol traffic while
queries execute as device kernels; no framework dependencies.
"""

from __future__ import annotations

import json
import math
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.fault import FaultError, Unavailable
from greptimedb_tpu.fault.retry import Cancelled, DeadlineExceeded
from greptimedb_tpu.query.engine import QueryContext, QueryEngine
from greptimedb_tpu.query.result import QueryResult
from greptimedb_tpu.utils.metrics import HTTP_REQUESTS, QUERY_DURATION, REGISTRY


class HttpServer:
    def __init__(self, query_engine: QueryEngine, host: str = "127.0.0.1",
                 port: int = 4000, user_provider=None,
                 timeout_s: Optional[float] = None):
        self.qe = query_engine
        self.host = host
        self.port = port
        self.user_provider = user_provider
        self.timeout_s = timeout_s
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        # initialize the jax backend from the MAIN thread: some PJRT
        # plugins refuse lazy initialization from worker threads
        import jax
        jax.devices()

        qe = self.qe

        provider = self.user_provider

        class Handler(_Handler):
            query_engine = qe
            user_provider = provider
            # socketserver honors this as the per-connection socket
            # timeout (http.timeout_s option)
            if self.timeout_s:
                timeout = self.timeout_s

        class Server(ThreadingHTTPServer):
            # default backlog (5) resets connections under benchmark-level
            # concurrency (50 clients connecting at once); daemon threads
            # so a hung handler can't block process exit
            request_queue_size = 128
            daemon_threads = True

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)


class _Handler(BaseHTTPRequestHandler):
    query_engine: QueryEngine = None  # injected
    user_provider = None  # injected
    protocol_version = "HTTP/1.1"
    # headers and body go out in separate send()s — without NODELAY,
    # Nagle holds the second segment for the peer's delayed ACK and
    # every keep-alive request eats a flat ~40 ms (round-5: single-
    # connection latency 44 ms with a 1.2 ms engine)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet
        pass

    # ---- plumbing ----------------------------------------------------------

    def _params(self) -> dict:
        parsed = urllib.parse.urlparse(self.path)
        params = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        return params

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _form_or_query(self) -> dict:
        params = self._params()
        body = self._body()
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if body and ctype in ("application/x-www-form-urlencoded", ""):
            try:
                form = {k: v[0] for k, v in
                        urllib.parse.parse_qs(body.decode()).items()}
                params = {**form, **params}
            except UnicodeDecodeError:
                pass
        self._raw_body = body
        return params

    def _send(self, code: int, payload, content_type="application/json"):
        # a ShmPayload (serving fabric's zero-copy handoff) is written
        # straight from its shared-memory view — duck-typed so this
        # module never imports shm
        shm_payload = None
        if getattr(payload, "is_shm_payload", False):
            shm_payload = payload
            data = payload.view
        elif isinstance(payload, bytes):
            data = payload
        else:
            data = json.dumps(payload).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            # W3C egress: echo the request's trace context so the caller
            # can join its spans to ours (set per traced request in _route)
            tp = getattr(self, "_traceparent", None)
            if tp:
                self.send_header("traceparent", tp)
            self.end_headers()
            self.wfile.write(data)
        finally:
            if shm_payload is not None:
                shm_payload.release()
        route = urllib.parse.urlparse(self.path).path
        HTTP_REQUESTS.inc(path=route, status=str(code))


    def _ctx(self, params: dict) -> QueryContext:
        from greptimedb_tpu.session import Channel
        # X-Greptime-Timezone: per-request session timezone (reference
        # servers/src/http — HTTP is stateless, so SET TIME ZONE can't
        # persist; clients pin it per request via this header)
        tz = self.headers.get("X-Greptime-Timezone") or \
            params.get("timezone")
        if tz:
            from greptimedb_tpu.utils.time import tzinfo_for

            tzinfo_for(tz)  # fail fast on a typo'd zone name
        user = getattr(self, "_user", None)
        # X-Greptime-Tenant: admission-control identity for fair
        # scheduling; falls back to the authenticated user, then the db
        tenant = self.headers.get("X-Greptime-Tenant") \
            or params.get("tenant") \
            or getattr(user, "username", None)
        # X-Greptime-Timeout: per-request deadline ("500ms", "5s", or a
        # bare millisecond count); absent = session/config default
        from greptimedb_tpu.utils import deadline

        timeout_ms = deadline.parse_timeout_ms(
            self.headers.get("X-Greptime-Timeout")
            or params.get("timeout"))
        from greptimedb_tpu.utils import tracing

        return QueryContext(db=params.get("db", "public"),
                            channel=Channel.HTTP,
                            timezone=tz or None,
                            tenant=tenant,
                            timeout_ms=timeout_ms,
                            user=user,
                            # the request trace installed by _route's
                            # ingress span (adopted from an incoming
                            # traceparent header, or freshly minted) —
                            # the engine joins the same trace
                            trace_id=tracing.current_trace_id())

    # ---- routing -----------------------------------------------------------

    def do_GET(self):
        self._route()

    def do_POST(self):
        self._route()

    def _route(self):
        path = urllib.parse.urlparse(self.path).path
        self._traceparent = None
        try:
            if path == "/health" or path == "/ready":
                return self._send(200, {})
            if path in ("/dashboard", "/dashboard/"):
                from greptimedb_tpu.servers.dashboard import PAGE

                return self._send(200, PAGE.encode(),
                                  "text/html; charset=utf-8")
            if path == "/metrics":
                # content negotiation: an OpenMetrics scraper gets the
                # exemplar-bearing exposition (trace_id exemplars on
                # histogram buckets + the spec's # EOF), classic
                # scrapers keep the byte-stable text format
                om = "application/openmetrics-text" in \
                    (self.headers.get("Accept") or "")
                ctype = ("application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8") if om \
                    else "text/plain; version=0.0.4"
                return self._send(
                    200, REGISTRY.render(openmetrics=om).encode(), ctype)
            if self.user_provider is not None:
                # Basic auth on every data route (reference
                # servers/src/http/authorize.rs; /health and /metrics
                # stay open)
                from greptimedb_tpu.auth import AuthError
                try:
                    self._user = self.user_provider.authenticate_basic(
                        self.headers.get("Authorization") or "")
                except AuthError as e:
                    data = json.dumps({"code": 7002, "error": str(e)}).encode()
                    self.send_response(401)
                    self.send_header("WWW-Authenticate",
                                     'Basic realm="greptimedb"')
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    HTTP_REQUESTS.inc(path=path, status="401")
                    return
            from greptimedb_tpu.utils import tracing

            # every data route runs under a request root span: the
            # incoming W3C traceparent (if any) is adopted so our spans
            # join the caller's trace, and _send echoes the context back
            with tracing.request_span(f"http:{path}",
                                      traceparent=self.headers.get(
                                          "traceparent")):
                self._traceparent = tracing.to_traceparent()
                return self._route_traced(path)
        except DeadlineExceeded as e:
            # typed deadline expiry: the timeout shape (408), not 503 —
            # the client asked for the bound it just hit
            self._send(408, {"code": 3001, "error": str(e),
                             "execution_time_ms": 0})
        except Cancelled as e:
            # typed cancellation (KILL / DELETE-to-kill / disconnect):
            # nginx's 499 "client closed request" shape
            self._send(499, {"code": 3002, "error": str(e),
                             "execution_time_ms": 0})
        except Unavailable as e:
            # typed degradation (retries + route refresh exhausted): a
            # 503 the client should back off on, not a stack trace
            self._send(503, {"code": 5003, "error": str(e),
                             "execution_time_ms": 0})
        except Exception as e:  # noqa: BLE001 — wire boundary
            traceback.print_exc()
            self._send(400, {"code": 3000, "error": str(e),
                             "execution_time_ms": 0})

    def _route_traced(self, path: str):
        try:
            if path.startswith("/debug/pprof/"):
                # on-demand profiling (reference servers/src/http/pprof.rs
                # + mem_prof.rs) — folded CPU stacks / tracemalloc heap.
                # Sits BEHIND the auth gate: stack samples and heap
                # contents are sensitive (only /health and /metrics are
                # exempt, matching authorize.rs)
                from greptimedb_tpu.utils import profiling

                qs = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                if path == "/debug/pprof/cpu":
                    secs = min(float(qs.get("seconds", ["5"])[0]), 60.0)
                    out = profiling.sample_cpu(seconds=secs)
                    return self._send(200, out.encode(), "text/plain")
                if path == "/debug/pprof/mem":
                    if qs.get("action", [""])[0] == "stop":
                        out = profiling.mem_profile_stop()
                    else:
                        out = profiling.mem_profile(
                            top=int(qs.get("top", ["50"])[0]))
                    return self._send(200, out.encode(), "text/plain")
                return self._send(404, {"error": f"no route {path}"})
            if path == "/v1/faults":
                # chaos-state debug surface: armed points, partitions,
                # and per-series fire counts — what a red scenario run
                # pulls first to see which schedule actually hit
                from greptimedb_tpu.fault import FAULTS, chaos_seed
                from greptimedb_tpu.utils.metrics import FAULT_INJECTIONS

                return self._send(200, {
                    "chaos_seed": chaos_seed(),
                    "faults": FAULTS.describe(),
                    "partitions": FAULTS.partitions(),
                    "fired": [{"labels": labels, "count": count}
                              for labels, count in
                              FAULT_INJECTIONS.series()]})
            if path == "/v1/maintenance":
                # background maintenance plane debug surface: queue
                # depth + job list (newest first) + stall counters
                from greptimedb_tpu.utils.metrics import (
                    WRITE_STALL_SECONDS,
                )

                maint = getattr(self.query_engine.region_engine,
                                "maintenance", None)
                params = self._params()
                n = int(params.get("limit", "100"))
                return self._send(200, {
                    "enabled": maint is not None,
                    "queue_depth": maint.queue_depth() if maint else 0,
                    "rollup_rules": [
                        {"resolution_ms": r.resolution_ms,
                         "fields": list(r.fields), "auto": r.auto}
                        for r in (maint.rollup_rules if maint else [])],
                    "write_stall_seconds": WRITE_STALL_SECONDS.total(),
                    "jobs": [j.to_dict()
                             for j in (maint.jobs() if maint else [])[:n]],
                })
            if path == "/v1/profile/flame":
                # continuous profiler's rolling flame windows (auth-
                # gated like /v1/traces: stack frames leak code layout).
                # Default folded stacks (text); ?format=speedscope for
                # the JSON document; ?stage= filters to one stage
                from greptimedb_tpu.utils import flame

                params = self._params()
                if not flame.running():
                    return self._send(503, {
                        "error": "continuous profiling is disabled "
                                 "(enable [profiling] / GTPU_PROFILE)"})
                if params.get("format", "folded") == "speedscope":
                    return self._send(200, flame.speedscope())
                out = flame.folded(stage=params.get("stage") or None)
                return self._send(200, out.encode(), "text/plain")
            if path == "/v1/profile/cluster":
                # merged cluster profile: this node + every digest that
                # rode in on Flight piggybacks / heartbeats
                from greptimedb_tpu.utils import flame

                return self._send(200, flame.cluster_view())
            if path == "/v1/slow_queries":
                # debug surface of the slow-query ring; behind the auth
                # gate (query text is sensitive, unlike /metrics)
                from greptimedb_tpu.utils import slow_query

                params = self._params()
                n = int(params.get("limit", "50"))
                return self._send(200, {
                    "slow_queries": [r.to_dict()
                                     for r in slow_query.records(n)],
                    "threshold_ms": slow_query.threshold_ms()})
            if path.startswith("/v1/traces/"):
                # one trace's span tree by id (auth-gated like
                # /v1/slow_queries — span attrs carry query shape);
                # tools/trace_dump.py renders it, and the stage-
                # histogram exemplars at /metrics point here
                from greptimedb_tpu.utils import tracing

                tid = path.rsplit("/", 1)[1].lower()
                # accept the zero-padded 32-hex form our own
                # traceparent egress emits for internally-minted ids
                # (same normalization as parse_traceparent)
                if len(tid) == 32 and tid.startswith("0" * 16):
                    tid = tid[16:]
                spans = tracing.spans_for(tid)
                if not spans:
                    return self._send(404, {"error": f"no spans for "
                                                     f"trace {tid!r}"})
                wire = tracing.spans_to_wire(spans)
                for w, s in zip(wire, spans):
                    w["node"] = s.node
                return self._send(200, {
                    "trace_id": tid,
                    "spans": wire,
                    "tree": tracing.render_tree(spans)})
            if path == "/v1/queries" or path.startswith("/v1/queries/"):
                return self._handle_queries(path)
            if path == "/v1/sql":
                return self._handle_sql()
            if path == "/v1/promql":
                return self._handle_promql_range(v1=True)
            if path.startswith("/v1/prometheus/api/v1/") or path.startswith("/api/v1/"):
                sub = path.split("/api/v1/", 1)[1]
                if sub == "query_range":
                    return self._handle_promql_range()
                if sub == "query":
                    return self._handle_promql_instant()
                if sub == "labels":
                    return self._handle_labels()
                if sub.startswith("label/") and sub.endswith("/values"):
                    return self._handle_label_values(sub.split("/")[1])
                if sub == "series":
                    return self._handle_series()
                return self._send(404, _prom_err("unknown endpoint"))
            if path in ("/v1/influxdb/write", "/v1/influxdb/api/v2/write",
                        "/influxdb/write"):
                return self._handle_influx_write()
            if path in ("/v1/opentsdb/api/put", "/opentsdb/api/put"):
                return self._handle_opentsdb_put()
            if path in ("/v1/prometheus/write", "/v1/prometheus/api/v1/write"):
                return self._handle_prom_remote_write()
            if path in ("/v1/prometheus/read", "/v1/prometheus/api/v1/read"):
                return self._handle_prom_remote_read()
            if path in ("/v1/otlp/v1/metrics",):
                return self._handle_otlp_metrics()
            if path in ("/v1/otlp/v1/traces",):
                return self._handle_otlp_traces()
            if path == "/v1/scripts":
                return self._handle_scripts()
            if path == "/v1/run-script":
                return self._handle_run_script()
            return self._send(404, {"error": f"no route {path}"})
        except DeadlineExceeded as e:
            self._send(408, {"code": 3001, "error": str(e),
                             "execution_time_ms": 0})
        except Cancelled as e:
            self._send(499, {"code": 3002, "error": str(e),
                             "execution_time_ms": 0})
        except Unavailable as e:
            # typed degradation (retries + route refresh exhausted): a
            # 503 the client should back off on, not a stack trace
            self._send(503, {"code": 5003, "error": str(e),
                             "execution_time_ms": 0})
        except Exception as e:  # noqa: BLE001 — wire boundary
            traceback.print_exc()
            self._send(400, {"code": 3000, "error": str(e),
                             "execution_time_ms": 0})

    # ---- /v1/queries (running-queries surface) -----------------------------

    def do_DELETE(self):
        self._route()

    def _handle_queries(self, path: str):
        """GET /v1/queries lists live statements on this frontend;
        DELETE /v1/queries/<id> cancels one (the HTTP twin of
        KILL QUERY <id>)."""
        from greptimedb_tpu.utils import deadline

        if self.command == "DELETE":
            qid_s = path[len("/v1/queries/"):] \
                if path.startswith("/v1/queries/") else ""
            try:
                qid = int(qid_s)
            except ValueError:
                return self._send(400,
                                  {"error": f"bad query id {qid_s!r}"})
            if deadline.RUNNING.kill(qid, reason="DELETE /v1/queries"):
                return self._send(200, {"killed": qid})
            return self._send(404, {"error": f"no running query {qid}"})
        return self._send(200, {"queries": deadline.RUNNING.list()})

    # ---- /v1/sql (reference http.rs:724 sql handler) -----------------------

    def _handle_sql(self):
        from greptimedb_tpu.servers.encode import encode_sql_payload
        from greptimedb_tpu.utils import deadline

        params = self._form_or_query()
        sql = params.get("sql")
        if not sql:
            return self._send(400, {"code": 1004, "error": "missing sql"})
        ctx = self._ctx(params)
        # pre-create the statement token so a client that hangs up
        # mid-execution cancels the work it abandoned (the engine arms
        # the deadline and registers it in the running-queries table)
        token = deadline.CancelToken()
        ctx.cancel_token = token
        stop_watch = deadline.watch_disconnect(self.connection, token)
        t0 = time.perf_counter()
        try:
            with QUERY_DURATION.time(kind="sql"):
                results = self.query_engine.execute_sql(sql, ctx)
        finally:
            stop_watch()
        # the admission slot was released inside execute_sql (at
        # execute-done): serialization below never occupies an
        # execution slot, and runs on the bounded encode pool rather
        # than this request thread (byte-identical either way)
        elapsed = round((time.perf_counter() - t0) * 1000, 3)
        pool = getattr(self.query_engine.concurrency, "encode", None)
        if pool is not None:
            rows = sum(r.num_rows for r in results if r.is_query)
            data = pool.run(encode_sql_payload, results, elapsed,
                            cost_rows=rows, shm_result=True)
        else:
            data = encode_sql_payload(results, elapsed)
        self._send(200, data)

    # ---- Prometheus API (reference http.rs:724-744) ------------------------

    def _handle_promql_range(self, v1=False):
        from greptimedb_tpu.promql.engine import PromqlEngine, SeriesMatrix

        params = self._form_or_query()
        query = params.get("query") or params.get("promql")
        if not query:
            return self._send(400, _prom_err("missing query"))
        try:
            start = _prom_time(params["start"])
            end = _prom_time(params["end"])
            step = _prom_duration(params.get("step", "60"))
        except (KeyError, ValueError) as e:
            return self._send(400, _prom_err(f"bad range params: {e}"))
        ctx = self._ctx(params)
        engine = PromqlEngine(self.query_engine)
        with QUERY_DURATION.time(kind="promql_range"):
            times, result = engine.eval_matrix(query, start, end, step, ctx)
        if isinstance(result, SeriesMatrix):
            payload = _matrix_json(times, result)
        else:
            vals = np.broadcast_to(np.asarray(result, dtype=np.float64),
                                   times.shape)
            payload = {"resultType": "matrix",
                       "result": [{"metric": {},
                                   "values": _values_json(times, vals)}]}
        self._send(200, {"status": "success", "data": payload})

    def _handle_promql_instant(self):
        from greptimedb_tpu.promql.engine import PromqlEngine, SeriesMatrix

        params = self._form_or_query()
        query = params.get("query")
        if not query:
            return self._send(400, _prom_err("missing query"))
        t = _prom_time(params.get("time", str(time.time())))
        ctx = self._ctx(params)
        engine = PromqlEngine(self.query_engine)
        with QUERY_DURATION.time(kind="promql_instant"):
            times, result = engine.eval_matrix(query, t, t, 1.0, ctx)
        if isinstance(result, SeriesMatrix):
            vals = np.asarray(result.values)
            out = []
            for i, lab in enumerate(result.labels):
                v = vals[i, -1]
                if math.isnan(v):
                    continue
                metric = dict(lab)
                if result.metric:
                    metric["__name__"] = result.metric
                out.append({"metric": metric, "value": [t, _fmt_float(v)]})
            payload = {"resultType": "vector", "result": out}
        else:
            v = float(np.asarray(result).reshape(-1)[-1])
            payload = {"resultType": "scalar", "value": [t, _fmt_float(v)]}
        self._send(200, {"status": "success", "data": payload})

    def _handle_labels(self):
        params = self._form_or_query()
        ctx = self._ctx(params)
        qe = self.query_engine
        labels = {"__name__"}
        matches = _match_params(self)
        tables = [m for m in matches] or qe.catalog.list_tables(ctx.db)
        for t in tables:
            try:
                info = qe.catalog.table(ctx.db, _metric_of(t))
            except CatalogError:
                continue  # matcher named a non-existent metric: skip it
            labels.update(c.name for c in info.schema.tag_columns)
        self._send(200, {"status": "success", "data": sorted(labels)})

    def _handle_label_values(self, label: str):
        params = self._form_or_query()
        ctx = self._ctx(params)
        qe = self.query_engine
        if label == "__name__":
            return self._send(200, {"status": "success",
                                    "data": sorted(qe.catalog.list_tables(ctx.db))})
        values: set = set()
        for t in qe.catalog.list_tables(ctx.db):
            try:
                info = qe._table(t, ctx)
            except (CatalogError, Unavailable, FaultError,
                    OSError, ValueError):
                # dropped concurrently, or its region failed to open
                # (WAL replay / manifest read): label discovery skips
                # the broken table instead of failing the endpoint
                continue
            if label not in {c.name for c in info.schema.tag_columns}:
                continue
            for rid in info.region_ids:  # union across all regions
                region = qe.region_engine.region(rid)
                values.update(str(v) for v in region.registry.values.get(label, []))
        self._send(200, {"status": "success", "data": sorted(values)})

    def _handle_series(self):
        from greptimedb_tpu.promql.engine import PromqlEngine, SeriesMatrix

        params = self._form_or_query()
        matches = _match_params(self)
        if not matches:
            return self._send(400, _prom_err("match[] required"))
        start = _prom_time(params.get("start", "0"))
        end = _prom_time(params.get("end", str(time.time())))
        ctx = self._ctx(params)
        engine = PromqlEngine(self.query_engine)
        from greptimedb_tpu.promql.parser import parse_promql, VectorSelector
        out = []
        for m in matches:
            node = parse_promql(m)
            if isinstance(node, VectorSelector):
                # series existence over the whole [start, end] range: one
                # eval at `end` with the range as the lookback window
                from greptimedb_tpu.promql.engine import EvalParams
                p = EvalParams(end, end, 1.0, np.asarray([end]))
                result = engine._eval_instant_selector(
                    node, p, ctx, lookback=max(end - start, 1.0))
            else:
                _, result = engine.eval_matrix(m, end, end, 1.0, ctx)
            if isinstance(result, SeriesMatrix):
                vals = np.asarray(result.values)
                for i, lab in enumerate(result.labels):
                    if vals.size and np.isnan(vals[i]).all():
                        continue
                    metric = dict(lab)
                    if result.metric:
                        metric["__name__"] = result.metric
                    out.append(metric)
        self._send(200, {"status": "success", "data": out})

    # ---- write protocols ---------------------------------------------------

    def _handle_influx_write(self):
        from greptimedb_tpu.servers.influx import (
            LineProtocolError,
            write_lines,
        )

        params = self._form_or_query()
        body = getattr(self, "_raw_body", b"") or self._body()
        db = params.get("db") or params.get("bucket") or "public"
        precision = params.get("precision", "ns")
        try:
            n = write_lines(self.query_engine, db, body.decode(), precision)
        except LineProtocolError as e:
            # typed 4xx naming the bad line numbers: a torn/partial line
            # from a crashed client must fail loudly, never silently
            # sink the rest of the batch (_send counts the request)
            return self._send(400, {"code": 1004, "error": str(e),
                                    "lines": e.lines})
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()
        HTTP_REQUESTS.inc(path="/v1/influxdb/write", status="204")
        _ = n

    def _handle_prom_remote_write(self):
        from greptimedb_tpu.servers.prom_store import handle_remote_write

        params = self._params()
        body = self._body()
        db = params.get("db", "public")
        handle_remote_write(self.query_engine, body, db)
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()
        HTTP_REQUESTS.inc(path="/v1/prometheus/write", status="204")

    def _handle_prom_remote_read(self):
        from greptimedb_tpu.servers.prom_store import handle_remote_read

        params = self._params()
        body = self._body()
        db = params.get("db", "public")
        resp = handle_remote_read(self.query_engine, body, db)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-protobuf")
        self.send_header("Content-Encoding", "snappy")
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)
        HTTP_REQUESTS.inc(path="/v1/prometheus/read", status="200")

    def _handle_otlp_metrics(self):
        from greptimedb_tpu.servers.otlp import handle_otlp_metrics

        body = self._body()
        db = self._params().get("db", "public")
        n = handle_otlp_metrics(self.query_engine, body, db)
        self._send(200, {"partialSuccess": {}})
        _ = n

    def _handle_otlp_traces(self):
        from greptimedb_tpu.servers.otlp import handle_otlp_traces

        body = self._body()
        db = self._params().get("db", "public")
        n = handle_otlp_traces(self.query_engine, body, db)
        self._send(200, {"partialSuccess": {}})
        _ = n

    # ---- scripts (reference http.rs scripts router + src/script) -----------

    def _script_engine(self):
        qe = self.query_engine
        if not hasattr(qe, "_script_engine"):
            from greptimedb_tpu.script import ScriptEngine
            qe._script_engine = ScriptEngine(qe)
        return qe._script_engine

    def _handle_scripts(self):
        from greptimedb_tpu.script import ScriptError

        params = self._params()
        db = params.get("db", "public")
        name = params.get("name")
        if self.command == "GET":
            if name:
                code = self._script_engine().get_script(db, name)
                if code is None:
                    return self._send(404, {"error": f"script {name!r} not found"})
                return self._send(200, {"code": 0, "script": code})
            return self._send(200, {"code": 0,
                                    "scripts": self._script_engine().list_scripts(db)})
        if not name:
            return self._send(400, {"error": "missing name"})
        code = self._body().decode()
        try:
            self._script_engine().insert_script(db, name, code)
        except ScriptError as e:
            return self._send(400, {"code": 1004, "error": str(e)})
        return self._send(200, {"code": 0})

    def _handle_run_script(self):
        from greptimedb_tpu.script import ScriptError

        params = self._params()
        db = params.get("db", "public")
        name = params.get("name")
        if not name:
            return self._send(400, {"error": "missing name"})
        t0 = time.perf_counter()
        try:
            with QUERY_DURATION.time(kind="script"):
                result = self._script_engine().run_script(db, name)
        except ScriptError as e:
            return self._send(400, {"code": 1004, "error": str(e)})
        elapsed = round((time.perf_counter() - t0) * 1000, 3)
        return self._send(200, {"code": 0,
                                "output": [{"records": _records_json(result)}],
                                "execution_time_ms": elapsed})

    def _handle_opentsdb_put(self):
        """OpenTSDB JSON put (reference servers/src/opentsdb.rs +
        http.rs:793-797)."""
        from greptimedb_tpu.servers.influx import Point, write_points

        body = self._body()
        data = json.loads(body.decode())
        if isinstance(data, dict):
            data = [data]
        points = []
        for d in data:
            ts = int(d["timestamp"])
            # OpenTSDB: seconds or milliseconds by magnitude
            ts_ms = ts * 1000 if ts < 10_000_000_000 else ts
            points.append(Point(
                measurement=d["metric"],
                tags=sorted(d.get("tags", {}).items()),
                fields=[("greptime_value", float(d["value"]))],
                ts=ts_ms,
            ))
        n = write_points(self.query_engine, "public", points, precision="ms")
        self._send(200, {"success": n, "failed": 0})


# ---- formatting ------------------------------------------------------------


def _records_json(r: QueryResult) -> dict:
    # columnar encoding (timestamps stay epoch ints, like greptime's
    # HTTP default) — shared with the encode-pool workers
    from greptimedb_tpu.servers.encode import records_json

    return records_json(r)


def _matrix_json(times: np.ndarray, sm) -> dict:
    vals = np.asarray(sm.values)
    out = []
    for i, lab in enumerate(sm.labels):
        metric = dict(lab)
        if sm.metric:
            metric["__name__"] = sm.metric
        series_vals = _values_json(times, vals[i])
        if series_vals:
            out.append({"metric": metric, "values": series_vals})
    return {"resultType": "matrix", "result": out}


def _values_json(times: np.ndarray, vals: np.ndarray) -> list:
    out = []
    for t, v in zip(times.tolist(), np.asarray(vals).tolist()):
        if v is None or (isinstance(v, float) and math.isnan(v)):
            continue
        out.append([t, _fmt_float(v)])
    return out


def _fmt_float(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def _prom_err(msg: str) -> dict:
    return {"status": "error", "errorType": "bad_data", "error": msg}


def _prom_time(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        pass
    import datetime as dt
    t = s.replace("Z", "+00:00")
    return dt.datetime.fromisoformat(t).timestamp()


def _prom_duration(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        from greptimedb_tpu.promql.parser import parse_duration_s
        return parse_duration_s(s)


def _match_params(handler: _Handler) -> list[str]:
    parsed = urllib.parse.urlparse(handler.path)
    qs = urllib.parse.parse_qs(parsed.query)
    matches = qs.get("match[]", [])
    body = getattr(handler, "_raw_body", b"")
    if body:
        try:
            form = urllib.parse.parse_qs(body.decode())
            matches += form.get("match[]", [])
        except UnicodeDecodeError:
            pass
    return matches


def _metric_of(match_expr: str) -> str:
    """Metric name from a simple match[] selector."""
    return match_expr.split("{")[0].strip() or match_expr
