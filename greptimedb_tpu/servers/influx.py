"""InfluxDB line protocol ingestion (mirrors reference servers::influxdb +
operator Inserter auto-create, src/operator/src/insert.rs:112).

`measurement,tag=a,tag2=b field=1.0,field2=2i 1465839830100400200`

Tables are auto-created on first write (tags -> TAG STRING columns, fields
typed from the first-seen value, `ts` time index); later writes with new
fields auto-ALTER.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.datatypes import (
    ColumnSchema, DataType, DictVector, RecordBatch, Schema, SemanticType,
)
from greptimedb_tpu.utils.metrics import INGEST_ROWS


class LineProtocolError(Exception):
    pass


@dataclass
class Point:
    measurement: str
    tags: list[tuple[str, str]]
    fields: list[tuple[str, object]]
    ts: Optional[int]  # raw integer timestamp (precision applied later)


def parse_line_protocol(text: str) -> list[Point]:
    points = []
    for raw in text.split("\n"):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        points.append(_parse_line(line))
    return points


def _split_unescaped(s: str, sep: str, escapable: str) -> list[str]:
    parts, cur, i = [], [], 0
    in_quote = False
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s):
            cur.append(ch)
            cur.append(s[i + 1])
            i += 2
            continue
        if ch == '"':
            in_quote = not in_quote
            cur.append(ch)
        elif ch == sep and not in_quote:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return parts


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_line(line: str) -> Point:
    # split into measurement+tags | fields | timestamp on unescaped spaces
    sections = _split_unescaped(line, " ", ", ")
    sections = [s for s in sections if s != ""]
    if len(sections) < 2:
        raise LineProtocolError(f"malformed line: {line!r}")
    head = sections[0]
    fields_part = sections[1]
    ts = None
    if len(sections) >= 3:
        try:
            ts = int(sections[2])
        except ValueError:
            raise LineProtocolError(f"bad timestamp in {line!r}")
    head_parts = _split_unescaped(head, ",", " ,")
    measurement = _unescape(head_parts[0])
    tags = []
    for t in head_parts[1:]:
        if "=" not in t:
            raise LineProtocolError(f"bad tag {t!r}")
        k, v = t.split("=", 1)
        tags.append((_unescape(k), _unescape(v)))
    fields = []
    for f in _split_unescaped(fields_part, ",", " ,"):
        if "=" not in f:
            raise LineProtocolError(f"bad field {f!r}")
        k, v = f.split("=", 1)
        fields.append((_unescape(k), _parse_field_value(v)))
    if not fields:
        raise LineProtocolError(f"no fields in {line!r}")
    return Point(measurement, tags, fields, ts)


def _parse_field_value(v: str):
    if v.startswith('"') and v.endswith('"') and len(v) >= 2:
        return v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if v in ("t", "T", "true", "True", "TRUE"):
        return True
    if v in ("f", "F", "false", "False", "FALSE"):
        return False
    if v.endswith("i") or v.endswith("u"):
        return int(v[:-1])
    return float(v)


# precision -> (numerator, denominator) for exact integer ts -> ms
# conversion (ns-epoch values exceed 2^53, so float math loses precision)
_PRECISION_TO_MS = {"ns": (1, 1_000_000), "u": (1, 1000), "us": (1, 1000),
                    "ms": (1, 1), "s": (1000, 1), "m": (60_000, 1),
                    "h": (3_600_000, 1)}


def write_points(query_engine, db: str, points: list[Point],
                 precision: str = "ns") -> int:
    """Group points per measurement, auto-create/alter tables, write."""
    import time as _time

    from greptimedb_tpu.query.engine import QueryContext

    scale = _PRECISION_TO_MS.get(precision)
    if scale is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    ctx = QueryContext(db=db)
    by_table: dict[str, list[Point]] = {}
    for p in points:
        by_table.setdefault(p.measurement, []).append(p)
    total = 0
    now_ms = int(_time.time() * 1000)
    for table_name, pts in by_table.items():
        info = _ensure_table(query_engine, ctx, table_name, pts)
        schema = info.schema
        n = len(pts)
        tag_names = [c.name for c in schema.tag_columns]
        field_names = [c.name for c in schema.field_columns]
        cols: dict = {}
        for t in tag_names:
            cols[t] = DictVector.encode(
                [dict(p.tags).get(t) for p in pts]
            )
        num, den = scale
        ts_vals = np.asarray(
            [now_ms if p.ts is None else int(p.ts) * num // den for p in pts],
            dtype=np.int64,
        )
        cols[schema.time_index.name] = ts_vals
        for fn in field_names:
            c = schema.column(fn)
            vals = [dict(p.fields).get(fn) for p in pts]
            if c.dtype.is_float:
                cols[fn] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals])
            elif c.dtype is DataType.BOOL:
                cols[fn] = np.asarray([bool(v) for v in vals])
            elif c.dtype.is_string:
                cols[fn] = DictVector.encode(
                    [None if v is None else str(v) for v in vals])
            else:
                cols[fn] = np.asarray(
                    [0 if v is None else int(v) for v in vals], dtype=np.int64)
        batch = RecordBatch(schema, cols)
        # route through the partition-aware write sharding so line-protocol
        # and SQL writes agree on row→region placement
        total += query_engine._sharded_write(info, batch, delete=False)
    INGEST_ROWS.inc(total, protocol="influxdb")
    return total


def _ensure_table(query_engine, ctx, name: str, pts: list[Point]):
    qe = query_engine
    tags_seen = list(dict.fromkeys(k for p in pts for k, _ in p.tags))
    fields_seen: dict[str, object] = {}
    for p in pts:
        for k, v in p.fields:
            fields_seen.setdefault(k, v)
    try:
        info = qe._table(name, ctx)
    except CatalogError:
        cols = [ColumnSchema(t, DataType.STRING, SemanticType.TAG) for t in tags_seen]
        cols.append(ColumnSchema("ts", DataType.TIMESTAMP_MILLISECOND,
                                 SemanticType.TIMESTAMP, nullable=False))
        for fn, v in fields_seen.items():
            cols.append(ColumnSchema(fn, _field_type(v), SemanticType.FIELD))
        schema = Schema(cols)
        info = qe.catalog.create_table(ctx.db, name, schema, options={},
                                       if_not_exists=True)
        for rid in info.region_ids:
            qe.region_engine.create_region(rid, schema)
            qe._open_regions.add(rid)
        return info
    # auto-ALTER for new field columns (reference insert.rs:112
    # create_or_alter_tables_on_demand)
    missing = [fn for fn in fields_seen if fn not in info.schema]
    missing_tags = [t for t in tags_seen if t not in info.schema]
    if missing_tags:
        raise LineProtocolError(
            f"new tag column(s) {missing_tags} on existing table {name!r} "
            "are not supported")
    if missing:
        from greptimedb_tpu.sql import ast
        for fn in missing:
            dt = _field_type(fields_seen[fn])
            type_name = {"float64": "DOUBLE", "int64": "BIGINT",
                         "bool": "BOOLEAN", "string": "STRING"}[dt.value]
            qe.execute_statement(
                ast.AlterTable(name, "add_column",
                               column=ast.ColumnDef(fn, type_name)), ctx)
        info = qe._table(name, ctx)
    return info


def _field_type(v) -> DataType:
    if isinstance(v, bool):
        return DataType.BOOL
    if isinstance(v, int):
        # stored as FLOAT64: integer columns have no NULL representation in
        # the columnar store yet, and sparse influx fields need NULLs
        return DataType.FLOAT64
    if isinstance(v, str):
        return DataType.STRING
    return DataType.FLOAT64
