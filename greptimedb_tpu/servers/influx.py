"""InfluxDB line protocol ingestion (mirrors reference servers::influxdb +
operator Inserter auto-create, src/operator/src/insert.rs:112).

`measurement,tag=a,tag2=b field=1.0,field2=2i 1465839830100400200`

Tables are auto-created on first write (tags -> TAG STRING columns, fields
typed from the first-seen value, `ts` time index); later writes with new
fields auto-ALTER (all new columns in one schema swap).

Hot path: `write_lines` parses straight into per-table column slabs
(greptimedb_tpu/ingest.py) — escape-free lines (the overwhelming
Telegraf/TSBS shape) take a split-based fast lane, escaped/quoted lines
fall back to the char-walking parser — and lands as one RecordBatch per
table on the bulk write path. Malformed lines reject the request with a
typed error naming every bad line NUMBER (a torn half-line from a
crashed client must 4xx loudly, not vanish with the rest of the batch).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np

from greptimedb_tpu.ingest import TableSlab, write_slabs
from greptimedb_tpu.utils.metrics import INGEST_ROWS

__all__ = ["LineProtocolError", "Point", "parse_line_protocol",
           "parse_lines_columnar", "write_lines", "write_points"]


class LineProtocolError(Exception):
    """Malformed line-protocol input. `lines` carries the 1-based line
    numbers at fault (the HTTP layer renders them in its 400 body)."""

    def __init__(self, msg: str, lines: Optional[list[int]] = None):
        super().__init__(msg)
        self.lines = lines or []


@dataclass
class Point:
    measurement: str
    tags: list[tuple[str, str]]
    fields: list[tuple[str, object]]
    ts: Optional[int]  # raw integer timestamp (precision applied later)


def parse_line_protocol(text: str) -> list[Point]:
    points = []
    for raw in text.split("\n"):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        points.append(_parse_line(line))
    return points


def _split_unescaped(s: str, sep: str, escapable: str) -> list[str]:
    parts, cur, i = [], [], 0
    in_quote = False
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s):
            cur.append(ch)
            cur.append(s[i + 1])
            i += 2
            continue
        if ch == '"':
            in_quote = not in_quote
            cur.append(ch)
        elif ch == sep and not in_quote:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return parts


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_line(line: str) -> Point:
    # split into measurement+tags | fields | timestamp on unescaped spaces
    sections = _split_unescaped(line, " ", ", ")
    sections = [s for s in sections if s != ""]
    if len(sections) < 2 or len(sections) > 3:
        # > 3: trailing junk after the timestamp — rejecting matches the
        # fast/fused lanes (silently dropping sections would make the
        # lanes diverge on escaped lines)
        raise LineProtocolError(f"malformed line: {line!r}")
    head = sections[0]
    fields_part = sections[1]
    ts = None
    if len(sections) >= 3:
        try:
            ts = int(sections[2])
        except ValueError:
            raise LineProtocolError(f"bad timestamp in {line!r}")
    head_parts = _split_unescaped(head, ",", " ,")
    measurement = _unescape(head_parts[0])
    tags = []
    for t in head_parts[1:]:
        if "=" not in t:
            raise LineProtocolError(f"bad tag {t!r}")
        k, v = t.split("=", 1)
        tags.append((_unescape(k), _unescape(v)))
    fields = []
    for f in _split_unescaped(fields_part, ",", " ,"):
        if "=" not in f:
            raise LineProtocolError(f"bad field {f!r}")
        k, v = f.split("=", 1)
        fields.append((_unescape(k), _parse_field_value(v)))
    if not fields:
        raise LineProtocolError(f"no fields in {line!r}")
    return Point(measurement, tags, fields, ts)


def _parse_field_value(v: str):
    if v.startswith('"') and v.endswith('"') and len(v) >= 2:
        return v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if v in ("t", "T", "true", "True", "TRUE"):
        return True
    if v in ("f", "F", "false", "False", "FALSE"):
        return False
    try:
        if v.endswith("i") or v.endswith("u"):
            return int(v[:-1])
        out = float(v)
    except ValueError:
        raise LineProtocolError(f"bad field value {v!r}") from None
    if not math.isfinite(out):
        # the wire protocol has no NaN/inf literals — Python's float()
        # accepting "NaN"/"inf" silently would store poison values a
        # SUM/AVG then spreads over the whole window
        raise LineProtocolError(f"non-finite field value {v!r}")
    return out


# precision -> (numerator, denominator) for exact integer ts -> ms
# conversion (ns-epoch values exceed 2^53, so float math loses precision)
_PRECISION_TO_MS = {"ns": (1, 1_000_000), "u": (1, 1000), "us": (1, 1000),
                    "ms": (1, 1), "s": (1000, 1), "m": (60_000, 1),
                    "h": (3_600_000, 1)}


_NUM_LEAD = frozenset("0123456789-+.")


def _parse_line_fast(line: str):
    """Escape-free fast lane: plain str.split + an inlined numeric
    field decode — no char walking, no per-value function call for the
    overwhelming float case. Lines carrying backslashes or quotes take
    the full escape-aware parser. Returns
    (measurement, tags, fields, raw_ts)."""
    if "\\" in line or '"' in line:
        p = _parse_line(line)
        return p.measurement, p.tags, p.fields, p.ts
    sections = line.split(" ")
    ns = len(sections)
    if ns == 3:
        head, fields_part, ts_part = sections
        try:
            ts = int(ts_part)
        except ValueError:
            raise LineProtocolError(
                f"bad timestamp in {line!r}") from None
    elif ns == 2:
        head, fields_part = sections
        ts = None
    else:
        # consecutive unescaped spaces (or a lone measurement): re-split
        # tolerantly, then re-validate
        sections = [s for s in sections if s]
        if len(sections) < 2 or len(sections) > 3:
            raise LineProtocolError(f"malformed line: {line!r}")
        return _parse_line_fast(" ".join(sections))
    head_parts = head.split(",")
    measurement = head_parts[0]
    if not measurement:
        raise LineProtocolError(f"missing measurement in {line!r}")
    tags = []
    for t in head_parts[1:]:
        k, sep, v = t.partition("=")
        if not sep or not k:
            raise LineProtocolError(f"bad tag {t!r}")
        tags.append((k, v))
    fields = []
    for fkv in fields_part.split(","):
        k, sep, v = fkv.partition("=")
        if not sep or not k or not v:
            raise LineProtocolError(f"bad field {fkv!r}")
        if v[0] in _NUM_LEAD:
            try:
                if v[-1] in "iu":
                    fv = int(v[:-1])
                else:
                    fv = float(v)
                    if not math.isfinite(fv):
                        raise LineProtocolError(
                            f"non-finite field value {v!r}")
            except ValueError:
                raise LineProtocolError(
                    f"bad field value {v!r}") from None
        else:
            # bools, quoted strings, and float() spellings like "inf"
            # that must be rejected with the right message
            fv = _parse_field_value(v)
        fields.append((k, fv))
    return measurement, tags, fields, ts


def parse_lines_columnar(text: str, precision: str = "ns",
                         now_ms: Optional[int] = None
                         ) -> dict[str, TableSlab]:
    """Parse a whole request body straight into per-measurement column
    slabs. ANY malformed line rejects the request with a typed error
    listing every bad line number — partial/torn lines must never
    silently drop (or silently take the batch down with them).

    The regular shape (no escapes/quotes, 2-3 space-separated sections
    — the entire Telegraf/TSBS stream) takes a FUSED lane: split,
    numeric decode, and column append happen in one pass with no
    per-line function call and no intermediate (key, value) tuples.
    Irregular lines fall back to `_parse_line_fast` (which itself falls
    back to the escape-aware char walker); both lanes produce identical
    rows — the parse-fuzz suite pins that."""
    import time as _time

    scale = _PRECISION_TO_MS.get(precision)
    if scale is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    num, den = scale
    if now_ms is None:
        now_ms = int(_time.time() * 1000)
    slabs: dict[str, TableSlab] = {}
    bad: list[tuple[int, str]] = []

    def slow_lane(line: str, line_no: int) -> None:
        try:
            measurement, tags, fields, ts = _parse_line_fast(line)
        except LineProtocolError as e:
            bad.append((line_no, str(e)))
            return
        slab = slabs.get(measurement)
        if slab is None:
            slab = slabs[measurement] = TableSlab()
        slab.add_row(tags, fields,
                     now_ms if ts is None else ts * num // den)

    for line_no, raw in enumerate(text.split("\n"), 1):
        line = raw.strip()
        if not line or line[0] == "#":
            continue
        if "\\" in line or '"' in line:
            slow_lane(line, line_no)
            continue
        sections = line.split(" ")
        ns = len(sections)
        if ns == 3:
            head, fields_part, ts_part = sections
            try:
                ts_ms = int(ts_part) * num // den
            except ValueError:
                bad.append((line_no, f"bad timestamp in {line!r}"))
                continue
        elif ns == 2:
            head, fields_part = sections
            ts_ms = now_ms
        else:
            slow_lane(line, line_no)  # double spaces / lone measurement
            continue
        head_parts = head.split(",")
        measurement = head_parts[0]
        if not measurement:
            bad.append((line_no, f"missing measurement in {line!r}"))
            continue
        slab = slabs.get(measurement)
        if slab is None:
            slab = slabs[measurement] = TableSlab()
        r = slab.rows
        tag_cols = slab.tags
        field_cols = slab.fields
        appended = 0
        nfields = 0
        err = None
        for t in head_parts[1:]:
            k, sep, v = t.partition("=")
            if not sep or not k:
                err = f"bad tag {t!r}"
                break
            col = tag_cols.get(k)
            if col is None:
                col = tag_cols[k] = [None] * r
            if len(col) == r:
                col.append(v)
                appended += 1
            else:
                col[-1] = v
        if err is None:
            for fkv in fields_part.split(","):
                k, sep, v = fkv.partition("=")
                if not sep or not k or not v:
                    err = f"bad field {fkv!r}"
                    break
                if v[0] in _NUM_LEAD:
                    try:
                        if v[-1] in "iu":
                            fv = int(v[:-1])
                        else:
                            fv = float(v)
                            if not math.isfinite(fv):
                                err = f"non-finite field value {v!r}"
                                break
                    except ValueError:
                        err = f"bad field value {v!r}"
                        break
                else:
                    try:
                        fv = _parse_field_value(v)
                    except LineProtocolError as e:
                        err = str(e)
                        break
                nfields += 1
                col = field_cols.get(k)
                if col is None:
                    col = field_cols[k] = [None] * r
                if len(col) == r:
                    col.append(fv)
                    appended += 1
                else:
                    col[-1] = fv
        if err is None and nfields == 0:
            err = f"no fields in {line!r}"
        if err is not None:
            # roll the partial row back out of the slab columns
            for col in tag_cols.values():
                if len(col) > r:
                    col.pop()
            for col in field_cols.values():
                if len(col) > r:
                    col.pop()
            bad.append((line_no, err))
            continue
        slab.ts.append(ts_ms)
        slab.rows = r + 1
        if appended != len(tag_cols) + len(field_cols):
            for col in tag_cols.values():
                if len(col) != slab.rows:
                    col.append(None)
            for col in field_cols.values():
                if len(col) != slab.rows:
                    col.append(None)
    if bad:
        shown = "; ".join(f"line {n}: {m}" for n, m in bad[:5])
        more = f" (+{len(bad) - 5} more)" if len(bad) > 5 else ""
        raise LineProtocolError(
            f"rejected {len(bad)} bad line(s): {shown}{more}",
            lines=[n for n, _ in bad])
    return slabs


def _vector_parse(text: str, num: int, den: int, now_ms: int):
    """Zero-copy columnar lane for the regular single-measurement shape
    (the entire Telegraf/TSBS stream): rewrite the body's section
    separators to commas and hand it to Arrow's C CSV reader, then
    validate + strip the `key=` prefixes and decode values with
    vectorized kernels — the whole parse runs at memory bandwidth,
    releases the GIL, and lands directly in dictionary/float columns.

    Returns {measurement: VectorSlab} or None when ANY precondition
    fails (escapes, quotes, comments, mixed measurements, ragged rows,
    non-float fields, non-finite values, inconsistent key order) — the
    Python lanes then re-parse with exact per-line diagnostics. The
    parity test pins both lanes to identical batches."""
    if "\\" in text or '"' in text or "#" in text:
        return None
    body = text.strip()
    if not body:
        return None
    # single-measurement precheck at C speed BEFORE paying the CSV
    # parse: every line must open with the first line's measurement (a
    # typical Telegraf batch mixes cpu/mem/disk... — those bodies must
    # not pay a full Arrow pass that is guaranteed to be discarded)
    meas_end = min((body + ",").find(","), (body + " ").find(" "))
    meas = body[:meas_end]
    if not meas:
        return None
    nl = body.count("\n")
    if body.count("\n" + meas + ",") + body.count("\n" + meas + " ") != nl:
        return None
    import pyarrow as pa
    from pyarrow import compute as pc
    from pyarrow import csv as pacsv

    from greptimedb_tpu.datatypes.vector import DictVector
    from greptimedb_tpu.ingest import VectorSlab

    head = body.split("\n", 1)[0]
    try:
        measurement, first_tags, first_fields, first_ts = \
            _parse_line_fast(head)
    except LineProtocolError:
        return None
    if not first_fields or any(not isinstance(v, float)
                               for _, v in first_fields):
        return None  # int/bool/string fields: the Python lanes decode
    try:
        table = pacsv.read_csv(
            pa.BufferReader(body.replace(" ", ",").encode()),
            read_options=pacsv.ReadOptions(
                autogenerate_column_names=True),
            parse_options=pacsv.ParseOptions(delimiter=","))
    except pa.ArrowInvalid:
        return None  # ragged rows (mixed shapes / torn lines)
    ncols = table.num_columns
    has_ts = first_ts is not None
    nkv = len(first_tags) + len(first_fields)
    if ncols != 1 + nkv + (1 if has_ts else 0):
        return None
    n = table.num_rows
    c0 = table.column(0)
    if not (pa.types.is_string(c0.type)
            and pc.all(pc.equal(c0, measurement)).as_py()):
        return None
    if has_ts:
        ts_col = table.column(ncols - 1)
        if not pa.types.is_integer(ts_col.type):
            return None
        raw = ts_col.to_numpy(zero_copy_only=False).astype(np.int64)
        if ts_col.null_count:
            return None
        ts = raw * num // den if (num, den) != (1, 1) else raw
    else:
        ts = np.full(n, now_ms, dtype=np.int64)
    tags: dict = {}
    fields: dict = {}
    keys = [k for k, _ in first_tags] + [k for k, _ in first_fields]
    for i, key in enumerate(keys, start=1):
        col = table.column(i)
        if not pa.types.is_string(col.type) or col.null_count:
            return None
        col = col.combine_chunks()
        prefix = key + "="
        if not pc.all(pc.starts_with(col, prefix)).as_py():
            return None  # key order varies across lines
        vals = pc.utf8_slice_codeunits(col, start=len(prefix),
                                       stop=1 << 30)
        if i <= len(first_tags):
            d = vals.dictionary_encode()
            tags[key] = DictVector(
                d.indices.to_numpy(zero_copy_only=False).astype(
                    np.int32),
                d.dictionary.to_numpy(zero_copy_only=False).astype(
                    object))
        else:
            try:
                f = pc.cast(vals, pa.float64())
            except pa.ArrowInvalid:
                return None  # suffixed ints / bools mid-column
            if f.null_count or not pc.all(pc.is_finite(f)).as_py():
                # Arrow parses "inf"/"nan" silently — the Python lane
                # must produce the line-numbered rejection instead
                return None
            fields[key] = f.to_numpy(zero_copy_only=False)
    return {measurement: VectorSlab(n, tags, fields, ts)}


def write_lines(query_engine, db: str, text: str,
                precision: str = "ns") -> int:
    """The line-protocol front door: columnar parse + bulk write (one
    RecordBatch per measurement, one partition scatter, group-committed
    WAL). Raises LineProtocolError (HTTP 400) on any malformed line."""
    import time as _time

    from greptimedb_tpu.query.engine import QueryContext

    scale = _PRECISION_TO_MS.get(precision)
    if scale is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    now_ms = int(_time.time() * 1000)
    slabs = _vector_parse(text, scale[0], scale[1], now_ms)
    if slabs is None:
        slabs = parse_lines_columnar(text, precision, now_ms=now_ms)
    total = write_slabs(query_engine, QueryContext(db=db), slabs)
    INGEST_ROWS.inc(total, protocol="influxdb")
    return total


def write_points(query_engine, db: str, points: list[Point],
                 precision: str = "ns") -> int:
    """Point-object write surface (OTLP/OpenTSDB build Points
    programmatically): funnels into the same columnar bulk path as
    `write_lines`."""
    import time as _time

    from greptimedb_tpu.query.engine import QueryContext

    scale = _PRECISION_TO_MS.get(precision)
    if scale is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    num, den = scale
    now_ms = int(_time.time() * 1000)
    slabs: dict[str, TableSlab] = {}
    for p in points:
        slab = slabs.get(p.measurement)
        if slab is None:
            slab = slabs[p.measurement] = TableSlab()
        slab.add_row(p.tags, p.fields,
                     now_ms if p.ts is None else int(p.ts) * num // den)
    total = write_slabs(query_engine, QueryContext(db=db), slabs)
    INGEST_ROWS.inc(total, protocol="influxdb")
    return total
