"""MySQL wire-protocol server.

Mirrors reference src/servers/src/mysql (opensrv-mysql `AsyncMysqlShim`
impl, handler.rs:153, on_query :357): a real MySQL client can connect,
authenticate (any credentials accepted unless a UserProvider is installed),
and run SQL against the query engine. Implements the text protocol
(protocol 41, handshake v10): COM_QUERY, COM_PING, COM_INIT_DB, COM_QUIT,
plus enough of the federated-query shims (SELECT @@version_comment and
friends, federated.rs analog) for standard clients to connect cleanly.
Prepared statements (handler.rs:153 on_prepare/on_execute): binary
COM_STMT_PREPARE / COM_STMT_EXECUTE / COM_STMT_CLOSE / COM_STMT_RESET
with typed parameter decoding and binary resultset rows — the default
path for connector libraries and ORMs.

EOF-style result sets (CLIENT_DEPRECATE_EOF not advertised) keep encoding
simple and broadly compatible.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Optional

from greptimedb_tpu.fault import Unavailable
from greptimedb_tpu.fault.retry import Cancelled, DeadlineExceeded
from greptimedb_tpu.query.engine import QueryContext, QueryEngine

CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_PLUGIN_AUTH = 0x00080000
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_TRANSACTIONS = 0x00002000
CLIENT_SSL = 0x00000800

SERVER_CAPS = (
    CLIENT_PROTOCOL_41
    | CLIENT_CONNECT_WITH_DB
    | CLIENT_PLUGIN_AUTH
    | CLIENT_SECURE_CONNECTION
    | CLIENT_LONG_PASSWORD
    | CLIENT_TRANSACTIONS
)

COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A

MYSQL_TYPE_TINY = 1
MYSQL_TYPE_SHORT = 2
MYSQL_TYPE_LONG = 3
MYSQL_TYPE_FLOAT = 4
MYSQL_TYPE_LONGLONG = 8
MYSQL_TYPE_INT24 = 9
MYSQL_TYPE_DOUBLE = 5
MYSQL_TYPE_NULL = 6
MYSQL_TYPE_VAR_STRING = 253
MYSQL_TYPE_STRING = 254
MYSQL_TYPE_BLOB = 252
MYSQL_TYPE_TINY_BLOB = 249
MYSQL_TYPE_MEDIUM_BLOB = 250
MYSQL_TYPE_LONG_BLOB = 251
MYSQL_TYPE_TIMESTAMP = 7
MYSQL_TYPE_DATETIME = 12
MYSQL_TYPE_DATE = 10
MYSQL_TYPE_VARCHAR = 15
MYSQL_TYPE_YEAR = 13
MYSQL_TYPE_DECIMAL = 0
MYSQL_TYPE_NEWDECIMAL = 246


# wire fragments shared with the encode-pool workers (servers/encode.py)
from greptimedb_tpu.servers.encode import (  # noqa: E402
    _coldef,
    _eof,
    encode_mysql_result,
    encode_mysql_rows,
    lenc_int,
)


class _PacketIO:
    """MySQL packet framing: 3-byte little-endian length + sequence id."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def read_packet(self) -> Optional[bytes]:
        header = self._read_exact(4)
        if header is None:
            return None
        length = header[0] | (header[1] << 8) | (header[2] << 16)
        self.seq = (header[3] + 1) & 0xFF
        body = self._read_exact(length)
        return body

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def send_packet(self, payload: bytes) -> None:
        while True:
            chunk, payload = payload[: 0xFFFFFF], payload[0xFFFFFF:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq])
            self.seq = (self.seq + 1) & 0xFF
            self.sock.sendall(header + chunk)
            if len(chunk) < 0xFFFFFF:
                break

    def reset_seq(self) -> None:
        self.seq = 0


class _Session(socketserver.BaseRequestHandler):
    def handle(self):
        import socket as _socket

        # wire-protocol packets go out in several send()s per response;
        # Nagle + delayed-ACK adds ~40 ms per round-trip otherwise
        self.request.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        io = _PacketIO(self.request)
        server: MysqlServer = self.server.owner  # type: ignore[attr-defined]
        # ---- handshake v10 ----
        import secrets
        caps_offered = SERVER_CAPS | (CLIENT_SSL if server.tls else 0)
        salt = bytes(secrets.choice(range(0x21, 0x7F)) for _ in range(20))
        hs = (
            b"\x0a"  # protocol version 10
            + b"greptimedb-tpu-8.0\x00"
            + struct.pack("<I", threading.get_ident() & 0xFFFFFFFF)
            + salt[:8]
            + b"\x00"
            + struct.pack("<H", caps_offered & 0xFFFF)
            + bytes([0x21])  # utf8_general_ci
            + struct.pack("<H", 0x0002)  # status: autocommit
            + struct.pack("<H", (caps_offered >> 16) & 0xFFFF)
            + bytes([21])  # auth plugin data len
            + b"\x00" * 10
            + salt[8:]
            + b"\x00"
            + b"mysql_native_password\x00"
        )
        io.send_packet(hs)
        resp = io.read_packet()
        if resp is None:
            return
        # SSLRequest: caps with CLIENT_SSL set and NO username — the
        # client upgrades the connection before re-sending the real
        # HandshakeResponse over TLS (protocol::connection_phase)
        tls_active = False
        if len(resp) >= 4 and len(resp) < 36 \
                and struct.unpack("<I", resp[:4])[0] & CLIENT_SSL:
            if server.tls is None:
                return  # offered no TLS but client demanded it
            self.request = server.tls_context.wrap_socket(
                self.request, server_side=True)
            io.sock = self.request  # sequence id continues
            tls_active = True
            resp = io.read_packet()
            if resp is None:
                return
        if server.tls is not None and server.tls.mode == "require" \
                and not tls_active:
            io.send_packet(_err(3159, "HY000", "connections must use TLS"))
            return
        # HandshakeResponse41: capabilities(4) maxpkt(4) charset(1) filler(23)
        # then NUL-terminated username
        if len(resp) < 32:
            return
        db = "public"
        user = ""
        auth_resp = b""
        try:
            caps = struct.unpack("<I", resp[:4])[0]
            pos = 32
            end = resp.index(b"\x00", pos)
            user = resp[pos:end].decode()
            pos = end + 1
            # auth response (lenenc when CLIENT_SECURE_CONNECTION)
            if pos < len(resp):
                alen = resp[pos]
                auth_resp = resp[pos + 1:pos + 1 + alen]
                pos += 1 + alen
            if caps & CLIENT_CONNECT_WITH_DB and pos < len(resp):
                end = resp.index(b"\x00", pos)
                db = resp[pos:end].decode() or "public"
        except (ValueError, IndexError):
            pass
        user_info = None
        if server.user_provider is not None:
            from greptimedb_tpu.auth import AuthError
            try:
                if hasattr(server.user_provider, "authenticate_mysql"):
                    user_info = server.user_provider.authenticate_mysql(
                        user, auth_resp, salt)
                elif not server.user_provider.allow(user):
                    raise AuthError(f"access denied for user {user!r}")
            except AuthError:
                io.send_packet(
                    _err(1045, "28000", f"Access denied for user {user!r}"))
                return
        io.send_packet(_ok())
        from greptimedb_tpu.session import Channel
        ctx = QueryContext(db=db, channel=Channel.MYSQL, user=user_info,
                           tenant=getattr(user_info, "username", None)
                           or (user or None))
        # prepared-statement registry, per connection (handler.rs:153
        # keeps a SqlPlan map keyed by stmt id the same way); the third
        # slot caches parameter types — libmysqlclient connectors send the
        # type block only on the FIRST execute (new-params-bound=1) and
        # omit it on re-executes
        stmts: dict[int, list] = {}
        next_stmt_id = 1
        # ---- command loop ----
        while True:
            io.reset_seq()
            pkt = io.read_packet()
            if pkt is None or not pkt:
                return
            cmd, body = pkt[0], pkt[1:]
            if cmd == COM_QUIT:
                return
            if cmd == COM_PING:
                io.send_packet(_ok())
                continue
            if cmd == COM_INIT_DB:
                ctx = ctx.with_db(body.decode() or "public")
                io.send_packet(_ok())
                continue
            if cmd == COM_STMT_PREPARE:
                sql = body.decode("utf-8", "replace").strip().rstrip(";")
                n_params = _count_params(sql)
                stmt_id = next_stmt_id
                next_stmt_id += 1
                stmts[stmt_id] = [sql, n_params, None]
                _send_prepare_ok(io, stmt_id, n_params)
                continue
            if cmd == COM_STMT_EXECUTE:
                try:
                    stmt_id = struct.unpack("<I", body[:4])[0]
                    if stmt_id not in stmts:
                        io.send_packet(
                            _err(1243, "HY000", f"unknown stmt {stmt_id}"))
                        continue
                    sql, n_params, cached_types = stmts[stmt_id]
                    params, types = _decode_exec_params(
                        body, n_params, cached_types)
                    stmts[stmt_id][2] = types
                    bound = _bind_params(sql, params)
                    result = _dispatch(server.query_engine, bound, ctx,
                                       sock=self.request)
                except DeadlineExceeded as e:
                    # ER_QUERY_TIMEOUT: max_execution_time shape
                    io.send_packet(_err(3024, "HY000", str(e)[:400]))
                    continue
                except Cancelled as e:
                    # ER_QUERY_INTERRUPTED: KILL QUERY shape
                    io.send_packet(_err(1317, "70100", str(e)[:400]))
                    continue
                except Unavailable as e:
                    # typed overload/degradation: 1040 tells clients to
                    # back off and retry, not report a syntax error
                    io.send_packet(_err(1040, "08004", str(e)[:400]))
                    continue
                except Exception as e:  # noqa: BLE001 — wire must stay up
                    io.send_packet(_err(1064, "42000", str(e)[:400]))
                    continue
                _send_result(io, result, binary=True,
                             pool=_encode_pool(server))
                continue
            if cmd == COM_STMT_CLOSE:
                stmts.pop(struct.unpack("<I", body[:4])[0], None)
                continue  # no response, per protocol
            if cmd == 0x18:  # COM_STMT_SEND_LONG_DATA
                # protocol: NO response — answering would desync the
                # connection (client pipelines execute right behind it).
                # Long-data chunks aren't accumulated; the subsequent
                # execute fails cleanly if it references the missing param.
                continue
            if cmd == COM_STMT_RESET:
                io.send_packet(_ok())
                continue
            if cmd != COM_QUERY:
                io.send_packet(_err(1047, "08S01", f"unknown command {cmd}"))
                continue
            sql = body.decode("utf-8", "replace").strip().rstrip(";")
            try:
                result = _dispatch(server.query_engine, sql, ctx,
                                   sock=self.request)
            except DeadlineExceeded as e:
                io.send_packet(_err(3024, "HY000", str(e)[:400]))
                continue
            except Cancelled as e:
                io.send_packet(_err(1317, "70100", str(e)[:400]))
                continue
            except Unavailable as e:
                io.send_packet(_err(1040, "08004", str(e)[:400]))
                continue
            except Exception as e:  # noqa: BLE001 — wire must stay up
                io.send_packet(_err(1064, "42000", str(e)[:400]))
                continue
            _send_result(io, result, pool=_encode_pool(server))


def _dispatch(engine: QueryEngine, sql: str, ctx: QueryContext,
              sock=None):
    """Run the SQL, shimming the session variables standard clients probe
    on connect (reference servers/src/mysql/federated.rs)."""
    low = sql.lower()
    if low.startswith(("commit", "rollback", "begin", "start transaction")):
        return None  # accepted, no-op
    if low.startswith("set "):
        # SET now reaches the engine: _set_var stores session vars in
        # the connection-scoped ctx.extensions, which is how
        # `SET max_execution_time = 500` arms the deadline plane for
        # every later statement on this connection. Client-compat vars
        # the parser/engine can't digest stay an accepted no-op.
        try:
            engine.execute_one(sql, ctx)
        except Unavailable:
            raise  # typed degradation must reach the wire mapping
        except Exception:  # noqa: BLE001 — connector-compat vars vary
            pass
        return None
    if "@@" in low and low.startswith("select"):
        # SELECT @@version_comment / @@max_allowed_packet / ...
        names, vals = [], []
        for var in low.replace("select", "", 1).split(","):
            var = var.strip().split(" ")[0]
            name = var.replace("@@", "").split(".")[-1]
            names.append("@@" + name)
            # a var this connection SET (e.g. max_execution_time)
            # reads back its session value, not the static shim
            vals.append(str(ctx.extensions.get(
                name, _SESSION_VARS.get(name, ""))))
        return ("rows", names, [vals])
    from greptimedb_tpu.utils import tracing

    # the MySQL wire has no headers: a W3C traceparent rides a leading
    # SQL comment instead. Each statement is one request-root span; the
    # connection-scoped ctx adopts the per-statement trace so the
    # engine (and its spans/ledger) join it.
    with tracing.request_span(
            "mysql:query",
            traceparent=tracing.traceparent_from_sql(sql)):
        ctx.trace_id = tracing.current_trace_id()
        from greptimedb_tpu.utils import deadline

        # per-statement cancel token: a client that hangs up mid-query
        # cancels the work (EOF on the session socket); the engine arms
        # the deadline from max_execution_time / config defaults
        token = deadline.CancelToken()
        ctx.cancel_token = token
        stop_watch = deadline.watch_disconnect(sock, token) \
            if sock is not None else (lambda: None)
        try:
            res = engine.execute_one(sql, ctx)
        finally:
            stop_watch()
            ctx.cancel_token = None
        if not res.is_query:
            return ("affected", res.affected_rows)
        # the QueryResult itself, NOT materialized rows: row building is
        # the GIL-heaviest half of serialization and belongs on the
        # encode pool (encode_mysql_result), not the session thread
        return ("result", res)


_SESSION_VARS = {
    "version_comment": "greptimedb-tpu",
    "max_allowed_packet": "16777216",
    "session.auto_increment_increment": "1",
    "auto_increment_increment": "1",
    "character_set_client": "utf8",
    "character_set_connection": "utf8",
    "character_set_results": "utf8",
    "character_set_server": "utf8",
    "collation_server": "utf8_general_ci",
    "collation_connection": "utf8_general_ci",
    "init_connect": "",
    "interactive_timeout": "28800",
    "license": "Apache-2.0",
    "lower_case_table_names": "0",
    "max_execution_time": "0",
    "net_write_timeout": "60",
    "performance_schema": "0",
    "sql_mode": "",
    "system_time_zone": "UTC",
    "time_zone": "UTC",
    "tx_isolation": "REPEATABLE-READ",
    "transaction_isolation": "REPEATABLE-READ",
    "wait_timeout": "28800",
}


def _count_params(sql: str) -> int:
    """Count `?` placeholders outside string literals, backtick-quoted
    identifiers, and `--` comments."""
    n = 0
    in_str: Optional[str] = None
    in_comment = False
    i = 0
    while i < len(sql):
        c = sql[i]
        if in_comment:
            if c == "\n":
                in_comment = False
        elif in_str is not None:
            if c == in_str:
                # '' escape inside a string stays inside it
                if i + 1 < len(sql) and sql[i + 1] == in_str:
                    i += 1
                else:
                    in_str = None
        elif c == "-" and sql[i:i + 2] == "--":
            in_comment = True
        elif c in ("'", '"', "`"):
            in_str = c
        elif c == "?":
            n += 1
        i += 1
    return n


def _send_prepare_ok(io: _PacketIO, stmt_id: int, n_params: int) -> None:
    """COM_STMT_PREPARE_OK. Result-column count is reported as 0 — the
    execute response carries its own authoritative column metadata, which
    is what client libraries actually read (the reference defers planning
    the same way, handler.rs:163 do_describe on a param-less dummy)."""
    io.send_packet(
        b"\x00"
        + struct.pack("<I", stmt_id)
        + struct.pack("<H", 0)          # columns (see docstring)
        + struct.pack("<H", n_params)
        + b"\x00"                        # filler
        + struct.pack("<H", 0)          # warnings
    )
    if n_params:
        for i in range(n_params):
            io.send_packet(_coldef(f"?{i}", MYSQL_TYPE_VAR_STRING))
        io.send_packet(_eof())


_LENC_TYPES = frozenset({
    MYSQL_TYPE_VAR_STRING, MYSQL_TYPE_STRING, MYSQL_TYPE_VARCHAR,
    MYSQL_TYPE_BLOB, MYSQL_TYPE_TINY_BLOB, MYSQL_TYPE_MEDIUM_BLOB,
    MYSQL_TYPE_LONG_BLOB, MYSQL_TYPE_DECIMAL, MYSQL_TYPE_NEWDECIMAL,
})


def _read_lenc(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 251:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def _decode_exec_params(body: bytes, n_params: int,
                        cached_types: Optional[list] = None) -> tuple:
    """Decode COM_STMT_EXECUTE binary parameter values (protocol binary
    value encoding; the subset real connectors send). Returns
    (params, types) — callers cache `types` per statement because the
    type block is only sent when new-params-bound=1 (first execute)."""
    if n_params == 0:
        return [], cached_types
    pos = 4 + 1 + 4  # stmt_id, flags, iteration_count
    nb_len = (n_params + 7) // 8
    null_bitmap = body[pos:pos + nb_len]
    pos += nb_len
    new_bound = body[pos]
    pos += 1
    types = []
    if new_bound:
        for _ in range(n_params):
            types.append((body[pos], body[pos + 1]))
            pos += 2
    elif cached_types is not None:
        types = cached_types
    else:
        raise ValueError(
            "execute with new-params-bound=0 but no types cached")
    params: list = []
    for i in range(n_params):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            params.append(None)
            continue
        ftype, flags = types[i]
        unsigned = bool(flags & 0x80)
        if ftype == MYSQL_TYPE_NULL:
            params.append(None)
        elif ftype == MYSQL_TYPE_TINY:
            v = body[pos]
            params.append(v if unsigned else struct.unpack("<b", body[pos:pos+1])[0])
            pos += 1
        elif ftype in (MYSQL_TYPE_SHORT, MYSQL_TYPE_YEAR):
            fmt = "<H" if unsigned else "<h"
            params.append(struct.unpack_from(fmt, body, pos)[0])
            pos += 2
        elif ftype in (MYSQL_TYPE_LONG, MYSQL_TYPE_INT24):
            fmt = "<I" if unsigned else "<i"
            params.append(struct.unpack_from(fmt, body, pos)[0])
            pos += 4
        elif ftype == MYSQL_TYPE_LONGLONG:
            fmt = "<Q" if unsigned else "<q"
            params.append(struct.unpack_from(fmt, body, pos)[0])
            pos += 8
        elif ftype == MYSQL_TYPE_FLOAT:
            params.append(struct.unpack_from("<f", body, pos)[0])
            pos += 4
        elif ftype == MYSQL_TYPE_DOUBLE:
            params.append(struct.unpack_from("<d", body, pos)[0])
            pos += 8
        elif ftype in (MYSQL_TYPE_TIMESTAMP, MYSQL_TYPE_DATETIME,
                       MYSQL_TYPE_DATE):
            dlen = body[pos]
            pos += 1
            y = mo = d = h = mi = s = us = 0
            if dlen >= 4:
                y, mo, d = struct.unpack_from("<HBB", body, pos)
            if dlen >= 7:
                h, mi, s = struct.unpack_from("<BBB", body, pos + 4)
            if dlen >= 11:
                us = struct.unpack_from("<I", body, pos + 7)[0]
            pos += dlen
            if dlen <= 4:
                params.append(f"{y:04d}-{mo:02d}-{d:02d}")
            else:
                frac = f".{us:06d}" if us else ""
                params.append(
                    f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}{frac}")
        elif ftype in _LENC_TYPES:
            ln, pos = _read_lenc(body, pos)
            params.append(body[pos:pos + ln].decode("utf-8", "replace"))
            pos += ln
        else:
            raise ValueError(f"unsupported parameter type {ftype}")
    return params, types


def _bind_params(sql: str, params: list) -> str:
    """Substitute decoded values for `?` placeholders (outside string
    literals, backticked identifiers, and `--` comments), rendering SQL
    literals with proper quoting."""
    out = []
    it = iter(params)
    in_str: Optional[str] = None
    in_comment = False
    i = 0
    while i < len(sql):
        c = sql[i]
        if in_comment:
            out.append(c)
            if c == "\n":
                in_comment = False
        elif in_str is not None:
            out.append(c)
            if c == in_str:
                if i + 1 < len(sql) and sql[i + 1] == in_str:
                    out.append(sql[i + 1])
                    i += 1
                else:
                    in_str = None
        elif c == "-" and sql[i:i + 2] == "--":
            in_comment = True
            out.append(c)
        elif c in ("'", '"', "`"):
            in_str = c
            out.append(c)
        elif c == "?":
            try:
                v = next(it)
            except StopIteration:
                raise ValueError("not enough parameters bound") from None
            out.append(_sql_literal(v))
        else:
            out.append(c)
        i += 1
    return "".join(out)


def _sql_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    # this dialect's lexer treats backslash as a literal character — the
    # ONLY escape is the doubled single-quote (sql/lexer.py string regex)
    s = str(v).replace("'", "''")
    return f"'{s}'"


def _ok(affected: int = 0) -> bytes:
    return b"\x00" + lenc_int(affected) + lenc_int(0) + struct.pack("<H", 0x0002) + struct.pack("<H", 0)


def _err(code: int, state: str, msg: str) -> bytes:
    return b"\xff" + struct.pack("<H", code) + b"#" + state.encode() + msg.encode()


def _encode_pool(server):
    """The engine's concurrency-plane encode pool, or None for engines
    constructed without one (encoding then runs inline, pre-pool
    behavior)."""
    conc = getattr(server.query_engine, "concurrency", None)
    return getattr(conc, "encode", None)


def _send_result(io: _PacketIO, result, binary: bool = False,
                 pool=None) -> None:
    """Text resultset for COM_QUERY; binary-protocol rows for
    COM_STMT_EXECUTE (all columns declared VAR_STRING, so binary values
    are length-encoded strings — connectors convert from the metadata).
    Row serialization runs on the bounded encode pool when one is
    wired (the session thread parks on the future instead of holding
    the GIL); the session loop only stamps sequence ids and writes."""
    if result is None:
        io.send_packet(_ok())
        return
    if result[0] == "affected":
        io.send_packet(_ok(result[1]))
        return
    if result[0] == "result":
        res = result[1]
        if pool is not None:
            packets = pool.run(encode_mysql_result, res, binary,
                               cost_rows=res.num_rows)
        else:
            packets = encode_mysql_result(res, binary)
    else:
        _, names, rows = result
        if pool is not None:
            packets = pool.run(encode_mysql_rows, names, rows, binary,
                               cost_rows=len(rows))
        else:
            packets = encode_mysql_rows(names, rows, binary)
    for p in packets:
        io.send_packet(p)


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MysqlServer:
    """Threaded MySQL server over the shared QueryEngine."""

    def __init__(self, query_engine: QueryEngine, host: str = "127.0.0.1",
                 port: int = 4002, user_provider=None, tls=None):
        self.query_engine = query_engine
        self.user_provider = user_provider
        self.tls = tls
        self.tls_context = tls.make_context() if tls is not None else None
        self._server = _TcpServer((host, port), _Session)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
