"""OpenTSDB telnet protocol (mirrors reference servers::opentsdb,
src/servers/src/opentsdb.rs + codec: line-based TCP `put` commands).

    put <metric> <timestamp> <value> <tagk=tagv> [<tagk=tagv> ...]

Timestamps are seconds or milliseconds by magnitude (like the HTTP
/api/put endpoint in http.py). `version` and `exit` are handled for
telnet compatibility; malformed puts answer a diagnostic line, matching
OpenTSDB's telnet behavior.
"""

from __future__ import annotations

import socketserver
import threading
from typing import Optional

from greptimedb_tpu.fault.retry import Unavailable
from greptimedb_tpu.query.engine import QueryEngine
from greptimedb_tpu.utils.metrics import INGEST_ROWS

GREPTIME_VALUE = "greptime_value"


def parse_put_line(line: str):
    """One telnet put command → (metric, ts_ms, value, tags)."""
    parts = line.split()
    if not parts or parts[0] != "put":
        raise ValueError(f"unknown command {parts[0] if parts else ''!r}")
    if len(parts) < 4:
        raise ValueError("put needs: metric timestamp value [tags]")
    metric = parts[1]
    ts = int(float(parts[2]))
    ts_ms = ts * 1000 if ts < 10_000_000_000 else ts
    value = float(parts[3])
    tags = []
    for kv in parts[4:]:
        k, sep, v = kv.partition("=")
        if not sep or not k or not v:
            raise ValueError(f"bad tag {kv!r}")
        tags.append((k, v))
    return metric, ts_ms, value, sorted(tags)


class _Session(socketserver.StreamRequestHandler):
    disable_nagle_algorithm = True

    def handle(self):
        server: OpentsdbServer = self.server.owner  # type: ignore[attr-defined]
        from greptimedb_tpu.servers.influx import Point, write_points

        while True:
            raw = self.rfile.readline()
            if not raw:
                return
            line = raw.decode(errors="replace").strip()
            if not line:
                continue
            cmd = line.split(None, 1)[0]
            if cmd == "exit":
                return
            if cmd == "version":
                self.wfile.write(b"greptimedb_tpu opentsdb endpoint\n")
                continue
            try:
                metric, ts_ms, value, tags = parse_put_line(line)
                point = Point(measurement=metric, tags=tags,
                              fields=[(GREPTIME_VALUE, value)], ts=ts_ms)
                n = write_points(server.query_engine, server.db, [point],
                                 precision="ms")
                INGEST_ROWS.inc(n, protocol="opentsdb")
            except Unavailable as e:
                # typed backpressure: the telnet protocol has no status
                # codes, but "unavailable" is what tcollector-style
                # clients pattern-match to back off and retry
                self.wfile.write(f"put: unavailable: {e}\n".encode())
            except Exception as e:  # noqa: BLE001 — wire boundary
                self.wfile.write(f"put: {e}\n".encode())


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class OpentsdbServer:
    """Telnet-mode OpenTSDB ingestion over the shared QueryEngine."""

    def __init__(self, query_engine: QueryEngine, host: str = "127.0.0.1",
                 port: int = 4242, db: str = "public"):
        self.query_engine = query_engine
        self.db = db
        self._server = _TcpServer((host, port), _Session)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
