"""OTLP/HTTP metrics ingestion.

Mirrors reference src/servers/src/otlp/metrics.rs: an
ExportMetricsServiceRequest (protobuf) is flattened into per-metric tables —
data-point attributes become tags, `greptime_timestamp`/`greptime_value`
carry the sample. Gauge and Sum map directly; Histogram explodes into
`<name>_bucket` (le tag) / `<name>_sum` / `<name>_count` tables; Summary
into `<name>` with a `quantile` tag — the same shape Prometheus exporters
produce.
"""

from __future__ import annotations

import struct

from greptimedb_tpu.servers.influx import Point, write_points
from greptimedb_tpu.utils import protowire as pw
from greptimedb_tpu.utils.metrics import REGISTRY

INGEST_ROWS = REGISTRY.counter(
    "greptimedb_tpu_otlp_rows_total", "Rows ingested via OTLP metrics"
)


def _any_value(data: bytes) -> str:
    for f, wt, v in pw.iter_fields(data):
        if f == 1:
            return v.decode()
        if f == 2:
            return "true" if v else "false"
        if f == 3:
            return str(pw.varint_to_sint64(v))
        if f == 4:
            return str(pw.fixed64_to_double(v))
    return ""


def _keyvalue(data: bytes) -> tuple[str, str]:
    key, val = "", ""
    for f, _wt, v in pw.iter_fields(data):
        if f == 1:
            key = v.decode()
        elif f == 2:
            val = _any_value(v)
    return key, val


def _number_point(data: bytes) -> tuple[dict, int, float]:
    attrs: dict[str, str] = {}
    ts_ns = 0
    value = 0.0
    for f, wt, v in pw.iter_fields(data):
        if f == 7:  # attributes
            k, val = _keyvalue(v)
            attrs[k] = val
        elif f == 3:  # time_unix_nano (fixed64)
            ts_ns = v
        elif f == 4:  # as_double
            value = pw.fixed64_to_double(v)
        elif f == 6:  # as_int (sfixed64)
            value = float(struct.unpack("<q", struct.pack("<Q", v))[0])
    return attrs, ts_ns, value


def _histogram_point(data: bytes):
    attrs: dict[str, str] = {}
    ts_ns = 0
    count = 0
    total = 0.0
    bucket_counts: list[int] = []
    bounds: list[float] = []
    for f, wt, v in pw.iter_fields(data):
        if f == 9:  # attributes
            k, val = _keyvalue(v)
            attrs[k] = val
        elif f == 3:
            ts_ns = v
        elif f == 4:  # count fixed64
            count = v
        elif f == 5:  # sum double
            total = pw.fixed64_to_double(v)
        elif f == 6:  # bucket_counts packed fixed64
            if isinstance(v, bytes):
                bucket_counts = [
                    struct.unpack("<Q", v[i:i + 8])[0] for i in range(0, len(v), 8)
                ]
        elif f == 7:  # explicit_bounds packed double
            if isinstance(v, bytes):
                bounds = [
                    struct.unpack("<d", v[i:i + 8])[0] for i in range(0, len(v), 8)
                ]
    return attrs, ts_ns, count, total, bucket_counts, bounds


def parse_metrics_request(body: bytes) -> list[Point]:
    """ExportMetricsServiceRequest -> flat list of Points."""
    points: list[Point] = []
    for f, _wt, rm in pw.iter_fields(body):
        if f != 1:  # resource_metrics
            continue
        resource_attrs: dict[str, str] = {}
        scope_metrics = []
        for f2, _wt2, v2 in pw.iter_fields(rm):
            if f2 == 1:  # Resource
                for f3, _wt3, v3 in pw.iter_fields(v2):
                    if f3 == 1:
                        k, val = _keyvalue(v3)
                        resource_attrs[k] = val
            elif f2 == 2:
                scope_metrics.append(v2)
        for sm in scope_metrics:
            for f3, _wt3, metric in pw.iter_fields(sm):
                if f3 != 2:  # Metric
                    continue
                points.extend(_metric_points(metric, resource_attrs))
    return points


def _metric_points(metric: bytes, resource_attrs: dict[str, str]) -> list[Point]:
    name = ""
    gauge_pts, sum_pts, hist_pts = [], [], []
    for f, _wt, v in pw.iter_fields(metric):
        if f == 1:
            name = v.decode()
        elif f == 5:  # Gauge
            for f2, _wt2, dp in pw.iter_fields(v):
                if f2 == 1:
                    gauge_pts.append(dp)
        elif f == 7:  # Sum
            for f2, _wt2, dp in pw.iter_fields(v):
                if f2 == 1:
                    sum_pts.append(dp)
        elif f == 9:  # Histogram
            for f2, _wt2, dp in pw.iter_fields(v):
                if f2 == 1:
                    hist_pts.append(dp)
    table = _sanitize(name)
    out: list[Point] = []
    for dp in gauge_pts + sum_pts:
        attrs, ts_ns, value = _number_point(dp)
        tags = sorted({**resource_attrs, **attrs}.items())
        out.append(Point(measurement=table, tags=tags,
                         fields=[("greptime_value", value)], ts=ts_ns // 1_000_000))
    for dp in hist_pts:
        attrs, ts_ns, count, total, bucket_counts, bounds = _histogram_point(dp)
        base_tags = {**resource_attrs, **attrs}
        ts_ms = ts_ns // 1_000_000
        cum = 0
        for i, bc in enumerate(bucket_counts):
            cum += bc
            le = repr(bounds[i]) if i < len(bounds) else "+Inf"
            out.append(Point(measurement=table + "_bucket",
                             tags=sorted({**base_tags, "le": le}.items()),
                             fields=[("greptime_value", float(cum))], ts=ts_ms))
        out.append(Point(measurement=table + "_sum", tags=sorted(base_tags.items()),
                         fields=[("greptime_value", total)], ts=ts_ms))
        out.append(Point(measurement=table + "_count", tags=sorted(base_tags.items()),
                         fields=[("greptime_value", float(count))], ts=ts_ms))
    return out


def _sanitize(name: str) -> str:
    import re

    return re.sub(r"[^0-9a-zA-Z_]", "_", name) or "unknown_metric"


def handle_otlp_metrics(query_engine, body: bytes, db: str = "public") -> int:
    points = parse_metrics_request(body)
    n = write_points(query_engine, db, points, precision="ms")
    INGEST_ROWS.inc(n)
    return n


# ---------------------------------------------------------------- traces

TRACE_TABLE_NAME = "opentelemetry_traces"

TRACE_ROWS = REGISTRY.counter(
    "greptimedb_tpu_otlp_trace_rows_total", "Spans ingested via OTLP traces"
)

_SPAN_KINDS = {0: "SPAN_KIND_UNSPECIFIED", 1: "SPAN_KIND_INTERNAL",
               2: "SPAN_KIND_SERVER", 3: "SPAN_KIND_CLIENT",
               4: "SPAN_KIND_PRODUCER", 5: "SPAN_KIND_CONSUMER"}
_STATUS_CODES = {0: "STATUS_CODE_UNSET", 1: "STATUS_CODE_OK",
                 2: "STATUS_CODE_ERROR"}


def _attrs_json(pairs: dict) -> str:
    import json as _json

    return _json.dumps(pairs, sort_keys=True)


def _span_to_point(span: bytes, resource_attrs: dict, scope_name: str,
                   scope_version: str) -> Point:
    """One OTLP Span message -> one row (reference
    servers/src/otlp/trace.rs write_span_to_row: trace ids are tags,
    everything else fields, time index = span start)."""
    trace_id = span_id = parent_span_id = ""
    name = trace_state = ""
    kind = 0
    start_ns = end_ns = 0
    attrs: dict[str, str] = {}
    status_code, status_msg = 0, ""
    n_events = n_links = 0
    for f, _wt, v in pw.iter_fields(span):
        if f == 1:
            trace_id = v.hex()
        elif f == 2:
            span_id = v.hex()
        elif f == 3:
            trace_state = v.decode()
        elif f == 4:
            parent_span_id = v.hex()
        elif f == 5:
            name = v.decode()
        elif f == 6:
            kind = v
        elif f == 7:
            start_ns = v
        elif f == 8:
            end_ns = v
        elif f == 9:
            k, val = _keyvalue(v)
            attrs[k] = val
        elif f == 11:
            n_events += 1
        elif f == 13:
            n_links += 1
        elif f == 15:
            for f2, _wt2, sv in pw.iter_fields(v):
                if f2 == 2:
                    status_msg = sv.decode()
                elif f2 == 3:
                    status_code = sv
    return Point(
        measurement=TRACE_TABLE_NAME,
        tags=[("trace_id", trace_id), ("span_id", span_id),
              ("parent_span_id", parent_span_id)],
        fields=[
            ("resource_attributes", _attrs_json(resource_attrs)),
            ("scope_name", scope_name),
            ("scope_version", scope_version),
            ("trace_state", trace_state),
            ("span_name", name),
            ("span_kind", _SPAN_KINDS.get(int(kind), str(kind))),
            ("span_status_code", _STATUS_CODES.get(int(status_code),
                                                   str(status_code))),
            ("span_status_message", status_msg),
            ("span_attributes", _attrs_json(attrs)),
            ("span_events_count", float(n_events)),
            ("span_links_count", float(n_links)),
            ("end", int(end_ns)),
            ("duration_nano", float(max(end_ns - start_ns, 0))),
        ],
        ts=start_ns // 1_000_000,
    )


def parse_traces_request(body: bytes) -> list[Point]:
    """ExportTraceServiceRequest: resource_spans(1) -> resource(1) +
    scope_spans(2) -> scope(1) + spans(2)."""
    out: list[Point] = []
    for f, _wt, rs in pw.iter_fields(body):
        if f != 1:
            continue
        resource_attrs: dict[str, str] = {}
        scope_blocks: list[bytes] = []
        for f2, _wt2, v in pw.iter_fields(rs):
            if f2 == 1:  # Resource
                for f3, _wt3, kv in pw.iter_fields(v):
                    if f3 == 1:
                        k, val = _keyvalue(kv)
                        resource_attrs[k] = val
            elif f2 == 2:  # ScopeSpans
                scope_blocks.append(v)
        for block in scope_blocks:
            scope_name = scope_version = ""
            spans: list[bytes] = []
            for f2, _wt2, v in pw.iter_fields(block):
                if f2 == 1:  # InstrumentationScope
                    for f3, _wt3, sv in pw.iter_fields(v):
                        if f3 == 1:
                            scope_name = sv.decode()
                        elif f3 == 2:
                            scope_version = sv.decode()
                elif f2 == 2:
                    spans.append(v)
            for span in spans:
                out.append(_span_to_point(span, resource_attrs, scope_name,
                                          scope_version))
    return out


def handle_otlp_traces(query_engine, body: bytes, db: str = "public") -> int:
    points = parse_traces_request(body)
    n = write_points(query_engine, db, points, precision="ms")
    TRACE_ROWS.inc(n)
    return n
