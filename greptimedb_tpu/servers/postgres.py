"""PostgreSQL wire-protocol server.

Mirrors reference src/servers/src/postgres (pgwire 0.20 handler.rs,
server.rs): startup/auth handshake, the simple query protocol ('Q'), and
the extended protocol (Parse/Bind/Describe/Execute/Sync) far enough for
psql and standard drivers. All values are sent in text format with proper
type OIDs so clients render ints/floats/timestamps natively.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Optional

import numpy as np

from greptimedb_tpu.datatypes.types import DataType
from greptimedb_tpu.fault.retry import (
    Cancelled,
    DeadlineExceeded,
    Unavailable,
)
from greptimedb_tpu.query.engine import QueryContext, QueryEngine

OID_BOOL = 16
OID_INT8 = 20
OID_FLOAT8 = 701
OID_TEXT = 25
OID_TIMESTAMP = 1114

SSL_REQUEST_CODE = 80877103
CANCEL_REQUEST_CODE = 80877102
PROTOCOL_3 = 196608


def _oid_for(dt) -> int:
    try:
        if dt is None:
            return OID_TEXT
        if dt.is_timestamp:
            return OID_TIMESTAMP
        if dt.is_float:
            return OID_FLOAT8
        if dt in (DataType.INT64, DataType.INT32, DataType.UINT64, DataType.UINT32):
            return OID_INT8
        if dt is DataType.BOOL:
            return OID_BOOL
    except AttributeError:
        pass
    return OID_TEXT


#: memoized RowDescription bodies keyed by the result shape — repeat
#: dashboard shapes re-packed identical field descriptors per response.
#: Benign-race dict under the GIL, bounded by a wholesale clear.
_ROWDESC_CACHE: dict = {}


def _row_description(names, dtypes) -> bytes:
    key = (tuple(names), tuple(getattr(dt, "value", None)
                               for dt in dtypes))
    cached = _ROWDESC_CACHE.get(key)
    if cached is None:
        fields = b""
        for name, dt in zip(names, dtypes):
            fields += (
                name.encode() + b"\x00"
                + struct.pack("!IhIhih", 0, 0, _oid_for(dt), -1, -1, 0)
            )
        cached = struct.pack("!h", len(names)) + fields
        if len(_ROWDESC_CACHE) > 512:
            _ROWDESC_CACHE.clear()
        _ROWDESC_CACHE[key] = cached
    return cached


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock

    def read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def read_message(self) -> Optional[tuple[bytes, bytes]]:
        t = self.read_exact(1)
        if t is None:
            return None
        raw = self.read_exact(4)
        if raw is None:
            return None
        (length,) = struct.unpack("!I", raw)
        body = self.read_exact(length - 4) if length > 4 else b""
        return t, body or b""

    def send(self, type_byte: bytes, body: bytes = b"") -> None:
        self.sock.sendall(type_byte + struct.pack("!I", len(body) + 4) + body)

    def send_many(self, messages) -> None:
        """Frame (type, body) pairs into a buffer flushed in ~1 MiB
        chunks — the byte stream is identical to per-message sends,
        without one syscall (and one Nagle hazard) per data row, and
        without materializing a huge resultset's full wire image.
        sendall accepts the bytearray directly (no copy)."""
        buf = bytearray()
        for type_byte, body in messages:
            buf += type_byte
            buf += struct.pack("!I", len(body) + 4)
            buf += body
            if len(buf) >= (1 << 20):
                self.sock.sendall(buf)
                buf = bytearray()
        if buf:
            self.sock.sendall(buf)


class _Session(socketserver.BaseRequestHandler):
    def handle(self):
        import socket as _socket

        self.request.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        conn = _Conn(self.request)
        server: PostgresServer = self.server.owner  # type: ignore[attr-defined]
        # ---- startup ----
        params = self._startup(conn, server)
        if params is None:
            return
        db = params.get("database", "public") or "public"
        from greptimedb_tpu.session import Channel
        ctx = QueryContext(db=db, channel=Channel.POSTGRES,
                           user=params.get("_user_info"))
        engine = server.query_engine
        # prepared statements / portals for the extended protocol
        stmts: dict[str, str] = {}
        portals: dict[str, str] = {}
        while True:
            msg = conn.read_message()
            if msg is None:
                return
            t, body = msg
            if t == b"X":  # Terminate
                return
            if t == b"Q":
                sql = body.rstrip(b"\x00").decode("utf-8", "replace")
                self._run_simple(conn, engine, sql, ctx)
                self._ready(conn)
            elif t == b"P":  # Parse: name\0 query\0 nparams...
                name_end = body.index(b"\x00")
                name = body[:name_end].decode()
                q_end = body.index(b"\x00", name_end + 1)
                stmts[name] = body[name_end + 1: q_end].decode("utf-8", "replace")
                conn.send(b"1")  # ParseComplete
            elif t == b"B":  # Bind: portal\0 stmt\0 ... (ignore params: no $n support yet)
                p_end = body.index(b"\x00")
                portal = body[:p_end].decode()
                s_end = body.index(b"\x00", p_end + 1)
                stmt_name = body[p_end + 1: s_end].decode()
                portals[portal] = stmts.get(stmt_name, "")
                conn.send(b"2")  # BindComplete
            elif t == b"D":  # Describe
                kind, name = body[:1], body[1:].rstrip(b"\x00").decode()
                sql = portals.get(name, "") if kind == b"P" else stmts.get(name, "")
                # NoData keeps drivers happy without pre-planning the query
                conn.send(b"n")
            elif t == b"E":  # Execute: portal\0 maxrows
                p_end = body.index(b"\x00")
                portal = body[:p_end].decode()
                sql = portals.get(portal, "")
                if sql:
                    self._run_simple(conn, engine, sql, ctx, suppress_empty=True)
                else:
                    conn.send(b"I")  # EmptyQueryResponse
            elif t == b"S":  # Sync
                self._ready(conn)
            elif t == b"H":  # Flush
                pass
            elif t == b"C":  # Close
                conn.send(b"3")  # CloseComplete
            else:
                self._error(conn, f"unsupported message type {t!r}")
                self._ready(conn)

    # ---- helpers ----
    def _startup(self, conn: _Conn, server) -> Optional[dict]:
        while True:
            raw = conn.read_exact(4)
            if raw is None:
                return None
            (length,) = struct.unpack("!I", raw)
            body = conn.read_exact(length - 4)
            if body is None:
                return None
            (code,) = struct.unpack("!I", body[:4])
            if code == SSL_REQUEST_CODE:
                if server.tls is None:
                    self.request.sendall(b"N")  # no TLS configured
                    continue
                # 'S' then upgrade the accepted socket in place
                self.request.sendall(b"S")
                self.request = server.tls_context.wrap_socket(
                    self.request, server_side=True)
                conn.sock = self.request
                self._tls_active = True
                continue
            if code == CANCEL_REQUEST_CODE:
                return None
            if code != PROTOCOL_3:
                return None
            if server.tls is not None and server.tls.mode == "require" \
                    and not getattr(self, "_tls_active", False):
                self._error(conn, "server requires TLS (sslmode=require)")
                return None
            parts = body[4:].split(b"\x00")
            params = {}
            for k, v in zip(parts[::2], parts[1::2]):
                if k:
                    params[k.decode()] = v.decode()
            user = params.get("user", "")
            if server.user_provider is not None:
                # AuthenticationCleartextPassword (reference pgwire
                # startup handler, servers/src/postgres/handler.rs)
                conn.send(b"R", struct.pack("!I", 3))
                pwd = self._read_password(conn)
                from greptimedb_tpu.auth import AuthError
                try:
                    if pwd is None:
                        # client sent something other than PasswordMessage
                        # (or hung up) — fail closed, don't try ''
                        raise AuthError("no password message")
                    if hasattr(server.user_provider, "authenticate"):
                        params["_user_info"] = server.user_provider.authenticate(
                            user, pwd)
                    elif not server.user_provider.allow(user):
                        raise AuthError(user)
                except AuthError:
                    self._error(
                        conn,
                        f"password authentication failed for user {user!r}")
                    return None
            conn.send(b"R", struct.pack("!I", 0))  # AuthenticationOk
            for k, v in (
                ("server_version", "16.0 (greptimedb-tpu)"),
                ("server_encoding", "UTF8"),
                ("client_encoding", "UTF8"),
                ("DateStyle", "ISO"),
                ("TimeZone", "UTC"),
                ("integer_datetimes", "on"),
            ):
                conn.send(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")
            conn.send(b"K", struct.pack("!II", threading.get_ident() & 0x7FFFFFFF, 0))
            self._ready(conn)
            return params

    def _read_password(self, conn: _Conn) -> Optional[str]:
        """Read a PasswordMessage ('p') from the client."""
        tag = conn.read_exact(1)
        if tag != b"p":
            return None
        raw = conn.read_exact(4)
        if raw is None:
            return None
        (length,) = struct.unpack("!I", raw)
        body = conn.read_exact(length - 4)
        if body is None:
            return None
        return body.rstrip(b"\x00").decode()

    def _ready(self, conn: _Conn) -> None:
        conn.send(b"Z", b"I")

    def _error(self, conn: _Conn, msg: str,
               sqlstate: bytes = b"42601") -> None:
        body = b"SERROR\x00" + b"C" + sqlstate + b"\x00" \
            + b"M" + msg.encode()[:900] + b"\x00\x00"
        conn.send(b"E", body)

    def _run_simple(self, conn: _Conn, engine: QueryEngine, sql: str,
                    ctx: QueryContext, suppress_empty: bool = False) -> None:
        sql = sql.strip().rstrip(";")
        if not sql:
            conn.send(b"I")
            return
        low = sql.lower()
        if low.startswith(("begin", "commit", "rollback", "discard")):
            conn.send(b"C", b"SET\x00")
            return
        if low.startswith("set "):
            # SET reaches the engine so session vars persist on the
            # connection ctx — `SET statement_timeout = '500ms'` arms
            # the deadline plane for every later statement here; vars
            # the parser can't digest stay an accepted no-op
            try:
                engine.execute_one(sql, ctx)
            except (DeadlineExceeded, Cancelled) as e:
                self._error(conn, str(e), sqlstate=b"57014")
                return
            except Unavailable as e:
                self._error(conn, str(e), sqlstate=b"53300")
                return
            except Exception:  # noqa: BLE001 — client-compat vars vary
                pass
            conn.send(b"C", b"SET\x00")
            return
        from greptimedb_tpu.utils import deadline, tracing

        try:
            # header-less wire: a W3C traceparent rides a leading SQL
            # comment; each statement is one request-root span
            with tracing.request_span(
                    "postgres:query",
                    traceparent=tracing.traceparent_from_sql(sql)):
                # the CONNECTION ctx executes (a fresh one here used to
                # drop the session vars SET just stored); per-statement
                # token so a hung-up client cancels its work
                ctx.trace_id = tracing.current_trace_id()
                token = deadline.CancelToken()
                ctx.cancel_token = token
                stop_watch = deadline.watch_disconnect(conn.sock, token)
                try:
                    res = engine.execute_one(sql, ctx)
                finally:
                    stop_watch()
                    ctx.cancel_token = None
        except (DeadlineExceeded, Cancelled) as e:
            # query_canceled: PG uses 57014 for both statement_timeout
            # expiry and pg_cancel_backend-style cancellation
            self._error(conn, str(e), sqlstate=b"57014")
            return
        except Unavailable as e:
            # typed backpressure/degradation: SQLSTATE 53300
            # (too_many_connections) tells drivers to back off —
            # NOT the 42601 syntax-error a generic failure maps to
            self._error(conn, str(e), sqlstate=b"53300")
            return
        except Exception as e:  # noqa: BLE001 — wire must stay up
            self._error(conn, str(e))
            return
        if not res.is_query:
            tag = f"INSERT 0 {res.affected_rows}" if low.startswith("insert") else f"SELECT {res.affected_rows}"
            if low.startswith(("create", "drop", "alter", "truncate")):
                tag = low.split()[0].upper() + " TABLE"
            elif low.startswith("delete"):
                tag = f"DELETE {res.affected_rows}"
            conn.send(b"C", tag.encode() + b"\x00")
            return
        # RowDescription (memoized per result shape) + every DataRow +
        # CommandComplete framed into ONE write
        dtypes = list(getattr(res, "dtypes", [])) or [None] * len(res.names)
        messages = [(b"T", _row_description(res.names, dtypes))]
        rows = res.rows()
        for row in rows:
            body = bytearray(struct.pack("!h", len(row)))
            for v in row:
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    body += b"\xff\xff\xff\xff"  # length -1: NULL
                else:
                    s = _fmt(v).encode()
                    body += struct.pack("!i", len(s))
                    body += s
            messages.append((b"D", bytes(body)))
        messages.append((b"C", f"SELECT {len(rows)}\x00".encode()))
        conn.send_many(messages)


def _fmt(v) -> str:
    if isinstance(v, (bool, np.bool_)):
        return "t" if v else "f"
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    return str(v)


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PostgresServer:
    def __init__(self, query_engine: QueryEngine, host: str = "127.0.0.1",
                 port: int = 4003, user_provider=None, tls=None):
        self.query_engine = query_engine
        self.user_provider = user_provider
        self.tls = tls
        self.tls_context = tls.make_context() if tls is not None else None
        self._server = _TcpServer((host, port), _Session)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
