"""Prometheus remote storage protocol: remote_write + remote_read.

Mirrors reference src/servers/src/prom_store.rs + http/prom_store.rs:
snappy-compressed protobuf bodies; each metric becomes a table whose tags
are the label set, with `greptime_timestamp` as the time index and
`greptime_value` as the single field (prom_row_builder.rs analog).
remote_read evaluates matchers against those tables and streams the series
back as a snappy ReadResponse.
"""

from __future__ import annotations

import re
from collections import defaultdict

from greptimedb_tpu.catalog.catalog import CatalogError
from greptimedb_tpu.query.engine import QueryContext
from greptimedb_tpu.utils import protowire as pw
from greptimedb_tpu.utils import snappy
from greptimedb_tpu.utils.metrics import REGISTRY

GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"

INGEST_ROWS = REGISTRY.counter(
    "greptimedb_tpu_prom_store_rows_total",
    "Rows ingested via Prometheus remote write"
)


# ---------------------------------------------------------------- decode


def parse_write_request(body: bytes) -> list[tuple[dict, list[tuple[float, int]]]]:
    """Snappy+protobuf WriteRequest -> [(labels, [(value, ts_ms)])]."""
    raw = snappy.decompress(body)
    series = []
    for field, _wt, v in pw.iter_fields(raw):
        if field != 1:  # timeseries
            continue
        labels: dict[str, str] = {}
        samples: list[tuple[float, int]] = []
        for f2, _wt2, v2 in pw.iter_fields(v):
            if f2 == 1:  # Label
                name = value = ""
                for f3, _wt3, v3 in pw.iter_fields(v2):
                    if f3 == 1:
                        name = v3.decode()
                    elif f3 == 2:
                        value = v3.decode()
                labels[name] = value
            elif f2 == 2:  # Sample
                val, ts = 0.0, 0
                for f3, wt3, v3 in pw.iter_fields(v2):
                    if f3 == 1:
                        val = pw.fixed64_to_double(v3)
                    elif f3 == 2:
                        ts = pw.varint_to_sint64(v3)
                samples.append((val, ts))
        if samples:
            series.append((labels, samples))
    return series


def handle_remote_write(query_engine, body: bytes, db: str = "public") -> int:
    """Decode and ingest a remote-write body. Returns rows written.

    Columnar fast path: each decoded series bulk-extends its metric's
    column slab (a series' samples share ONE label set, so tag columns
    extend with a repeated value instead of per-sample appends), and
    each metric table gets one RecordBatch through the partition
    scatter onto the bulk write path."""
    from greptimedb_tpu.ingest import TableSlab, ensure_table

    series = parse_write_request(body)
    ctx = QueryContext(db=db)
    slabs: dict[str, TableSlab] = {}
    for labels, samples in series:
        table = _sanitize(labels.get("__name__", "unknown_metric"))
        slab = slabs.get(table)
        if slab is None:
            slab = slabs[table] = TableSlab()
        n = len(samples)
        for k, v in labels.items():
            if k != "__name__":
                slab.extend_column("tag", k, [v] * n)
        slab.extend_column("field", GREPTIME_VALUE,
                           [value for value, _ in samples])
        slab.extend_rows([ts for _, ts in samples])
    total = 0
    for table, slab in slabs.items():
        # label columns create in sorted order (stable table shapes
        # regardless of series arrival order), via the shared schema
        # bootstrap every front door uses
        slab.tags = {k: slab.tags[k] for k in sorted(slab.tags)}
        info = ensure_table(query_engine, ctx, table, slab,
                            time_index=GREPTIME_TIMESTAMP,
                            value_field=GREPTIME_VALUE)
        batch = slab.to_batch(info.schema)
        total += query_engine._sharded_write(info, batch, delete=False)
    INGEST_ROWS.inc(total)
    return total


def _sanitize(metric: str) -> str:
    return re.sub(r"[^0-9a-zA-Z_]", "_", metric)


# ---------------------------------------------------------------- read


def parse_read_request(body: bytes) -> list[dict]:
    """Snappy+protobuf ReadRequest -> [{start_ms, end_ms, matchers}]."""
    raw = snappy.decompress(body)
    queries = []
    for field, _wt, v in pw.iter_fields(raw):
        if field != 1:
            continue
        q = {"start_ms": 0, "end_ms": 0, "matchers": []}
        for f2, wt2, v2 in pw.iter_fields(v):
            if f2 == 1:
                q["start_ms"] = pw.varint_to_sint64(v2)
            elif f2 == 2:
                q["end_ms"] = pw.varint_to_sint64(v2)
            elif f2 == 3:
                mtype, name, value = 0, "", ""
                for f3, _wt3, v3 in pw.iter_fields(v2):
                    if f3 == 1:
                        mtype = v3
                    elif f3 == 2:
                        name = v3.decode()
                    elif f3 == 3:
                        value = v3.decode()
                q["matchers"].append((mtype, name, value))
        queries.append(q)
    return queries


def handle_remote_read(query_engine, body: bytes, db: str = "public") -> bytes:
    """Evaluate a ReadRequest -> snappy-compressed ReadResponse."""
    queries = parse_read_request(body)
    ctx = QueryContext(db=db)
    results = b""
    for q in queries:
        metric = None
        for mtype, name, value in q["matchers"]:
            if name == "__name__" and mtype == 0:
                metric = _sanitize(value)
        series_blobs = b""
        if metric is not None:
            series_blobs = _query_series(query_engine, ctx, metric, q)
        results += pw.field_bytes(1, series_blobs)  # QueryResult
    resp = results
    return snappy.compress(resp)


def _query_series(query_engine, ctx, table: str, q: dict) -> bytes:
    try:
        info = query_engine._table(table, ctx)
    except CatalogError:
        return b""
    conds = [f"{GREPTIME_TIMESTAMP} >= {q['start_ms']}",
             f"{GREPTIME_TIMESTAMP} <= {q['end_ms']}"]
    for mtype, name, value in q["matchers"]:
        if name == "__name__":
            continue
        if name not in info.schema.names:
            if mtype in (0, 2) and value != "":
                return b""  # matcher on a label the table doesn't have
            continue
        esc = value.replace("'", "''")
        if mtype == 0:
            conds.append(f"{name} = '{esc}'")
        elif mtype == 1:
            conds.append(f"{name} != '{esc}'")
        # regex matchers (2, 3) filtered after scan below
    tag_names = [c.name for c in info.schema.tag_columns]
    sel_cols = ", ".join(tag_names + [GREPTIME_TIMESTAMP, GREPTIME_VALUE])
    sql = (f"SELECT {sel_cols} FROM {table} WHERE {' AND '.join(conds)} "
           f"ORDER BY {GREPTIME_TIMESTAMP}")
    res = query_engine.execute_one(sql, QueryContext(db=ctx.db))
    rows = res.rows()
    # regex matcher post-filter
    regex = [(re.compile(v), name, t == 3)
             for t, name, v in q["matchers"] if t in (2, 3) and name != "__name__"]
    # group rows into series by tag tuple
    series: dict[tuple, list[tuple[int, float]]] = defaultdict(list)
    n_tags = len(tag_names)
    for row in rows:
        tags = tuple(row[:n_tags])
        skip = False
        for rx, name, negate in regex:
            idx = tag_names.index(name) if name in tag_names else None
            val = "" if idx is None or tags[idx] is None else str(tags[idx])
            m = rx.fullmatch(val) is not None
            if m == negate:
                skip = True
                break
        if skip:
            continue
        ts, val = row[n_tags], row[n_tags + 1]
        if val is None:
            continue
        series[tags].append((int(ts), float(val)))
    out = b""
    for tags, samples in sorted(series.items(), key=lambda kv: kv[0]):
        labels = pw.field_bytes(
            1, pw.field_str(1, "__name__") + pw.field_str(2, table)
        )
        for name, value in zip(tag_names, tags):
            if value is None:
                continue
            labels += pw.field_bytes(1, pw.field_str(1, name) + pw.field_str(2, str(value)))
        sample_blobs = b""
        for ts, val in samples:
            sample_blobs += pw.field_bytes(2, pw.field_double(1, val) + pw.field_varint(2, ts))
        out += pw.field_bytes(1, labels + sample_blobs)  # TimeSeries
    return out
