"""Server-side TLS configuration for the wire protocols.

Mirrors reference src/servers/src/tls.rs (TlsOption: mode +
cert/key paths, reloadable context). `TlsConfig.make_context()` builds
one ssl.SSLContext per server; MySQL upgrades after the client's
SSLRequest (CLIENT_SSL capability), PostgreSQL after the SSLRequest
startup code — both mid-handshake STARTTLS-style upgrades on the
accepted socket.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass


@dataclass
class TlsConfig:
    cert_path: str
    key_path: str
    # 'prefer': offer TLS, allow plaintext; 'require': reject plaintext
    # clients (reference tls.rs TlsMode subset that matters server-side)
    mode: str = "prefer"

    def __post_init__(self):
        if self.mode not in ("prefer", "require"):
            raise ValueError(f"bad TLS mode {self.mode!r}")

    def make_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        return ctx
