"""Session state (mirrors reference `src/session`: `QueryContext` with
catalog/schema/timezone/channel, src/session/src/context.rs:39).

`QueryContext` travels with every statement from the wire protocol down
through the query engine; servers stamp the channel and authenticated
user, `USE <db>` mutates the current schema, and the timezone feeds
timestamp rendering/coercion.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from greptimedb_tpu.catalog.catalog import DEFAULT_DB

__all__ = ["Channel", "QueryContext", "DEFAULT_DB"]


class Channel(enum.Enum):
    """Wire protocol a request arrived on (reference
    src/session/src/context.rs Channel enum)."""

    UNKNOWN = "unknown"
    HTTP = "http"
    GRPC = "grpc"
    MYSQL = "mysql"
    POSTGRES = "postgres"
    INFLUX = "influx"
    OPENTSDB = "opentsdb"
    PROMETHEUS = "prometheus"
    OTLP = "otlp"
    FLOW = "flow"


@dataclass
class QueryContext:
    """Per-request session context (reference QueryContext,
    src/session/src/context.rs:39 — catalog/schema/timezone/channel,
    plus the authenticated user)."""

    db: str = DEFAULT_DB
    # None = "not set by the client" — QueryEngine.execute_sql resolves it
    # to the engine's default_timezone option; a client-set value wins
    timezone: Optional[str] = None
    channel: Channel = Channel.UNKNOWN
    user: Optional[object] = None  # auth.UserInfo when authenticated
    # fair-scheduling identity for the admission controller; servers
    # stamp it from X-Greptime-Tenant / the authenticated user, falling
    # back to "default" (concurrency/admission.py)
    tenant: Optional[str] = None
    # W3C trace context for cross-process propagation (SURVEY §5)
    trace_id: Optional[str] = None
    # deadline plane (utils/deadline.py): timeout_ms is the requested
    # per-statement budget (0/None = fall back to [query]
    # default_timeout_ms); servers stamp it from X-Greptime-Timeout /
    # max_execution_time / statement_timeout. cancel_token is the live
    # per-statement CancelToken while a statement is executing — servers
    # cancel it on client disconnect, KILL QUERY finds it via the
    # running-queries registry
    timeout_ms: Optional[float] = None
    cancel_token: Optional[object] = None  # deadline.CancelToken
    extensions: dict = field(default_factory=dict)

    @property
    def current_schema(self) -> str:
        return self.db

    def with_db(self, db: str) -> "QueryContext":
        return QueryContext(db=db, timezone=self.timezone,
                            channel=self.channel, user=self.user,
                            tenant=self.tenant,
                            trace_id=self.trace_id,
                            timeout_ms=self.timeout_ms,
                            cancel_token=self.cancel_token,
                            extensions=self.extensions)
