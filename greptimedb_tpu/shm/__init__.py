"""Cross-process serving fabric (ISSUE 19): a shared-memory artifact
plane N frontend processes on one box attach to.

What rides the fabric:

- **fast-lane templates** (`concurrency/fast_lane.py`): a template miss
  probes the fabric before paying the probe-verification parses; a
  local build publishes its verified binder so peers adopt instead of
  re-probing. Peer-DDL safety rides per-(db, table) fabric versions
  bumped through `ConcurrencyPlane.invalidate_table` plus the existing
  per-hit TableInfo snapshot checks.
- **plan-cache entries** (`concurrency/plan_cache.py`): a shape miss
  probes the fabric for a peer's validated canonical plan; adoption
  re-runs the same `_info_matches` safety net every in-process hit
  runs.
- **XLA executables**: with the fabric on, every process defaults its
  persistent compilation cache to one namespace under the fabric
  directory (`<fabric_dir>/xla-cache`), so process 2's first query hits
  a compiled executable instead of paying XLA compile.
- **zero-copy result handoff** (`shm/results.py`): process-mode encode
  workers write encoded payloads into a shared-memory arena and return
  an offset; the socket writer sends straight from the mapping.
- **worker metrics** (`shm/metrics_bridge.py`): encode workers publish
  their cumulative counters through the fabric so the parent's
  /metrics is exact, not a parent-side approximation.

Configuration: `[shm]` options (`fabric`, `fabric_bytes`,
`fabric_dir`) with `GTPU_SHM_FABRIC` / `GTPU_SHM_FABRIC_BYTES` /
`GTPU_SHM_FABRIC_DIR` env twins (children of a ProcessCluster inherit
the environment, so one setting covers the whole box). The fabric is
opt-in (off by default): a single-process deployment pays nothing.

Degradation contract: attach failure, a corrupt slot, or a layout
version mismatch detaches THIS process to its private in-process lane
— typed, counted (`shm_fabric_events_total{event="detach"}`), and
byte-for-byte identical output either way.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from greptimedb_tpu.shm.fabric import (  # noqa: F401 — package surface
    Fabric,
    FabricError,
    SEGMENT_PREFIX,
    segment_name,
)
from greptimedb_tpu.utils.metrics import SHM_FABRIC_BYTES, SHM_FABRIC_EVENTS

_TRUE = ("1", "true", "on", "yes")


@dataclass
class ShmConfig:
    #: master switch for the whole fabric plane (opt-in)
    fabric: bool = False
    #: bytes per shared segment (artifact fabric and result arena each)
    fabric_bytes: int = 64 << 20
    #: directory holding the lockfiles + the shared XLA cache namespace;
    #: every process pointing at the same directory shares one fabric
    fabric_dir: str = ""


def default_fabric_dir() -> str:
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"gtpu-fabric-{uid}")


def config_from_env() -> ShmConfig:
    """The env-twin layer (options.apply_shm writes these so spawned
    children — encode workers, ProcessCluster datanodes — inherit)."""
    cfg = ShmConfig()
    cfg.fabric = os.environ.get("GTPU_SHM_FABRIC", "").lower() in _TRUE
    raw = os.environ.get("GTPU_SHM_FABRIC_BYTES", "")
    if raw:
        try:
            cfg.fabric_bytes = max(1 << 20, int(raw))
        except ValueError:
            pass
    cfg.fabric_dir = os.environ.get("GTPU_SHM_FABRIC_DIR", "") \
        or default_fabric_dir()
    return cfg


# singleton state: one attached fabric per process. `failed` latches a
# detach so a corrupt fabric is probed once, not per request.
_state = {"fabric": None, "inited": False}
_state_lock = threading.Lock()


def get_fabric():
    """The process-wide attached Fabric, or None (disabled, never
    configured, or detached after a failure). Never raises."""
    with _state_lock:
        if _state["inited"]:
            return _state["fabric"]
        _state["inited"] = True
        cfg = config_from_env()
        if not cfg.fabric:
            return None
        try:
            f = Fabric(cfg.fabric_dir, size=cfg.fabric_bytes)
        except (FabricError, OSError, ValueError):
            SHM_FABRIC_EVENTS.inc(event="detach", kind="fabric")
            return None
        _state["fabric"] = f
        import atexit

        # engines share the singleton, so no plane shutdown may close
        # it; the process closes it on the way out (last one unlinks)
        atexit.register(shutdown_fabric)
        SHM_FABRIC_BYTES.set(float(cfg.fabric_bytes),
                             segment="fabric", dim="size")
        return f


def detach(reason: str = "corrupt"):
    """Degrade this process to the private in-process lane: close the
    fabric (peers keep theirs) and latch the failure. Typed + counted;
    serving continues without it."""
    with _state_lock:
        f = _state["fabric"]
        _state["fabric"] = None
        _state["inited"] = True
    if f is not None:
        if reason == "corrupt":
            SHM_FABRIC_EVENTS.inc(event="corrupt", kind="fabric")
        SHM_FABRIC_EVENTS.inc(event="detach", kind="fabric")
        try:
            f.close()
        except OSError:
            pass


def shutdown_fabric():
    """Clean detach at plane shutdown (the last process out unlinks the
    segment); resets the singleton so tests can re-init."""
    with _state_lock:
        f = _state["fabric"]
        _state["fabric"] = None
        _state["inited"] = False
    if f is not None:
        try:
            f.close()
        except OSError:
            pass
    from greptimedb_tpu.shm import results

    results.shutdown_arena()


_stats_installed = {"done": False}


def install_stats_collector() -> None:
    """Register the fabric-gauge collector once per process (tests
    build many planes; one collector serves them all)."""
    with _state_lock:
        if _stats_installed["done"]:
            return
        _stats_installed["done"] = True
    from greptimedb_tpu.utils.metrics import REGISTRY

    REGISTRY.register_collector(collect_fabric_stats)


def collect_fabric_stats() -> None:
    """Scrape-time collector: refresh the fabric gauges (registered by
    ConcurrencyPlane when the fabric attaches)."""
    with _state_lock:
        f = _state["fabric"]
    if f is None:
        return
    try:
        st = f.stats()
    except (FabricError, OSError, ValueError):
        return
    if st:
        SHM_FABRIC_BYTES.set(float(st["size"]), segment="fabric",
                             dim="size")
        SHM_FABRIC_BYTES.set(float(st["heap_used"]), segment="fabric",
                             dim="used")


def apply_shared_xla_cache() -> None:
    """Point this process's persistent XLA compilation cache at the
    fabric's shared namespace (unless the operator pinned an explicit
    one) — the shared-executable leg of the tentpole: process 2's first
    query loads the executable process 1 compiled."""
    cfg = config_from_env()
    if not cfg.fabric:
        return
    if os.environ.get("GREPTIMEDB_TPU_COMPILATION_CACHE_DIR"):
        return  # operator override wins
    os.environ["GREPTIMEDB_TPU_COMPILATION_CACHE_DIR"] = \
        os.path.join(cfg.fabric_dir, "xla-cache")
